"""North-star benchmark: 100k bindings x 5k clusters replica division on TPU.

Reproduces BASELINE.json config 5 ("descheduler rebalance storm: 100k
bindings x 5k clusters, dynamic-weight division with taint/toleration
filters"): every binding re-divides its replicas against live availability
with previous placements credited (Steady semantics), exactly the
generic_scheduler assignReplicas subtree this build tensorizes.

Measurement protocol (BASELINE.md):
- the TPU pass runs the fused schedule_step (estimator availability +
  min-merge + unified division) over binding chunks; inputs are generated
  on-device from a seed so the tunnel's host<->device bandwidth is not the
  thing measured; per-chunk placement summaries are reduced on device.
- placements are verified identical against the pure-Python oracle
  (karmada_tpu.refimpl) on a sampled chunk.
- the baseline is the oracle's per-binding cost measured on the sample and
  scaled to the full population (the reference repo publishes no numbers;
  BASELINE.md directs generating the baseline from the divider semantics).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = p50 wall seconds for the full 100k x 5k pass.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--bindings", type=int, default=100_000)
    p.add_argument("--clusters", type=int, default=5_000)
    p.add_argument("--chunk", type=int, default=4096)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--sample", type=int, default=512, help="oracle sample size")
    p.add_argument("--cpu", action="store_true", help="force CPU jax (debug)")
    p.add_argument(
        "--trace-dir",
        default="",
        help="capture a jax.profiler (xprof) trace of the timed passes into "
        "this directory — the SURVEY section-5 tracing analogue of the "
        "reference's slow-op trace + pprof endpoints",
    )
    p.add_argument("--dims", type=int, default=4)
    p.add_argument(
        "--config",
        type=int,
        default=5,
        choices=(1, 2, 3, 4, 5),
        help="BASELINE.json workload config (default 5: 100k x 5k "
        "dynamic-weight rebalance storm); 1-4 run the smaller scenario "
        "suites through the full engine",
    )
    return p


def run_engine_config(config: int) -> dict:
    """Configs 1-4: the engine-level BASELINE scenarios (full control-plane
    packing path, CPU-or-TPU agnostic). Returns the result JSON dict."""
    import time as _time

    import numpy as np

    from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
    from karmada_tpu.api.policy import SpreadConstraint, ClusterAffinity, LabelSelector
    from karmada_tpu.utils.builders import (
        aggregated_placement,
        duplicated_placement,
        dynamic_weight_placement,
        static_weight_placement,
        synthetic_fleet,
        new_cluster,
    )
    from karmada_tpu.utils.quantity import parse_resource_list

    req = parse_resource_list({"cpu": "250m", "memory": "512Mi"})
    if config == 1:
        # samples/nginx: Duplicated across 3 members
        clusters = [new_cluster(f"member{i}") for i in (1, 2, 3)]
        placement = duplicated_placement()
        problems = [
            BindingProblem(key="nginx", placement=placement, replicas=2,
                           requests=req, gvk="apps/v1/Deployment")
        ]
        metric = "config1_nginx_duplicated"
    elif config == 2:
        clusters = [new_cluster(f"member{i}") for i in (1, 2, 3)]
        placement = static_weight_placement(
            {"member1": 2, "member2": 1, "member3": 1}
        )
        problems = [
            BindingProblem(key="web", placement=placement, replicas=10,
                           requests=req, gvk="apps/v1/Deployment")
        ]
        metric = "config2_static_weight_10"
    elif config == 3:
        from karmada_tpu.api.cluster import ResourceModel, ResourceModelRange, AllocatableModeling

        clusters = synthetic_fleet(20, seed=3)
        for cl in clusters:  # per-cluster ResourceModels (grade buckets)
            cl.spec.resource_models = [
                ResourceModel(grade=g, ranges=[
                    ResourceModelRange(name="cpu", min=1000 * 2**g, max=1000 * 2**(g + 1)),
                    ResourceModelRange(name="memory", min=(2 << 30) * 2**g,
                                       max=(2 << 30) * 2**(g + 1)),
                ])
                for g in range(3)
            ]
            cl.status.resource_summary.allocatable_modelings = [
                AllocatableModeling(grade=g, count=10 * (g + 1)) for g in range(3)
            ]
        placement = aggregated_placement()
        problems = [
            BindingProblem(key=f"b{i}", placement=placement,
                           replicas=(i % 20) + 1, requests=req,
                           gvk="apps/v1/Deployment")
            for i in range(100)
        ]
        metric = "config3_aggregated_models_100x20"
    else:  # config 4
        clusters = synthetic_fleet(500, seed=4)
        placement = dynamic_weight_placement(
            cluster_affinity=ClusterAffinity(
                label_selector=LabelSelector(match_labels={"env": "prod"})
            ),
            spread_constraints=[
                SpreadConstraint(spread_by_field="region", min_groups=2, max_groups=4),
                SpreadConstraint(spread_by_field="cluster", min_groups=2, max_groups=10),
            ],
        )
        problems = [
            BindingProblem(key=f"b{i}", placement=placement,
                           replicas=(i % 40) + 1, requests=req,
                           gvk="apps/v1/Deployment")
            for i in range(10_000)
        ]
        metric = "config4_spread_region_10kx500"

    snap = ClusterSnapshot(clusters)
    sched = TensorScheduler(snap, chunk_size=4096)
    # warm with the full set so every padded chunk shape is traced; the
    # steady-state number is what the always-on scheduler process sees
    sched.schedule(problems)
    t0 = _time.perf_counter()
    results = sched.schedule(problems)
    wall = _time.perf_counter() - t0
    ok = sum(1 for r in results if r.success)
    print(f"# config {config}: {ok}/{len(problems)} scheduled in {wall:.3f}s",
          file=sys.stderr)
    return {
        "metric": metric,
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": 1.0,
    }


def main():
    args = build_parser().parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.config != 5:
        print(json.dumps(run_engine_config(args.config)))
        return
    import jax
    import jax.numpy as jnp
    from jax import lax

    from karmada_tpu.ops.divide import _divide_batch
    from karmada_tpu.ops.estimate import (
        gather_profile_rows,
        general_estimate,
        merge_estimates,
    )
    from karmada_tpu import refimpl as R

    b_total, c, r = args.bindings, args.clusters, args.dims
    chunk = args.chunk
    n_chunks = (b_total + chunk - 1) // chunk
    dev = jax.devices()[0]
    print(f"# device: {dev.platform}:{dev.device_kind}", file=sys.stderr)

    # ---- fleet capacity (one-time, represents the cluster snapshot) -------
    key = jax.random.key(0)
    kcap, kfeas = jax.random.split(key)
    # heterogeneous capacity: cpu-milli, memory bytes, pods, storage
    scales = jnp.asarray([512_000, 4 << 40, 5_500, 1 << 42], jnp.int64)[:r]
    available_cap = (
        jax.random.uniform(kcap, (c, r), minval=0.05, maxval=1.0)
        * scales[None, :].astype(jnp.float32)
    ).astype(jnp.int64)
    has_summary = jnp.ones((c,), bool)
    # taint/toleration filter outcome: ~8% of clusters tainted; ~30% of
    # bindings tolerate (composed into the feasibility mask, as the engine
    # does after bitset evaluation)
    tainted = jax.random.uniform(kfeas, (c,)) < 0.08

    # 8 request profiles (cpu-milli, bytes, pods, storage) — the engine
    # interns request rows (np.unique) so the estimator runs per profile
    profiles = jnp.stack(
        [
            jnp.asarray([250, 1 << 29, 1, 1 << 30], jnp.int64)[:r] * (p + 1)
            for p in range(8)
        ]
    )
    # int32 fast path justification (ops/dispense wide=False contract):
    # avail <= min_d(cap_d/req_d) <= 512000/250 = 2048; fresh weights
    # <= avail+prev <= 2078; x replicas(<100) ~ 2.1e5; per-row weight sums
    # <= 5000 x 2078 ~ 1.04e7 — all << 2^31. Verified by the oracle check.
    # Packed-key dispense gate (take_by_weight_fast): w 12 bits, prev 5
    # bits, idx bits from --clusters; falls back to the plain narrow kernel
    # when the key exceeds 31 bits (huge fleets).
    i_bits = max(1, (c - 1).bit_length())
    fast = (12, 5, min(c, 128), True) if 12 + 5 + i_bits <= 31 else None

    # ---- device mesh: shard the binding axis over every visible chip ------
    # (the north-star target is v5e-8; on one chip this is a no-op, on a
    # multi-chip slice GSPMD partitions generation + solve row-parallel with
    # zero collectives — bindings are independent). Validated on the virtual
    # 8-device CPU mesh by tests/test_parallel_graft.py.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    mesh = None
    if len(devs) > 1 and chunk % len(devs) == 0:
        mesh = Mesh(np.array(devs), ("b",))
        print(f"# mesh: {len(devs)} devices over the binding axis",
              file=sys.stderr)

    def shard_rows(*arrays):
        """with_sharding_constraint over the leading (binding) axis."""
        if mesh is None:
            return arrays
        out = []
        for a in arrays:
            spec = P("b", *([None] * (a.ndim - 1)))
            out.append(
                jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))
            )
        return tuple(out)

    # NOTE: the fleet arrays (per_profile, tainted) are threaded through as
    # jit ARGUMENTS everywhere below — large captured device constants
    # inside a lax.scan body hang XLA compilation on the tunneled backend
    def gen_chunk(i, tainted_arg):
        k = jax.random.fold_in(jax.random.key(42), i)
        k1, k2, k3, k4, k5, k6, k7 = jax.random.split(k, 7)
        replicas = jax.random.randint(k1, (chunk,), 1, 100, dtype=jnp.int32)
        prof_idx = jax.random.randint(k2, (chunk,), 0, 8)
        tolerates = jax.random.uniform(k3, (chunk, 1)) < 0.30
        candidates = ~tainted_arg[None, :] | tolerates
        # previous placements: ~70% of bindings hold replicas on up to 8
        # clusters. Sites are drawn SPARSELY ([chunk, 8] indices scattered
        # into the row) rather than via a [chunk, C] uniform — the dense
        # draw was the single largest remaining cost in the fused program
        has_prev = jax.random.uniform(k4, (chunk, 1)) < 0.7
        sites = jax.random.randint(k5, (chunk, 8), 0, c)
        cnts = jax.random.randint(k6, (chunk, 8), 1, 30, dtype=jnp.int32)
        prev0 = (
            jnp.zeros((chunk, c), jnp.int32)
            .at[jnp.arange(chunk)[:, None], sites]
            .set(cnts)
        )
        prev = jnp.where(has_prev & candidates, prev0, 0)
        fresh = jax.random.uniform(k7, (chunk,)) < 0.05
        strategy = jnp.full((chunk,), 2, jnp.int32)  # DynamicWeight
        static_w = jnp.zeros((chunk, c), jnp.int32)
        return shard_rows(
            prof_idx, strategy, replicas, candidates, static_w, prev, fresh
        )

    per_profile = general_estimate(available_cap, profiles)  # [8, C]

    def solve_chunk(i, table, tainted_arg):
        prof_idx, strategy, replicas, candidates, static_w, prev, fresh = (
            gen_chunk(i, tainted_arg)
        )
        general = gather_profile_rows(table, prof_idx)
        avail = merge_estimates(replicas, (general,))
        assignment, unsched = _divide_batch(
            strategy, replicas, candidates, static_w, avail, prev, fresh,
            False,  # has_aggregated: config-5 workload is pure DynamicWeight
            False,  # wide: int32 products proven above
            fast,  # packed-key top_k dispense: replicas <= 99 -> k_top 128;
            # products < 2^24 -> exact f32 floor-div (take_by_weight_fast)
        )
        placed = (assignment > 0).sum(axis=1).astype(jnp.int32)
        total = assignment.sum(axis=1).astype(jnp.int32)
        return placed, total, unsched

    @jax.jit
    def solve_all(table, tainted_arg):
        # ONE dispatch for the full pass: the tunnel costs ~100ms per jit
        # call, so the 25-chunk stream runs as a lax.scan inside a single
        # XLA program; per-chunk summaries are stacked on device
        def body(carry, i):
            return carry, solve_chunk(i, table, tainted_arg)
        _, outs = lax.scan(body, 0, jnp.arange(n_chunks))
        return outs

    # ---- timed passes -----------------------------------------------------
    times = []
    summary = None
    jax.block_until_ready((per_profile, tainted))
    # warm the trace (compile is ~40s first run, cached after)
    jax.tree.map(np.asarray, solve_all(per_profile, tainted))
    import contextlib

    trace_ctx = (
        jax.profiler.trace(args.trace_dir)
        if args.trace_dir
        else contextlib.nullcontext()
    )
    with trace_ctx:
      for rep in range(args.repeats):
        t0 = time.perf_counter()
        outs = solve_all(per_profile, tainted)
        outs = jax.tree.map(np.asarray, outs)  # host fetch = full completion
        t1 = time.perf_counter()
        times.append(t1 - t0)
        if rep == 0:
            placed = outs[0].reshape(-1)[:b_total]
            total = outs[1].reshape(-1)[:b_total]
            unsched = outs[2].reshape(-1)[:b_total]
            summary = (placed, total, unsched)
        print(f"# pass {rep}: {t1 - t0:.3f}s", file=sys.stderr)
    p50 = float(np.median(times))
    placed, total, unsched = summary
    print(
        f"# scheduled {int((~unsched).sum())}/{b_total} bindings, "
        f"mean clusters/binding {placed[~unsched].mean():.1f}",
        file=sys.stderr,
    )

    # ---- identical-placement verification + baseline on a sample ----------
    @jax.jit
    def full_chunk0(table, tainted_arg):
        prof_idx, strategy, replicas, candidates, static_w, prev, fresh = (
            gen_chunk(0, tainted_arg)
        )
        general = gather_profile_rows(table, prof_idx)
        avail = merge_estimates(replicas, (general,))
        assignment, unsched = _divide_batch(
            strategy, replicas, candidates, static_w, avail, prev, fresh,
            False, False, fast,
        )
        return (prof_idx, strategy, replicas, candidates, static_w, prev,
                fresh, assignment, unsched)

    (prof_idx, strategy, replicas, candidates, static_w, prev, fresh,
     kernel_assign, kernel_unsched) = map(
        np.asarray, full_chunk0(per_profile, tainted)
    )
    requests = np.asarray(profiles)[prof_idx]
    cap_np = np.asarray(available_cap)

    sample = min(args.sample, chunk)
    t0 = time.perf_counter()
    mismatches = 0
    for i in range(sample):
        cand_idx = np.flatnonzero(candidates[i])
        req = requests[i]
        est = []
        for j in cand_idx:
            per_dim = [
                max(int(cap_np[j, d]), 0) // int(req[d])
                for d in range(r)
                if req[d] > 0
            ]
            est.append(min(per_dim) if per_dim else R.MAX_INT32)
        avail = R.merge_estimates(int(replicas[i]), [est], len(cand_idx))
        prob = R.DivisionProblem(
            replicas=int(replicas[i]),
            strategy=R.DYNAMIC_WEIGHT,
            candidates=cand_idx.tolist(),
            available=avail,
            prev={int(j): int(prev[i, j]) for j in np.flatnonzero(prev[i])} or None,
            fresh=bool(fresh[i]),
        )
        try:
            want = R.assign_replicas(prob)
            want_row = np.zeros(c, np.int32)
            for j, n_rep in want.items():
                want_row[j] = n_rep
            if kernel_unsched[i] or not np.array_equal(kernel_assign[i], want_row):
                mismatches += 1
        except R.UnschedulableError:
            if not kernel_unsched[i]:
                mismatches += 1
    t_oracle = time.perf_counter() - t0
    baseline_full = t_oracle / sample * b_total
    print(
        f"# identical-placement check: {sample - mismatches}/{sample} match; "
        f"oracle {t_oracle / sample * 1e3:.2f} ms/binding -> "
        f"{baseline_full:.1f}s extrapolated for {b_total}",
        file=sys.stderr,
    )
    if mismatches:
        print(f"# WARNING: {mismatches} placement mismatches", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": f"p50_schedule_{b_total // 1000}kx{c}_dynamic_weight",
                "value": round(p50, 4),
                "unit": "s",
                "vs_baseline": round(baseline_full / p50, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
