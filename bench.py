"""North-star benchmark: 100k bindings x 5k clusters through the ENGINE.

Reproduces BASELINE.json config 5 ("descheduler rebalance storm: 100k
bindings x 5k clusters, dynamic-weight division with taint/toleration
filters") through the REAL scheduling engine — TensorScheduler.schedule()
over BindingProblem objects against a ClusterSnapshot built from Cluster API
objects. The device-resident fleet table (scheduler/fleet.py) makes the
steady-storm pass one fused dispatch + one compact fetch; this is the
engine number, not a kernel-only number (round 1 measured the kernel alone
and was called on it — VERDICT.md "What's weak" #1).

Measurement protocol (BASELINE.md):
- warm passes compile + tune the entry buffer, timed passes measure the
  steady rebalance storm: every binding re-divides its replicas against
  live availability with previous placements credited (Steady semantics).
- placements are verified identical against TWO independent
  implementations: the pure-Python oracle (karmada_tpu.refimpl, the
  semantics port of the Go divider) on rows sampled across every chunk, and
  the vectorized-numpy host divider (refimpl.divider_np) on EVERY row.
- baselines: vs_python_oracle extrapolates the pure-Python per-binding cost
  (the interpreter-relative multiple round 1 reported); vs_numpy_host times
  the vectorized-numpy divider on the full set (the conservative,
  compiled-host-comparable multiple — the in-tree Go divider the target
  names is a per-binding loop, so honest vectorized numpy is the closest
  calibration this image allows; no Go toolchain exists here).
  ``vs_baseline`` reports the CONSERVATIVE number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
value = p50 wall seconds for the full 100k x 5k engine pass.

A mixed-strategy verification phase (all four strategies x Steady/Fresh/
scale-up/scale-down cohorts) runs the same engine against the oracle so the
identical-placement claim spans every assignment mode, not just the
headline workload (VERDICT.md "What's weak" #3).
"""

from __future__ import annotations

import argparse
import gc
import hashlib
import json
import os
import sys
import time

import numpy as np


# Tier hygiene: each sub-tier dels its engine/results then gc.collect()s
# so its device residents free before the next tier allocates (three live
# engines exceed HBM at C=5000).


def build_parser():
    p = argparse.ArgumentParser()
    # None = "caller didn't say": resolved per tier in main() (the
    # headline tiers run 100k x 5k, --observability 20k x 512) — an
    # EXPLICIT --bindings 100000 must mean 100000 everywhere
    p.add_argument("--bindings", type=int, default=None)
    p.add_argument("--clusters", type=int, default=None)
    p.add_argument("--chunk", type=int, default=4096)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument(
        "--sample", type=int, default=1024,
        help="pure-Python-oracle sample size (spread across all chunks)",
    )
    p.add_argument(
        "--mix-sample", type=int, default=1024,
        help="mixed-strategy verification rows (all 4 strategies x cohorts)",
    )
    p.add_argument("--cpu", action="store_true", help="force CPU jax (debug)")
    p.add_argument(
        "--kernel-only", action="store_true",
        help="round-1 protocol: fused solve kernel with on-device input "
        "generation (no engine, no API objects) — the multichip/sharding "
        "diagnostic, not the headline metric",
    )
    p.add_argument(
        "--shard", default="",
        help="BxC mesh for the kernel step, e.g. 4x2 (requires B*C visible "
        "devices; with C>1 the cluster axis shards and the dispense sorts "
        "ride c-axis collectives). Runs make_sharded_step on host-built "
        "inputs, verifies placement identity against the unsharded step, "
        "and reports both timings.",
    )
    p.add_argument(
        "--multichip", action="store_true",
        help="the REAL multichip tier (supersedes the MULTICHIP_r0* toy "
        "dryruns): run the ENGINE storm at every --mesh-sizes size on "
        "forced host devices — steady p50 scaling curve, placement "
        "bit-identity vs the single-device engine, per-pass host<->device "
        "transfer bytes, and a live donated-buffer-reuse assertion. "
        "Defaults to 20k x 512 (CPU rig); on a real TPU slice set "
        "KARMADA_TPU_DRYRUN_REAL_DEVICES=1 and the headline shape.",
    )
    p.add_argument(
        "--mesh-sizes", default="1,2,4,8",
        help="comma-separated device counts for --multichip "
        "(each must be a power of two; 1 = the single-device reference)",
    )
    p.add_argument(
        "--no-verify", action="store_true",
        help="skip the oracle/numpy verification phases (timing only)",
    )
    p.add_argument(
        "--trace-dir",
        default="",
        help="capture a jax.profiler (xprof) trace of the timed passes into "
        "this directory — the SURVEY section-5 tracing analogue of the "
        "reference's slow-op trace + pprof endpoints",
    )
    p.add_argument("--dims", type=int, default=4)
    p.add_argument(
        "--hetero", type=int, default=0,
        help="config-5 variant: N UNIQUE placements (distinct label "
        "selectors / tolerations / static weights) spread across the "
        "bindings — stresses placement compilation, mask interning, and "
        "the fleet table's MAX_SLOTS rebuild behavior (SURVEY section 7 "
        "label-selector cost warning). 0 = the homogeneous headline "
        "workload",
    )
    p.add_argument(
        "--cold-start", action="store_true",
        help="measure the plane-restart cold wave: spawn three fresh "
        "engine processes over the headline workload — seed (populate "
        "the persistent compile cache + trace manifest), cold (both "
        "disabled: the pre-cache baseline), restore (manifest prewarm + "
        "cached restart) — and report first-wave latency for each. The "
        "parent never touches jax (single-client accelerator: each child "
        "owns the claim in turn)",
    )
    p.add_argument(
        "--cold-child", default="", choices=("", "seed", "cold", "restore"),
        help=argparse.SUPPRESS,
    )
    p.add_argument(
        "--check", default="", metavar="RECORD",
        help="perf-regression guard (ISSUE 12): compare RECORD.json "
        "against the newest committed BENCH_*.json with the same metric "
        "using tools/benchguard.py's per-metric directional noise "
        "bands; prints the verdict table and exits non-zero on any "
        "regression or missing guarded metric",
    )
    p.add_argument(
        "--observability", action="store_true",
        help="run the wave-trace observability tier: a whole-plane storm "
        "wave (default 20k bindings x 512 clusters; --bindings/--clusters "
        "override) through detector->scheduler->binding->works with wave "
        "tracing on, recording the per-phase attribution, the kernel "
        "compile/device/host split, and the coverage of the externally "
        "measured wall clock — the BENCH_OBS_r*.json record",
    )
    p.add_argument(
        "--chaos", action="store_true",
        help="run the chaos-failover tier (default 20k bindings x 512 "
        "clusters; --bindings/--clusters override): a whole-plane storm "
        "with ordered ClusterAffinities placements and live gRPC "
        "estimator servers, then a seeded fault-injection wave killing "
        "--chaos-kill clusters and SIGSTOP-partitioning one estimator "
        "server mid-wave; records time-to-stable-placement, displaced-"
        "binding count, batched-solve count, breaker transitions, and "
        "verifies the recovered placements against the numpy ordered-"
        "failover oracle replaying the same event log — the "
        "BENCH_CHAOS_r*.json record",
    )
    p.add_argument("--chaos-kill", type=int, default=8,
                   help="clusters killed by the chaos wave (K)")
    p.add_argument("--chaos-seed", type=int, default=1,
                   help="fault-injection seed (the replay key)")
    p.add_argument(
        "--quota", action="store_true",
        help="run the quota-enforcement tier (default 20k bindings x 512 "
        "clusters; --bindings/--clusters override): workloads across "
        "--quota-namespaces quota'd namespaces, FRQ limits tightened to "
        "used + headroom, then a CronFederatedHPA surge rescales half "
        "the fleet simultaneously through the scale-up dispense path "
        "against the quotas. Verifies every pass's admission decisions "
        "AND placements against the sequential numpy oracle "
        "(refimpl.quota_np), measures enforcement overhead against "
        "quota-disabled storms, and proves a quota raise clears "
        "QuotaExceeded without a full re-pack — the BENCH_QUOTA_r*.json "
        "record",
    )
    p.add_argument("--quota-namespaces", type=int, default=32,
                   help="quota'd namespaces the workloads spread across")
    p.add_argument(
        "--quota-headroom", type=float, default=0.4,
        help="fraction of the surge's delta demand each namespace's "
        "tightened quota leaves room for (the rest denies)",
    )
    p.add_argument(
        "--preemption", action="store_true",
        help="run the scarcity-plane tier (default 20k bindings x 512 "
        "clusters; --bindings/--clusters override): fill the fleet with "
        "priority-0 workloads, saturate member capacity exactly, then "
        "land a high-priority surge that cannot fit — the batched "
        "preemption kernel selects victims plane-wide and the demanders "
        "re-solve against the freed capacity in the same pass. Verifies "
        "victim selection AND final placements against the sequential "
        "numpy oracle (refimpl.preempt_np), measures armed-vs-disarmed "
        "steady-storm overhead, and runs a drift-rebalance round through "
        "the continuous descheduler under an exact disruption budget — "
        "the BENCH_PREEMPT_r*.json record",
    )
    p.add_argument("--preempt-surge", type=int, default=1000,
                   help="high-priority bindings in the scarcity surge")
    p.add_argument(
        "--preempt-budget", type=int, default=64,
        help="disruption budget for the drift-rebalance round "
        "(KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION)",
    )
    p.add_argument(
        "--scale", action="store_true",
        help="force the scale-1M tier (1M bindings x 5k clusters: steady, "
        "availability-drift churn, the row-churn delta tiers at "
        "0.1%%/1%%/10%% churn with the full-solve bit-identity oracle, and "
        "the legacy-path run) even when --bindings/--no-verify would "
        "otherwise skip it; the default 100k run includes it already",
    )
    p.add_argument(
        "--estimator-only", action="store_true",
        help="run just the estimator-512 wire tier (4 live gRPC server "
        "processes): full-refresh storm p50 over the batched protocol, "
        "no-movement refresh p50 over GetGenerations pings, the unary-"
        "fallback parity run, and per-pass RPC counts — the "
        "BENCH_ESTIMATOR_r*.json record",
    )
    p.add_argument(
        "--config",
        type=int,
        default=5,
        choices=(1, 2, 3, 4, 5),
        help="BASELINE.json workload config (default 5: 100k x 5k "
        "dynamic-weight rebalance storm); 1-4 run the smaller scenario "
        "suites through the full engine",
    )
    return p



def settle_engine(engine, run_pass, *, floor: int, cap: int, label: str) -> int:
    """THE warm-loop contract, shared by every tier: keep running passes
    until one dispatches no unseen XLA trace AND no cap-shrink desire is
    accumulating (a pending sustained shrink compiles its one allowed
    trace within SHRINK_SUSTAIN passes — it must land here, not in a
    timed window). Returns the number of passes run."""
    for i in range(cap):
        t0 = time.perf_counter()
        run_pass(i)
        fresh = engine.last_pass_new_trace
        print(
            f"# {label} {i}: {time.perf_counter() - t0:.1f}s "
            f"new_trace={fresh}",
            file=sys.stderr,
        )
        if (
            i + 1 >= floor and not fresh
            and not engine.cap_shrink_pending
        ):
            return i + 1
    return cap


# --------------------------------------------------------------------------
# shared verification helpers
# --------------------------------------------------------------------------


def _oracle_inputs(snap, problems, engine):
    """Host-pack problems (the general path, independent of the fleet
    table) into the arrays the oracle and numpy divider consume."""
    compiled = [engine._compiled(p.placement) for p in problems]
    feasible, strategy, replicas, static_w, requests, prev, fresh = (
        engine._pack_chunk(problems, compiled, 0)
    )
    return feasible, strategy, replicas, static_w, requests, prev, fresh


def _general_avail_np(cap_np, requests):
    """numpy mirror of the general estimator: min over requested dims of
    floor(available/request); MAX_INT32 when nothing is requested."""
    from karmada_tpu.refimpl import MAX_INT32

    b, r = requests.shape
    c = cap_np.shape[0]
    out = np.full((b, c), MAX_INT32, np.int64)
    cap = np.maximum(cap_np, 0)
    for d in range(r):
        req = requests[:, d]
        ratio = cap[None, :, d] // np.maximum(req[:, None], 1)
        out = np.where((req > 0)[:, None], np.minimum(out, ratio), out)
    return np.minimum(out, MAX_INT32).astype(np.int64)


def _verify_rows(snap, problems, results, engine, sample_idx):
    """Compare engine results against the pure-Python oracle on the given
    rows. The availability input comes from the engine's profile table
    (which includes the resource-model estimator path — raw floor division
    would falsely flag every config-3-style fleet); the oracle independently
    re-executes the estimator MERGE and the full DIVISION semantics.
    Returns (ok, bad)."""
    from karmada_tpu import refimpl as R

    sub = [problems[i] for i in sample_idx]
    feasible, strategy, replicas, static_w, requests, prev, fresh = (
        _oracle_inputs(snap, sub, engine)
    )
    uniq, inv = np.unique(requests, axis=0, return_inverse=True)
    table = np.asarray(engine._profile_table(uniq))  # [P, C]; -1 = no answer
    ok = bad = 0
    for k, i in enumerate(sample_idx):
        res = results[i]
        cand_idx = np.flatnonzero(feasible[k])
        if len(cand_idx) == 0:
            good = not res.success
            ok, bad = ok + good, bad + (not good)
            continue
        est = [int(table[inv[k], j]) for j in cand_idx]
        avail = R.merge_estimates(int(replicas[k]), [est], len(cand_idx))
        prob = R.DivisionProblem(
            replicas=int(replicas[k]),
            strategy=int(strategy[k]),
            candidates=cand_idx.tolist(),
            available=avail,
            static_weights=[int(static_w[k, j]) for j in cand_idx],
            prev={int(j): int(prev[k, j]) for j in np.flatnonzero(prev[k])}
            or None,
            fresh=bool(fresh[k]),
        )
        try:
            want = R.assign_replicas(prob)
            want_named = {
                snap.names[j]: n for j, n in want.items() if n > 0
            }
            good = res.success and dict(res.clusters) == want_named
        except R.UnschedulableError:
            good = (not res.success) and "not enough" in res.error
        ok, bad = ok + good, bad + (not good)
    return ok, bad


# --------------------------------------------------------------------------
# configs 1-4: engine scenarios
# --------------------------------------------------------------------------


def run_engine_config(config: int) -> dict:
    """Configs 1-4: the engine-level BASELINE scenarios (full control-plane
    packing path, CPU-or-TPU agnostic), oracle-verified row by row."""
    import time as _time

    from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
    from karmada_tpu.api.policy import SpreadConstraint, ClusterAffinity, LabelSelector
    from karmada_tpu.utils.builders import (
        aggregated_placement,
        duplicated_placement,
        dynamic_weight_placement,
        static_weight_placement,
        synthetic_fleet,
        new_cluster,
    )
    from karmada_tpu.utils.quantity import parse_resource_list

    req = parse_resource_list({"cpu": "250m", "memory": "512Mi"})
    verify_spread = False
    if config == 1:
        # samples/nginx: Duplicated across 3 members
        clusters = [new_cluster(f"member{i}") for i in (1, 2, 3)]
        placement = duplicated_placement()
        problems = [
            BindingProblem(key="nginx", placement=placement, replicas=2,
                           requests=req, gvk="apps/v1/Deployment")
        ]
        metric = "config1_nginx_duplicated"
    elif config == 2:
        clusters = [new_cluster(f"member{i}") for i in (1, 2, 3)]
        placement = static_weight_placement(
            {"member1": 2, "member2": 1, "member3": 1}
        )
        problems = [
            BindingProblem(key="web", placement=placement, replicas=10,
                           requests=req, gvk="apps/v1/Deployment")
        ]
        metric = "config2_static_weight_10"
    elif config == 3:
        from karmada_tpu.api.cluster import ResourceModel, ResourceModelRange, AllocatableModeling

        clusters = synthetic_fleet(20, seed=3)
        for cl in clusters:  # per-cluster ResourceModels (grade buckets)
            cl.spec.resource_models = [
                ResourceModel(grade=g, ranges=[
                    ResourceModelRange(name="cpu", min=1000 * 2**g, max=1000 * 2**(g + 1)),
                    ResourceModelRange(name="memory", min=(2 << 30) * 2**g,
                                       max=(2 << 30) * 2**(g + 1)),
                ])
                for g in range(3)
            ]
            cl.status.resource_summary.allocatable_modelings = [
                AllocatableModeling(grade=g, count=10 * (g + 1)) for g in range(3)
            ]
        placement = aggregated_placement()
        problems = [
            BindingProblem(key=f"b{i}", placement=placement,
                           replicas=(i % 20) + 1, requests=req,
                           gvk="apps/v1/Deployment")
            for i in range(100)
        ]
        metric = "config3_aggregated_models_100x20"
    else:  # config 4
        clusters = synthetic_fleet(500, seed=4)
        placement = dynamic_weight_placement(
            cluster_affinity=ClusterAffinity(
                label_selector=LabelSelector(match_labels={"env": "prod"})
            ),
            spread_constraints=[
                SpreadConstraint(spread_by_field="region", min_groups=2, max_groups=4),
                SpreadConstraint(spread_by_field="cluster", min_groups=2, max_groups=10),
            ],
        )
        problems = [
            BindingProblem(key=f"b{i}", placement=placement,
                           replicas=(i % 40) + 1, requests=req,
                           gvk="apps/v1/Deployment")
            for i in range(10_000)
        ]
        metric = "config4_spread_region_10kx500"
        verify_spread = True

    snap = ClusterSnapshot(clusters)
    sched = TensorScheduler(snap, chunk_size=4096)
    # warm with the full set so every padded chunk shape is traced; the
    # steady-state number is what the always-on scheduler process sees
    sched.schedule(problems)
    t0 = _time.perf_counter()
    results = sched.schedule(problems)
    wall = _time.perf_counter() - t0
    ok = sum(1 for r in results if r.success)

    # oracle verification: every row for small configs, a spread sample for
    # config 4 (whose selection narrowing is covered by its own golden
    # tests — the oracle verifies the division on the selected candidates)
    t0 = _time.perf_counter()
    if verify_spread:
        # EXACT placement identity for the spread config: the pure-Python
        # spread-selection oracle (refimpl.spread — independent of the
        # engine's scheduler/spread+groups path) narrows the candidates,
        # then the division oracle re-derives the assignment; every row
        # must match the engine bit for bit (VERDICT r3 item 8)
        from karmada_tpu import refimpl as R
        from karmada_tpu.refimpl.spread import select_spread_clusters

        host_eng = TensorScheduler(snap)
        feasible, strategy, reps_arr, static_w, requests, prev, fr = (
            _oracle_inputs(snap, problems, host_eng)
        )
        uniq, inv = np.unique(requests, axis=0, return_inverse=True)
        table = np.asarray(host_eng._profile_table(uniq))
        region_of = {
            j: snap.clusters[j].spec.region for j in range(len(snap.names))
        }
        constraints = {
            sc.spread_by_field: (sc.min_groups, sc.max_groups)
            for sc in placement.spread_constraints
        }
        n_ok = n_bad = 0
        t_oracle0 = _time.perf_counter()
        for i in range(len(problems)):
            res = results[i]
            reps_i = int(reps_arr[i])
            cand = np.flatnonzero(feasible[i])
            est_all = [int(v) for v in table[inv[i]]]
            merged = R.merge_estimates(reps_i, [est_all], len(est_all))
            score = {int(j): 100 if prev[i, j] > 0 else 0 for j in cand}
            credited = {
                int(j): merged[j] + int(prev[i, j]) for j in cand
            }
            sel = select_spread_clusters(
                [int(j) for j in cand], region_of, score, credited,
                constraints, reps_i, duplicated=False,
            ) if len(cand) else None
            if sel is None:
                good = not res.success
            else:
                prob = R.DivisionProblem(
                    replicas=reps_i,
                    strategy=int(strategy[i]),
                    candidates=sel,
                    available=R.merge_estimates(
                        reps_i, [[est_all[j] for j in sel]], len(sel)
                    ),
                    static_weights=[int(static_w[i, j]) for j in sel],
                    prev={
                        int(j): int(prev[i, j])
                        for j in np.flatnonzero(prev[i])
                    } or None,
                    fresh=bool(fr[i]),
                )
                try:
                    want = R.assign_replicas(prob)
                    want_named = {
                        snap.names[j]: n for j, n in want.items() if n > 0
                    }
                    good = res.success and dict(res.clusters) == want_named
                except R.UnschedulableError:
                    good = (not res.success) and "not enough" in res.error
            n_ok, n_bad = n_ok + good, n_bad + (not good)
        t_oracle = _time.perf_counter() - t_oracle0
        vs_baseline = round(t_oracle / max(wall, 1e-9), 1)
    else:
        n_ok, n_bad = _verify_rows(
            snap, problems, results, TensorScheduler(snap), list(range(len(problems)))
        )
        t_oracle = _time.perf_counter() - t0
        per_binding = t_oracle / max(1, n_ok + n_bad)
        vs_baseline = round(per_binding * len(problems) / max(wall, 1e-9), 1)
    print(
        f"# config {config}: {ok}/{len(problems)} scheduled in {wall:.3f}s; "
        f"oracle check {n_ok} ok / {n_bad} bad",
        file=sys.stderr,
    )
    return {
        "metric": metric,
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": vs_baseline,
        "verified_rows": n_ok,
        "verified_mismatches": n_bad,
    }


# --------------------------------------------------------------------------
# config 5: the engine north star
# --------------------------------------------------------------------------


def build_headline_workload(b_total: int, c: int):
    """The config-5 headline fleet + bindings (the control plane's API
    objects), shared by the north-star tier and the cold-start children:
    same seeds and placement mix in every process, so the trace manifest a
    seed process writes covers exactly the shapes a restored process
    dispatches."""
    import types

    from karmada_tpu.api.cluster import Toleration
    from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        synthetic_fleet,
    )
    from karmada_tpu.utils.quantity import parse_resource_list

    t0 = time.perf_counter()
    clusters = synthetic_fleet(c, seed=7, taint_fraction=0.08)
    snap = ClusterSnapshot(clusters)
    names = snap.names
    print(f"# fleet build: {time.perf_counter() - t0:.2f}s", file=sys.stderr)

    # ~30% of bindings tolerate the dedicated taint (two placement objects
    # -> two compiled masks; taint/toleration filter in the feasibility)
    tol = Toleration(key="fleet.io/dedicated", operator="Exists")
    pl_plain = dynamic_weight_placement()
    pl_tol = dynamic_weight_placement(cluster_tolerations=[tol])
    profiles = [
        parse_resource_list(
            {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
        )
        for p in range(8)
    ]

    t0 = time.perf_counter()
    rng = np.random.default_rng(42)
    replicas = rng.integers(1, 100, b_total)
    prof_idx = rng.integers(0, 8, b_total)
    tol_mask = rng.random(b_total) < 0.30
    has_prev = rng.random(b_total) < 0.7
    prev_sites = rng.integers(0, c, (b_total, 8))
    prev_counts = rng.integers(1, 30, (b_total, 8))
    n_prev = rng.integers(1, 9, b_total)
    fresh = rng.random(b_total) < 0.05
    problems = [
        BindingProblem(
            key=f"b{i}",
            placement=pl_tol if tol_mask[i] else pl_plain,
            replicas=int(replicas[i]),
            requests=profiles[prof_idx[i]],
            gvk="apps/v1/Deployment",
            prev=(
                {
                    names[prev_sites[i, k]]: int(prev_counts[i, k])
                    for k in range(n_prev[i])
                }
                if has_prev[i]
                else {}
            ),
            fresh=bool(fresh[i]),
        )
        for i in range(b_total)
    ]
    print(f"# problem build: {time.perf_counter() - t0:.2f}s", file=sys.stderr)
    return types.SimpleNamespace(
        clusters=clusters, snap=snap, names=names, tol=tol,
        pl_plain=pl_plain, pl_tol=pl_tol, profiles=profiles,
        replicas=replicas, prof_idx=prof_idx, problems=problems,
    )


# --------------------------------------------------------------------------
# estimator-512 wire tier: batched protocol + generation-gated refresh
# --------------------------------------------------------------------------


def run_estimator_tier(args, tier_status=None) -> dict:
    """Availability from LIVE gRPC accurate estimators: 512 clusters
    multiplexed across 4 real server processes (python -m
    karmada_tpu.estimator --spec-file). Three timed shapes:

    - FULL refresh (invalidate(drop=True) per pass): every cluster re-pays
      the wire, but the batched protocol makes it ONE MaxAvailableReplicas
      Batch RPC per server process instead of clusters x profiles unary
      calls.
    - NO-MOVEMENT refresh (invalidate() per pass): one GetGenerations ping
      per server proves nothing moved, the memoized profile columns stay
      valid, and the fan-out never runs — the steady-state staleness check
      a cluster-status heartbeat triggers.
    - UNARY FALLBACK (KARMADA_TPU_ESTIMATOR_BATCH=0, full refresh): the
      mixed-version path — per-profile calls pipelined over each server
      channel via grpc futures.

    Identity: each cluster's estimator holds one node whose allocatable
    equals the snapshot's free capacity, so min-merge(general, accurate)
    == general and placements must match the snapshot-fed engine bit for
    bit on BOTH protocols. Per-pass RPC counts are recorded to prove the
    O(servers) steady shape."""
    import os

    from karmada_tpu.estimator.accurate import BATCH_ENV
    from karmada_tpu.estimator.fleet import spawn_estimator_fleet
    from karmada_tpu.scheduler import (
        BindingProblem,
        ClusterSnapshot,
        TensorScheduler,
    )
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        synthetic_fleet,
    )
    from karmada_tpu.utils.quantity import parse_resource_list

    if tier_status is None:
        tier_status = {}
    c_e, b_e, n_servers = 512, 10_000, 4
    e_clusters = synthetic_fleet(c_e, seed=77)
    e_snap = ClusterSnapshot(e_clusters)
    e_names = e_snap.names
    dims = list(e_snap.dims)
    free = np.maximum(np.asarray(e_snap.available_cap), 0)
    pl_plain = dynamic_weight_placement()
    profiles = [
        parse_resource_list(
            {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
        )
        for p in range(8)
    ]
    rng_e = np.random.default_rng(17)
    e_problems = [
        BindingProblem(
            key=f"e{i}", placement=pl_plain,
            replicas=int(rng_e.integers(1, 80)),
            requests=profiles[int(rng_e.integers(0, 8))],
            gvk="apps/v1/Deployment",
        )
        for i in range(b_e)
    ]
    with spawn_estimator_fleet(
        e_names, free, dims, n_servers=n_servers, index=e_snap.index,
    ) as fleet:
        registry = fleet.registry
        # the deadline must clear a full UNARY fan-out on the bench rig
        # (the fallback tier re-pays 512 x 8 per-profile RPCs per pass);
        # the batch path never comes near it
        batch = registry.make_batch_estimator(e_names, timeout_seconds=60.0)
        eng_est = TensorScheduler(
            e_snap, chunk_size=args.chunk, extra_estimators=[batch]
        )
        t0 = time.perf_counter()
        eng_est.schedule(e_problems)
        print(
            f"# estimator-512 warm pass: {time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )
        for _ in range(2):
            eng_est.schedule(e_problems)

        def timed_passes(tag: str, *, drop: bool, reps: int = 3):
            times, rpcs, res = [], [], None
            for rep in range(reps):
                registry.invalidate(drop=drop)
                c0 = dict(registry.rpc_counts)
                f0 = registry.fanout_seconds_total
                t0 = time.perf_counter()
                res = eng_est.schedule(e_problems)
                times.append(time.perf_counter() - t0)
                rpcs.append(
                    {k: registry.rpc_counts[k] - c0[k] for k in c0}
                )
                print(
                    f"# estimator-512 {tag} pass {rep}: {times[-1]:.3f}s "
                    f"(wire {registry.fanout_seconds_total - f0:.3f}s, "
                    f"rpcs {rpcs[-1]})",
                    file=sys.stderr,
                )
            return float(np.median(times)), rpcs[-1], res

        full_p50, rpc_full, e_res = timed_passes("full-refresh", drop=True)
        refresh_p50, rpc_steady, _ = timed_passes("no-movement", drop=False)

        # unary-fallback parity: the same tier forced onto the per-profile
        # protocol (old-server shape), pipelined over each channel, plus a
        # width-1 reference = the reference's blocking-sequential wire
        # shape measured on THIS rig (r05's 8.28 s came from a larger one)
        from karmada_tpu.estimator.accurate import WIDTH_ENV

        saved_env = {
            k: os.environ.get(k) for k in (BATCH_ENV, WIDTH_ENV)
        }
        os.environ[BATCH_ENV] = "0"
        try:
            fb_p50, rpc_fb, fb_res = timed_passes("fallback", drop=True)
            os.environ[WIDTH_ENV] = "1"
            fb_seq, _rpc_seq, _ = timed_passes(
                "fallback-sequential", drop=True, reps=1
            )
        finally:
            for key, val in saved_env.items():
                if val is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = val

        n_est = sum(1 for r in e_res if r.success)
        # identity vs the snapshot-fed engine on the same problems
        eng_plain = TensorScheduler(e_snap, chunk_size=args.chunk)
        p_res = eng_plain.schedule(e_problems)

        def identical(res):
            return sum(
                1 for a, b_ in zip(res, p_res)
                if a.success == b_.success
                and dict(a.clusters) == dict(b_.clusters)
            )

        ident = identical(e_res)
        fb_ident = identical(fb_res)
        print(
            f"# estimator-512 tier: full-refresh p50 {full_p50:.3f}s, "
            f"no-movement refresh p50 {refresh_p50:.3f}s, fallback p50 "
            f"{fb_p50:.3f}s, {n_est}/{b_e} scheduled, identity vs "
            f"snapshot-fed {ident}/{b_e} (fallback {fb_ident}/{b_e})",
            file=sys.stderr,
        )
        if ident != b_e or fb_ident != b_e:
            # divergence is a TIER FAILURE, not a footnote: flag it in the
            # parsed status so the record (and the generated docs' FAILED-
            # tiers row) can never bury it
            print(
                f"# WARNING: estimator-512 divergence: batch "
                f"{b_e - ident}, fallback {b_e - fb_ident}",
                file=sys.stderr,
            )
            tier_status["estimator-512"] = (
                f"DIVERGED: batch {b_e - ident}/{b_e}, "
                f"fallback {b_e - fb_ident}/{b_e} rows"
            )
        del eng_est, eng_plain, e_res, p_res, fb_res, e_problems
        gc.collect()
        return {
            "metric": f"estimator512_wire_{b_e // 1000}kx{c_e}",
            "value": round(full_p50, 4),
            "unit": "s",
            "estimator512_p50": round(full_p50, 4),
            "estimator512_refresh_p50": round(refresh_p50, 4),
            "estimator512_fallback_p50": round(fb_p50, 4),
            "estimator512_fallback_seq_s": round(fb_seq, 4),
            "estimator512_identical": ident == b_e,
            "estimator512_fallback_identical": fb_ident == b_e,
            "estimator512_rpc_full": rpc_full,
            "estimator512_rpc_steady": rpc_steady,
            "estimator512_rpc_fallback": rpc_fb,
            "estimator512_n_servers": n_servers,
        }


def run_engine_north_star(args) -> dict:
    import jax

    from karmada_tpu.refimpl.divider_np import assign_batch_np
    from karmada_tpu.scheduler import (
        BindingProblem,
        ClusterSnapshot,
        TensorScheduler,
    )
    from karmada_tpu.utils.builders import (
        aggregated_placement,
        duplicated_placement,
        dynamic_weight_placement,
        static_weight_placement,
        synthetic_fleet,
    )

    b_total, c = args.bindings, args.clusters
    dev = jax.devices()[0]
    print(f"# device: {dev.platform}:{dev.device_kind}", file=sys.stderr)

    # ---- fleet + bindings (the control plane's API objects) ---------------
    w = build_headline_workload(b_total, c)
    clusters, snap, names = w.clusters, w.snap, w.names
    tol, pl_plain, pl_tol = w.tol, w.pl_plain, w.pl_tol
    profiles, replicas, prof_idx = w.profiles, w.replicas, w.prof_idx

    def make_hetero_placements(n: int, seed: int = 5) -> list:
        # n unique placements: distinct matchExpressions over the fleet's
        # tier/env label vocabulary, toleration variants, and (a slice)
        # distinct static weight lists — every one is a separate
        # compile_placement + fleet cp-slot
        from karmada_tpu.api.policy import (
            ClusterAffinity as CA, LabelSelector as LS,
            LabelSelectorRequirement as LSR,
        )

        out: list = []
        rng_h = np.random.default_rng(seed)
        tiers = [f"t{k}" for k in range(16)]
        envs = ["prod", "staging", "dev"]
        for u in range(n):
            n_t = int(rng_h.integers(2, 9))
            tier_vals = sorted(
                str(t) for t in rng_h.choice(tiers, n_t, replace=False)
            )
            env_vals = sorted(
                str(e)
                for e in rng_h.choice(envs, int(rng_h.integers(1, 3)), replace=False)
            )
            aff = CA(
                label_selector=LS(
                    match_expressions=[
                        LSR(key="tier", operator="In", values=tier_vals),
                        LSR(key="env", operator="In", values=env_vals),
                    ]
                )
            )
            tols = [tol] if u % 3 == 0 else []
            mode = u % 10
            if mode < 8:
                pl = dynamic_weight_placement(
                    cluster_affinity=aff, cluster_tolerations=tols
                )
            elif mode == 8:
                pl = duplicated_placement()
                pl.cluster_affinity = aff
                pl.cluster_tolerations = tols
            else:
                picks = rng_h.choice(c, 24, replace=False)
                pl = static_weight_placement(
                    {
                        names[int(j)]: int(w)
                        for j, w in zip(picks, rng_h.integers(1, 6, 24))
                    }
                )
                pl.cluster_affinity = aff
                pl.cluster_tolerations = tols
            out.append(pl)
        from karmada_tpu.scheduler.fleet import MAX_SLOTS

        print(
            f"# heterogeneous tier: {len(out)} unique placements "
            f"(MAX_SLOTS check: {'EXCEEDS' if len(out) > MAX_SLOTS else 'fits'} "
            f"the {MAX_SLOTS}-slot fleet table)",
            file=sys.stderr,
        )
        return out

    problems = w.problems
    if args.hetero:
        # --hetero N swaps every binding's placement for one of N unique
        # ones; everything else (replicas, profiles, prev, fresh) stays
        # the headline workload
        hetero_pls = make_hetero_placements(args.hetero)
        problems = [
            BindingProblem(
                key=p.key, placement=hetero_pls[i % len(hetero_pls)],
                replicas=p.replicas, requests=p.requests, gvk=p.gvk,
                prev=p.prev, fresh=p.fresh,
            )
            for i, p in enumerate(problems)
        ]

    # ---- engine: warm (compile + entry-buffer tune), then timed -----------
    engine = TensorScheduler(snap, chunk_size=args.chunk)
    t0 = time.perf_counter()
    engine.schedule(problems)
    print(f"# warm/compile pass: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    # adaptive settle: buffer-cap votes land a few passes after demand
    # changes and every cap change is a fresh XLA trace, so loop until a
    # pass dispatches no unseen trace signature (engine.last_pass_new_trace)
    # with a 4-pass floor covering the 2-3-vote shrink windows — the timed
    # window below must only ever run already-compiled traces
    settle_engine(
        engine, lambda i: engine.schedule(problems),
        floor=4, cap=12, label="settle pass",
    )

    import contextlib

    trace_ctx = (
        jax.profiler.trace(args.trace_dir)
        if args.trace_dir
        else contextlib.nullcontext()
    )
    times = []
    results = None
    def show(tag, wall, eng=None):
        breakdown = dict(getattr(eng or engine, "last_breakdown", {}))
        parts = " ".join(
            f"{k}={v:.1f}" if k == "fetch_mb"
            else f"{k}={int(v)}" if k in ("changed_rows", "delta_rows")
            else f"{k}={v * 1e3:.0f}ms"
            for k, v in breakdown.items()
        )
        print(f"# {tag}: {wall:.3f}s  [{parts}]", file=sys.stderr)

    breakdown = {}
    with trace_ctx:
        for rep in range(args.repeats):
            t0 = time.perf_counter()
            results = engine.schedule(problems)
            t1 = time.perf_counter()
            times.append(t1 - t0)
            breakdown = dict(getattr(engine, "last_breakdown", {}))
            show(f"pass {rep}", t1 - t0)
    p50 = float(np.median(times))

    # ---- churn tier: live availability drift between passes ---------------
    # The steady storm re-divides everything on device but ships ~no bytes
    # home (placements unchanged -> delta fetch). A real descheduler storm
    # sees capacities move, so time passes where EVERY cluster's allocations
    # drifted: the snapshot swaps in place (update_snapshot), masks and
    # estimator tables rebuild, and every row's result re-ships.
    n_churn_timed = max(4, args.repeats)
    drift_snaps = []
    rng_c = np.random.default_rng(99)
    for _ in range(8 + n_churn_timed):
        for cl in clusters:
            rs = cl.status.resource_summary
            for dim, q in list(rs.allocated.items()):
                alloc = rs.allocatable.get(dim, 0)
                rs.allocated[dim] = int(
                    min(max(0, q + int(rng_c.integers(-3, 4)) * max(1, alloc // 200)), alloc)
                )
        drift_snaps.append(ClusterSnapshot(clusters))
    # adaptive churn warm: caps re-tier under the drift load and each
    # distinct cap is one XLA trace — warm until a drift pass dispatches
    # no unseen trace (min 2 passes: onset re-tiers the caps, the next
    # compiles whichever of the delta/speculative traces engages)
    def churn_warm_pass(i):
        assert engine.update_snapshot(drift_snaps[i])
        engine.schedule(problems)

    n_warm = settle_engine(
        engine, churn_warm_pass, floor=2, cap=8, label="churn warm pass",
    )
    churn_times = []
    for rep, snap_r in enumerate(drift_snaps[n_warm:n_warm + n_churn_timed]):
        t0 = time.perf_counter()
        swapped = engine.update_snapshot(snap_r)
        assert swapped
        engine.schedule(problems)
        t1 = time.perf_counter()
        churn_times.append(t1 - t0)
        show(f"churn pass {rep}", t1 - t0)
    churn_p50 = float(np.median(churn_times))
    churn_max = float(np.max(churn_times))
    print(
        f"# churn (full availability drift): p50 {churn_p50:.3f}s "
        f"max {churn_max:.3f}s over {len(churn_times)} passes",
        file=sys.stderr,
    )

    tier_status: dict = {}

    def _subtier(name, fn, default):
        """Optional sub-tiers must not kill the bench line: a transient
        tunnel failure (e.g. remote-compile broken pipe mid-1M-warm) in one
        tier is reported, the headline metrics still print, and the tier's
        metric records an explicit null + error status (never a
        fast-looking 0.0 — VERDICT r4 weak #4)."""
        try:
            out = fn()
            # a tier may have flagged its own soft failure (e.g. placement
            # divergence) — never clobber it with "ok"
            tier_status.setdefault(name, "ok")
            return out
        except Exception as e:  # noqa: BLE001 — report-and-continue by design
            print(f"# WARNING: {name} sub-tier FAILED: {e!r}", file=sys.stderr)
            tier_status[name] = f"error: {e!r}"
            return default

    # ---- heterogeneous-placement sub-tier (default run only) --------------
    # 3.5k UNIQUE placements across the same bindings: stresses selector
    # compilation, mask interning, and the fleet cp-table at scale (SURVEY
    # section 7 label-selector warning). A dedicated full run is available
    # via --hetero N.
    def _hetero_tier() -> float:
        h_pls = make_hetero_placements(3500)
        h_problems = [
            BindingProblem(
                key=p.key, placement=h_pls[i % len(h_pls)],
                replicas=p.replicas, requests=p.requests, gvk=p.gvk,
                prev=p.prev, fresh=p.fresh,
            )
            for i, p in enumerate(problems)
        ]
        h_engine = TensorScheduler(snap, chunk_size=args.chunk)
        t0 = time.perf_counter()
        h_engine.schedule(h_problems)
        print(f"# hetero warm pass: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
        # adaptive stabilize: cap shrink fires after up to 3 votes and
        # every cap change is a fresh trace — it must land here, not in a
        # timed pass
        settle_engine(
            h_engine, lambda i: h_engine.schedule(h_problems),
            floor=3, cap=8, label="hetero settle",
        )
        h_times = []
        for rep in range(3):
            t0 = time.perf_counter()
            h_res = h_engine.schedule(h_problems)
            h_times.append(time.perf_counter() - t0)
        hetero_p50 = float(np.median(h_times))
        n_h = sum(1 for r_ in h_res if r_.success)
        # spot-verify placements against the pure-Python oracle
        h_idx = list(range(0, b_total, max(1, b_total // 256)))[:256]
        h_ok, h_bad = _verify_rows(snap, h_problems, h_res, h_engine, h_idx)
        print(
            f"# hetero tier (3500 unique placements): p50 "
            f"{hetero_p50:.3f}s, {n_h}/{b_total} scheduled, oracle "
            f"{h_ok}/{len(h_idx)} identical",
            file=sys.stderr,
        )
        if h_bad:
            print(f"# WARNING: hetero mismatches: {h_bad}", file=sys.stderr)
        del h_engine, h_res, h_problems
        gc.collect()
        return hetero_p50

    hetero_p50 = None
    ran_hetero = False
    if not args.hetero and not args.no_verify:
        ran_hetero = True
        hetero_p50 = _subtier("hetero-3500", _hetero_tier, None)

    # ---- >MAX_SLOTS-unique sub-tier (the old 8192-slot cliff) -------------
    # 9000 unique placements over 50k bindings: the slot cap now scales
    # with the HBM budget and retires unreferenced slots, so this tier
    # must keep ONE fleet table across passes (no rebuild-per-call) and
    # post a steady p50.
    def _hetero9k_tier() -> tuple:
        from karmada_tpu.scheduler.fleet import MAX_SLOTS as _MS

        k_pls = make_hetero_placements(9000)
        b_k = min(b_total, 50_000)
        k_problems = [
            BindingProblem(
                key=f"k{i}", placement=k_pls[i % len(k_pls)],
                replicas=int(replicas[i]), requests=profiles[prof_idx[i]],
                gvk="apps/v1/Deployment",
            )
            for i in range(b_k)
        ]
        k_engine = TensorScheduler(snap, chunk_size=args.chunk)
        t0 = time.perf_counter()
        k_engine.schedule(k_problems)
        print(f"# hetero-9000 warm pass: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        table_obj = k_engine._fleet
        settle_engine(
            k_engine, lambda i: k_engine.schedule(k_problems),
            floor=4, cap=8, label="hetero-9000 settle",
        )
        k_times = []
        for rep in range(2):
            t0 = time.perf_counter()
            k_res = k_engine.schedule(k_problems)
            k_times.append(time.perf_counter() - t0)
        hetero9k_p50 = float(np.median(k_times))
        survived = k_engine._fleet is table_obj
        k_idx = list(range(0, b_k, max(1, b_k // 128)))[:128]
        k_ok, k_bad = _verify_rows(snap, k_problems, k_res, k_engine, k_idx)
        print(
            f"# hetero-9000 tier (> {_MS} uniques, {b_k // 1000}k bindings): "
            f"p50 {hetero9k_p50:.3f}s, table survived={survived}, oracle "
            f"{k_ok}/{len(k_idx)} identical",
            file=sys.stderr,
        )
        if k_bad or not survived:
            print(
                f"# WARNING: hetero-9000 mismatches={k_bad} "
                f"survived={survived}",
                file=sys.stderr,
            )

        # ---- slot-eviction churn: rotate ~10% NEW unique placements per
        # pass (VERDICT r4 next #7). Each rotation retires ~900 now-
        # unreferenced cp slots and appends ~900 never-seen selectors while
        # the other 90% of rows keep their placements — the case that
        # stresses eviction + append + delta-base survival together. Keys
        # stay stable so fleet rows persist; only the rotated rows' slots
        # and masks change. Runs in its OWN failure domain (the nested
        # _subtier) so a transient churn failure cannot discard the steady
        # measurement above.
        def rotate(pass_no: int) -> list:
            fresh_pls = make_hetero_placements(900, seed=10_000 + pass_no)
            lane = pass_no % 10
            return [
                BindingProblem(
                    key=p.key, placement=fresh_pls[i % len(fresh_pls)],
                    replicas=p.replicas, requests=p.requests, gvk=p.gvk,
                )
                if i % 10 == lane
                else p
                for i, p in enumerate(k_problems)
            ]

        def _rotation_churn() -> float:
            nonlocal k_problems, k_res
            def rotation_warm_pass(i):
                nonlocal k_problems
                k_problems = rotate(i)
                k_engine.schedule(k_problems)

            rot = settle_engine(
                k_engine, rotation_warm_pass, floor=2, cap=5,
                label="hetero-9000 rotation warm",
            )
            kc_times = []
            for i in range(3):
                k_problems = rotate(rot + i)
                t0 = time.perf_counter()
                k_res = k_engine.schedule(k_problems)
                kc_times.append(time.perf_counter() - t0)
                print(
                    f"# hetero-9000 rotation pass: {kc_times[-1]:.3f}s",
                    file=sys.stderr,
                )
            churn_p = float(np.median(kc_times))
            survived_churn = k_engine._fleet is table_obj
            tbl = k_engine._fleet
            print(
                f"# hetero-9000 churn diag: slots={len(tbl._cp_pl)} "
                f"max={tbl._max_slots()} gvk={len(tbl._gvk_list)} "
                f"profiles={len(tbl._profiles)} rows={tbl.n_rows}",
                file=sys.stderr,
            )
            kc_ok, kc_bad = _verify_rows(
                snap, k_problems, k_res, k_engine, k_idx
            )
            print(
                f"# hetero-9000 slot-eviction churn (10% unique rotation/"
                f"pass): p50 {churn_p:.3f}s, table survived="
                f"{survived_churn}, oracle {kc_ok}/{len(k_idx)} identical",
                file=sys.stderr,
            )
            if kc_bad or not survived_churn:
                print(
                    f"# WARNING: hetero-9000 churn mismatches={kc_bad} "
                    f"survived={survived_churn}",
                    file=sys.stderr,
                )
            return churn_p

        hetero9k_churn_local = _subtier(
            "hetero-9000-churn", _rotation_churn, None
        )
        del k_engine, k_res, k_problems
        gc.collect()
        return hetero9k_p50, hetero9k_churn_local

    hetero9k_p50 = hetero9k_churn = None
    ran_hetero9k = False
    if not args.hetero and not args.no_verify:
        ran_hetero9k = True
        h9 = _subtier("hetero-9000", _hetero9k_tier, None)
        if h9 is not None:
            hetero9k_p50, hetero9k_churn = h9

    # ---- live-estimator sub-tier (VERDICT r4 next #5) ---------------------
    # The batched-wire + generation-gated-refresh tier, shared with
    # ``--estimator-only`` (run_estimator_tier): full-refresh storm p50
    # over one batch RPC per server, no-movement refresh p50 over
    # GetGenerations pings, and the unary-fallback parity run.
    def _estimator_tier() -> dict:
        return run_estimator_tier(args, tier_status)

    est512 = None
    ran_est512 = False
    if not args.hetero and not args.no_verify and b_total == 100_000:
        ran_est512 = True
        est512 = _subtier("estimator-512", _estimator_tier, None)

    # ---- 1M x 5k scale tier (first-class, VERDICT r3 item 9) --------------
    # Ten times the headline bindings through the same engine: steady +
    # full-drift churn p50s with sampled oracle verification. The dense
    # resident would exceed its HBM budget at this cap, so this tier also
    # keeps the legacy entry-resident path honest.
    def _scale1m_tier() -> tuple:
        b_m = 1_000_000
        rng_m = np.random.default_rng(1234)
        reps_m = rng_m.integers(1, 100, b_m)
        prof_m = rng_m.integers(0, 8, b_m)
        tol_m = rng_m.random(b_m) < 0.30
        t0 = time.perf_counter()
        m_problems = [
            BindingProblem(
                key=f"m{i}",
                placement=pl_tol if tol_m[i] else pl_plain,
                replicas=int(reps_m[i]),
                requests=profiles[prof_m[i]],
                gvk="apps/v1/Deployment",
            )
            for i in range(b_m)
        ]
        print(f"# 1M problem build: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        m_engine = TensorScheduler(snap, chunk_size=args.chunk)
        t0 = time.perf_counter()
        try:
            m_engine.schedule(m_problems)
        except Exception as e:  # noqa: BLE001 — tunnel compile drops are
            # transient (broken pipe on long remote compiles); one retry
            # resumes from the persistent compilation cache
            print(f"# 1M warm failed ({e!r}); retrying once",
                  file=sys.stderr)
            time.sleep(10)
            m_engine.schedule(m_problems)
        print(f"# 1M warm pass: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        # adaptive settle (same contract as the headline tier: no timed
        # pass may dispatch an unseen trace)
        settle_engine(
            m_engine, lambda i: m_engine.schedule(m_problems),
            floor=4, cap=12, label="1M settle pass",
        )
        m_times = []
        for rep in range(3):
            t0 = time.perf_counter()
            m_res = m_engine.schedule(m_problems)
            m_times.append(time.perf_counter() - t0)
            show(f"1M steady pass {rep}", m_times[-1], m_engine)
        m1_steady = float(np.median(m_times))
        # row churn: mutate a fixed fraction of rows per pass against a
        # STABLE snapshot — the regime the incremental (dirty-row) solve
        # path serves. Cost must track churn size, not plane size; the
        # per-pass breakdown must prove the sub dispatch packed exactly
        # the dirty set, and placements must stay bit-identical to the
        # full-solve oracle (verified after the legacy tier below, once
        # the resident memory is free for a second 1M engine).
        def _digest_rows(res, n):
            out = np.empty(n, np.uint64)
            for i in range(n):
                r = res[i]
                blob = (
                    repr(sorted(r.clusters.items()))
                    if r.success else "!" + str(r.error)
                )
                out[i] = int.from_bytes(
                    hashlib.blake2b(blob.encode(), digest_size=8).digest(),
                    "little",
                )
            return out

        rng_c = np.random.default_rng(20_777)
        m_churn_tiers: dict = {}
        m_churn_states: list = []  # (label, problems, digests) for oracle

        def m_row_churn(frac):
            dirty_n = int(b_m * frac)

            def mutate():
                for i in rng_c.choice(b_m, dirty_n, replace=False):
                    p = m_problems[i]
                    m_problems[i] = BindingProblem(
                        key=p.key, placement=p.placement,
                        replicas=(p.replicas % 99) + 1,
                        requests=p.requests, gvk=p.gvk,
                    )

            def warm_pass(_i):
                mutate()
                m_engine.schedule(m_problems)

            settle_engine(
                m_engine, warm_pass, floor=2, cap=8,
                label=f"1M row-churn {frac:.1%} settle",
            )
            times = []
            res = None
            for rep in range(3):
                mutate()
                t0 = time.perf_counter()
                res = m_engine.schedule(m_problems)
                times.append(time.perf_counter() - t0)
                bd = m_engine._fleet.last_breakdown
                dirty = int(bd.get("dirty_rows", -1))
                packed = int(bd.get("rows_packed", -1))
                show(
                    f"1M row-churn {frac:.1%} pass {rep}", times[-1], m_engine
                )
                assert dirty == dirty_n and packed == dirty_n, (
                    f"delta pass dispatched {dirty} dirty / {packed} packed "
                    f"rows for a {dirty_n}-row churn set"
                )
            m_churn_states.append(
                (f"{frac:.1%}", list(m_problems), _digest_rows(res, b_m))
            )
            return float(np.median(times))

        for frac, t_key in (
            (0.001, "churn0p1pct"),
            (0.01, "churn1pct"),
            (0.10, "churn10pct"),
        ):
            m_churn_tiers[t_key] = m_row_churn(frac)
        print(
            "# 1M row-churn p50: " + ", ".join(
                f"{k} {v:.3f}s" for k, v in m_churn_tiers.items()
            ),
            file=sys.stderr,
        )
        # churn: adaptive full-availability-drift warm (the onset pass
        # re-tiers the caps, the next compiles the delta-wire trace those
        # caps select; loop until compile-stable) + 4 timed passes
        m_drifts = []
        for _ in range(12):
            for cl in clusters:
                rs = cl.status.resource_summary
                for dim, q in list(rs.allocated.items()):
                    alloc = rs.allocatable.get(dim, 0)
                    rs.allocated[dim] = int(min(max(
                        0, q + int(rng_m.integers(-3, 4)) * max(1, alloc // 200)
                    ), alloc))
            m_drifts.append(ClusterSnapshot(clusters))
        def m_churn_warm_pass(i):
            assert m_engine.update_snapshot(m_drifts[i])
            m_engine.schedule(m_problems)

        m_warm = settle_engine(
            m_engine, m_churn_warm_pass, floor=2, cap=8,
            label="1M churn warm pass",
        )
        m_churn_times = []
        for rep, snap_m in enumerate(m_drifts[m_warm:m_warm + 4]):
            t0 = time.perf_counter()
            swapped = m_engine.update_snapshot(snap_m)
            assert swapped
            m_res = m_engine.schedule(m_problems)
            m_churn_times.append(time.perf_counter() - t0)
            show(f"1M churn pass {rep}", m_churn_times[-1], m_engine)
        m1_churn = float(np.median(m_churn_times))
        m1_churn_max = float(np.max(m_churn_times))
        m_idx = list(range(0, b_m, max(1, b_m // 128)))[:128]
        m_ok, m_bad = _verify_rows(
            ClusterSnapshot(clusters), m_problems, m_res, m_engine, m_idx
        )
        print(
            f"# 1M x 5k tier: steady p50 {m1_steady:.3f}s, churn p50 "
            f"{m1_churn:.3f}s max {m1_churn_max:.3f}s, oracle "
            f"{m_ok}/{len(m_idx)} identical",
            file=sys.stderr,
        )
        if m_bad:
            print(f"# WARNING: 1M mismatches: {m_bad}", file=sys.stderr)
        # keep the legacy entry-resident path honest at scale too: with
        # the 6 GiB dense budget the 1M tier rides the dense path, so pin
        # the budget to 0 and post a steady p50 through the legacy solve
        # (the path any table beyond the budget runs on)
        del m_engine, m_res
        gc.collect()
        import karmada_tpu.scheduler.fleet as _fleet_mod

        saved_budget = _fleet_mod.DENSE_RESIDENT_MAX_BYTES
        _fleet_mod.DENSE_RESIDENT_MAX_BYTES = 0
        try:
            l_engine = TensorScheduler(snap, chunk_size=args.chunk)
            t0 = time.perf_counter()
            l_engine.schedule(m_problems)
            print(f"# 1M legacy warm pass: {time.perf_counter() - t0:.1f}s",
                  file=sys.stderr)
            # adaptive settle: the legacy e_cap's sustained-shrink window
            # is longer than three fixed passes — breaking early parked
            # its one allowed recompile inside the timed window (14.6s
            # recorded where the clean pass runs ~4s)
            settle_engine(
                l_engine, lambda i: l_engine.schedule(m_problems),
                floor=3, cap=12, label="1M legacy settle",
            )
            l_times = []
            for _ in range(3):
                t0 = time.perf_counter()
                l_engine.schedule(m_problems)
                l_times.append(time.perf_counter() - t0)
            m1_legacy = float(np.median(l_times))
            print(
                f"# 1M legacy steady p50: {m1_legacy:.3f}s",
                file=sys.stderr,
            )
            del l_engine
        finally:
            _fleet_mod.DENSE_RESIDENT_MAX_BYTES = saved_budget
        gc.collect()
        # bit-identity oracle for the row-churn tiers: a fresh engine with
        # the delta path killed (KARMADA_TPU_DELTA_SOLVE=0) full-solves
        # each tier's final problem state; every row's placement must hash
        # identical to what the delta passes returned.
        saved_delta = os.environ.get("KARMADA_TPU_DELTA_SOLVE")
        os.environ["KARMADA_TPU_DELTA_SOLVE"] = "0"
        try:
            o_engine = TensorScheduler(snap, chunk_size=args.chunk)
            for label, o_probs, digests in m_churn_states:
                t0 = time.perf_counter()
                o_res = o_engine.schedule(o_probs)
                o_dig = _digest_rows(o_res, b_m)
                bad = int(np.count_nonzero(o_dig != digests))
                print(
                    f"# 1M row-churn {label} oracle: full solve "
                    f"{time.perf_counter() - t0:.1f}s, {bad} rows diverge",
                    file=sys.stderr,
                )
                assert bad == 0, (
                    f"row-churn {label}: {bad} placements diverge from the "
                    "full-solve oracle"
                )
            del o_engine, o_res
        finally:
            if saved_delta is None:
                os.environ.pop("KARMADA_TPU_DELTA_SOLVE", None)
            else:
                os.environ["KARMADA_TPU_DELTA_SOLVE"] = saved_delta
        del m_problems, m_churn_states
        gc.collect()
        return {
            "steady": m1_steady,
            "churn": m1_churn,
            "churn_max": m1_churn_max,
            "legacy": m1_legacy,
            **m_churn_tiers,
        }

    m1 = None
    ran_1m = False
    if args.scale or (
        not args.hetero and not args.no_verify and b_total == 100_000
    ):
        ran_1m = True
        m1 = _subtier("scale-1M", _scale1m_tier, None)

    # ---- whole-plane storm tier (VERDICT r4 next #6) ----------------------
    # The FULL spine at 100k bindings: detector -> scheduler -> binding ->
    # works through the store, driven by a rebalancer storm (every binding
    # re-reconciles each wave). The engine rides the device; the recorded
    # number is HOST-path throughput — store applies, admission, watch
    # fan-out, Work rendering. Round 2 recorded ~2.3k bindings/s at
    # 2000x50; the target is >=2x that at 50x the binding count.
    def _whole_plane_tier() -> float:
        from karmada_tpu import cli as _cli
        from karmada_tpu.api import (
            PropagationPolicy,
            PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.api.core import ObjectMeta
        from karmada_tpu.controllers.extras import (
            ObjectReferenceSelector,
            WorkloadRebalancer,
            WorkloadRebalancerSpec,
        )
        from karmada_tpu.utils.builders import new_cluster, new_deployment

        n_wp, c_wp = 100_000, 250
        clock = [10_000.0]
        cp = _cli.cmd_init(clock=lambda: clock[0])
        for i in range(c_wp):
            cp.join_cluster(
                new_cluster(f"wp{i}", cpu="2000", memory="4000Gi")
            )
        cp.settle()
        t0 = time.perf_counter()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="wp-policy", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        ))
        for i in range(n_wp):
            cp.store.apply(
                new_deployment(f"wpa{i}", replicas=(i % 8) + 1)
            )
        print(f"# whole-plane build: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        cp.settle()
        cold = time.perf_counter() - t0
        n_works = len(cp.store.list("Work"))
        print(
            f"# whole-plane cold wave: {cold:.1f}s = {n_wp / cold:.0f} "
            f"bindings/s ({n_works} works rendered)",
            file=sys.stderr,
        )
        rb0 = cp.store.get("ResourceBinding", "default/wpa0-deployment")
        assert rb0 is not None and rb0.spec.clusters, "spine never divided"

        def storm_wave(tag: str) -> float:
            clock[0] += 60
            cp.store.apply(WorkloadRebalancer(
                meta=ObjectMeta(name=f"wp-storm-{tag}"),
                spec=WorkloadRebalancerSpec(workloads=[
                    ObjectReferenceSelector(kind="Deployment", name=f"wpa{i}")
                    for i in range(n_wp)
                ]),
            ))
            t0 = time.perf_counter()
            cp.settle()
            return time.perf_counter() - t0

        # adaptive warm: the first storms after the cold build still pay
        # heap/queue settlement (measured 48 s -> 33.8 s -> 11.3 s wave
        # sequence); warm until the wave cost FLATTENS (<30% improvement)
        # so the timed window records steady-state throughput
        prev_w = None
        for wi in range(4):
            w = storm_wave(f"warm{wi}")
            print(
                f"# whole-plane warm{wi} wave: {w:.1f}s = "
                f"{n_wp / w:.0f} bindings/s",
                file=sys.stderr,
            )
            if prev_w is not None and w > prev_w * 0.7:
                break
            prev_w = w
        waves = []
        for k in range(3):
            waves.append(storm_wave(f"t{k}"))
            print(
                f"# whole-plane wave {k}: {waves[-1]:.1f}s = "
                f"{n_wp / waves[-1]:.0f} bindings/s",
                file=sys.stderr,
            )
        rate = n_wp / float(np.median(waves))
        # convergence: every binding observed at its latest generation with
        # a full assignment (sampled)
        for i in range(0, n_wp, max(1, n_wp // 64)):
            rb = cp.store.get("ResourceBinding", f"default/wpa{i}-deployment")
            assert rb.status.scheduler_observed_generation == rb.meta.generation
            assert sum(tc.replicas for tc in rb.spec.clusters) == (i % 8) + 1
        print(
            f"# whole-plane storm: {rate:.0f} bindings/s "
            f"(round-2 referent 2300/s)",
            file=sys.stderr,
        )
        del cp
        gc.collect()
        return rate

    whole_plane = None
    ran_wp = False
    if not args.hetero and not args.no_verify and b_total == 100_000:
        ran_wp = True
        whole_plane = _subtier("whole-plane", _whole_plane_tier, None)

    # restore the measured-snapshot results for verification below (the
    # original ``snap`` holds copies of the pre-drift capacities)
    swapped = engine.update_snapshot(snap)
    assert swapped
    results = engine.schedule(problems)
    n_sched = sum(1 for r in results if r.success)
    print(
        f"# scheduled {n_sched}/{b_total} bindings via the engine",
        file=sys.stderr,
    )

    metric = f"p50_engine_schedule_{b_total // 1000}kx{c}_dynamic_weight"
    if args.hetero:
        metric = (
            f"p50_engine_hetero{args.hetero}_"
            f"{b_total // 1000}kx{c}"
        )
    def _r(v):
        return round(v, 4) if v is not None else None

    out = {
        "metric": metric,
        "value": round(p50, 4),
        "unit": "s",
        "churn_p50": round(churn_p50, 4),
        "churn_max": round(churn_max, 4),
    }
    if ran_hetero:
        out["hetero3500_p50"] = _r(hetero_p50)
    if ran_hetero9k:
        out["hetero9000_p50"] = _r(hetero9k_p50)
        out["hetero9k_churn_p50"] = _r(hetero9k_churn)
    if ran_est512:
        for key, val in (est512 or {}).items():
            if key.startswith("estimator512_"):
                out[key] = val
    if ran_wp:
        out["whole_plane_bindings_s"] = (
            round(whole_plane, 1) if whole_plane is not None else None
        )
    if ran_1m:
        m1d = m1 or {}
        out["scale1m_steady_p50"] = _r(m1d.get("steady"))
        out["scale1m_churn_p50"] = _r(m1d.get("churn"))
        out["scale1m_churn_max"] = _r(m1d.get("churn_max"))
        out["scale1m_legacy_p50"] = _r(m1d.get("legacy"))
        out["scale1m_churn0p1pct_p50"] = _r(m1d.get("churn0p1pct"))
        out["scale1m_churn1pct_p50"] = _r(m1d.get("churn1pct"))
        out["scale1m_churn10pct_p50"] = _r(m1d.get("churn10pct"))
    if tier_status:
        out["tiers"] = tier_status
    if args.no_verify:
        out["vs_baseline"] = 0.0
        return out

    # ---- full-set verification vs the vectorized-numpy host divider ------
    # (which is itself oracle-verified by tests/test_divider_np.py); also
    # times the conservative host baseline on identical pre-packed inputs
    host_eng = TensorScheduler(snap)
    chunk = 8192
    t_np = 0.0
    np_ok = np_bad = 0
    cap_np = snap.available_cap
    for start in range(0, b_total, chunk):
        sub = problems[start : start + chunk]
        feasible, strategy, reps, static_w, requests, prev, fr = (
            _oracle_inputs(snap, sub, host_eng)
        )
        uniq, inv = np.unique(requests, axis=0, return_inverse=True)
        t0 = time.perf_counter()
        per_prof = _general_avail_np(cap_np, uniq)
        avail = per_prof[inv]
        avail = np.minimum(
            np.where(avail == 2**31 - 1, reps[:, None], avail), 2**31 - 1
        ).astype(np.int32)
        got, unsched = assign_batch_np(
            strategy, reps, feasible, static_w, avail, prev, fr
        )
        t_np += time.perf_counter() - t0
        for k in range(len(sub)):
            res = results[start + k]
            if unsched[k] or not feasible[k].any():
                good = not res.success
            else:
                want = {
                    names[j]: int(got[k, j]) for j in np.flatnonzero(got[k])
                }
                good = res.success and dict(res.clusters) == want
            np_ok, np_bad = np_ok + good, np_bad + (not good)
    print(
        f"# numpy-host check: {np_ok}/{np_ok + np_bad} identical; "
        f"numpy divider wall {t_np:.2f}s for {b_total}",
        file=sys.stderr,
    )

    # ---- sampled verification vs the pure-Python oracle -------------------
    sample_idx = list(
        range(0, b_total, max(1, b_total // max(1, args.sample)))
    )[: args.sample]
    t0 = time.perf_counter()
    ok, bad = _verify_rows(snap, problems, results, host_eng, sample_idx)
    t_oracle = time.perf_counter() - t0
    per_binding = t_oracle / max(1, len(sample_idx))
    oracle_full = per_binding * b_total
    print(
        f"# oracle check: {ok}/{len(sample_idx)} identical across all "
        f"chunks; {per_binding * 1e3:.2f} ms/binding -> {oracle_full:.0f}s "
        f"extrapolated",
        file=sys.stderr,
    )

    # ---- mixed-strategy verification (all strategies x cohorts) -----------
    mix_n = args.mix_sample
    rng = np.random.default_rng(7)
    pl_static = static_weight_placement(
        {names[j]: int(w) for j, w in zip(range(0, c, max(1, c // 32)),
                                          rng.integers(1, 6, 32))}
    )
    mix_pls = [pl_plain, duplicated_placement(), pl_static,
               aggregated_placement()]
    mix = []
    for i in range(mix_n):
        reps_i = int(rng.integers(0, 100))
        # cohort and strategy indices are decorrelated so all 16
        # strategy x cohort combinations are exercised
        cohort = (i // 4) % 4  # steady-up / steady-down / fresh / no-prev
        if cohort == 0:  # scale-up: prev sum < replicas
            prev = {names[int(j)]: 1 for j in rng.choice(c, min(3, max(1, reps_i)), replace=False)} if reps_i > 3 else {}
            fr = False
        elif cohort == 1:  # scale-down: prev sum > replicas
            prev = {names[int(j)]: int(reps_i) + 2 for j in rng.choice(c, 2, replace=False)}
            fr = False
        elif cohort == 2:
            prev = {names[int(j)]: 2 for j in rng.choice(c, 2, replace=False)}
            fr = True
        else:
            prev, fr = {}, False
        mix.append(
            BindingProblem(
                key=f"m{i}", placement=mix_pls[i % 4], replicas=reps_i,
                requests=profiles[int(rng.integers(0, 8))],
                gvk="apps/v1/Deployment", prev=prev, fresh=fr,
            )
        )
    mix_results = engine.schedule(mix)
    mok, mbad = _verify_rows(snap, mix, mix_results, host_eng, list(range(mix_n)))
    print(
        f"# mixed-strategy oracle check: {mok}/{mix_n} identical "
        f"(duplicated/static/dynamic/aggregated x steady/fresh/scale)",
        file=sys.stderr,
    )

    mismatches = np_bad + bad + mbad
    if mismatches:
        print(f"# WARNING: {mismatches} placement mismatches", file=sys.stderr)
    out.update(
        {
            "vs_baseline": round(t_np / p50, 1),
            "vs_numpy_host": round(t_np / p50, 1),
            "vs_python_oracle": round(oracle_full / p50, 1),
            "verified_rows": np_ok + ok + mok,
            "verified_mismatches": mismatches,
        }
    )
    # native calibration (baselines/calibrate.py): a single-thread C++ -O2
    # re-execution of the reference's per-binding division loop (incl. the
    # per-binding calAvailableReplicas recompute) on THIS exact workload —
    # the defensible stand-in for "the in-tree Go divider" (no Go in image)
    import os

    cal_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "baselines", "CALIBRATION.json",
    )
    if os.path.exists(cal_path):
        with open(cal_path) as f:
            cal = json.load(f)
        if (
            cal.get("bindings") == b_total
            and cal.get("clusters") == c
            and cal.get("verified_rows", 0) >= b_total
            and cal.get("verified_mismatches", 1) == 0
        ):
            out["vs_cpp_native"] = round(cal["cpp_seconds"] / p50, 1)
            out["cpp_native_seconds"] = cal["cpp_seconds"]
            print(
                f"# native C++ divider baseline (calibrated): "
                f"{cal['cpp_seconds']:.2f}s -> {out['vs_cpp_native']}x",
                file=sys.stderr,
            )
    return out


# --------------------------------------------------------------------------
# --cold-start: plane-restart first-wave tier (persistent cache + manifest)
# --------------------------------------------------------------------------


def run_cold_child(args) -> dict:
    """One process of the cold-start tier: build the headline workload,
    time the FIRST engine wave (the wave a plane restart / HA failover
    serves); seed additionally settles (filling the manifest), restore
    settles and times the steady wave all ratios are quoted against.

    The parent's env decides the mode's cache/manifest state:

    - ``seed``    — fresh cache dir + manifest: its first wave IS the
      no-cache baseline, and it leaves both populated for ``restore``.
    - ``cold``    — cache and manifest disabled: the pre-cache control
      (what every restart paid before this subsystem existed).
    - ``restore`` — manifest prewarm (scheduler.prewarm.warmup, off the
      timed window) + the seed's persistent cache: the first wave must
      dispatch only already-compiled traces (``new_trace=False``).
    """
    import jax

    from karmada_tpu.scheduler import TensorScheduler

    mode = args.cold_child
    dev = jax.devices()[0]
    print(
        f"# cold-child {mode}: device {dev.platform}:{dev.device_kind}",
        file=sys.stderr,
    )
    out: dict = {"mode": mode}
    if mode == "restore":
        from karmada_tpu.scheduler.prewarm import warmup

        stats = warmup()
        out["prewarm"] = stats
        print(
            f"# prewarm: {stats['compiled']}/{stats['specs']} traces in "
            f"{stats['seconds']:.1f}s",
            file=sys.stderr,
        )
    w = build_headline_workload(args.bindings, args.clusters)
    engine = TensorScheduler(w.snap, chunk_size=args.chunk)
    t0 = time.perf_counter()
    engine.schedule(w.problems)
    first = time.perf_counter() - t0
    out["first_wave_s"] = round(first, 3)
    out["new_trace_first_pass"] = bool(engine.last_pass_new_trace)
    print(
        f"# {mode} first wave: {first:.1f}s "
        f"new_trace={engine.last_pass_new_trace}",
        file=sys.stderr,
    )
    # the cold child exists only for its first wave (the pre-cache
    # baseline): no manifest to record into and the parent quotes every
    # ratio against the RESTORE child's steady wave, so settling it
    # would burn minutes of compile for numbers nobody reads
    if mode == "cold":
        return out
    # settle (seed mode records the late cap-tune traces into the
    # manifest here — the restore child's prewarm replays ALL of them)
    settle_engine(
        engine, lambda i: engine.schedule(w.problems),
        floor=2, cap=12, label=f"{mode} settle",
    )
    if mode == "restore":
        from karmada_tpu.scheduler import BindingProblem

        # the steady wave (same problems, zero changed rows)
        times = []
        for _ in range(max(2, args.repeats)):
            t0 = time.perf_counter()
            engine.schedule(w.problems)
            times.append(time.perf_counter() - t0)
        out["steady_wave_s"] = round(float(np.median(times)), 3)
        # the warm WHOLE-PLANE wave the restart ratio is quoted against:
        # every binding changed (replicas bumped) in an already-warm
        # process, so the wave re-packs, re-uploads, and fetches ALL
        # rows — exactly the work a restart's first wave does minus the
        # restore overhead. The unchanged steady wave above fetches zero
        # rows; quoting the restart against it holds the first wave to a
        # bar no live all-change wave meets.
        bumped = [
            BindingProblem(
                key=p.key, placement=p.placement, replicas=p.replicas + 1,
                requests=p.requests, gvk=p.gvk, prev=p.prev, fresh=p.fresh,
            )
            for p in w.problems
        ]
        t0 = time.perf_counter()
        engine.schedule(bumped)
        out["warm_all_change_wave_s"] = round(time.perf_counter() - t0, 3)
        print(
            f"# warm all-change wave: {out['warm_all_change_wave_s']:.1f}s",
            file=sys.stderr,
        )
    return out


def run_cold_start(args) -> dict:
    """Parent of the cold-start tier: three fresh processes over the same
    headline workload, sharing one throwaway cache+manifest directory.
    The parent itself never imports jax — the accelerator backend is
    single-client, so each child must own the claim in turn."""
    import os
    import shutil
    import subprocess
    import tempfile

    cache_root = tempfile.mkdtemp(prefix="karmada_coldstart_")
    manifest = os.path.join(cache_root, "trace_manifest.json")

    def child(mode: str) -> dict:
        env = dict(os.environ)
        if mode == "cold":
            env["JAX_COMPILATION_CACHE_DIR"] = ""
            env["KARMADA_TPU_TRACE_MANIFEST"] = ""
        else:
            env["JAX_COMPILATION_CACHE_DIR"] = cache_root
            env["KARMADA_TPU_TRACE_MANIFEST"] = manifest
            # restart-resilient plane config: persist EVERY trace, not
            # just slow ones — the utility kernels (row scatter, meta
            # gather) each compile under the default 1 s threshold, but a
            # restart re-pays all of them at once on the first wave
            env["KARMADA_TPU_CACHE_MIN_COMPILE_SECS"] = "0"
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--cold-child", mode,
            "--bindings", str(args.bindings),
            "--clusters", str(args.clusters),
            "--chunk", str(args.chunk),
            "--repeats", str(args.repeats),
        ]
        if args.cpu:
            cmd.append("--cpu")
        print(f"# cold-start: spawning {mode} child", file=sys.stderr)
        proc = subprocess.run(
            cmd, env=env, stdout=subprocess.PIPE, text=True
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"cold-start {mode} child exited rc={proc.returncode}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])

    try:
        seed = child("seed")
        cold = child("cold")
        restore = child("restore")
    finally:
        shutil.rmtree(cache_root, ignore_errors=True)
    steady = restore["steady_wave_s"]
    warm = restore["warm_all_change_wave_s"]
    return {
        "metric": (
            f"cold_start_first_wave_{args.bindings // 1000}k"
            f"x{args.clusters}"
        ),
        "value": restore["first_wave_s"],
        "unit": "s",
        # the headline ratio: how much faster a restored restart's first
        # wave is than the pre-cache cold wave it replaces
        "vs_baseline": round(cold["first_wave_s"] / restore["first_wave_s"], 2),
        "seed_first_wave_s": seed["first_wave_s"],
        "cold_first_wave_s": cold["first_wave_s"],
        "restore_first_wave_s": restore["first_wave_s"],
        "steady_wave_s": steady,
        "warm_all_change_wave_s": warm,
        "cold_over_steady": round(cold["first_wave_s"] / steady, 2),
        "restore_over_steady": round(restore["first_wave_s"] / steady, 2),
        # the acceptance ratios: a restart's first wave re-packs,
        # re-uploads, and fetches EVERY row, so the fair warm bar is the
        # all-change wave (which does the same work warm), not the
        # unchanged steady wave (which fetches zero rows)
        "cold_over_warm": round(cold["first_wave_s"] / warm, 2),
        "restore_over_warm": round(restore["first_wave_s"] / warm, 2),
        "restore_new_trace_first_pass": restore["new_trace_first_pass"],
        "prewarm": restore.get("prewarm"),
    }


# --------------------------------------------------------------------------
# --observability: wave-trace attribution over a whole-plane storm
# --------------------------------------------------------------------------


def run_chaos(args) -> dict:
    """ISSUE 7 acceptance tier: the failure half of the plane at storm
    scale. A 20k x 512 whole-plane fleet under an ordered-failover policy
    (ClusterAffinities [primary, fallback]) with availability served by
    LIVE gRPC estimator servers; a seeded chaos wave flips K member
    clusters NotReady (cluster.health fault point -> the real
    condition->taint->NoExecute-eviction machinery) and SIGSTOP-partitions
    one estimator server mid-wave. Records time-to-stable-placement, the
    displaced-binding count against the batched-solve count (failover must
    reschedule in O(chunks) solves, not O(bindings)), the estimator
    breaker's open->half-open->closed recovery, and verifies the final
    placements bit-for-bit against the numpy per-binding oracle
    (refimpl.failover_np.replay_failover) consuming the same fault-event
    log."""
    import signal

    from karmada_tpu import cli as _cli
    from karmada_tpu.api import (
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.api.core import ObjectMeta
    from karmada_tpu.api.policy import ClusterAffinityTerm, LabelSelector
    from karmada_tpu.controllers.extras import (
        ObjectReferenceSelector,
        WorkloadRebalancer,
        WorkloadRebalancerSpec,
    )
    from karmada_tpu.estimator.fleet import spawn_estimator_fleet
    from karmada_tpu.refimpl.failover_np import replay_failover
    from karmada_tpu.scheduler import ClusterSnapshot
    from karmada_tpu.scheduler.snapshot import compile_placement
    from karmada_tpu.utils import backoff, faultinject
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        new_cluster,
        new_deployment,
    )
    from karmada_tpu.utils.features import FAILOVER, feature_gate
    from karmada_tpu.utils.metrics import circuit_state, degraded_passes

    n, c, kill_k, seed = args.bindings, args.clusters, args.chaos_kill, args.chaos_seed
    n_servers = 4
    n_fallback = max(c // 8, kill_k + 2)

    def group_term(g):
        return ClusterAffinityTerm(
            affinity_name=f"grp-{g}",
            label_selector=LabelSelector(match_labels={"group": g}),
        )

    from karmada_tpu.estimator.accurate import NodeState
    from karmada_tpu.utils.member import MemberCluster
    from karmada_tpu.utils.quantity import parse_resource_list

    feature_gate.set(FAILOVER, True)
    clock = [10_000.0]
    cp = _cli.cmd_init(clock=lambda: clock[0])
    for i in range(c):
        group = "fallback" if i >= c - n_fallback else "primary"
        name = f"ch{i:04d}"
        caps = {
            "cpu": f"{2000 + 8 * (i % 37)}", "memory": "4000Gi",
            "pods": 10_000,
        }
        # members carry REAL node state (one node = the cluster's caps):
        # the status controller derives genuine resource summaries from
        # it, so availability is capacity math (not the no-summary
        # sentinel clamp) and the estimator servers mirror it exactly —
        # the oracle-identity precondition
        member = MemberCluster(name)
        member.nodes = [
            NodeState(
                name=f"{name}-n0", allocatable=parse_resource_list(caps)
            )
        ]
        cp.join_cluster(
            new_cluster(name, labels={"group": group}, **caps), member
        )
    cp.settle()

    # live estimator fleet over the SAME capacities the snapshot carries
    # (min-merge(general, accurate) == general, so placements stay
    # oracle-checkable); ISSUE 4's invariant keeps degraded passes
    # un-replayable while a server is partitioned
    snap0 = ClusterSnapshot(sorted(
        cp.store.list("Cluster"), key=lambda cl: cl.name
    ))
    free = np.maximum(np.asarray(snap0.available_cap), 0)
    dims = list(snap0.dims)
    t0 = time.perf_counter()
    fleet_ctx = spawn_estimator_fleet(
        snap0.names, free, dims, n_servers=n_servers, index=snap0.index,
        timeout_seconds=3.0,
    )
    fleet = fleet_ctx.__enter__()
    record: dict = {}
    try:
        cp.scheduler.estimator_registry = fleet.registry
        cp.scheduler.extra_estimators = [
            fleet.registry.make_batch_estimator(
                snap0.names, timeout_seconds=5.0
            )
        ]
        print(
            f"# chaos build: {c} clusters, {n_servers} estimator server "
            f"processes in {time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )

        t0 = time.perf_counter()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="chaos-policy", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(
                    cluster_affinities=[
                        group_term("primary"), group_term("fallback"),
                    ]
                ),
            ),
        ))
        profiles = [
            {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
            for p in range(8)
        ]
        for i in range(n):
            prof = profiles[i % 8]
            cp.store.apply(new_deployment(
                f"ch{i}", replicas=(i % 8) + 1, cpu=prof["cpu"],
                memory=prof["memory"],
            ))
        print(f"# chaos workload build: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        cp.settle()
        print(f"# chaos cold wave: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)

        def storm_wave(tag: str) -> float:
            clock[0] += 60
            cp.store.apply(WorkloadRebalancer(
                meta=ObjectMeta(name=f"chaos-storm-{tag}"),
                spec=WorkloadRebalancerSpec(workloads=[
                    ObjectReferenceSelector(kind="Deployment", name=f"ch{i}")
                    for i in range(n)
                ]),
            ))
            t0 = time.perf_counter()
            cp.settle()
            return time.perf_counter() - t0

        prev_w = None
        for wi in range(3):
            w = storm_wave(f"warm{wi}")
            print(f"# chaos warm{wi} wave: {w:.1f}s", file=sys.stderr)
            if prev_w is not None and w > prev_w * 0.7:
                break
            prev_w = w

        # ---- steady reference (fault injection DISARMED: the injection
        # points are live in every hot path below, armed-off)
        steady = [storm_wave(f"steady{k}") for k in range(3)]
        steady_p50 = float(np.median(steady))
        print(f"# chaos steady storm p50 (disarmed): {steady_p50:.2f}s",
              file=sys.stderr)

        # ---- record pre-kill placements + pick the kill set from the
        # clusters actually carrying placements (seeded, replayable)
        before: dict[str, dict[str, int]] = {}
        for i in range(n):
            rb = cp.store.get("ResourceBinding", f"default/ch{i}-deployment")
            before[rb.meta.namespaced_name] = {
                tc.name: tc.replicas for tc in rb.spec.clusters
            }
        placed_primary = sorted({
            name for placed in before.values() for name in placed
        })
        primary_names = {
            cl.name for cl in cp.store.list("Cluster")
            if cl.meta.labels.get("group") == "primary"
        }
        candidates = [p for p in placed_primary if p in primary_names]
        rng = np.random.default_rng(seed)
        kill = sorted(
            rng.choice(candidates, size=min(kill_k, len(candidates)),
                       replace=False).tolist()
        )
        spec = ";".join(f"cluster.health=down,match={k}" for k in kill)
        est_conn = fleet.conns[0]
        stopped_proc = fleet.procs[0]
        est_channel = f"estimator@{est_conn.target}"

        # ---- the chaos wave: arm the seeded kills and partition
        # estimator server 0, then settle. The next heartbeat (the tick
        # at the head of the settle) flips the K members NotReady INSIDE
        # the wave; taints -> NoExecute evictions -> the cluster event
        # re-gates the whole 20k grid, and the displaced rows reschedule
        # through the ranked ordered-failover path as batched solves —
        # all while one estimator server is black-holed (its clusters
        # answer -1, the pass is degraded-not-stalled, and its breaker
        # opens). Time-to-stable-placement is this settle's wall clock.
        d0 = degraded_passes.value(channel="estimator")
        inj = faultinject.arm(spec, seed=seed)
        stopped_proc.send_signal(signal.SIGSTOP)
        solves0 = cp.scheduler._engine.solve_batches
        clock[0] += 60
        t0 = time.perf_counter()
        cp.settle()
        time_to_stable = time.perf_counter() - t0
        solves_wave = cp.scheduler._engine.solve_batches - solves0
        degraded_wave = degraded_passes.value(channel="estimator") - d0
        print(
            f"# chaos wave: stable in {time_to_stable:.1f}s, "
            f"{solves_wave} batched solves, degraded estimator "
            f"passes={degraded_wave}",
            file=sys.stderr,
        )

        # ---- verify: every binding against the per-binding numpy oracle
        # replaying the same event log
        after: dict[str, dict[str, int]] = {}
        displaced = 0
        killed_set = set(kill)
        for i in range(n):
            rb = cp.store.get("ResourceBinding", f"default/ch{i}-deployment")
            key = rb.meta.namespaced_name
            after[key] = {tc.name: tc.replicas for tc in rb.spec.clusters}
            if killed_set & set(before[key]):
                displaced += 1
        engine = cp.scheduler._engine
        esnap = engine.snapshot
        pl = cp.store.get(
            "PropagationPolicy", "default/chaos-policy"
        ).spec.placement
        cpl = compile_placement(pl, esnap)
        term_masks = np.stack([m for _, m in cpl.terms])
        base = cpl.taint_ok & cpl.spread_field_ok
        # per-profile availability rows (general == merged: the estimator
        # mirrors the snapshot, and -1 never survives the min-merge)
        pods_dim = esnap.dim_index("pods")
        avail_rows = {}
        from karmada_tpu.utils.quantity import parse_resource_list

        for p, prof in enumerate(profiles):
            reqs = np.zeros((1, len(esnap.dims)), np.int64)
            for d, q in parse_resource_list(prof).items():
                di = esnap.dim_index(d)
                if di is not None:
                    reqs[0, di] = q
            if pods_dim is not None:
                reqs[0, pods_dim] = 1
            avail_rows[p] = engine._availability_np(
                reqs, np.asarray([8], np.int32)
            )[0]
        keys = list(before)
        want = replay_failover(
            inj.log,
            esnap.names,
            before,
            {k: term_masks for k in keys},
            {k: base for k in keys},
            {k: cpl.strategy for k in keys},
            {k: (i % 8) + 1 for i, k in enumerate(keys)},
            {k: cpl.static_weights for k in keys},
            {k: avail_rows[i % 8] for i, k in enumerate(keys)},
        )
        mismatches = [
            k for k in keys if want[k] != after[k]
        ]
        oracle_identical = not mismatches
        print(
            f"# chaos oracle: {len(keys) - len(mismatches)}/{len(keys)} "
            f"placements identical, {displaced} displaced by "
            f"{len(kill)} killed clusters",
            file=sys.stderr,
        )
        if mismatches:
            k = mismatches[0]
            print(
                f"# chaos oracle FIRST MISMATCH {k}: want {want[k]} "
                f"got {after[k]} (before {before[k]})",
                file=sys.stderr,
            )

        # ---- degraded storms with the server STILL partitioned: the
        # breaker crosses its threshold and opens — a breaker-open pass
        # answers -1 with zero wire cost and is observable on the
        # karmada_tpu_circuit_state gauge
        degraded_storm_s = [storm_wave(f"degraded{k}") for k in range(2)]
        breaker_open = est_conn.breaker.state == backoff.OPEN or (
            circuit_state.value(channel=est_channel) == backoff.OPEN
        )
        print(
            f"# chaos degraded storms (server partitioned): "
            f"{', '.join(f'{s:.1f}s' for s in degraded_storm_s)}; "
            f"estimator breaker open={breaker_open}",
            file=sys.stderr,
        )

        # ---- recovery: un-partition the estimator server; the breaker
        # must close half-open -> closed without operator action
        stopped_proc.send_signal(signal.SIGCONT)
        faultinject.disarm()
        import grpc as _grpc

        try:
            _grpc.channel_ready_future(est_conn._channel).result(timeout=30)
        except Exception:  # noqa: BLE001 — recovery probe below decides
            pass
        recovered = False
        storm = 0.0
        deadline = time.time() + 30.0
        while time.time() < deadline:
            clock[0] += 60
            fleet.registry.invalidate(drop=True)
            storm = storm_wave("recover")
            if est_conn.breaker.state == backoff.CLOSED:
                recovered = True
                break
            time.sleep(0.5)
        print(
            f"# chaos recovery: breaker "
            f"{'closed' if recovered else 'STILL OPEN'} after server "
            f"resume (last recover wave {storm:.1f}s)",
            file=sys.stderr,
        )

        record = {
            "metric": f"chaos_storm_{n // 1000}kx{c}",
            "value": round(time_to_stable, 4),
            "unit": "s",
            # the acceptance slot: oracle-identical fraction (1.0 passes)
            "vs_baseline": round(
                (len(keys) - len(mismatches)) / max(len(keys), 1), 6
            ),
            "time_to_stable_s": round(time_to_stable, 4),
            "steady_p50_disarmed_s": round(steady_p50, 4),
            "killed_clusters": kill,
            "est_server_partitioned": est_conn.target,
            "displaced_bindings": displaced,
            "degraded_storm_s": [round(s, 4) for s in degraded_storm_s],
            "solves_failover_wave": int(solves_wave),
            "oracle_identical": oracle_identical,
            "oracle_mismatches": len(mismatches),
            "breaker_open_observed": bool(breaker_open),
            "breaker_recovered_closed": bool(recovered),
            "degraded_estimator_passes": int(
                degraded_passes.value(channel="estimator") - d0
            ),
            "replay_events": len(inj.log),
            "chaos_seed": seed,
        }
    finally:
        feature_gate.set(FAILOVER, False)
        faultinject.disarm()
        try:
            fleet_ctx.__exit__(None, None, None)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
    del cp
    gc.collect()
    return record


def run_quota(args) -> dict:
    """ISSUE 8 acceptance tier: the quota plane at storm scale. Workloads
    spread across N quota'd namespaces schedule against FRQ limits
    tightened to leave only --quota-headroom of the surge's delta demand,
    then a CronFederatedHPA surge rescales half the fleet simultaneously
    through the scale-up dispense path. Every engine pass's admission
    decisions and placements are verified against the sequential numpy
    oracle (refimpl.quota_np.admit_wave_np + the per-binding divider),
    steady storms run with enforcement on AND off (the overhead bound),
    and one namespace's quota raise must clear its QuotaExceeded
    conditions without re-packing the rest of the fleet."""
    import calendar
    import os

    from karmada_tpu import cli as _cli
    from karmada_tpu.api import (
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.api.autoscaling import (
        CronFederatedHPA,
        CronFederatedHPARule,
        CronFederatedHPASpec,
        ScaleTargetRef,
    )
    from karmada_tpu.api.core import ObjectMeta
    from karmada_tpu.api.policy import (
        FederatedResourceQuota,
        FederatedResourceQuotaSpec,
        StaticClusterAssignment,
    )
    from karmada_tpu.api.work import SCHEDULED
    from karmada_tpu.controllers.extras import (
        ObjectReferenceSelector,
        WorkloadRebalancer,
        WorkloadRebalancerSpec,
    )
    from karmada_tpu.refimpl.divider_np import assign_batch_np
    from karmada_tpu.refimpl.quota_np import admit_wave_np, cluster_caps_seq
    from karmada_tpu.scheduler.quota import QUOTA_EXCEEDED_ERROR
    from karmada_tpu.scheduler.snapshot import compile_placement
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        new_cluster,
        new_deployment,
    )
    from karmada_tpu.utils.quantity import parse_resource_list

    n, c = args.bindings, args.clusters
    n_ns = max(2, args.quota_namespaces)
    headroom = args.quota_headroom
    cap_ns_count = min(4, n_ns)  # namespaces that ALSO carry static caps
    surge_delta = 3
    base = calendar.timegm((2026, 1, 1, 8, 59, 0, 0, 0, 0))
    clock = [float(base)]
    cp = _cli.cmd_init(clock=lambda: clock[0])
    t0 = time.perf_counter()
    for i in range(c):
        cp.join_cluster(new_cluster(
            f"q{i:04d}",
            cpu=f"{2000 + 8 * (i % 37)}", memory="4000Gi", pods=1_000_000,
        ))
    cp.settle()
    namespaces = [f"nsq{k:02d}" for k in range(n_ns)]
    pl = dynamic_weight_placement()
    for ns in namespaces:
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="pol", namespace=ns),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=pl,
            ),
        ))
        # generous initial limits: the cold wave admits everything, then
        # the bench tightens to used + headroom once usage is live
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="quota", namespace=ns),
            spec=FederatedResourceQuotaSpec(
                overall={"cpu": 1 << 40, "memory": 1 << 50}
            ),
        ))
    print(f"# quota build: {c} clusters + {n_ns} FRQs in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    t0 = time.perf_counter()
    req = parse_resource_list({"cpu": "500m", "memory": "512Mi"})
    keys = []
    for i in range(n):
        ns = namespaces[i % n_ns]
        cp.store.apply(new_deployment(
            f"w{i}", namespace=ns, replicas=(i % 4) + 1,
            cpu="500m", memory="512Mi",
        ))
        keys.append(f"{ns}/w{i}-deployment")
    cp.settle()
    print(f"# quota cold wave (+build): {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    def storm_wave(tag: str) -> float:
        clock[0] += 1
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name=f"quota-storm-{tag}"),
            spec=WorkloadRebalancerSpec(workloads=[
                ObjectReferenceSelector(
                    kind="Deployment", name=f"w{i}",
                    namespace=namespaces[i % n_ns],
                )
                for i in range(n)
            ]),
        ))
        t0 = time.perf_counter()
        cp.settle()
        return time.perf_counter() - t0

    prev_w = None
    for wi in range(3):
        w = storm_wave(f"warm{wi}")
        print(f"# quota warm{wi} wave: {w:.1f}s", file=sys.stderr)
        if prev_w is not None and w > prev_w * 0.7:
            break
        prev_w = w

    # ---- steady storms, enforcement ON vs DISARMED, interleaved so rig
    # warm-up drift cannot masquerade as enforcement cost (ON: delta
    # demand 0 — the enforcement cost is the admission mask pass; OFF:
    # the kill switch leaves one `is None` check on the engine hook).
    # Beside the whole-settle wall (which the shared rig swings ~2x wave
    # to wave), the ENGINE-schedule seconds per storm are tracked — the
    # admission hook lives entirely inside engine.schedule, so that pair
    # is the deterministic face of the enforcement-overhead claim.
    engine0 = cp.scheduler._engine
    sched_s = [0.0]
    inner0 = engine0.schedule

    def timed_schedule(problems):
        t0 = time.perf_counter()
        res = inner0(problems)
        sched_s[0] += time.perf_counter() - t0
        return res

    engine0.schedule = timed_schedule
    steady_on: list = []
    steady_off: list = []
    sched_on: list = []
    sched_off: list = []
    try:
        for k in range(3):
            sched_s[0] = 0.0
            steady_on.append(storm_wave(f"on{k}"))
            sched_on.append(sched_s[0])
            os.environ["KARMADA_TPU_QUOTA_ENFORCEMENT"] = "0"
            try:
                sched_s[0] = 0.0
                steady_off.append(storm_wave(f"off{k}"))
                sched_off.append(sched_s[0])
            finally:
                os.environ.pop("KARMADA_TPU_QUOTA_ENFORCEMENT", None)
    finally:
        engine0.schedule = inner0
    on_p50 = float(np.median(steady_on))
    off_p50 = float(np.median(steady_off))
    sched_on_p50 = float(np.median(sched_on))
    sched_off_p50 = float(np.median(sched_off))
    print(
        f"# quota steady storm p50: enforcement on {on_p50:.2f}s / off "
        f"{off_p50:.2f}s wall ({on_p50 / max(off_p50, 1e-9):.3f}x); "
        f"engine schedule {sched_on_p50:.2f}s / {sched_off_p50:.2f}s "
        f"({sched_on_p50 / max(sched_off_p50, 1e-9):.3f}x)",
        file=sys.stderr,
    )

    # ---- tighten every namespace's quota to used + headroom x the
    # surge's delta demand, and give the first cap_ns_count namespaces a
    # static-assignment cap on cluster 0 (folds into availability)
    surged = [i for i in range(n) if i % 2 == 0]
    surged_per_ns: dict[str, int] = {}
    for i in surged:
        nsn = namespaces[i % n_ns]
        surged_per_ns[nsn] = surged_per_ns.get(nsn, 0) + 1
    cpu_req = req["cpu"]
    limits: dict[str, int] = {}
    for k, ns in enumerate(namespaces):
        frq = cp.store.get("FederatedResourceQuota", f"{ns}/quota")
        used = int(frq.status.overall_used.get("cpu", 0))
        surge_demand = surged_per_ns.get(ns, 0) * surge_delta * cpu_req
        limit = used + int(surge_demand * headroom)
        limits[ns] = limit
        frq.spec.overall = {"cpu": limit}
        if k < cap_ns_count:
            frq.spec.static_assignments = [StaticClusterAssignment(
                cluster_name="q0000", hard={"cpu": 2000}
            )]
        cp.store.apply(frq)
    cp.settle()

    # ---- capture every engine pass of the surge for the oracle replay:
    # (keys, namespaces, replicas, prev dicts, fresh, remaining tensor,
    # ns ids, engine results) in engine arrival order
    engine = cp.scheduler._engine
    esnap = engine.snapshot
    passes: list = []
    inner = engine.schedule

    def capture_schedule(problems):
        q = engine.quota
        snap_rem = (
            (q.remaining.copy(), dict(q.ns_index), q.generation)
            if q is not None
            else None
        )
        res = inner(problems)
        passes.append((list(problems), snap_rem, list(res)))
        return res

    engine.schedule = capture_schedule
    solves0 = engine.solve_batches
    try:
        for i in surged:
            nsn = namespaces[i % n_ns]
            cp.store.apply(CronFederatedHPA(
                meta=ObjectMeta(name=f"surge-w{i}", namespace=nsn),
                spec=CronFederatedHPASpec(
                    scale_target_ref=ScaleTargetRef(
                        kind="Deployment", name=f"w{i}"
                    ),
                    rules=[CronFederatedHPARule(
                        name="surge", schedule="0 9 * * *",
                        target_replicas=(i % 4) + 1 + surge_delta,
                    )],
                ),
            ))
        cp.settle()
        clock[0] = float(base) + 90  # cross 09:00: every rule fires
        t0 = time.perf_counter()
        cp.settle()
        surge_s = time.perf_counter() - t0
    finally:
        engine.schedule = inner
    surge_solves = engine.solve_batches - solves0
    print(
        f"# quota surge wave: {surge_s:.1f}s, {surge_solves} batched "
        f"solves over {len(passes)} engine passes",
        file=sys.stderr,
    )

    # ---- oracle replay: admission via the sequential numpy loop,
    # placements via the per-pass batched numpy divider over cap-folded
    # availability — decisions AND placements must match every pass
    cpl = compile_placement(pl, esnap)
    base_mask = cpl.terms[0][1] & cpl.taint_ok & cpl.spread_field_ok
    dims = list(esnap.dims)
    cpu_dim = dims.index("cpu")
    pods_dim = esnap.dim_index("pods")
    req_vec = np.zeros(len(dims), np.int64)
    for d, qty in req.items():
        j = esnap.dim_index(d)
        if j is not None:
            req_vec[j] = qty
    if pods_dim is not None:
        req_vec[pods_dim] = max(req_vec[pods_dim], 1)
    # base availability row shared per replicas-count (engine mirror —
    # the chaos-bench precedent: inputs shared, decision math oracle-own)
    avail_rows: dict[int, np.ndarray] = {}

    def avail_row(reps: int) -> np.ndarray:
        row = avail_rows.get(reps)
        if row is None:
            row = engine._availability_np(
                req_vec[None, :], np.asarray([reps], np.int32)
            )[0]
            avail_rows[reps] = row
        return row

    # oracle cap rows per namespace (cluster_caps_seq: the sequential
    # per-cluster loop, one row per capped namespace)
    cap_rows_by_ns: dict[str, np.ndarray] = {}
    for k in range(cap_ns_count):
        frq = cp.store.get(
            "FederatedResourceQuota", f"{namespaces[k]}/quota"
        )
        caps = np.full((1, c, len(dims)), 2**62, np.int64)
        for assignment in frq.spec.static_assignments:
            col = esnap.index.get(assignment.cluster_name)
            if col is not None:
                for res, hard in assignment.hard.items():
                    j = esnap.dim_index(res)
                    if j is not None:
                        caps[0, col, j] = int(hard)
        cap_rows_by_ns[namespaces[k]] = cluster_caps_seq(caps, 0, req_vec)

    adm_checked = adm_mismatch = 0
    pl_checked = pl_mismatch = 0
    strategy = np.int32(cpl.strategy)
    for problems, snap_rem, results in passes:
        if snap_rem is None:
            continue
        remaining, ns_index, _gen = snap_rem
        ns_ids = [ns_index.get(p.namespace, -1) for p in problems]
        demand = np.zeros((len(problems), len(dims)), np.int64)
        for row_i, p in enumerate(problems):
            if ns_ids[row_i] < 0:
                continue
            delta = p.replicas - sum(p.prev.values())
            if delta > 0:
                demand[row_i] = req_vec * delta
        want_admit, _used = admit_wave_np(ns_ids, demand, remaining)
        got_admit = [r.error != QUOTA_EXCEEDED_ERROR for r in results]
        adm_checked += len(problems)
        adm_mismatch += sum(
            1 for w, g in zip(want_admit, got_admit) if w != g
        )
        # placements of the admitted rows: one batched numpy divide
        adm_idx = [
            i for i, (w, r) in enumerate(zip(want_admit, results))
            if w and r.success and problems[i].replicas > 0
        ]
        if not adm_idx:
            continue
        b = len(adm_idx)
        reps = np.fromiter(
            (problems[i].replicas for i in adm_idx), np.int32, b
        )
        prev = np.zeros((b, c), np.int32)
        fresh = np.zeros(b, bool)
        avail = np.zeros((b, c), np.int64)
        for row_i, i in enumerate(adm_idx):
            p = problems[i]
            fresh[row_i] = p.fresh
            for name, r_prev in p.prev.items():
                col = esnap.index.get(name)
                if col is not None:
                    prev[row_i, col] = r_prev
            row = avail_row(p.replicas).astype(np.int64)
            cap = cap_rows_by_ns.get(p.namespace)
            if cap is not None:
                row = np.minimum(row, cap.astype(np.int64))
            avail[row_i] = row
        cand = np.broadcast_to(base_mask, (b, c))
        assignment, unsched = assign_batch_np(
            np.full(b, strategy, np.int32), reps, cand,
            np.zeros((b, c), np.int32),
            np.minimum(avail, 2**31 - 1).astype(np.int32),
            prev, fresh,
        )
        for row_i, i in enumerate(adm_idx):
            want = {
                esnap.names[j]: int(assignment[row_i, j])
                for j in np.flatnonzero(assignment[row_i] > 0)
            }
            pl_checked += 1
            if bool(unsched[row_i]):
                # adm_idx rows are engine-SUCCESSFUL: the oracle calling
                # one unschedulable is itself a divergence, not a skip
                pl_mismatch += 1
                if pl_mismatch == 1:
                    print(
                        f"# quota oracle FIRST placement mismatch "
                        f"{problems[i].key}: oracle unschedulable, engine "
                        f"placed {results[i].clusters}",
                        file=sys.stderr,
                    )
                continue
            if want != results[i].clusters:
                pl_mismatch += 1
                if pl_mismatch == 1:
                    print(
                        f"# quota oracle FIRST placement mismatch "
                        f"{problems[i].key}: want {want} got "
                        f"{results[i].clusters}",
                        file=sys.stderr,
                    )
    print(
        f"# quota oracle: admission {adm_checked - adm_mismatch}/"
        f"{adm_checked} identical, placements "
        f"{pl_checked - pl_mismatch}/{pl_checked} identical",
        file=sys.stderr,
    )

    # ---- post-surge state: denied bindings carry QuotaExceeded and
    # keep their pre-surge replicas
    denied_keys = []
    scaled = 0
    for i in surged:
        rb = cp.store.get("ResourceBinding", keys[i])
        cond = next(
            (cc for cc in rb.status.conditions if cc.type == SCHEDULED),
            None,
        )
        total = sum(tc.replicas for tc in rb.spec.clusters)
        if cond is not None and not cond.status:
            denied_keys.append(keys[i])
            assert cond.reason == "QuotaExceeded", cond
        elif total == (i % 4) + 1 + surge_delta:
            scaled += 1
    print(
        f"# quota surge outcome: {scaled} scaled, {len(denied_keys)} "
        f"denied with QuotaExceeded",
        file=sys.stderr,
    )

    # ---- quota raise clears denials WITHOUT a full re-pack: raise ONE
    # namespace's limit and count the extra batched solves
    raise_ns = None
    for ns in namespaces:
        if any(k.startswith(ns + "/") for k in denied_keys):
            raise_ns = ns
            break
    raise_clear = raise_solves = None
    if raise_ns is not None:
        ns_denied = [k for k in denied_keys if k.startswith(raise_ns + "/")]
        solves0 = engine.solve_batches
        frq = cp.store.get("FederatedResourceQuota", f"{raise_ns}/quota")
        frq.spec.overall = {"cpu": limits[raise_ns] + (1 << 40)}
        cp.store.apply(frq)
        clock[0] += 60
        cp.settle()
        raise_solves = cp.scheduler._engine.solve_batches - solves0
        cleared = sum(
            1
            for k in ns_denied
            if next(
                cc
                for cc in cp.store.get(
                    "ResourceBinding", k
                ).status.conditions
                if cc.type == SCHEDULED
            ).status
        )
        raise_clear = cleared == len(ns_denied)
        print(
            f"# quota raise on {raise_ns}: {cleared}/{len(ns_denied)} "
            f"denials cleared in {raise_solves} batched solve(s)",
            file=sys.stderr,
        )

    record = {
        "metric": f"quota_surge_{n // 1000}kx{c}",
        "value": round(surge_s, 4),
        "unit": "s",
        # acceptance slot: identical fraction over admission + placements
        "vs_baseline": round(
            (adm_checked - adm_mismatch + pl_checked - pl_mismatch)
            / max(adm_checked + pl_checked, 1),
            6,
        ),
        "surge_wave_s": round(surge_s, 4),
        "surge_solves": int(surge_solves),
        "surge_engine_passes": len(passes),
        "quota_namespaces": n_ns,
        "capped_namespaces": cap_ns_count,
        "surged_bindings": len(surged),
        "scaled_bindings": int(scaled),
        "denied_bindings": len(denied_keys),
        "admission_checked": int(adm_checked),
        "admission_identical": adm_mismatch == 0,
        "placements_checked": int(pl_checked),
        "placements_identical": pl_mismatch == 0,
        "steady_p50_enforced_s": round(on_p50, 4),
        "steady_p50_disabled_s": round(off_p50, 4),
        "enforcement_overhead_x": round(on_p50 / max(off_p50, 1e-9), 4),
        # the deterministic overhead face: engine.schedule seconds alone
        # (admission lives there; the settle wall swings ~2x on the rig)
        "steady_sched_enforced_s": round(sched_on_p50, 4),
        "steady_sched_disabled_s": round(sched_off_p50, 4),
        "sched_overhead_x": round(
            sched_on_p50 / max(sched_off_p50, 1e-9), 4
        ),
        "raise_namespace": raise_ns,
        "raise_cleared_all": raise_clear,
        "raise_solves": raise_solves,
    }
    del cp
    gc.collect()
    return record


def run_preemption(args) -> dict:
    """ISSUE 14 acceptance tier: the scarcity plane at storm scale.

    A fleet of C member clusters carries B priority-0 workloads, member
    capacity is then saturated EXACTLY (the spot market is fully
    subscribed), and a high-priority surge lands that cannot fit
    anywhere. The batched preemption kernel must select victims
    plane-wide in ONE dispatch, the demanders must place against the
    freed capacity in the same engine pass (solve_batches counts prove
    the shape), and both the victim set and the final placements must be
    bit-identical to the sequential numpy oracle. A drift-rebalance
    round through the continuous descheduler then re-places the worst-
    drifted residents under an EXACT disruption budget, and interleaved
    armed/disarmed steady storms bound the disarmed cost."""
    import os

    from karmada_tpu import cli as _cli
    from karmada_tpu.api import (
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.api.core import ObjectMeta
    from karmada_tpu.api.policy import LabelSelector
    from karmada_tpu.controllers.extras import (
        ObjectReferenceSelector,
        WorkloadRebalancer,
        WorkloadRebalancerSpec,
    )
    from karmada_tpu.estimator.accurate import NodeState
    from karmada_tpu.refimpl.preempt_np import (
        preempt_and_place_np,
        rebalance_np,
    )
    from karmada_tpu.scheduler.quota import per_replica_vector
    from karmada_tpu.scheduler.snapshot import compile_placement
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        new_cluster,
        new_deployment,
    )
    from karmada_tpu.utils.member import MemberCluster
    from karmada_tpu.utils.metrics import preemptions_total
    from karmada_tpu.utils.quantity import parse_resource_list

    n, c = args.bindings, args.clusters
    n_hi = max(1, args.preempt_surge)
    budget = max(1, args.preempt_budget)
    reps_low = 2
    cpu_req = 500  # milli per replica

    from karmada_tpu.api.policy import ClusterAffinity

    cp = _cli.cmd_init(enable_drift_rebalancer=True)
    cp.drift_rebalancer.active = False  # manual rounds only
    members: dict = {}
    # cluster groups spread the priority-0 residents across the fleet
    # (the per-binding estimates carry no intra-wave decrement, so an
    # ungrouped identical-profile fill would stack on the first columns
    # — groups model the tenancy structure a real spot fleet has)
    n_groups = max(1, min(64, c // 8))
    t0 = time.perf_counter()
    for i in range(c):
        name = f"p{i:04d}"
        caps = {"cpu": "200", "memory": "4000Gi", "pods": 1_000_000}
        m = MemberCluster(name)
        m.nodes = [NodeState(
            name=f"{name}-n0", allocatable=parse_resource_list(caps)
        )]
        members[name] = m
        cp.join_cluster(new_cluster(
            name, labels={"group": f"g{i % n_groups}"}, **caps
        ), m)
    cp.settle()
    pl = dynamic_weight_placement()

    def policy(name, match, priority=0, placement=pl):
        return PropagationPolicy(
            meta=ObjectMeta(name=name, namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment",
                    label_selector=LabelSelector(match_labels=match),
                )],
                placement=placement,
                priority=priority,
            ),
        )

    for k in range(n_groups):
        cp.store.apply(policy(
            f"low-g{k}",
            {"tier": "low", "grp": f"g{k}"},
            placement=dynamic_weight_placement(
                cluster_affinity=ClusterAffinity(
                    label_selector=LabelSelector(
                        match_labels={"group": f"g{k}"}
                    )
                )
            ),
        ))
    cp.store.apply(policy("high", {"tier": "high"}, priority=100))
    for i in range(n):
        cp.store.apply(new_deployment(
            f"w{i}", replicas=reps_low, cpu="500m", memory="512Mi",
            labels={"tier": "low", "grp": f"g{i % n_groups}"},
        ))
    cp.settle()
    print(
        f"# preempt build: {c} clusters + {n} low bindings in "
        f"{time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )

    def sync_member_usage(saturate: bool = False):
        """node.requested mirrors bound replicas (the kubelet's role in
        this harness); ``saturate`` then clamps each node's cpu
        allocatable down TO its requested — the fully-subscribed spot
        fleet the scarcity scenario needs."""
        usage = {name: {} for name in members}
        for rb in cp.store.list("ResourceBinding"):
            req = (
                rb.spec.replica_requirements.resource_request
                if rb.spec.replica_requirements
                else {}
            )
            for tc in rb.spec.clusters:
                acc = usage.get(tc.name)
                if acc is None:
                    continue
                for res, qty in req.items():
                    acc[res] = acc.get(res, 0) + qty * tc.replicas
                acc["pods"] = acc.get("pods", 0) + tc.replicas
        for name, m in members.items():
            m.nodes[0].requested = dict(usage[name])
            if saturate:
                m.nodes[0].allocatable = dict(
                    m.nodes[0].allocatable,
                    cpu=usage[name].get("cpu", 0),
                )
        cp.settle()

    # warm storms until flat (the settle_engine discipline, driven
    # through whole-plane rebalancer waves)
    def storm_wave(tag: str) -> float:
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name=f"preempt-storm-{tag}"),
            spec=WorkloadRebalancerSpec(workloads=[
                ObjectReferenceSelector(
                    kind="Deployment", name=f"w{i}", namespace="default"
                )
                for i in range(n)
            ]),
        ))
        t0 = time.perf_counter()
        cp.settle()
        return time.perf_counter() - t0

    prev_w = None
    for wi in range(3):
        w = storm_wave(f"warm{wi}")
        print(f"# preempt warm{wi} wave: {w:.1f}s", file=sys.stderr)
        if prev_w is not None and w > prev_w * 0.7:
            break
        prev_w = w

    # ---- armed-vs-disarmed steady storms, interleaved (the quota-tier
    # discipline: rig warm-up drift cannot masquerade as arming cost).
    # A handful of PLACED high-priority bindings keeps the armed path's
    # priority scan + victim-source arming genuinely engaged while no
    # binding is unschedulable — the disarmed-claim's exact shape.
    for i in range(50):
        cp.store.apply(new_deployment(
            f"warmhi{i}", replicas=1, cpu="500m", memory="512Mi",
            labels={"tier": "high"},
        ))
    cp.settle()
    engine0 = cp.scheduler._inproc_engine()
    sched_s = [0.0]
    inner0 = engine0.schedule

    def timed_schedule(problems):
        t0 = time.perf_counter()
        res = inner0(problems)
        sched_s[0] += time.perf_counter() - t0
        return res

    engine0.schedule = timed_schedule
    steady_armed: list = []
    steady_off: list = []
    sched_armed: list = []
    sched_off: list = []
    try:
        for k in range(3):
            sched_s[0] = 0.0
            steady_armed.append(storm_wave(f"armed{k}"))
            sched_armed.append(sched_s[0])
            os.environ["KARMADA_TPU_PREEMPTION"] = "0"
            try:
                sched_s[0] = 0.0
                steady_off.append(storm_wave(f"off{k}"))
                sched_off.append(sched_s[0])
            finally:
                os.environ.pop("KARMADA_TPU_PREEMPTION", None)
    finally:
        engine0.schedule = inner0
    armed_p50 = float(np.median(steady_armed))
    off_p50 = float(np.median(steady_off))
    sched_armed_p50 = float(np.median(sched_armed))
    sched_off_p50 = float(np.median(sched_off))
    overhead_x = sched_armed_p50 / max(sched_off_p50, 1e-9)
    print(
        f"# preempt steady storm p50: armed {armed_p50:.2f}s / disarmed "
        f"{off_p50:.2f}s wall ({armed_p50 / max(off_p50, 1e-9):.3f}x); "
        f"engine schedule {sched_armed_p50:.2f}s / {sched_off_p50:.2f}s "
        f"({overhead_x:.3f}x)",
        file=sys.stderr,
    )

    # ---- saturate the fleet exactly and snapshot pre-surge state
    sync_member_usage(saturate=True)
    engine = cp.scheduler._inproc_engine()
    esnap = engine.snapshot
    dims = list(esnap.dims)
    base_caps = np.asarray(esnap.available_cap).copy()
    cpu_dim = esnap.dim_index("cpu")
    assert int(np.maximum(base_caps[:, cpu_dim], 0).sum()) == 0, (
        "saturation failed: free cpu remains"
    )
    # the resident pool, in the victim-source's iteration order
    pre_surge = [
        (
            rb.meta.namespaced_name,
            {tc.name: tc.replicas for tc in rb.spec.clusters},
            (
                rb.spec.replica_requirements.resource_request
                if rb.spec.replica_requirements
                else {}
            ),
            getattr(rb.spec, "priority", 0),
        )
        for rb in cp.store.list("ResourceBinding")
        if rb.spec.clusters
    ]

    # ---- the scarcity surge, every engine pass captured
    passes: list = []
    inner = engine.schedule

    def capture_schedule(problems):
        res = inner(problems)
        passes.append((
            list(problems), list(res), engine.last_preemption,
        ))
        return res

    engine.schedule = capture_schedule
    solves0 = engine.solve_batches
    try:
        for i in range(n_hi):
            cp.store.apply(new_deployment(
                f"hi{i}", replicas=reps_low, cpu="500m", memory="512Mi",
                labels={"tier": "high"},
            ))
        t0 = time.perf_counter()
        cp.settle()
        surge_s = time.perf_counter() - t0
    finally:
        engine.schedule = inner
    surge_solves = engine.solve_batches - solves0
    outcome_passes = [
        (pp, rr, oo) for pp, rr, oo in passes if oo is not None and oo.victims
    ]
    print(
        f"# preempt surge wave: {surge_s:.1f}s, {surge_solves} batched "
        f"solves over {len(passes)} engine passes "
        f"({len(outcome_passes)} with preemption)",
        file=sys.stderr,
    )

    # ---- oracle replay: sequential victim selection + per-binding
    # boosted divides, sharing NO selection code with the kernel. Inputs
    # (row order, placements, requests) are shared — the chaos-bench
    # precedent — the decision math is the oracle's own.
    victim_keys_engine = sorted(
        rb.meta.namespaced_name
        for rb in cp.store.list("ResourceBinding")
        if any(
            t.reason == "PreemptedByHigherPriority"
            for t in rb.spec.graceful_eviction_tasks
        )
    )
    cpl = compile_placement(pl, esnap)
    base_mask = cpl.terms[0][1] & cpl.taint_ok & cpl.spread_field_ok
    vic_checked = vic_mismatch = 0
    pl_checked = pl_mismatch = 0
    oracle_victims: list = []
    if outcome_passes:
        problems0, results0, _out0 = outcome_passes[0]
        demanders = [
            p for p in problems0 if getattr(p, "priority", 0) > 0
        ]
        wave_keys = {p.key for p in problems0}
        keys, prios, demand_rows, freed_rows = [], [], [], []
        victim_ok, weights = [], []
        assigned_by_key: dict = {}
        requests_by_key: dict = {}
        for p in demanders:
            keys.append(p.key)
            prios.append(getattr(p, "priority", 0))
            vec = per_replica_vector(p.requests, dims)
            requests_by_key[p.key] = vec
            short = p.replicas - (0 if p.fresh else sum(p.prev.values()))
            demand_rows.append(vec * max(short, 0))
            freed_rows.append(np.zeros(len(dims), np.int64))
            victim_ok.append(False)
            weights.append(0)
        for key, placement, req, prio in pre_surge:
            if key in wave_keys:
                continue
            keys.append(key)
            prios.append(prio)
            vec = per_replica_vector(req, dims)
            requests_by_key[key] = vec
            assigned_by_key[key] = placement
            total = sum(placement.values())
            demand_rows.append(np.zeros(len(dims), np.int64))
            freed_rows.append(vec * total)
            victim_ok.append(total > 0)
            weights.append(total)
        oracle_victims, oracle_placed = preempt_and_place_np(
            keys, prios,
            np.stack(demand_rows), np.stack(freed_rows),
            victim_ok, weights,
            names=esnap.names,
            assigned=assigned_by_key,
            requests=requests_by_key,
            # UNCLAMPED base caps: an overcommitted dim must stay
            # negative until the freed capacity digs it out — the
            # engine's clamp-AFTER-add order (host_profile_table)
            base_caps=base_caps,
            demanders=[p.key for p in demanders],
            candidates={
                p.key: np.asarray(base_mask) for p in demanders
            },
            strategies={p.key: int(cpl.strategy) for p in demanders},
            replicas={p.key: p.replicas for p in demanders},
            prev={p.key: dict(p.prev) for p in demanders},
        )
        vic_checked = len(
            set(oracle_victims) | set(victim_keys_engine)
        )
        vic_mismatch = len(
            set(oracle_victims) ^ set(victim_keys_engine)
        )
        for p in demanders:
            want = oracle_placed.get(p.key, {})
            rb = cp.store.get("ResourceBinding", p.key)
            got = (
                {tc.name: tc.replicas for tc in rb.spec.clusters}
                if rb is not None
                else {}
            )
            pl_checked += 1
            if want != got:
                pl_mismatch += 1
                if pl_mismatch == 1:
                    print(
                        f"# preempt oracle FIRST placement mismatch "
                        f"{p.key}: want {want} got {got}",
                        file=sys.stderr,
                    )
    print(
        f"# preempt oracle: victims {vic_checked - vic_mismatch}/"
        f"{vic_checked} identical, placements "
        f"{pl_checked - pl_mismatch}/{pl_checked} identical",
        file=sys.stderr,
    )
    preempted_count = sum(preemptions_total.samples().values())

    # ---- drift-rebalance round: fresh spot capacity arrives, the
    # continuous descheduler re-places the worst drifted residents under
    # an exact budget, oracle-verified
    n_new = 8
    for i in range(n_new):
        name = f"new{i:02d}"
        caps = {"cpu": "400", "memory": "4000Gi", "pods": 1_000_000}
        m = MemberCluster(name)
        m.nodes = [NodeState(
            name=f"{name}-n0", allocatable=parse_resource_list(caps)
        )]
        members[name] = m
        cp.join_cluster(new_cluster(name, **caps), m)
    cp.settle()
    engine = cp.scheduler._inproc_engine()
    dsnap = engine.snapshot

    # the oracle's trigger set: per-binding fresh one-row divides over
    # the SAME candidate/availability inputs, sequential (placements
    # differ per group policy, so candidates compile per placement)
    o_keys, o_current, o_cands, o_strats, o_reps, o_avail = (
        [], {}, {}, {}, {}, {}
    )
    avail_rows: dict = {}
    cpl_cache: dict = {}
    for kind, rb, problem in cp.drift_rebalancer._candidates():
        key = rb.meta.namespaced_name
        o_keys.append(key)
        o_current[key] = {tc.name: tc.replicas for tc in rb.spec.clusters}
        dcpl = cpl_cache.get(id(rb.spec.placement))
        if dcpl is None:
            dcpl = compile_placement(rb.spec.placement, dsnap)
            cpl_cache[id(rb.spec.placement)] = dcpl
        o_cands[key] = np.asarray(
            dcpl.terms[0][1] & dcpl.taint_ok & dcpl.spread_field_ok
        )
        o_strats[key] = int(dcpl.strategy)
        o_reps[key] = rb.spec.replicas
        row = avail_rows.get(rb.spec.replicas)
        if row is None:
            vec = per_replica_vector(
                problem.requests, list(dsnap.dims)
            )[None, :]
            row = engine._availability_np(
                vec, np.asarray([rb.spec.replicas], np.int32)
            )[0]
            avail_rows[rb.spec.replicas] = row
        o_avail[key] = row
    t0 = time.perf_counter()
    os.environ["KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION"] = str(budget)
    try:
        stats = cp.drift_rebalancer.rebalance_once()
        cp.settle()  # the triggered bindings re-place as Fresh waves
    finally:
        os.environ.pop("KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION", None)
    drift_s = time.perf_counter() - t0
    _odrifts, oracle_triggered = rebalance_np(
        o_keys,
        names=dsnap.names,
        current=o_current,
        candidates=o_cands,
        strategies=o_strats,
        replicas=o_reps,
        avail=o_avail,
        budget=budget,
    )
    drift_identical = stats["triggered"] == oracle_triggered
    budget_exact = len(stats["triggered"]) == min(
        budget, stats["drifted"]
    )
    replaced = sum(
        1
        for key in stats["triggered"]
        for rb in [cp.store.get("ResourceBinding", key)]
        if rb is not None
        and rb.status.last_scheduled_time is not None
        and rb.spec.reschedule_triggered_at is not None
        and rb.status.last_scheduled_time
        >= rb.spec.reschedule_triggered_at
    )
    print(
        f"# preempt drift round: {stats['drifted']} drifted, "
        f"{len(stats['triggered'])}/{budget} triggered "
        f"(oracle identical={drift_identical}, budget exact="
        f"{budget_exact}, {replaced} re-placed) in {drift_s:.1f}s",
        file=sys.stderr,
    )

    record = {
        "metric": f"preempt_storm_{n // 1000}kx{c}",
        "value": round(surge_s, 4),
        "unit": "s",
        # acceptance slot: identical fraction over victims + placements
        "vs_baseline": round(
            (vic_checked - vic_mismatch + pl_checked - pl_mismatch)
            / max(vic_checked + pl_checked, 1),
            6,
        ),
        "surge_wave_s": round(surge_s, 4),
        "surge_solves": int(surge_solves),
        "surge_engine_passes": len(passes),
        "preemption_passes": len(outcome_passes),
        "surged_bindings": n_hi,
        "victims_evicted": len(victim_keys_engine),
        "victims_checked": int(vic_checked),
        "victims_identical": vic_mismatch == 0,
        "placements_checked": int(pl_checked),
        "placements_identical": pl_mismatch == 0,
        "preemptions_total": int(preempted_count),
        "steady_p50_armed_s": round(armed_p50, 4),
        "steady_p50_disarmed_s": round(off_p50, 4),
        "steady_sched_armed_s": round(sched_armed_p50, 4),
        "steady_sched_disarmed_s": round(sched_off_p50, 4),
        # the guarded disarmed-vs-armed claim: engine.schedule seconds
        # alone (arming lives there; the settle wall swings on the rig)
        "preempt_overhead_x": round(overhead_x, 4),
        "drift_round_s": round(drift_s, 4),
        "drift_scored": int(stats["scored"]),
        "drift_drifted": int(stats["drifted"]),
        "drift_budget": int(budget),
        "drift_triggered": len(stats["triggered"]),
        "drift_budget_exact": bool(budget_exact),
        "drift_oracle_identical": bool(drift_identical),
        "drift_replaced": int(replaced),
    }
    del cp
    gc.collect()
    return record


def run_observability(args) -> dict:
    """ISSUE 6 acceptance tier: one whole-plane storm wave (detector ->
    scheduler -> binding -> works) with the wave tracer on. The record
    proves the measurement layer itself: the wave's span tree must cover
    >=95% of the externally measured wall clock, with the kernel span
    split into compile/device/host components and the per-phase breakdown
    rendered into the docs tables (tools/docs_from_bench.py)."""
    from karmada_tpu import cli as _cli
    from karmada_tpu.api import (
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.api.core import ObjectMeta
    from karmada_tpu.controllers.extras import (
        ObjectReferenceSelector,
        WorkloadRebalancer,
        WorkloadRebalancerSpec,
    )
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        new_cluster,
        new_deployment,
    )
    from karmada_tpu.utils.metrics import kernel_compiles
    from karmada_tpu.utils.tracing import tracer

    n, c = args.bindings, args.clusters

    clock = [10_000.0]
    cp = _cli.cmd_init(clock=lambda: clock[0])
    for i in range(c):
        cp.join_cluster(new_cluster(f"obs{i}", cpu="2000", memory="4000Gi"))
    cp.settle()
    t0 = time.perf_counter()
    cp.store.apply(PropagationPolicy(
        meta=ObjectMeta(name="obs-policy", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=dynamic_weight_placement(),
        ),
    ))
    for i in range(n):
        cp.store.apply(new_deployment(f"obs{i}", replicas=(i % 8) + 1))
    print(f"# observability build: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    cp.settle()
    cold_wall = time.perf_counter() - t0
    n_works = len(cp.store.list("Work"))
    print(
        f"# observability cold wave: {cold_wall:.1f}s "
        f"({n_works} works rendered)",
        file=sys.stderr,
    )
    cold_summary = tracer.wave_summary()

    def storm_wave(tag: str) -> tuple:
        """One rebalancer storm wave; returns (wall_s, summaries of the
        waves the settle produced, main summary = largest total)."""
        clock[0] += 60
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name=f"obs-storm-{tag}"),
            spec=WorkloadRebalancerSpec(workloads=[
                ObjectReferenceSelector(kind="Deployment", name=f"obs{i}")
                for i in range(n)
            ]),
        ))
        before = set(tracer.waves())
        t0 = time.perf_counter()
        cp.settle()
        wall = time.perf_counter() - t0
        new = [w for w in tracer.waves() if w not in before]
        sums = [tracer.wave_summary(w) for w in new] or [
            tracer.wave_summary()
        ]
        main = max(sums, key=lambda s: s["total_s"])
        return wall, sums, main

    # warm until the wave cost flattens (same discipline as the
    # whole-plane tier: the first storms still pay heap/queue settlement
    # and fleet-table compiles)
    prev_w = None
    for wi in range(4):
        w, _, _ = storm_wave(f"warm{wi}")
        print(f"# observability warm{wi} wave: {w:.1f}s", file=sys.stderr)
        if prev_w is not None and w > prev_w * 0.7:
            break
        prev_w = w

    # ISSUE 12 (b): the device-byte ledger across the measured steady
    # wave — resident bytes must not move between steady passes, and the
    # gauge's samples must sum to the engine's exact nbytes ledger
    eng = getattr(cp.scheduler, "_engine", None)
    bytes_before = eng.device_bytes() if eng is not None else {}

    wall, sums, main = storm_wave("measured")
    # the acceptance number: how much of the externally measured wall
    # clock the wave tree attributes to named spans (every settle the
    # storm ran counts — a wave the ring dropped would show here)
    attributed = sum(s["total_s"] for s in sums)
    coverage = attributed / wall if wall else 0.0
    compiles: dict[str, float] = {}
    for key, v in kernel_compiles.samples().items():
        kern = dict(key).get("kernel", "?")
        compiles[kern] = compiles.get(kern, 0) + v
    print(
        f"# observability measured wave: {wall:.2f}s, trace covers "
        f"{coverage * 100:.1f}% ({len(sums)} wave(s), "
        f"{main['spans']} spans in the main wave)",
        file=sys.stderr,
    )
    # device-byte ledger columns (ISSUE 12 b)
    from karmada_tpu.utils.history import render_history_table
    from karmada_tpu.utils.metrics import device_bytes as device_bytes_gauge

    bytes_after = eng.device_bytes() if eng is not None else {}
    dev_samples = device_bytes_gauge.samples()
    gauge_total = sum(
        v for k, v in dev_samples.items()
        if dict(k).get("kind") in bytes_after
    )
    platforms = sorted({
        dict(k).get("platform", "?") for k in dev_samples
        if dict(k).get("kind") in bytes_after
    })
    dev_constant = bool(bytes_after) and bytes_before == bytes_after
    # gated on a non-empty ledger: an engine that never built must
    # record "not verified", never a vacuous 0 == 0 pass
    dev_matches = bool(bytes_after) and (
        int(gauge_total) == sum(bytes_after.values())
    )
    print(
        f"# observability device bytes: {bytes_after} "
        f"(steady-constant={dev_constant}, gauge-sum-matches="
        f"{dev_matches}, platform={platforms})",
        file=sys.stderr,
    )
    # the history-backed per-wave table (ISSUE 12 a)
    hist = tracer.history
    hist_rows = hist.rows(window=10)
    print(render_history_table(hist_rows), file=sys.stderr)
    record = {
        "metric": f"observability_wave_{n // 1000}kx{c}",
        "value": round(wall, 4),
        "unit": "s",
        # the tier's acceptance ratio rides the vs_baseline slot: span-
        # attributed seconds over measured wall seconds (>= 0.95 passes)
        "vs_baseline": round(coverage, 4),
        "coverage_vs_wall": round(coverage, 4),
        "trace_total_s": round(attributed, 4),
        "bindings_s": round(n / wall, 1) if wall else None,
        "works": n_works,
        "cold_wave_s": round(cold_wall, 4),
        "cold_phases": cold_summary["phases"],
        "phases": main["phases"],
        "span_counts": main["span_counts"],
        "device_s": main["device_s"],
        "compile_s": main["compile_s"],
        "host_s": main["host_s"],
        "kernel_compiles": compiles,
        "waves_in_window": len(sums),
        # ISSUE 12: device-byte ledger + per-wave history columns
        "device_bytes": {k: int(v) for k, v in sorted(bytes_after.items())},
        "device_bytes_total": int(sum(bytes_after.values())),
        "device_bytes_steady_constant": dev_constant,
        "device_bytes_matches_gauge": dev_matches,
        "device_bytes_platform": ",".join(platforms),
        "history_waves": hist.sampled,
        "history_rows": hist_rows[-8:],
        "history_digests": hist.digests(window=64)["series"],
    }
    # ISSUE 13: the provenance (explain) tier — armed-vs-disarmed storm
    # overhead, capture sizes, a live denied binding's decision chain,
    # and the flight record's worst-binding explanations
    record.update(run_explain_tier(cp, clock, storm_wave))
    del cp
    gc.collect()
    # ISSUE 10: the 4-process stitched wave + flight-recorder proof
    record.update(run_stitched_observability(args))
    return record


def run_explain_tier(cp, clock, storm_wave) -> dict:
    """ISSUE 13 acceptance phase, riding the in-proc observability
    plane: (a) the same rebalancer storm armed vs disarmed — armed runs
    ONE extra explain dispatch per pass and must stay within the
    benchguard noise band; (b) capture sizes off the ExplainStore ring;
    (c) a live FederatedResourceQuota denial whose full decision chain
    `karmadactl-tpu explain` resolves; (d) a seeded SLO-breach flight
    record carrying the wave's worst-binding explanations, re-rendered
    identically offline by `trace analyze`."""
    import os
    import tempfile

    from karmada_tpu import cli as _cli
    from karmada_tpu.api import (
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.api.core import ObjectMeta
    from karmada_tpu.api.policy import (
        FederatedResourceQuota,
        FederatedResourceQuotaSpec,
    )
    from karmada_tpu.utils import explainstore as _expl
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        new_deployment,
    )

    eng = getattr(cp.scheduler, "_engine", None)
    if eng is None:
        return {}
    _expl.reset_store()
    estore = _expl.store()

    # disarmed / armed / disarmed interleave (shared rigs drift; the
    # overhead ratio reads against the disarmed MEAN). The first armed
    # wave warms the explain kernel traces off the timed window.
    dis1, _, _ = storm_wave("explain-off1")
    eng.set_explain(estore)
    warm, _, _ = storm_wave("explain-warm")
    armed_wall, _, _ = storm_wave("explain-armed")
    caps = estore.captures()
    cap_bind = sum(c.bindings for c in caps)
    cap_bytes = sum(c.nbytes() for c in caps)
    uniq_masks = sum(len(c.uniq_masks) for c in caps)
    eng.set_explain(None)
    dis2, _, _ = storm_wave("explain-off2")
    disarmed = (dis1 + dis2) / 2
    overhead = (armed_wall / disarmed) if disarmed else None
    print(
        f"# explain tier: armed {armed_wall:.2f}s (warm {warm:.2f}s) vs "
        f"disarmed {dis1:.2f}/{dis2:.2f}s -> {overhead:.3f}x; "
        f"{cap_bind} bindings captured in {len(caps)} capture(s), "
        f"{cap_bytes / 1e6:.2f} MB interned ({uniq_masks} unique mask "
        "rows)",
        file=sys.stderr,
    )

    # a LIVE quota denial under an armed flight recorder: the denial
    # wave both resolves through `karmadactl-tpu explain` AND breaches
    # the seeded SLO, so the flight record carries THIS wave's
    # worst-binding (the denied one) explanations — re-rendered
    # identically offline by `trace analyze`
    eng.set_explain(estore)
    flight_dir = tempfile.mkdtemp(prefix="karmada_tpu_flight_expl_")
    saved = {
        k: os.environ.get(k)
        for k in ("KARMADA_TPU_TRACE_SLO_SECONDS", "KARMADA_TPU_FLIGHT_DIR")
    }
    resolved = False
    binding_doc = None
    flight_identical = None
    try:
        os.environ["KARMADA_TPU_TRACE_SLO_SECONDS"] = "0.0001"
        os.environ["KARMADA_TPU_FLIGHT_DIR"] = flight_dir
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="expl-policy", namespace="expl"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(
                        api_version="apps/v1", kind="Deployment"
                    )
                ],
                placement=dynamic_weight_placement(),
            ),
        ))
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="expl"),
            spec=FederatedResourceQuotaSpec(overall={"cpu": 0}),
        ))
        cp.store.apply(
            new_deployment("explain-denied", namespace="expl", replicas=4)
        )
        clock[0] += 60
        cp.settle()
        doc = _cli.cmd_explain_placement("expl/explain-denied-deployment")
        binding_doc = doc.get("binding")
        resolved = bool(
            binding_doc
            and binding_doc.get("reason") == "QuotaExceeded"
            and "QuotaExceeded" in (binding_doc.get("stages") or {})
            and binding_doc.get("candidates")
        )
        analysis = _cli.cmd_trace_analyze(
            os.path.join(flight_dir, "flight.jsonl")
        )
        expl_ctx = analysis.get("explain")
        flight_identical = bool(analysis.get("identical")) and any(
            w.get("reason") == "QuotaExceeded"
            for w in (expl_ctx or {}).get("worst", [])
        )
    except Exception as exc:  # noqa: BLE001 — the proof is recorded,
        # never crashes the whole bench record
        print(f"# explain tier: flight proof failed: {exc!r}",
              file=sys.stderr)
        flight_identical = False
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        eng.set_explain(None)
    print(
        f"# explain tier: live denied binding resolved={resolved} "
        f"(reason={binding_doc.get('reason') if binding_doc else None})",
        file=sys.stderr,
    )
    print(
        f"# explain tier: flight record explain re-render identical="
        f"{flight_identical}",
        file=sys.stderr,
    )
    return {
        "explain_armed_wave_s": round(armed_wall, 4),
        "explain_disarmed_wave_s": round(disarmed, 4),
        "explain_overhead_x": round(overhead, 4) if overhead else None,
        "explain_captures": len(caps),
        "explain_capture_bindings": int(cap_bind),
        "explain_capture_bytes": int(cap_bytes),
        "explain_unique_masks": int(uniq_masks),
        "explain_resolved": resolved,
        "explain_denied_stage": "QuotaExceeded" if resolved else "?",
        "explain_flight_identical": flight_identical,
    }


def run_stitched_observability(args) -> dict:
    """ISSUE 10 acceptance phase: one storm wave over a LIVE 4-process
    plane — this process is the scheduler plane, writing through a real
    store-bus process, solving through a solver-sidecar process that
    itself min-merges availability from an estimator-server process
    (``--estimator``) — with the trace context propagated over every
    channel. Records the stitched wave (per-process self time,
    per-channel client/server/network columns, cross-process coverage of
    the externally measured wall), then arms the flight recorder + a
    seeded solver fault (breaker trip mid-wave) and proves the recorded
    JSONL re-renders identically offline (``trace analyze``)."""
    import os
    import tempfile

    from karmada_tpu import cli as _cli
    from karmada_tpu.api import (
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.api.core import ObjectMeta
    from karmada_tpu.bus.agent import ReplicaStoreFacade
    from karmada_tpu.bus.service import StoreReplica
    from karmada_tpu.controllers.extras import (
        ObjectReferenceSelector,
        WorkloadRebalancer,
        WorkloadRebalancerSpec,
    )
    from karmada_tpu.localup import scrape_line, spawn_child
    from karmada_tpu.solver.client import RemoteSolver
    from karmada_tpu.utils import faultinject
    from karmada_tpu.utils import tracing as trc
    from karmada_tpu.utils.builders import (
        dynamic_weight_placement,
        new_cluster,
        new_deployment,
    )
    from karmada_tpu.utils.tracing import tracer

    # a smaller shape than the in-proc phase: every write is now a real
    # gRPC round-trip and the point is the MEASUREMENT layer, not plane
    # throughput (the 20kx512 coverage number above stands on its own)
    n = max(min(args.bindings // 10, 2000), 256)
    c = min(args.clusters, 64)
    py = sys.executable
    procs: list = []
    flight_dir = tempfile.mkdtemp(prefix="karmada_tpu_flight_")
    saved_env = {
        k: os.environ.get(k)
        for k in ("KARMADA_TPU_TRACE_SLO_SECONDS", "KARMADA_TPU_FLIGHT_DIR",
                  "KARMADA_TPU_FAULT_SPEC", "KARMADA_TPU_FAULT_SEED",
                  "KARMADA_TPU_BUS_BATCH", "KARMADA_TPU_BUS_TEMPLATE_DELTA")
    }
    replica = solver_client = None
    try:
        # ---- the other three processes -------------------------------
        t0 = time.perf_counter()
        bus_proc = spawn_child(
            [py, "-m", "karmada_tpu.bus", "--address", "127.0.0.1:0",
             "--metrics-port", "0"],
        )
        procs.append(bus_proc)
        endpoints = json.loads(scrape_line(bus_proc, r'(\{"bus".*\})'))
        bus_port, bus_metrics = endpoints["bus"], endpoints["metrics"]

        spec = {
            f"st{i:03d}": {"cpu": 2_000_000, "memory": 4000 << 30,
                           "pods": 1_000_000}
            for i in range(c)
        }
        names = sorted(spec)
        spec_f = tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False
        )
        json.dump(spec, spec_f)
        spec_f.close()
        est_proc = spawn_child(
            [py, "-m", "karmada_tpu.estimator", "--spec-file", spec_f.name,
             "--metrics-port", "0"],
        )
        procs.append(est_proc)
        est_port = int(scrape_line(est_proc, r"port (\d+)", timeout=120))
        est_metrics = int(scrape_line(
            est_proc, r"metrics listening on port (\d+)", timeout=30
        ))

        solver_cmd = [
            py, "-m", "karmada_tpu.solver", "--address", "127.0.0.1:0",
            "--metrics-port", "0", "--warmup-manifest", "",
        ]
        for name in names:
            solver_cmd += ["--estimator", f"{name}=127.0.0.1:{est_port}"]
        solver_proc = spawn_child(solver_cmd)
        procs.append(solver_proc)
        solver_port = int(scrape_line(
            solver_proc, r"port (\d+)", timeout=120
        ))
        solver_metrics = int(scrape_line(
            solver_proc, r"metrics listening on port (\d+)", timeout=30
        ))
        trc.register_peer("bus", f"127.0.0.1:{bus_metrics}")
        trc.register_peer("estimator", f"127.0.0.1:{est_metrics}")
        trc.register_peer("solver", f"127.0.0.1:{solver_metrics}")
        print(
            f"# stitched plane up in {time.perf_counter() - t0:.1f}s "
            f"(bus:{bus_port} estimator:{est_port} solver:{solver_port})",
            file=sys.stderr,
        )

        # ---- this process: the scheduler plane over the bus ----------
        replica = StoreReplica(f"127.0.0.1:{bus_port}")
        replica.start()
        if not replica.wait_synced(30):
            raise RuntimeError("bus replica failed to sync")
        solver_client = RemoteSolver(
            f"127.0.0.1:{solver_port}", timeout_seconds=600.0
        )
        clock = [10_000.0]
        cp = _cli.cmd_init(
            clock=lambda: clock[0],
            store=ReplicaStoreFacade(replica),
            solver=solver_client,
        )
        for name in names:
            cp.join_cluster(new_cluster(name, cpu="2000", memory="4000Gi"))
        cp.settle()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="st-policy", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment"
                )],
                placement=dynamic_weight_placement(),
            ),
        ))
        for i in range(n):
            cp.store.apply(new_deployment(f"st{i}", replicas=(i % 8) + 1))

        def settle_through_echoes() -> float:
            """Settle until the write-echo stream quiesces: a settle's
            writes become locally visible only via the bus echo, which
            can land after run_until_settled returns. The measured wall
            ends at the LAST settle that did work — the trailing idle
            probes are this harness confirming quiescence, not plane
            time."""
            t0 = time.perf_counter()
            cp.settle()
            last_work = time.perf_counter()
            idle = 0
            while idle < 3:
                time.sleep(0.05)
                if cp.settle() == 0:
                    idle += 1
                else:
                    idle = 0
                    last_work = time.perf_counter()
            return last_work - t0

        boot = settle_through_echoes()
        print(f"# stitched boot wave: {boot:.1f}s "
              f"({len(cp.store.list('Work'))} works)", file=sys.stderr)

        def storm(tag: str) -> tuple:
            clock[0] += 60
            # drain the PREVIOUS burst's echo tail until its wave closes
            # so the measured window starts clean (bounded: a stubborn
            # straggler falls through to the inherited-wave fallback)
            drain_deadline = time.monotonic() + 5.0
            while (
                tracer.open_wave() is not None
                and time.monotonic() < drain_deadline
            ):
                cp.settle()
                time.sleep(0.05)
            before = set(tracer.waves())
            # the wave open RIGHT NOW (the previous storm's echo tail
            # can keep one open past its idle probes) absorbs this
            # storm's spans — a pure id-diff would attribute the whole
            # storm to "no new wave" and read as ~0% coverage
            inherited = tracer.open_wave()
            cp.store.apply(WorkloadRebalancer(
                meta=ObjectMeta(name=f"st-storm-{tag}"),
                spec=WorkloadRebalancerSpec(workloads=[
                    ObjectReferenceSelector(kind="Deployment", name=f"st{i}")
                    for i in range(n)
                ]),
            ))
            wall = settle_through_echoes()
            new = [w for w in tracer.waves() if w not in before]
            if inherited is not None and inherited not in new:
                new.append(inherited)
            return wall, new

        for wi in range(2):
            w, _ = storm(f"warm{wi}")
            print(f"# stitched warm{wi} wave: {w:.1f}s", file=sys.stderr)

        wall, new_waves = storm("measured")
        local = trc.trace_debug_doc()
        peer_docs = trc.fetch_peer_dumps(trc.peers())
        doc = trc.stitch_dumps(local, peer_docs)
        waves = [w for w in doc["waves"] if w["wave"] in new_waves]
        attributed = sum(w["total_s"] for w in waves)
        coverage = attributed / wall if wall else 0.0
        main = max(waves, key=lambda w: w["total_s"])
        print(
            f"# stitched measured wave: {wall:.2f}s, cross-process trace "
            f"covers {coverage * 100:.1f}% across {main['procs']} "
            f"(channels: { {k: v['rpcs'] for k, v in main['channels'].items()} })",
            file=sys.stderr,
        )
        phases = main.get("phases") or {}
        top_phase = max(phases.items(), key=lambda kv: kv[1]) if phases else ("", 0.0)

        # ---- ISSUE 11: batched vs unary parity + throughput ----------
        # the whole-plane storm re-runs with the columnar channel forced
        # off (KARMADA_TPU_BUS_BATCH=0 pins every connection unary,
        # KARMADA_TPU_BUS_TEMPLATE_DELTA=0 full-renders every Work) and
        # the final plane state must be IDENTICAL: same placements, and
        # template-delta rehydration byte-equivalent to full rendering
        def plane_state():
            import copy

            from karmada_tpu.controllers.propagation import work_manifests
            from karmada_tpu.utils.codec import to_jsonable

            def canon(doc):
                doc = copy.deepcopy(doc)
                meta = doc.get("meta") or {}
                for k in ("resource_version", "uid", "creation_timestamp"):
                    meta.pop(k, None)
                for bag in ("labels", "annotations"):
                    d = meta.get(bag) or {}
                    for k in list(d):
                        if "permanent-id" in k:
                            del d[k]
                return doc

            placements = {
                rb.meta.namespaced_name: sorted(
                    (tc.name, tc.replicas) for tc in rb.spec.clusters
                )
                for rb in cp.store.list("ResourceBinding")
            }
            manifests = {}
            for w in cp.store.list("Work"):
                docs = work_manifests(cp.store, w)
                manifests[w.meta.namespaced_name] = (
                    [canon(to_jsonable(m)) for m in docs]
                    if docs
                    else None
                )
            return placements, manifests

        batched_state = plane_state()
        delta_works = sum(
            1 for w in cp.store.list("Work")
            if w.spec.workload_template is not None
            and w.spec.workload_template.digest
        )
        n_templates = len(cp.store.list("WorkloadTemplate"))
        os.environ["KARMADA_TPU_BUS_BATCH"] = "0"
        os.environ["KARMADA_TPU_BUS_TEMPLATE_DELTA"] = "0"
        unary_wall, _ = storm("unary")
        unary_state = plane_state()
        for k in ("KARMADA_TPU_BUS_BATCH", "KARMADA_TPU_BUS_TEMPLATE_DELTA"):
            if saved_env.get(k) is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = saved_env[k]
        parity = (
            batched_state[0] == unary_state[0]
            and batched_state[1] == unary_state[1]
        )
        print(
            f"# bus parity: batched wave {wall:.2f}s vs unary wave "
            f"{unary_wall:.2f}s ({unary_wall / wall if wall else 0:.1f}x), "
            f"plane state identical={parity} ({delta_works} template-delta "
            f"works over {n_templates} templates); top stitched phase "
            f"{top_phase[0]} {top_phase[1]:.2f}s",
            file=sys.stderr,
        )

        # ---- flight recorder: seeded breaker trip mid-wave -----------
        os.environ["KARMADA_TPU_FLIGHT_DIR"] = flight_dir
        os.environ["KARMADA_TPU_TRACE_SLO_SECONDS"] = "0.5"
        # seed the storm FIRST, then arm: the injections must hit the
        # CONTROLLERS' channel traffic mid-wave, not this driver's own
        # seed write. The solver errors mark passes degraded (in-proc
        # fallback); the bus errors burn the write path's 3 retry
        # attempts back-to-back, so the bus breaker TRIPS mid-wave
        # (threshold 3) and the wave's channel.breaker transition span
        # arms the recorder on its own
        clock[0] += 60
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name="st-storm-fault"),
            spec=WorkloadRebalancerSpec(workloads=[
                ObjectReferenceSelector(kind="Deployment", name=f"st{i}")
                for i in range(n)
            ]),
        ))
        faultinject.arm(
            "solver.rpc=error,count=6;bus.rpc=error,count=9",
            seed=args.chaos_seed,
        )
        fault_wall = settle_through_echoes()
        faultinject.disarm()
        del os.environ["KARMADA_TPU_TRACE_SLO_SECONDS"]
        flight_path = os.path.join(flight_dir, "flight.jsonl")
        records = (
            trc.load_flight_records(flight_path)
            if os.path.exists(flight_path)
            else []
        )
        fault_rec = next(
            (r for r in records
             if "breaker-transition" in r["reasons"]
             or "degraded-pass" in r["reasons"]),
            records[-1] if records else None,
        )
        analysis = trc.analyze_record(fault_rec) if fault_rec else {}
        flight_history = bool(
            (fault_rec or {}).get("history", {}) or {}
        ) and bool(fault_rec["history"].get("row"))
        print(
            f"# stitched fault wave: {fault_wall:.2f}s, "
            f"{len(records)} flight record(s), reasons "
            f"{fault_rec['reasons'] if fault_rec else []}, analyze "
            f"identical={analysis.get('identical')}, history context "
            f"attached={flight_history}",
            file=sys.stderr,
        )
        if analysis.get("table"):
            print(analysis["table"], file=sys.stderr)

        os.unlink(spec_f.name)
        return {
            "stitched_bindings": n,
            "stitched_clusters": c,
            "stitched_wall_s": round(wall, 4),
            "stitched_coverage_vs_wall": round(coverage, 4),
            "stitched": main,
            "stitched_waves_in_window": len(waves),
            # ISSUE 11: the columnar bus channel record — whole-plane
            # storm throughput over the REAL 4-process bus, the unary
            # re-run of the same storm (writes per-object, template
            # rendering full), and the plane-state parity verdict
            "stitched_bindings_s": round(n / wall, 1) if wall else None,
            "bus_unary_wall_s": round(unary_wall, 4),
            "bus_unary_vs_batched": (
                round(unary_wall / wall, 2) if wall else None
            ),
            "bus_parity_identical": parity,
            "bus_top_self_phase": top_phase[0],
            "bus_top_self_phase_s": round(top_phase[1], 4),
            "bus_template_delta_works": delta_works,
            "bus_templates": n_templates,
            "flight_recorded": bool(fault_rec),
            "flight_reasons": fault_rec["reasons"] if fault_rec else [],
            "flight_records": len(records),
            "flight_analyze_identical": analysis.get("identical"),
            # ISSUE 12: the record carries the breaching wave's history
            # row + recent-window digests; `trace analyze` renders the
            # breach-vs-recent table from them offline
            "flight_history_attached": flight_history,
            "flight_fault_wall_s": round(fault_wall, 4),
            # the recorder's disarmed steady-state (SLO env unset) is one
            # env read per wave boundary and zero per-span work — the
            # BENCH_r05 steady-storm path carries no recorder cost
            "recorder_disarmed_cost": "one env read per wave boundary",
        }
    finally:
        faultinject.disarm()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        trc.clear_peers()
        if solver_client is not None:
            solver_client.close()
        if replica is not None:
            replica.close()
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — teardown best-effort
                proc.kill()
        gc.collect()


# --------------------------------------------------------------------------
# --kernel-only: round-1 fused-kernel protocol (diagnostic)
# --------------------------------------------------------------------------


def run_kernel_only(args) -> dict:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from karmada_tpu.ops.divide import _divide_batch
    from karmada_tpu.ops.estimate import (
        gather_profile_rows,
        general_estimate,
        merge_estimates,
    )

    b_total, c, r = args.bindings, args.clusters, args.dims
    chunk = args.chunk
    n_chunks = (b_total + chunk - 1) // chunk
    dev = jax.devices()[0]
    print(f"# device: {dev.platform}:{dev.device_kind}", file=sys.stderr)

    key = jax.random.key(0)
    kcap, kfeas = jax.random.split(key)
    scales = jnp.asarray([512_000, 4 << 40, 5_500, 1 << 42], jnp.int64)[:r]
    available_cap = (
        jax.random.uniform(kcap, (c, r), minval=0.05, maxval=1.0)
        * scales[None, :].astype(jnp.float32)
    ).astype(jnp.int64)
    tainted = jax.random.uniform(kfeas, (c,)) < 0.08
    profiles = jnp.stack(
        [
            jnp.asarray([250, 1 << 29, 1, 1 << 30], jnp.int64)[:r] * (p + 1)
            for p in range(8)
        ]
    )
    i_bits = max(1, (c - 1).bit_length())
    fast = (12, 5, min(c, 128), True) if 12 + 5 + i_bits <= 31 else None

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    mesh = None
    if len(devs) > 1 and chunk % len(devs) == 0:
        mesh = Mesh(np.array(devs), ("b",))
        print(f"# mesh: {len(devs)} devices over the binding axis",
              file=sys.stderr)

    def shard_rows(*arrays):
        if mesh is None:
            return arrays
        out = []
        for a in arrays:
            spec = P("b", *([None] * (a.ndim - 1)))
            out.append(
                jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))
            )
        return tuple(out)

    def gen_chunk(i, tainted_arg):
        k = jax.random.fold_in(jax.random.key(42), i)
        k1, k2, k3, k4, k5, k6, k7 = jax.random.split(k, 7)
        replicas = jax.random.randint(k1, (chunk,), 1, 100, dtype=jnp.int32)
        prof_idx = jax.random.randint(k2, (chunk,), 0, 8)
        tolerates = jax.random.uniform(k3, (chunk, 1)) < 0.30
        candidates = ~tainted_arg[None, :] | tolerates
        has_prev = jax.random.uniform(k4, (chunk, 1)) < 0.7
        sites = jax.random.randint(k5, (chunk, 8), 0, c)
        cnts = jax.random.randint(k6, (chunk, 8), 1, 30, dtype=jnp.int32)
        prev0 = (
            jnp.zeros((chunk, c), jnp.int32)
            .at[jnp.arange(chunk)[:, None], sites]
            .set(cnts)
        )
        prev = jnp.where(has_prev & candidates, prev0, 0)
        fresh = jax.random.uniform(k7, (chunk,)) < 0.05
        strategy = jnp.full((chunk,), 2, jnp.int32)
        static_w = jnp.zeros((chunk, c), jnp.int32)
        return shard_rows(
            prof_idx, strategy, replicas, candidates, static_w, prev, fresh
        )

    per_profile = general_estimate(available_cap, profiles)

    def solve_chunk(i, table, tainted_arg):
        prof_idx, strategy, replicas, candidates, static_w, prev, fresh = (
            gen_chunk(i, tainted_arg)
        )
        general = gather_profile_rows(table, prof_idx)
        avail = merge_estimates(replicas, (general,))
        assignment, unsched = _divide_batch(
            strategy, replicas, candidates, static_w, avail, prev, fresh,
            False, False, fast,
        )
        placed = (assignment > 0).sum(axis=1).astype(jnp.int32)
        total = assignment.sum(axis=1).astype(jnp.int32)
        return placed, total, unsched

    @jax.jit
    def solve_all(table, tainted_arg):
        def body(carry, i):
            return carry, solve_chunk(i, table, tainted_arg)
        _, outs = lax.scan(body, 0, jnp.arange(n_chunks))
        return outs

    import contextlib

    times = []
    jax.block_until_ready((per_profile, tainted))
    jax.tree.map(np.asarray, solve_all(per_profile, tainted))
    trace_ctx = (
        jax.profiler.trace(args.trace_dir)
        if args.trace_dir
        else contextlib.nullcontext()
    )
    with trace_ctx:
        for rep in range(args.repeats):
            t0 = time.perf_counter()
            outs = solve_all(per_profile, tainted)
            outs = jax.tree.map(np.asarray, outs)
            t1 = time.perf_counter()
            times.append(t1 - t0)
            print(f"# pass {rep}: {t1 - t0:.3f}s", file=sys.stderr)
    p50 = float(np.median(times))
    unsched = outs[2].reshape(-1)[:b_total]
    print(
        f"# kernel-only: scheduled {int((~unsched).sum())}/{b_total}",
        file=sys.stderr,
    )
    return {
        "metric": f"p50_kernel_{b_total // 1000}kx{c}_dynamic_weight",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": 0.0,
    }


def run_multichip(args) -> dict:
    """The real multichip tier: the production ENGINE (fleet table +
    donated residents) sharded across a device mesh at every requested
    size, against the single-device engine as the identity reference.

    Measures per mesh size: steady storm p50 (decode included — the
    placements are the pass's product), per-pass host->device upload and
    device->host fetch bytes from the fleet breakdown, and a LIVE
    donation probe (the pre-pass resident buffer must be consumed by the
    next solve — the runtime face of graftlint IR005). On CPU rigs the
    forced host devices share one physical CPU, so the p50 curve proves
    identity/donation/transfer bounds, not speedup — the record carries
    that note for readers comparing against TPU slices."""
    import __graft_entry__ as graft

    sizes = [int(s) for s in args.mesh_sizes.split(",") if s.strip()]
    for s in sizes:
        if s & (s - 1):
            raise SystemExit(f"--mesh-sizes: {s} is not a power of two")
    # force the virtual CPU mesh BEFORE any jax import (XLA_FLAGS is
    # captured at jax import; KARMADA_TPU_DRYRUN_REAL_DEVICES=1 keeps a
    # real multi-chip backend instead)
    graft._force_cpu_platform(max(sizes))
    import jax

    from karmada_tpu.parallel.mesh import scheduling_mesh
    from karmada_tpu.scheduler import TensorScheduler

    b_total, c = args.bindings, args.clusters
    devs = jax.devices()
    print(
        f"# devices: {len(devs)} x {devs[0].platform}:{devs[0].device_kind}",
        file=sys.stderr,
    )
    w = build_headline_workload(b_total, c)
    problems = w.problems

    curve: dict = {}
    uploads: dict = {}
    fetches: dict = {}
    identical: dict = {}
    donated: dict = {}
    ref = None
    full_upload = None
    for m in sizes:
        key = str(m)
        mesh = scheduling_mesh(m) if m > 1 else False
        engine = TensorScheduler(
            w.snap, chunk_size=args.chunk, mesh=mesh, trace_manifest=""
        )
        first_bd: dict = {}

        def warm_pass(i, eng=engine, bd=first_bd):
            eng.schedule(problems)
            if i == 0:
                bd.update(eng._fleet.last_breakdown)

        settle_engine(
            engine, warm_pass, floor=2, cap=8, label=f"mesh={m} warm",
        )
        if full_upload is None:
            # the cold pass ships the whole packed grid: the bound the
            # steady-pass upload must stay well below
            full_upload = round(first_bd.get("upload_mb", 0.0), 6)
        # donation probe: the resident the table holds NOW must be
        # consumed (aliased, not copied) by the next pass's solve
        fleet = engine._fleet
        resident = (
            fleet._res_dense
            if fleet._res_dense is not None
            else fleet._resident_entries
        )
        engine.schedule(problems)
        donated[key] = bool(resident.is_deleted())
        times = []
        placements = None
        for rep in range(args.repeats):
            t0 = time.perf_counter()
            res = engine.schedule(problems)
            placements = [
                (dict(r.clusters), r.success) for r in res
            ]
            times.append(time.perf_counter() - t0)
            print(
                f"# mesh={m} pass {rep}: {times[-1]:.3f}s",
                file=sys.stderr,
            )
        bd = fleet.last_breakdown
        curve[key] = round(float(np.median(times)), 4)
        uploads[key] = round(bd.get("upload_mb", 0.0), 6)
        fetches[key] = round(bd.get("fetch_mb", 0.0), 6)
        if ref is None:
            ref = placements
            identical[key] = True
        else:
            identical[key] = placements == ref
        print(
            f"# mesh={m}: p50 {curve[key]}s identical={identical[key]} "
            f"donated={donated[key]} upload {uploads[key]:.4f}MB "
            f"fetch {fetches[key]:.4f}MB",
            file=sys.stderr,
        )
        del engine, fleet, resident, res
        gc.collect()

    return {
        "metric": f"multichip_scaling_{b_total // 1000}kx{c}",
        "value": curve[str(sizes[-1])],
        "unit": "s",
        # single-device p50 over the largest mesh's p50: >1 would be a
        # real speedup; ~1 on forced-host rigs (shared physical CPU)
        "vs_baseline": round(
            curve[str(sizes[0])] / max(curve[str(sizes[-1])], 1e-9), 2
        ),
        "mesh_sizes": sizes,
        "steady_p50_s": curve,
        "identical": identical,
        "donated": donated,
        "steady_upload_mb": uploads,
        "steady_fetch_mb": fetches,
        "full_grid_upload_mb": full_upload,
        "devices": len(devs),
        "platform": devs[0].platform,
        "note": (
            "real accelerator devices: the p50 curve is a genuine "
            "scaling measurement"
            if devs[0].platform != "cpu"
            else "forced host devices share one physical CPU: the curve "
            "proves placement identity, donation, and transfer bounds; "
            "real scaling needs a TPU slice"
        ),
    }


def run_sharded_kernel(args) -> dict:
    """2D-sharded kernel step (VERDICT r1 #6): shard the cluster axis over a
    ('b','c') mesh, verify placement identity against the unsharded step,
    and measure the sort-induced c-axis collective cost."""
    import jax
    import jax.numpy as jnp

    from karmada_tpu.parallel.solver import default_mesh, make_sharded_step, schedule_step

    b_mesh, _, c_mesh = args.shard.partition("x")
    b_mesh, c_mesh = int(b_mesh), int(c_mesh or 1)
    n_dev = b_mesh * c_mesh
    mesh = default_mesh(n_dev, cluster_axis=c_mesh, allow_cpu_fallback=True)
    print(f"# mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} on "
          f"{mesh.devices.flat[0].platform}", file=sys.stderr)

    b, c, r = args.bindings, args.clusters, args.dims
    rng = np.random.default_rng(0)
    scales = np.asarray([512_000, 4 << 40, 5_500, 1 << 42], np.int64)[:r]
    available_cap = (
        rng.uniform(0.05, 1.0, (c, r)) * scales[None, :]
    ).astype(np.int64)
    has_summary = np.ones(c, bool)
    requests = (
        np.asarray([250, 1 << 29, 1, 1 << 30], np.int64)[:r]
        * (rng.integers(1, 9, b))[:, None]
    )
    strategy = np.full(b, 2, np.int32)
    replicas = rng.integers(1, 100, b).astype(np.int32)
    candidates = rng.random((b, c)) < 0.9
    static_w = np.zeros((b, c), np.int32)
    prev = np.where(
        rng.random((b, c)) < 8.0 / c, rng.integers(1, 30, (b, c)), 0
    ).astype(np.int32)
    fresh = rng.random(b) < 0.05
    inputs = (available_cap, has_summary, requests, strategy, replicas,
              candidates, static_w, prev, fresh)
    statics = (False, False, None)  # has_aggregated, wide, fast

    sharded = make_sharded_step(mesh, shard_clusters=c_mesh > 1)
    ref = np.asarray(schedule_step(*inputs, *statics).assignment)
    out = sharded(*inputs, *statics)
    got = np.asarray(out.assignment)
    identical = bool(np.array_equal(ref, got))
    print(f"# identity under {args.shard} sharding: {identical}", file=sys.stderr)

    times = []
    for rep in range(args.repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(sharded(*inputs, *statics))
        times.append(time.perf_counter() - t0)
        print(f"# pass {rep}: {times[-1]:.3f}s", file=sys.stderr)
    t0 = time.perf_counter()
    jax.block_until_ready(schedule_step(*inputs, *statics))
    t_unsharded = time.perf_counter() - t0
    p50 = float(np.median(times))
    print(f"# unsharded single-device: {t_unsharded:.3f}s", file=sys.stderr)
    return {
        "metric": f"p50_sharded_{args.shard}_{b}x{c}",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(t_unsharded / p50, 2) if p50 else 0.0,
        "identical": identical,
    }


def main():
    args = build_parser().parse_args()
    if args.check:
        # the guard is pure JSON comparison — no jax, no plane; it must
        # stay runnable on a laptop that cannot build an engine
        import os

        repo_root = os.path.dirname(os.path.abspath(__file__))
        if repo_root not in sys.path:
            sys.path.insert(0, repo_root)
        from tools.benchguard import main as benchguard_main

        sys.exit(benchguard_main([args.check, "--root", repo_root]))
    # per-tier default scale (see build_parser): explicit flags always win
    if args.bindings is None:
        args.bindings = (
            20_000
            if (args.observability or args.chaos or args.quota
                or args.multichip or args.preemption)
            else 100_000
        )
    if args.clusters is None:
        args.clusters = (
            512
            if (args.observability or args.chaos or args.quota
                or args.multichip or args.preemption)
            else 5_000
        )
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.cold_child:
        print(json.dumps(run_cold_child(args)))
        return
    if args.cold_start:
        print(json.dumps(run_cold_start(args)))
        return
    if args.observability:
        print(json.dumps(run_observability(args)))
        return
    if args.chaos:
        print(json.dumps(run_chaos(args)))
        return
    if args.quota:
        print(json.dumps(run_quota(args)))
        return
    if args.preemption:
        print(json.dumps(run_preemption(args)))
        return
    if args.multichip:
        print(json.dumps(run_multichip(args)))
        return
    if args.estimator_only:
        tier_status: dict = {}
        record = run_estimator_tier(args, tier_status)
        if tier_status:
            record["tiers"] = tier_status
        print(json.dumps(record))
        return
    if args.config != 5:
        print(json.dumps(run_engine_config(args.config)))
        return
    if args.shard:
        print(json.dumps(run_sharded_kernel(args)))
        return
    if args.kernel_only:
        print(json.dumps(run_kernel_only(args)))
        return
    print(json.dumps(run_engine_north_star(args)))


if __name__ == "__main__":
    main()
