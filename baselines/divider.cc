// Native (C++) re-execution of the reference's replica-division hot path,
// per-binding, for baseline calibration (VERDICT r3 item 9: no Go
// toolchain in this image; C++ -O2 stands in for the in-tree Go divider).
//
// Semantics mirrored from pkg/scheduler/core/{assignment.go:208-239,
// division_algorithm.go:75-152} and pkg/util/helper/binding.go:112-144:
// per binding, the dynamic-weight division selects a cohort
// (steady scale-up / scale-down / fresh / no-op), checks availability
// (division_algorithm.go:76-78), and dispenses by largest remainder over a
// (weight desc, lastReplicas desc, index asc) sorted candidate list —
// exactly the per-binding loop shape the Go scheduler runs, including the
// O(C log C) sort per binding.
//
// stdin/stdout-free: reads a compact binary workload (see bench_cpp.py),
// writes a (site,count) entry stream; prints ONE line with the pure
// division wall time (input expansion and IO excluded).

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#pragma pack(push, 1)
struct Binding {
  uint8_t profile;
  uint8_t replicas;
  uint8_t tolerates;
  uint8_t fresh;
  uint8_t n_prev;
  uint16_t prev_site[8];
  uint8_t prev_count[8];
};
#pragma pack(pop)

struct Cand {
  int32_t weight;
  int32_t last;
  int32_t idx;
};

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: divider <input.bin> <output.bin> [--interned]\n");
    return 2;
  }
  // --interned: use the precomputed per-profile availability table (the
  // TPU engine's own interning optimization, NOT something the reference
  // does — calAvailableReplicas runs per binding per attempt,
  // core/util.go:54-104). Default = faithful per-binding estimation.
  bool interned = argc > 3 && std::strcmp(argv[3], "--interned") == 0;
  FILE* in = std::fopen(argv[1], "rb");
  if (!in) return 2;
  uint32_t B, C, P, R;
  if (std::fread(&B, 4, 1, in) != 1) return 2;
  if (std::fread(&C, 4, 1, in) != 1) return 2;
  if (std::fread(&P, 4, 1, in) != 1) return 2;
  if (std::fread(&R, 4, 1, in) != 1) return 2;
  std::vector<int32_t> avail((size_t)P * C);  // per (profile, cluster)
  if (std::fread(avail.data(), 4, avail.size(), in) != avail.size()) return 2;
  std::vector<int64_t> capacity((size_t)C * R);  // free capacity per cluster
  if (std::fread(capacity.data(), 8, capacity.size(), in) != capacity.size())
    return 2;
  std::vector<int64_t> requests((size_t)P * R);  // per-profile request vector
  if (std::fread(requests.data(), 8, requests.size(), in) != requests.size())
    return 2;
  std::vector<uint8_t> tainted(C);
  if (std::fread(tainted.data(), 1, C, in) != C) return 2;
  std::vector<Binding> bindings(B);
  if (std::fread(bindings.data(), sizeof(Binding), B, in) != B) return 2;
  std::fclose(in);
  std::vector<int32_t> av_row(C);

  std::vector<int32_t> out_entries;       // (site << 8 | count), row-major
  std::vector<int32_t> out_counts(B, 0);  // entries per binding (-1 = unsched)
  out_entries.reserve((size_t)B * 8);

  std::vector<Cand> cands;
  cands.reserve(C);
  std::vector<int32_t> prev_full(C);
  std::vector<int32_t> result(C);

  auto t0 = std::chrono::steady_clock::now();
  for (uint32_t i = 0; i < B; i++) {
    const Binding& b = bindings[i];
    const int32_t* av;
    if (interned) {
      av = &avail[(size_t)b.profile * C];
    } else {
      // the reference's calAvailableReplicas data flow: estimate per
      // binding per cluster from capacity / request (general.go:156-196 —
      // per-resource max division), exactly as the Go scheduler recomputes
      // it on every scheduling attempt
      const int64_t* req = &requests[(size_t)b.profile * R];
      for (uint32_t j = 0; j < C; j++) {
        int64_t best = INT32_MAX;
        const int64_t* cap = &capacity[(size_t)j * R];
        for (uint32_t d = 0; d < R; d++) {
          if (req[d] <= 0) continue;
          int64_t c64 = cap[d] < 0 ? 0 : cap[d];
          int64_t v = c64 / req[d];
          if (v < best) best = v;
        }
        av_row[j] = (int32_t)(best > INT32_MAX ? INT32_MAX : best);
      }
      av = av_row.data();
    }

    // previous assignment (spec.clusters), full — scale-down dispenses over
    // it even where the cluster is no longer a candidate
    std::memset(prev_full.data(), 0, C * 4);
    long assigned = 0;  // sum of prev on CANDIDATE clusters
    for (int k = 0; k < b.n_prev; k++) prev_full[b.prev_site[k]] = b.prev_count[k];

    // findClustersThatFit: taint/toleration filter (already-placed leniency)
    cands.clear();
    for (uint32_t j = 0; j < C; j++) {
      bool feas = (!tainted[j] || b.tolerates || prev_full[j] > 0);
      if (feas && prev_full[j] > 0) assigned += prev_full[j];
      if (feas) cands.push_back({av[j], 0, (int32_t)j});
    }
    int32_t N = b.replicas;
    if (cands.empty()) { out_counts[i] = -1; continue; }

    // cohort selection (assignment.go:208-239)
    bool fresh = b.fresh;
    bool scale_down = !fresh && assigned > N;
    bool scale_up = !fresh && assigned < N;
    std::memset(result.data(), 0, C * 4);

    long target = N;
    if (!fresh && assigned == N) {  // steady no-op: keep previous
      int n = 0;
      for (auto& cd : cands)
        if (prev_full[cd.idx] > 0) { result[cd.idx] = prev_full[cd.idx]; n++; }
      out_counts[i] = n;
      for (auto& cd : cands)
        if (result[cd.idx] > 0)
          out_entries.push_back((cd.idx << 8) | result[cd.idx]);
      continue;
    }
    if (scale_up) target = N - assigned;

    // weights + init by cohort (division_algorithm.go:101-152). Scale-down
    // dispenses over the FULL previous assignment — including clusters no
    // longer candidates (division_algorithm.go:101-117 quirk).
    long wsum = 0;
    if (scale_down) {
      cands.clear();
      for (uint32_t j = 0; j < C; j++)
        if (prev_full[j] > 0) cands.push_back({prev_full[j], 0, (int32_t)j});
      for (auto& cd : cands) wsum += cd.weight;
    } else {
      for (auto& cd : cands) {
        int32_t w;
        if (fresh) w = av[cd.idx] + (prev_full[cd.idx] > 0 ? prev_full[cd.idx] : 0);
        else w = av[cd.idx];
        cd.weight = w;
        cd.last = scale_up && prev_full[cd.idx] > 0 ? prev_full[cd.idx] : 0;
        if (scale_up && prev_full[cd.idx] > 0) result[cd.idx] = prev_full[cd.idx];
        wsum += w;
      }
    }
    if (wsum < target) { out_counts[i] = -1; continue; }  // unschedulable

    // Dispenser.TakeByWeight (binding.go:112-144): floors, then +1 down the
    // (weight desc, last desc, index asc) sorted list
    if (wsum > 0 && target > 0) {
      std::sort(cands.begin(), cands.end(), [](const Cand& a, const Cand& b2) {
        if (a.weight != b2.weight) return a.weight > b2.weight;
        if (a.last != b2.last) return a.last > b2.last;
        return a.idx < b2.idx;
      });
      long remain = target;
      for (auto& cd : cands) {
        long fl = (long)cd.weight * target / wsum;
        result[cd.idx] += (int32_t)fl;
        remain -= fl;
      }
      for (auto& cd : cands) {
        if (remain <= 0) break;
        if (cd.weight > 0) { result[cd.idx] += 1; remain--; }
      }
    }
    int n = 0;
    for (uint32_t j = 0; j < C; j++)
      if (result[j] > 0) { out_entries.push_back(((int32_t)j << 8) | result[j]); n++; }
    out_counts[i] = n;
  }
  auto t1 = std::chrono::steady_clock::now();
  double secs = std::chrono::duration<double>(t1 - t0).count();

  FILE* out = std::fopen(argv[2], "wb");
  uint32_t total = (uint32_t)out_entries.size();
  std::fwrite(&total, 4, 1, out);
  std::fwrite(out_counts.data(), 4, B, out);
  std::fwrite(out_entries.data(), 4, total, out);
  std::fclose(out);
  std::printf("{\"divider_cpp_seconds\": %.4f, \"bindings\": %u}\n", secs, B);
  return 0;
}
