"""Baseline calibration: native C++ divider vs numpy divider vs engine.

The north-star target names the in-tree Go divider; no Go toolchain exists
in this image, so ``divider.cc`` (g++ -O2) re-executes the reference's
per-binding division loop — same data flow as the Go scheduler: per
binding, filter candidates, pick the cohort, sort the candidate list,
largest-remainder dispense. This script generates the EXACT config-5
workload (same RNG streams as bench.py), feeds it to the native binary,
verifies placement identity against the numpy divider on every row, and
prints the calibration ratios.

Run: python baselines/calibrate.py [--bindings 100000 --clusters 5000]
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--bindings", type=int, default=100_000)
    p.add_argument("--clusters", type=int, default=5_000)
    p.add_argument("--skip-numpy", action="store_true")
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from karmada_tpu.refimpl.divider_np import assign_batch_np
    from karmada_tpu.scheduler import ClusterSnapshot, TensorScheduler
    from karmada_tpu.utils.builders import synthetic_fleet
    from karmada_tpu.utils.quantity import parse_resource_list

    b_total, c = args.bindings, args.clusters
    # ---- same workload as bench.py config 5 -------------------------------
    clusters = synthetic_fleet(c, seed=7, taint_fraction=0.08)
    snap = ClusterSnapshot(clusters)
    profiles_req = [
        parse_resource_list(
            {"cpu": f"{250 * (q + 1)}m", "memory": f"{512 * (q + 1)}Mi"}
        )
        for q in range(8)
    ]
    rng = np.random.default_rng(42)
    replicas = rng.integers(1, 100, b_total).astype(np.uint8)
    prof_idx = rng.integers(0, 8, b_total).astype(np.uint8)
    tol_mask = (rng.random(b_total) < 0.30).astype(np.uint8)
    has_prev = rng.random(b_total) < 0.7
    prev_sites = rng.integers(0, c, (b_total, 8)).astype(np.uint16)
    prev_counts = rng.integers(1, 30, (b_total, 8)).astype(np.uint8)
    n_prev = rng.integers(1, 9, b_total).astype(np.uint8)
    fresh = (rng.random(b_total) < 0.05).astype(np.uint8)
    n_prev = np.where(has_prev, n_prev, 0).astype(np.uint8)

    # per-(profile, cluster) availability: the engine's estimator table
    # WITHOUT the per-binding replica clamp (the C++ side applies the
    # reference's min-merge semantics per binding via weights; for dynamic
    # weight the clamp only matters via the MAX_INT32 sentinel, absent here)
    eng = TensorScheduler(snap)
    dims = snap.dims
    prof_rows = np.zeros((8, len(dims)), np.int64)
    for q, req in enumerate(profiles_req):
        for d, v in req.items():
            j = dims.index(d) if d in dims else None
            if j is not None:
                prof_rows[q, j] = v
        if "pods" in dims:
            prof_rows[q, dims.index("pods")] = max(
                prof_rows[q, dims.index("pods")], 1
            )
    table = np.asarray(eng._profile_table(prof_rows)).astype(np.int32)  # [8, C]
    tainted = np.zeros(c, np.uint8)
    for j, cl in enumerate(clusters):
        tainted[j] = any(t.key == "fleet.io/dedicated" for t in cl.spec.taints)

    # ---- write compact workload ------------------------------------------
    tmp = tempfile.mkdtemp(prefix="divider-cal-")
    inp, outp = os.path.join(tmp, "in.bin"), os.path.join(tmp, "out.bin")
    rec = np.zeros(
        b_total,
        dtype=np.dtype(
            [
                ("profile", np.uint8), ("replicas", np.uint8),
                ("tolerates", np.uint8), ("fresh", np.uint8),
                ("n_prev", np.uint8),
                ("prev_site", np.uint16, (8,)), ("prev_count", np.uint8, (8,)),
            ],
            align=False,
        ),
    )
    rec["profile"] = prof_idx
    rec["replicas"] = replicas
    rec["tolerates"] = tol_mask
    rec["fresh"] = fresh
    rec["n_prev"] = n_prev
    rec["prev_site"] = prev_sites
    rec["prev_count"] = prev_counts
    capacity = np.asarray(snap.available_cap, np.int64)  # [C, R] free cap
    with open(inp, "wb") as f:
        f.write(struct.pack("<IIII", b_total, c, 8, capacity.shape[1]))
        f.write(table.astype("<i4").tobytes())
        f.write(capacity.astype("<i8").tobytes())
        f.write(prof_rows.astype("<i8").tobytes())
        f.write(tainted.tobytes())
        f.write(rec.tobytes())

    # ---- run the native divider ------------------------------------------
    binary = os.path.join(os.path.dirname(os.path.abspath(__file__)), "divider")
    if not os.path.exists(binary):
        subprocess.run(
            ["g++", "-O2", "-o", binary, binary + ".cc"], check=True
        )
    out = subprocess.run(
        [binary, inp, outp], capture_output=True, text=True, check=True
    )
    stats = json.loads(out.stdout)
    t_cpp = stats["divider_cpp_seconds"]
    print(
        f"# C++ divider (faithful per-binding estimation): {t_cpp:.2f}s "
        f"for {b_total} bindings", file=sys.stderr,
    )
    out_i = subprocess.run(
        [binary, inp, outp + ".interned", "--interned"],
        capture_output=True, text=True, check=True,
    )
    t_cpp_interned = json.loads(out_i.stdout)["divider_cpp_seconds"]
    print(
        f"# C++ divider (+engine's profile interning): {t_cpp_interned:.2f}s",
        file=sys.stderr,
    )

    # ---- verify identity vs the numpy divider ----------------------------
    with open(outp, "rb") as f:
        total = struct.unpack("<I", f.read(4))[0]
        counts = np.frombuffer(f.read(4 * b_total), np.int32)
        entries = np.frombuffer(f.read(4 * total), np.int32)

    t_np = 0.0
    mismatches = 0
    checked = 0
    if not args.skip_numpy:
        starts = np.zeros(b_total, np.int64)
        np.cumsum(np.maximum(counts[:-1], 0), out=starts[1:])
        chunk = 8192
        for s in range(0, b_total, chunk):
            e = min(s + chunk, b_total)
            n = e - s
            feasible = (~tainted.astype(bool))[None, :] | tol_mask[s:e, None].astype(bool)
            prev = np.zeros((n, c), np.int32)
            rows = np.arange(n)[:, None]
            ks = np.arange(8)[None, :]
            sel = ks < n_prev[s:e, None]
            prev[rows.repeat(8, 1)[sel], prev_sites[s:e][sel].astype(np.int64)] = (
                prev_counts[s:e][sel]
            )
            feasible |= prev > 0
            avail = table[prof_idx[s:e]].astype(np.int32)
            reps = replicas[s:e].astype(np.int32)
            avail = np.minimum(
                np.where(avail == 2**31 - 1, reps[:, None], avail), 2**31 - 1
            ).astype(np.int32)
            strategy = np.full(n, 2, np.int32)
            static_w = np.zeros((n, c), np.int32)
            t0 = time.perf_counter()
            got, unsched = assign_batch_np(
                strategy, reps, feasible, static_w, avail, prev,
                fresh[s:e].astype(bool),
            )
            t_np += time.perf_counter() - t0
            for k in range(n):
                i = s + k
                if counts[i] == -1:
                    ok = bool(unsched[k]) or not feasible[k].any()
                else:
                    ent = entries[starts[i] : starts[i] + counts[i]]
                    mine = {int(x) >> 8: int(x) & 0xFF for x in ent}
                    ref = {
                        int(j): int(got[k, j]) for j in np.flatnonzero(got[k])
                    }
                    ok = mine == ref and not unsched[k]
                mismatches += not ok
                checked += 1
        print(
            f"# identity vs numpy divider: {checked - mismatches}/{checked}",
            file=sys.stderr,
        )
        print(
            f"# numpy divider wall: {t_np:.2f}s -> numpy/C++ ratio "
            f"{t_np / max(t_cpp, 1e-9):.2f}x",
            file=sys.stderr,
        )
    # persist the calibration so bench.py can report an estimated
    # vs-native multiple alongside vs_numpy
    cal_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "CALIBRATION.json"
    )
    with open(cal_path, "w") as f:
        json.dump(
            {
                "bindings": b_total,
                "clusters": c,
                "cpp_seconds": round(t_cpp, 4),
                "cpp_interned_seconds": round(t_cpp_interned, 4),
                "numpy_seconds": round(t_np, 4),
                "numpy_over_cpp": round(t_np / max(t_cpp, 1e-9), 3),
                "verified_rows": checked,
                "verified_mismatches": mismatches,
            },
            f,
            indent=1,
        )
    print(
        json.dumps(
            {
                "metric": "divider_cpp_baseline",
                "value": round(t_cpp, 4),
                "unit": "s",
                "cpp_interned_seconds": round(t_cpp_interned, 4),
                "numpy_seconds": round(t_np, 4),
                "numpy_over_cpp": round(t_np / max(t_cpp, 1e-9), 2),
                "verified_rows": checked,
                "verified_mismatches": mismatches,
            }
        )
    )


if __name__ == "__main__":
    main()
