"""Admission chain tests (ref: pkg/webhook validating/mutating handlers)."""

import pytest

from karmada_tpu.api import (
    ClusterAffinity,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
    SpreadConstraint,
)
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    ClusterAffinityTerm,
    ClusterPreferences,
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
    StaticClusterAssignment,
    StaticClusterWeight,
)
from karmada_tpu.webhook import ValidationError, default_admission_chain
from karmada_tpu.webhook.chain import PERMANENT_ID_ANNOTATION


def make_policy(placement=None, selectors=None):
    return PropagationPolicy(
        meta=ObjectMeta(name="p", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=selectors
            if selectors is not None
            else [ResourceSelector(api_version="apps/v1", kind="Deployment")],
            placement=placement or Placement(),
        ),
    )


@pytest.fixture
def chain():
    return default_admission_chain()


class TestMutation:
    def test_permanent_id_and_defaults(self, chain):
        policy = make_policy(
            Placement(spread_constraints=[SpreadConstraint(spread_by_field="cluster",
                                                           min_groups=0, max_groups=3)])
        )
        chain.admit("PropagationPolicy", policy)
        assert PERMANENT_ID_ANNOTATION in policy.meta.annotations
        assert policy.spec.placement.spread_constraints[0].min_groups == 1
        assert policy.spec.scheduler_name == "default-scheduler"


class TestValidation:
    def test_empty_selectors_rejected(self, chain):
        with pytest.raises(ValidationError, match="resourceSelectors"):
            chain.admit("PropagationPolicy", make_policy(selectors=[]))

    def test_affinity_exclusive(self, chain):
        pl = Placement(
            cluster_affinity=ClusterAffinity(cluster_names=["a"]),
            cluster_affinities=[ClusterAffinityTerm(affinity_name="g1")],
        )
        with pytest.raises(ValidationError, match="mutually exclusive"):
            chain.admit("PropagationPolicy", make_policy(pl))

    def test_duplicate_affinity_names(self, chain):
        pl = Placement(
            cluster_affinities=[
                ClusterAffinityTerm(affinity_name="g"),
                ClusterAffinityTerm(affinity_name="g"),
            ]
        )
        with pytest.raises(ValidationError, match="unique"):
            chain.admit("PropagationPolicy", make_policy(pl))

    def test_max_groups_lt_min_rejected(self, chain):
        pl = Placement(
            spread_constraints=[
                SpreadConstraint(spread_by_field="region", min_groups=3, max_groups=1)
            ]
        )
        with pytest.raises(ValidationError, match="maxGroups"):
            chain.admit("PropagationPolicy", make_policy(pl))

    def test_zero_static_weight_rejected(self, chain):
        pl = Placement(
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type="Divided",
                replica_division_preference="Weighted",
                weight_preference=ClusterPreferences(
                    static_weight_list=[
                        StaticClusterWeight(
                            target_cluster=ClusterAffinity(cluster_names=["a"]),
                            weight=0,
                        )
                    ]
                ),
            )
        )
        with pytest.raises(ValidationError, match="weights"):
            chain.admit("PropagationPolicy", make_policy(pl))

    def test_quota_over_assignment_rejected(self, chain):
        frq = FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="default"),
            spec=FederatedResourceQuotaSpec(
                overall={"cpu": 1000},
                static_assignments=[
                    StaticClusterAssignment(cluster_name="m1", hard={"cpu": 800}),
                    StaticClusterAssignment(cluster_name="m2", hard={"cpu": 800}),
                ],
            ),
        )
        with pytest.raises(ValidationError, match="exceed"):
            chain.admit("FederatedResourceQuota", frq)

    def test_store_integration_rejects(self, chain):
        from karmada_tpu.utils import Store

        store = Store(admission=chain.admit)
        with pytest.raises(ValidationError):
            store.apply(make_policy(selectors=[]))
        assert store.list("PropagationPolicy") == []
