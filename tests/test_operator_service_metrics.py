"""Operator workflow engine, estimator service contract, metrics registry."""

import numpy as np
import pytest

from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.estimator import AccurateEstimator, NodeSnapshot, NodeState
from karmada_tpu.estimator.service import (
    EstimatorClientPool,
    EstimatorService,
    MaxAvailableReplicasRequest,
    UnschedulableReplicasRequest,
)
from karmada_tpu.operator import (
    Job,
    Karmada,
    KarmadaOperator,
    KarmadaSpec,
    Task,
    WorkflowError,
)
from karmada_tpu.operator.karmada_operator import KarmadaComponents
from karmada_tpu.utils.metrics import Registry
from karmada_tpu.utils.quantity import parse_resource_list

DIMS = ["cpu", "memory", "pods", "ephemeral-storage"]


class TestWorkflow:
    def test_ordered_execution_with_subtasks(self):
        seen = []
        job = Job(
            tasks=[
                Task(name="a", run=lambda d: seen.append("a"),
                     tasks=[Task(name="a.1", run=lambda d: seen.append("a.1"))]),
                Task(name="b", run=lambda d: seen.append("b")),
            ]
        )
        job.run()
        assert seen == ["a", "a.1", "b"]
        assert job.completed == ["a", "a.1", "b"]

    def test_skip_gate_skips_children(self):
        seen = []
        job = Job(
            tasks=[
                Task(name="a", skip=lambda d: True, run=lambda d: seen.append("a"),
                     tasks=[Task(name="a.1", run=lambda d: seen.append("a.1"))]),
            ]
        )
        job.run()
        assert seen == []

    def test_failure_propagates(self):
        def boom(d):
            raise RuntimeError("nope")

        job = Job(tasks=[Task(name="bad", run=boom)])
        with pytest.raises(WorkflowError, match="bad"):
            job.run()


class TestKarmadaOperator:
    def test_install_and_deinit(self):
        op = KarmadaOperator()
        karmada = Karmada(
            meta=ObjectMeta(name="prod"),
            spec=KarmadaSpec(
                components=KarmadaComponents(descheduler=True),
                member_clusters=["m1", "m2"],
            ),
        )
        cp = op.reconcile(karmada)
        assert any(c.type == "Ready" and c.status for c in karmada.status.conditions)
        assert "join-members" in karmada.status.completed_tasks
        assert {c.name for c in cp.store.list("Cluster")} == {"m1", "m2"}
        assert cp.descheduler is not None
        op.deinit(karmada)
        assert not any(
            c.type == "Ready" and c.status for c in karmada.status.conditions
        )


class TestEstimatorService:
    def _service(self):
        nodes = [
            NodeState(
                name="n0",
                allocatable=parse_resource_list(
                    {"cpu": "8", "memory": "32Gi", "pods": 110}
                ),
            )
        ]
        est = AccurateEstimator("m1", NodeSnapshot(nodes, DIMS))
        est.unschedulable["default/web"] = 3
        return EstimatorService(est)

    def test_max_available_replicas(self):
        svc = self._service()
        resp = svc.max_available_replicas(
            MaxAvailableReplicasRequest(
                cluster="m1",
                resource_request=parse_resource_list({"cpu": "2", "pods": 1}),
            )
        )
        assert resp.max_replicas == 4

    def test_unschedulable_replicas(self):
        svc = self._service()
        resp = svc.get_unschedulable_replicas(
            UnschedulableReplicasRequest(cluster="m1", namespace="default", name="web")
        )
        assert resp.unschedulable_replicas == 3

    def test_pool_fanout_with_missing_cluster(self):
        svc = self._service()
        pool = EstimatorClientPool(
            resolver=lambda name: svc if name == "m1" else None
        )
        out = pool.max_available_replicas(
            ["m1", "ghost"], parse_resource_list({"cpu": "2", "pods": 1})
        )
        assert out == {"m1": 4, "ghost": -1}


class TestMetrics:
    def test_counter_and_histogram_render(self):
        reg = Registry()
        c = reg.counter("requests_total")
        h = reg.histogram("latency_seconds", buckets=(0.1, 1.0))
        c.inc(result="ok")
        c.inc(result="ok")
        h.observe(0.05)
        h.observe(0.5)
        text = reg.render()
        assert 'requests_total{result="ok"} 2.0' in text
        assert 'latency_seconds_bucket{le="0.1"} 1' in text
        assert 'latency_seconds_bucket{le="1.0"} 2' in text
        assert "latency_seconds_count 2" in text
        assert h.summary()["count"] == 2

    def test_scheduler_step_timers_populate(self):
        from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
        from karmada_tpu.utils.builders import duplicated_placement, new_cluster
        from karmada_tpu.utils.metrics import scheduling_algorithm_duration

        sched = TensorScheduler(ClusterSnapshot([new_cluster("m1")]))
        sched.schedule(
            [BindingProblem(key="b", placement=duplicated_placement(), replicas=1,
                            gvk="apps/v1/Deployment")]
        )
        assert (
            scheduling_algorithm_duration.summary(schedule_step="AssignReplicas")[
                "count"
            ]
            >= 1
        )
