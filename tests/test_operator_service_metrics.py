"""Operator workflow engine, estimator service contract, metrics registry."""

import numpy as np
import pytest

from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.estimator import AccurateEstimator, NodeSnapshot, NodeState
from karmada_tpu.estimator.service import (
    EstimatorClientPool,
    EstimatorService,
    MaxAvailableReplicasRequest,
    UnschedulableReplicasRequest,
)
from karmada_tpu.operator import (
    Job,
    Karmada,
    KarmadaOperator,
    KarmadaSpec,
    Task,
    WorkflowError,
)
from karmada_tpu.operator.karmada_operator import ComponentSpec, KarmadaComponents
from karmada_tpu.utils.metrics import Registry
from karmada_tpu.utils.quantity import parse_resource_list

DIMS = ["cpu", "memory", "pods", "ephemeral-storage"]


class TestWorkflow:
    def test_ordered_execution_with_subtasks(self):
        seen = []
        job = Job(
            tasks=[
                Task(name="a", run=lambda d: seen.append("a"),
                     tasks=[Task(name="a.1", run=lambda d: seen.append("a.1"))]),
                Task(name="b", run=lambda d: seen.append("b")),
            ]
        )
        job.run()
        assert seen == ["a", "a.1", "b"]
        assert job.completed == ["a", "a.1", "b"]

    def test_skip_gate_skips_children(self):
        seen = []
        job = Job(
            tasks=[
                Task(name="a", skip=lambda d: True, run=lambda d: seen.append("a"),
                     tasks=[Task(name="a.1", run=lambda d: seen.append("a.1"))]),
            ]
        )
        job.run()
        assert seen == []

    def test_failure_propagates(self):
        def boom(d):
            raise RuntimeError("nope")

        job = Job(tasks=[Task(name="bad", run=boom)])
        with pytest.raises(WorkflowError, match="bad"):
            job.run()


class TestKarmadaOperator:
    def test_install_and_deinit(self):
        op = KarmadaOperator()
        karmada = Karmada(
            meta=ObjectMeta(name="prod"),
            spec=KarmadaSpec(
                components=KarmadaComponents(descheduler=ComponentSpec(enabled=True)),
                member_clusters=["m1", "m2"],
            ),
        )
        cp = op.reconcile(karmada)
        assert any(c.type == "Ready" and c.status for c in karmada.status.conditions)
        assert "join-members" in karmada.status.completed_tasks
        assert {c.name for c in cp.store.list("Cluster")} == {"m1", "m2"}
        assert cp.descheduler is not None
        op.deinit(karmada)
        assert not any(
            c.type == "Ready" and c.status for c in karmada.status.conditions
        )


class TestEstimatorService:
    def _service(self):
        nodes = [
            NodeState(
                name="n0",
                allocatable=parse_resource_list(
                    {"cpu": "8", "memory": "32Gi", "pods": 110}
                ),
            )
        ]
        est = AccurateEstimator("m1", NodeSnapshot(nodes, DIMS))
        est.unschedulable["default/web"] = 3
        return EstimatorService(est)

    def test_max_available_replicas(self):
        svc = self._service()
        resp = svc.max_available_replicas(
            MaxAvailableReplicasRequest(
                cluster="m1",
                resource_request=parse_resource_list({"cpu": "2", "pods": 1}),
            )
        )
        assert resp.max_replicas == 4

    def test_unschedulable_replicas(self):
        svc = self._service()
        resp = svc.get_unschedulable_replicas(
            UnschedulableReplicasRequest(cluster="m1", namespace="default", name="web")
        )
        assert resp.unschedulable_replicas == 3

    def test_pool_fanout_with_missing_cluster(self):
        svc = self._service()
        pool = EstimatorClientPool(
            resolver=lambda name: svc if name == "m1" else None
        )
        out = pool.max_available_replicas(
            ["m1", "ghost"], parse_resource_list({"cpu": "2", "pods": 1})
        )
        assert out == {"m1": 4, "ghost": -1}


class TestMetrics:
    def test_counter_and_histogram_render(self):
        reg = Registry()
        c = reg.counter("requests_total")
        h = reg.histogram("latency_seconds", buckets=(0.1, 1.0))
        c.inc(result="ok")
        c.inc(result="ok")
        h.observe(0.05)
        h.observe(0.5)
        text = reg.render()
        assert 'requests_total{result="ok"} 2.0' in text
        assert 'latency_seconds_bucket{le="0.1"} 1' in text
        assert 'latency_seconds_bucket{le="1.0"} 2' in text
        assert "latency_seconds_count 2" in text
        assert h.summary()["count"] == 2

    def test_scheduler_step_timers_populate(self):
        from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
        from karmada_tpu.utils.builders import duplicated_placement, new_cluster
        from karmada_tpu.utils.metrics import scheduling_algorithm_duration

        sched = TensorScheduler(ClusterSnapshot([new_cluster("m1")]))
        sched.schedule(
            [BindingProblem(key="b", placement=duplicated_placement(), replicas=1,
                            gvk="apps/v1/Deployment")]
        )
        assert (
            scheduling_algorithm_duration.summary(schedule_step="AssignReplicas")[
                "count"
            ]
            >= 1
        )


class TestOperatorLifecycle:
    """install -> reconfigure (upgrade reconcile) -> failure path -> deinit
    (VERDICT r1 #10 done-criterion)."""

    def _cr(self):
        return Karmada(
            meta=ObjectMeta(name="plane", generation=1),
            spec=KarmadaSpec(member_clusters=["m1", "m2"]),
        )

    def test_install_reconfigure_deinit(self):
        from karmada_tpu.utils.builders import new_deployment
        from karmada_tpu.api import (
            PropagationPolicy, PropagationSpec, ResourceSelector,
        )
        from karmada_tpu.utils.builders import duplicated_placement

        op = KarmadaOperator()
        karmada = self._cr()
        cp = op.reconcile(karmada)
        assert karmada.status.observed_generation == 1
        assert karmada.status.installed_version == karmada.spec.version
        assert cp.descheduler is None

        # the installed plane actually propagates
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment")],
                placement=duplicated_placement())))
        cp.store.apply(new_deployment("web", replicas=2))
        cp.settle()
        assert cp.store.get("ResourceBinding", "default/web-deployment") is not None

        # reconfigure: enable descheduler, add a member, flip a gate
        karmada.meta.generation = 2
        karmada.spec.components.descheduler.enabled = True
        karmada.spec.member_clusters.append("m3")
        karmada.spec.feature_gates["Failover"] = True
        cp2 = op.reconcile(karmada)
        assert cp2 is cp  # upgrade reconcile, not reinstall
        assert cp.descheduler is not None
        assert {c.name for c in cp.store.list("Cluster")} == {"m1", "m2", "m3"}
        assert karmada.status.observed_generation == 2
        from karmada_tpu.utils.features import FAILOVER, feature_gate
        assert feature_gate.enabled(FAILOVER)

        # member removal drains on the next reconcile
        karmada.meta.generation = 3
        karmada.spec.member_clusters.remove("m2")
        op.reconcile(karmada)
        assert {c.name for c in cp.store.list("Cluster")} == {"m1", "m3"}

        op.deinit(karmada)
        assert "plane" not in op.instances
        assert not any(
            c.type == "Ready" and c.status for c in karmada.status.conditions
        )

    def test_version_upgrade_rolls_unpinned_components(self):
        op = KarmadaOperator()
        karmada = self._cr()
        op.reconcile(karmada)
        karmada.meta.generation = 2
        karmada.spec.version = "1.12.0"
        op.reconcile(karmada)
        assert karmada.status.installed_version == "1.12.0"
        assert karmada.spec.components.scheduler.version == "1.12.0"

    def test_version_skew_rejected_with_failure_condition(self):
        from karmada_tpu.operator.karmada_operator import ComponentSpec as CS

        op = KarmadaOperator()
        karmada = self._cr()
        karmada.spec.version = "1.13.0"
        karmada.spec.components.scheduler = CS(version="1.11.0")  # 2 minors
        with pytest.raises(WorkflowError):
            op.reconcile(karmada)
        assert karmada.status.failed_task == "validate"
        cond = [c for c in karmada.status.conditions if c.type == "Ready"][0]
        assert not cond.status and cond.reason == "TaskFailed"
