"""Test bootstrap: force CPU JAX with a virtual 8-device mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on a host-platform device mesh (SURVEY.md section 7 / driver contract).

The axon TPU-tunnel sitecustomize (when present) overrides platform selection
programmatically via ``jax.config.update("jax_platforms", "axon,cpu")``, so an
env var alone is not enough — we override the config the same way before any
backend initializes. Tests must never dial the single-client TPU tunnel.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
