"""Test bootstrap: force CPU JAX with a virtual 8-device mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on a host-platform device mesh (SURVEY.md section 7 / driver contract).
Must run before the first jax import anywhere in the test session.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
