"""Leader election over the Lease resource lock (client-go
tools/leaderelection semantics: tryAcquireOrRenew via CAS on the lock
object) + the Store/bus optimistic-concurrency precondition it builds on
(apiserver Update-with-resourceVersion -> 409 Conflict)."""

import pytest

from karmada_tpu.api.cluster import Lease
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.utils.leaderelect import LeaderElector
from karmada_tpu.utils.store import ConflictError, Store


class Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class TestConflictPrecondition:
    def test_apply_if_semantics(self):
        store = Store()
        obj = Resource(meta=ObjectMeta(name="a", namespace="ns"))
        # create-only precondition: rv 0 = must not exist
        store.apply(obj, expected_rv=0)
        rv = obj.meta.resource_version
        with pytest.raises(ConflictError):
            store.apply(
                Resource(meta=ObjectMeta(name="a", namespace="ns")),
                expected_rv=0,
            )
        # update with the right precondition succeeds, wrong one conflicts
        store.apply(
            Resource(meta=ObjectMeta(name="a", namespace="ns")),
            expected_rv=rv,
        )
        with pytest.raises(ConflictError):
            store.apply(
                Resource(meta=ObjectMeta(name="a", namespace="ns")),
                expected_rv=rv,
            )

    def test_conflict_travels_the_bus(self):
        from karmada_tpu.bus.service import StoreBusServer, StoreReplica

        store = Store()
        server = StoreBusServer(store)
        server.start()
        try:
            replica = StoreReplica(f"127.0.0.1:{server.port}")
            replica.start()
            assert replica.wait_synced(10)
            obj = Resource(meta=ObjectMeta(name="x", namespace="d"))
            rv = replica.apply(obj, expected_rv=0)
            assert rv > 0
            with pytest.raises(ConflictError):
                replica.apply(
                    Resource(meta=ObjectMeta(name="x", namespace="d")),
                    expected_rv=0,
                )
            replica.close()
        finally:
            server.stop()


class TestLeaderElector:
    def _pair(self, store, clock):
        a = LeaderElector(store, "lock", "a", lease_duration=4.0,
                          renew_deadline=2.0, clock=clock)
        b = LeaderElector(store, "lock", "b", lease_duration=4.0,
                          renew_deadline=2.0, clock=clock)
        return a, b

    def test_first_acquires_second_observes(self):
        store, clock = Store(), Clock()
        a, b = self._pair(store, clock)
        assert a.tick() and a.is_leader
        assert not b.tick() and not b.is_leader
        lease = store.get("Lease", "lock")
        assert lease.holder_identity == "a"
        # renewal keeps b out past the original expiry
        for _ in range(4):
            clock.t += 1.5
            assert a.tick()
            assert not b.tick()

    def test_expiry_hands_over_with_transition_count(self):
        store, clock = Store(), Clock()
        a, b = self._pair(store, clock)
        assert a.tick()
        clock.t += 10.0  # a stops renewing; lease expires
        assert b.tick() and b.is_leader
        lease = store.get("Lease", "lock")
        assert lease.holder_identity == "b"
        assert lease.lease_transitions == 1
        # a comes back: observes b and steps down
        assert not a.tick() and not a.is_leader

    def test_release_hands_over_immediately(self):
        store, clock = Store(), Clock()
        a, b = self._pair(store, clock)
        assert a.tick()
        a.release()
        assert not a.is_leader
        clock.t += 0.1  # far inside the old lease window
        assert b.tick() and b.is_leader

    def test_cas_race_single_winner(self):
        """Two candidates racing from the same observed state: exactly one
        CAS lands."""
        store, clock = Store(), Clock()
        a, b = self._pair(store, clock)
        # simulate the race: both read 'no lease', then both write. The
        # second write's precondition (rv 0) must fail.
        assert a.tick()
        with pytest.raises(ConflictError):
            store.apply(
                Lease(meta=ObjectMeta(name="lock"), renew_time=clock.t,
                      holder_identity="b", lease_duration_seconds=4.0),
                expected_rv=0,
            )
        assert not b.tick()

    def test_transient_write_failure_coasts_until_deadline(self):
        store, clock = Store(), Clock()
        a = LeaderElector(store, "lock", "a", lease_duration=4.0,
                          renew_deadline=2.0, clock=clock)
        assert a.tick()
        broken = [True]
        real_apply = store.apply

        def flaky_apply(obj, **kw):
            if broken[0]:
                raise RuntimeError("bus down")
            return real_apply(obj, **kw)

        store.apply = flaky_apply
        clock.t += 1.0
        assert a.tick()  # still inside renew deadline: coasts
        clock.t += 2.5
        assert not a.tick()  # deadline passed: deposed
        broken[0] = False
        # heals: re-acquires (lease is its own, not expired for others yet
        # -> held_by_self path)
        assert a.tick() and a.is_leader
