"""Process-deployment operator e2e: a Karmada CR installs, upgrades, and
tears down the REAL multi-process deployment (VERDICT r2 weak #7 — the
reference operator's core job is process/cert lifecycle, operator/pkg/
tasks/init; now the multi-process harness IS the thing the operator
installs).

Covers: the init task pipeline (certs -> TLS admission webhook -> solver ->
estimator -> plane -> pull agent -> wait-ready), writes round-tripping the
out-of-process TLS admission hop, upgrade reconciles (pull-member add with
plane restart), and deinit."""

import time

import pytest

from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.bus.service import StoreReplica
from karmada_tpu.operator.karmada_operator import Karmada, KarmadaSpec
from karmada_tpu.operator.process_operator import ProcessKarmadaOperator
from karmada_tpu.utils.builders import new_cluster, new_deployment


def wait_for(predicate, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="module")
def installed():
    op = ProcessKarmadaOperator()
    cr = Karmada(meta=ObjectMeta(name="demo", generation=1))
    cr.spec.pull_members = ["pull1"]
    cr.spec.feature_gates = {"Failover": True}
    cr.spec.components.estimators.enabled = True  # addon (off by default)
    inst = op.reconcile(cr)
    replica = StoreReplica(f"127.0.0.1:{inst.endpoints['bus']}")
    replica.start()
    assert replica.wait_synced(10)
    try:
        yield op, cr, inst, replica
    finally:
        replica.close()
        op.deinit(cr)


class TestProcessOperator:
    def test_install_pipeline_and_status(self, installed):
        op, cr, inst, r = installed
        assert any(c.type == "Ready" and c.status for c in cr.status.conditions)
        assert cr.status.completed_tasks[:2] == ["validate", "certs"]
        assert "wait-ready" in cr.status.completed_tasks
        for comp in ("webhook", "solver", "estimator", "plane", "agent-pull1"):
            assert inst.alive(comp), f"{comp} not running"
        # the PKI the certs task generated backs the webhook process
        assert inst.endpoints["webhook"].startswith("https://")

    def test_writes_round_trip_the_tls_admission_process(self, installed):
        op, cr, inst, r = installed
        # a policy write lands with the webhook-process mutation applied
        from karmada_tpu.api import (
            PropagationPolicy, PropagationSpec, ResourceSelector,
        )
        from karmada_tpu.utils.builders import duplicated_placement
        from karmada_tpu.webhook.chain import PERMANENT_ID_ANNOTATION

        r.apply(new_deployment("nginx", replicas=2))
        r.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="pp", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    placement=duplicated_placement(),
                ),
            )
        )
        assert wait_for(
            lambda: r.store.get("PropagationPolicy", "default/pp") is not None
        )
        stored = r.store.get("PropagationPolicy", "default/pp")
        assert PERMANENT_ID_ANNOTATION in stored.meta.annotations

        # an INVALID cluster is rejected BY THE WEBHOOK PROCESS: the bus
        # surfaces the denial as an apply error
        bad = new_cluster("Bad_Name!", cpu="1", memory="1Gi")
        with pytest.raises(RuntimeError):
            r.apply(bad)

        # and the workload actually propagates (plane + agent both live)
        def scheduled():
            rb = r.store.get("ResourceBinding", "default/nginx-deployment")
            return rb is not None and len(rb.spec.clusters) >= 2

        assert wait_for(scheduled, timeout=60.0)

    def test_upgrade_adds_pull_member_with_plane_restart(self, installed):
        op, cr, inst, r = installed
        old_plane = inst.procs["plane"].pid
        cr.meta.generation = 2
        cr.spec.pull_members = ["pull1", "pull2"]
        op.reconcile(cr)
        assert inst.procs["plane"].pid != old_plane  # restarted
        assert inst.alive("agent-pull2")
        assert cr.status.observed_generation == 2

    def test_deinit_and_reinstall(self):
        op = ProcessKarmadaOperator()
        cr = Karmada(meta=ObjectMeta(name="cycle", generation=1))
        inst = op.reconcile(cr)
        pki = inst.pki_dir
        op.deinit(cr)
        import os

        assert not os.path.isdir(pki)
        assert all(p.poll() is not None for p in inst.procs.values())
        assert any(
            c.type == "Ready" and not c.status for c in cr.status.conditions
        )
        inst2 = op.reconcile(cr)  # fresh install after deinit
        assert inst2.alive("plane")
        op.deinit(cr)

    def test_upgrade_preserves_store_state(self):
        """Plane restarts during upgrade must not wipe control-plane state:
        the plane checkpoints its store on shutdown and the successor
        restores it (the reference operator preserves etcd the same way)."""
        op = ProcessKarmadaOperator()
        cr = Karmada(meta=ObjectMeta(name="persist", generation=1))
        inst = op.reconcile(cr)
        r = StoreReplica(f"127.0.0.1:{inst.endpoints['bus']}")
        r.start()
        assert r.wait_synced(10)
        try:
            r.apply(new_deployment("kept", replicas=1))
            assert wait_for(
                lambda: r.store.get("Resource", "default/kept") is not None
            )
        finally:
            r.close()
        cr.meta.generation = 2
        cr.spec.feature_gates = {"Failover": True}  # forces plane restart
        op.reconcile(cr)
        r2 = StoreReplica(f"127.0.0.1:{inst.endpoints['bus']}")
        r2.start()
        assert r2.wait_synced(10)
        try:
            assert wait_for(
                lambda: r2.store.get("Resource", "default/kept") is not None,
                timeout=15.0,
            ), "store state lost across the upgrade plane restart"
        finally:
            r2.close()
            op.deinit(cr)

    def test_upgrade_member_cluster_change_restarts_plane(self):
        op = ProcessKarmadaOperator()
        cr = Karmada(meta=ObjectMeta(name="diff", generation=1))
        inst = op.reconcile(cr)
        try:
            old_pid = inst.procs["plane"].pid
            cr.meta.generation = 2
            cr.spec.member_clusters = ["m1", "m2", "m3"]
            op.reconcile(cr)
            assert inst.procs["plane"].pid != old_pid
            r = StoreReplica(f"127.0.0.1:{inst.endpoints['bus']}")
            r.start()
            assert r.wait_synced(10)
            try:
                assert wait_for(
                    lambda: len(r.store.list("Cluster")) >= 3, timeout=15.0
                )
            finally:
                r.close()
        finally:
            op.deinit(cr)

    def test_supervision_restarts_dead_components_at_pinned_ports(self):
        """The operator's supervision sweep (Deployment-controller
        analogue): SIGKILLed components restart at their PINNED endpoints;
        the plane returns from its periodic checkpoint; connected clients
        (bus replicas, the plane's solver channel) recover on their own."""
        op = ProcessKarmadaOperator(checkpoint_interval=0.5)
        cr = Karmada(meta=ObjectMeta(name="heal", generation=1))
        inst = op.reconcile(cr)
        bus = f"127.0.0.1:{inst.endpoints['bus']}"
        r = StoreReplica(bus)
        r.start()
        assert r.wait_synced(10)
        try:
            r.apply(new_deployment("pre-crash", replicas=1))
            assert wait_for(
                lambda: r.store.get("Resource", "default/pre-crash") is not None
            )
            time.sleep(1.2)  # let a periodic checkpoint cover the object

            # solver dies -> restarted at the same port; scheduling resumes
            solver_port = inst.endpoints["solver"]
            inst.procs["solver"].kill()
            inst.procs["solver"].wait(timeout=5)
            restarted = op.supervise(cr)
            assert "solver" in restarted
            assert inst.endpoints["solver"] == solver_port
            assert inst.alive("solver")

            # plane dies HARD (no shutdown checkpoint) -> restarted at the
            # same bus port from the periodic snapshot
            inst.procs["plane"].kill()
            inst.procs["plane"].wait(timeout=5)
            restarted = op.supervise(cr)
            assert "plane" in restarted
            assert f"127.0.0.1:{inst.endpoints['bus']}" == bus  # pinned

            def recovered():
                return r.store.get("Resource", "default/pre-crash") is not None

            assert wait_for(recovered, timeout=20.0), (
                "pre-crash state lost after hard plane kill"
            )

            # end-to-end health: a NEW workload schedules through the
            # restarted plane + solver
            from karmada_tpu.api import (
                PropagationPolicy, PropagationSpec, ResourceSelector,
            )
            from karmada_tpu.utils.builders import duplicated_placement

            def apply_ok():
                try:
                    r.apply(new_deployment("post-heal", replicas=1))
                    r.apply(
                        PropagationPolicy(
                            meta=ObjectMeta(name="heal-pp", namespace="default"),
                            spec=PropagationSpec(
                                resource_selectors=[
                                    ResourceSelector(
                                        api_version="apps/v1", kind="Deployment"
                                    )
                                ],
                                placement=duplicated_placement(),
                            ),
                        )
                    )
                    return True
                except Exception:
                    return False

            assert wait_for(apply_ok, timeout=15.0)

            def scheduled():
                rb = r.store.get(
                    "ResourceBinding", "default/post-heal-deployment"
                )
                return rb is not None and len(rb.spec.clusters) >= 1

            # generous timeout: the restarted solver may recompile its
            # traces from a cold cache under CPU contention
            assert wait_for(scheduled, timeout=150.0), (
                "scheduling never resumed after supervision restarts"
            )

            # webhook dies -> restarted at the SAME URL, so the live
            # plane's RemoteAdmission keeps working without a restart
            url = inst.endpoints["webhook"]
            inst.procs["webhook"].kill()
            inst.procs["webhook"].wait(timeout=5)
            restarted = op.supervise(cr)
            assert "webhook" in restarted
            assert inst.endpoints["webhook"] == url

            def admitted_write():
                try:
                    r.apply(new_deployment("post-webhook-heal", replicas=1))
                    return True
                except Exception:
                    return False

            assert wait_for(admitted_write, timeout=15.0), (
                "writes never recovered after webhook restart"
            )
        finally:
            r.close()
            op.deinit(cr)


class TestCrashLoopSupervision:
    def test_backoff_storm_cap_and_watchdog(self):
        """VERDICT r3 item 7: real supervision. A repeatedly-dying
        component backs off exponentially (a sweep inside the backoff
        window leaves it down), more than storm_cap restarts in the window
        surfaces CrashLoopBackOff on the Karmada CR, and the Supervisor
        WATCHDOG thread heals a kill with no manual sweep at all."""
        from karmada_tpu.operator.process_operator import Supervisor

        op = ProcessKarmadaOperator(
            checkpoint_interval=0.5, backoff_initial=1.5,
            backoff_max=4.0, storm_window=60.0, storm_cap=2,
        )
        cr = Karmada(meta=ObjectMeta(name="loop", generation=1))
        cr.spec.components.webhook.enabled = False  # lean deployment
        inst = op.reconcile(cr)
        try:
            # restart 1: immediate
            inst.procs["solver"].kill()
            inst.procs["solver"].wait(timeout=5)
            assert op.supervise(cr) == ["solver"]
            assert inst.alive("solver")
            # die again at once: the sweep DEFERS (inside backoff)
            inst.procs["solver"].kill()
            inst.procs["solver"].wait(timeout=5)
            assert op.supervise(cr) == []
            assert not inst.alive("solver")
            # after the backoff expires the sweep restarts it (2), and two
            # more cycles cross storm_cap=2 within the window
            for expected_restarts in (2, 3):
                assert wait_for(
                    lambda: op.supervise(cr) == ["solver"], timeout=15.0,
                    interval=0.3,
                ), f"backoff never expired before restart {expected_restarts}"
                inst.procs["solver"].kill()
                inst.procs["solver"].wait(timeout=5)
            assert cr.status.component_restarts["solver"] >= 3
            cond = {c.type: c for c in cr.status.conditions}[
                "ComponentsHealthy"
            ]
            assert cond.status is False
            assert cond.reason == "CrashLoopBackOff"
            assert "solver" in cond.message

            # the watchdog thread heals without any manual sweep: it keeps
            # sweeping through the (capped) backoff until the solver is up
            sup = Supervisor(op, cr, interval=0.3).start()
            try:
                assert wait_for(
                    lambda: inst.alive("solver"), timeout=20.0
                ), "watchdog never resurrected the crash-looping solver"
            finally:
                sup.stop()
        finally:
            op.deinit(cr)
