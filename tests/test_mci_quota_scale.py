"""MultiClusterIngress, quota estimate plugin, and a batch-scale smoke test."""

import numpy as np

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.networking import (
    ExposureRange,
    MultiClusterIngress,
    MultiClusterIngressSpec,
    MultiClusterService,
    MultiClusterServiceSpec,
)
from karmada_tpu.api.work import ReplicaRequirements
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.estimator import AccurateEstimator, NodeSnapshot, NodeState
from karmada_tpu.estimator.accurate import ResourceQuotaPlugin
from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    synthetic_fleet,
)
from karmada_tpu.utils.features import RESOURCE_QUOTA_ESTIMATE, feature_gate
from karmada_tpu.utils.quantity import parse_resource_list

DIMS = ["cpu", "memory", "pods", "ephemeral-storage"]


class TestMultiClusterIngress:
    def test_ingress_dispatched_to_serving_clusters(self):
        cp = ControlPlane()
        for i in (1, 2, 3):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        cp.settle()
        svc = Resource(
            api_version="v1", kind="Service",
            meta=ObjectMeta(name="web", namespace="default"),
            spec={"ports": [{"port": 80}]},
        )
        cp.members.get("member1").apply(svc)
        cp.store.apply(
            MultiClusterIngress(
                meta=ObjectMeta(name="web-ingress", namespace="default"),
                spec=MultiClusterIngressSpec(
                    rules=[{
                        "host": "web.example.com",
                        "http": {"paths": [{"path": "/", "backend": {
                            "service": {"name": "web"}}}]},
                    }]
                ),
            )
        )
        cp.settle()
        obj = cp.members.get("member1").get(
            "networking.k8s.io/v1/Ingress", "default", "web-ingress"
        )
        assert obj is not None
        assert cp.members.get("member2").get(
            "networking.k8s.io/v1/Ingress", "default", "web-ingress"
        ) is None
        mci = cp.store.get("MultiClusterIngress", "default/web-ingress")
        assert mci.status["clusters"] == ["member1"]


class TestResourceQuotaPlugin:
    def test_quota_caps_estimate(self):
        feature_gate.set(RESOURCE_QUOTA_ESTIMATE, True)
        try:
            nodes = [
                NodeState(
                    name="n0",
                    allocatable=parse_resource_list(
                        {"cpu": "64", "memory": "256Gi", "pods": 200}
                    ),
                )
            ]
            plugin = ResourceQuotaPlugin(
                {"default": parse_resource_list({"cpu": "3"})}
            )
            est = AccurateEstimator("m1", NodeSnapshot(nodes, DIMS), plugin)
            reqs = ReplicaRequirements(
                resource_request=parse_resource_list({"cpu": "1"}),
                namespace="default",
            )
            row = np.zeros((1, len(DIMS)), np.int64)
            row[0, 0] = 1000
            out = est.max_available_replicas(reqs, row)
            assert out.tolist() == [3]  # node fit 64, quota caps at 3
        finally:
            feature_gate.set(RESOURCE_QUOTA_ESTIMATE, False)


class TestBatchScale:
    def test_2k_bindings_500_clusters_batch(self):
        """Scale smoke: the batched engine handles thousands of bindings in
        one call with conserved replica sums (the CPU-side stand-in for the
        BASELINE workloads; the TPU path is bench.py)."""
        fleet = synthetic_fleet(500, seed=11)
        snap = ClusterSnapshot(fleet)
        sched = TensorScheduler(snap, chunk_size=1024)
        pl = dynamic_weight_placement()
        req = parse_resource_list({"cpu": "500m", "memory": "1Gi"})
        problems = [
            BindingProblem(
                key=f"b{i}", placement=pl, replicas=(i % 50) + 1,
                requests=req, gvk="apps/v1/Deployment",
            )
            for i in range(2000)
        ]
        results = sched.schedule(problems)
        scheduled = [r for r in results if r.success]
        assert len(scheduled) == 2000
        for p, r in zip(problems, results):
            assert sum(r.clusters.values()) == p.replicas
