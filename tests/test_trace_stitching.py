"""Cross-process wave tracing (ISSUE 10): context propagation over the
estimator/solver/bus channels, the stitcher, /debug/traces query
handling, and the slow-wave flight recorder.

Cross-process shape in one test process: the SERVER side of each gRPC
seam binds the tracer object at construction, so constructing a server
while a second ``WaveTracer`` (proc="estimator"/"solver"/"bus") is
installed as the module global gives that server its own ring — the
client side resolves the real global (proc="plane") at call time.  The
two rings then stitch exactly like two processes' /debug/traces dumps.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import karmada_tpu.utils.tracing as tracing
from karmada_tpu.utils.tracing import (
    ContextPropagatingExecutor,
    TraceContext,
    WaveTracer,
    decode_trace_metadata,
    stitch_dumps,
    trace_debug_doc,
    trace_metadata,
    tracer,
)

DIMS = ["cpu", "memory", "pods"]


@pytest.fixture(autouse=True)
def _clean_tracer():
    tracer.clear()
    tracer.set_process("plane")
    tracing.clear_peers()
    yield
    tracer.clear()
    tracing.clear_peers()


@pytest.fixture()
def server_tracer(monkeypatch):
    """A second ring standing in for a remote process: installed as the
    module global only while the caller constructs its gRPC server (the
    handlers bind the tracer object at construction)."""
    tr = WaveTracer()

    def build(proc_name, ctor):
        tr.set_process(proc_name)
        monkeypatch.setattr(tracing, "tracer", tr)
        try:
            return ctor()
        finally:
            monkeypatch.setattr(tracing, "tracer", tracer)

    build.ring = tr
    return build


# --------------------------------------------------------------------------
# context + metadata
# --------------------------------------------------------------------------


class TestTraceMetadata:
    def test_roundtrip(self):
        ctx = TraceContext(wave=7, trace_id="abc123", span_id=42, proc="plane")
        assert decode_trace_metadata(trace_metadata(ctx)) == ctx

    def test_no_context_is_empty(self):
        assert trace_metadata(None) == ()
        assert trace_metadata(
            TraceContext(wave=0, trace_id="", span_id=None, proc="plane")
        ) == ()

    def test_span_id_none_roundtrip(self):
        ctx = TraceContext(wave=1, trace_id="t", span_id=None, proc="agent")
        assert decode_trace_metadata(trace_metadata(ctx)) == ctx

    @pytest.mark.parametrize(
        "pairs",
        [
            (),
            None,
            (("karmada-tpu-wave", "3"),),  # no trace id
            (("karmada-tpu-trace", "t"), ("karmada-tpu-wave", "NaNope")),
            (("karmada-tpu-trace", "t"), ("karmada-tpu-span", "xyz")),
            ("not-a-pair",),
        ],
    )
    def test_malformed_metadata_decodes_none(self, pairs):
        """An untraced or garbled caller must never fail the RPC."""
        assert decode_trace_metadata(pairs) is None

    def test_foreign_metadata_ignored(self):
        pairs = (
            ("user-agent", "grpc-python"),
            ("karmada-tpu-trace", "t1"),
            ("karmada-tpu-wave", "4"),
            ("karmada-tpu-span", "9"),
            ("karmada-tpu-proc", "plane"),
        )
        ctx = decode_trace_metadata(pairs)
        assert ctx == TraceContext(wave=4, trace_id="t1", span_id=9,
                                   proc="plane")


# --------------------------------------------------------------------------
# tracer satellites: lock-stamped wave ids, end_wave return, evictions
# --------------------------------------------------------------------------


class TestTracerSatellites:
    def test_end_wave_returns_closed_id(self):
        tr = WaveTracer()
        w = tr.begin_wave("test")
        assert tr.end_wave() == w
        # idempotent close still names the last wave
        assert tr.end_wave() == w

    def test_span_keeps_wave_stamped_at_open(self):
        """A span opened before end_wave() but closed after a NEW wave
        began stays attributed to the wave it opened under."""
        tr = WaveTracer()
        w1 = tr.begin_wave("one")
        opened = threading.Event()
        release = threading.Event()

        def straggler():
            with tr.span("settle"):
                opened.set()
                release.wait(5)

        t = threading.Thread(target=straggler)
        t.start()
        assert opened.wait(5)
        assert tr.end_wave() == w1
        w2 = tr.begin_wave("two")
        release.set()
        t.join(5)
        tr.end_wave()
        spans = tr.dump(w1)
        assert [s["name"] for s in spans] == ["settle"]
        assert not tr.dump(w2)

    def test_wave_trace_ids_unique(self):
        tr = WaveTracer()
        w1 = tr.begin_wave()
        t1 = tr.wave_trace_id(w1)
        tr.end_wave()
        w2 = tr.begin_wave()
        t2 = tr.wave_trace_id(w2)
        assert t1 and t2 and t1 != t2

    def test_ring_eviction_counted(self):
        tr = WaveTracer(capacity=16)
        w = tr.begin_wave("storm")
        for i in range(40):
            tr.record("scheduler.pack", 0.001, i=i)
        tr.end_wave()
        assert len(tr.dump()) == 16
        assert tr.dropped_total == 24
        summary = tr.wave_summary(w)
        assert summary["dropped"] == 24
        # the registry counter moved in lockstep
        from karmada_tpu.utils.metrics import trace_spans_dropped

        assert trace_spans_dropped.value() >= 24

    def test_capacity_env_tunable(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_TRACE_CAPACITY", "32")
        assert WaveTracer().capacity == 32
        monkeypatch.setenv("KARMADA_TPU_TRACE_CAPACITY", "bogus")
        assert WaveTracer().capacity == 8192
        monkeypatch.delenv("KARMADA_TPU_TRACE_CAPACITY")
        assert WaveTracer(capacity=7).capacity == 7

    def test_debug_doc_surfaces_dropped(self):
        tr = WaveTracer(capacity=8)
        tr.begin_wave()
        for _ in range(20):
            tr.record("scheduler.pack", 0.001)
        tr.end_wave()
        doc = trace_debug_doc(tracer_obj=tr)
        assert doc["dropped"] == 12

    def test_executor_context_propagation(self):
        from concurrent.futures import ThreadPoolExecutor

        tr = WaveTracer()
        pool = ContextPropagatingExecutor(ThreadPoolExecutor(2), tr)
        w = tr.begin_wave("fanout")
        with tr.span("estimator.refresh") as parent:
            futs = [
                pool.submit(lambda: tr.record("estimator.rpc", 0.001))
                for _ in range(4)
            ]
            spans = [f.result(5) for f in futs]
        tr.end_wave()
        for sp in spans:
            assert sp.wave == w
            assert sp.parent_id == parent.span_id
        pool.shutdown()


# --------------------------------------------------------------------------
# /debug/traces query handling
# --------------------------------------------------------------------------


class TestDebugTracesQueries:
    @pytest.fixture()
    def server(self):
        from karmada_tpu.utils.metrics import MetricsServer

        srv = MetricsServer()
        port = srv.start()
        yield port
        srv.stop()

    def _get(self, port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return json.loads(resp.read().decode())

    def test_wave_filter(self, server):
        w1 = tracer.begin_wave()
        with tracer.span("settle"):
            pass
        tracer.end_wave()
        w2 = tracer.begin_wave()
        with tracer.span("settle"):
            with tracer.span("scheduler.pass"):
                pass
        tracer.end_wave()
        doc = self._get(server, f"/debug/traces?wave={w2}")
        assert {s["wave"] for s in doc["spans"]} == {w2}
        assert [w["wave"] for w in doc["waves"]] == [w2]
        assert len(doc["spans"]) == 2
        doc1 = self._get(server, f"/debug/traces?wave={w1}")
        assert len(doc1["spans"]) == 1

    def test_summary_drops_spans(self, server):
        tracer.begin_wave()
        with tracer.span("settle"):
            pass
        tracer.end_wave()
        doc = self._get(server, "/debug/traces?summary=1")
        assert "spans" not in doc
        assert doc["waves"]
        full = self._get(server, "/debug/traces?summary=0")
        assert "spans" in full

    def test_malformed_wave_is_400(self, server):
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            self._get(server, "/debug/traces?wave=banana")
        assert exc_info.value.code == 400
        body = json.loads(exc_info.value.read().decode())
        assert "banana" in body["error"]

    def test_doc_carries_proc_and_peers(self, server):
        tracing.register_peer("solver", "127.0.0.1:1")
        doc = self._get(server, "/debug/traces")
        assert doc["proc"] == "plane"
        assert doc["peers"] == {"solver": "127.0.0.1:1"}
        assert "dropped" in doc and "mesh" in doc


# --------------------------------------------------------------------------
# estimator channel propagation (real gRPC, two rings)
# --------------------------------------------------------------------------


def _estimator_service(name="c1"):
    from karmada_tpu.estimator.accurate import (
        AccurateEstimator,
        NodeCache,
        NodeState,
    )
    from karmada_tpu.estimator.service import EstimatorService

    cache = NodeCache(
        DIMS,
        [NodeState(name=f"{name}-n0",
                   allocatable={"cpu": 8000, "memory": 1 << 32, "pods": 110})],
    )
    return EstimatorService(AccurateEstimator(name, cache))


class TestEstimatorPropagation:
    def test_batch_rpc_records_server_span_under_caller_wave(
        self, server_tracer
    ):
        from karmada_tpu.estimator.grpc_transport import (
            EstimatorGrpcServer,
            GrpcEstimatorConnection,
        )
        from karmada_tpu.estimator.service import (
            MaxAvailableReplicasBatchRequest,
        )

        srv = server_tracer(
            "estimator", lambda: EstimatorGrpcServer(_estimator_service())
        )
        port = srv.start()
        conn = GrpcEstimatorConnection(
            "c1", f"127.0.0.1:{port}", timeout_seconds=5.0
        )
        try:
            w = tracer.begin_wave("test")
            with tracer.span("settle"):
                with tracer.span("estimator.refresh"):
                    conn.call(
                        "MaxAvailableReplicasBatch",
                        MaxAvailableReplicasBatchRequest(
                            clusters=["c1"], dims=DIMS,
                            rows=[[1000, 1 << 20, 1]],
                        ),
                    )
            tracer.end_wave()
            client = [
                s for s in tracer.dump(w) if s["name"] == "estimator.rpc"
            ]
            assert len(client) == 1
            assert client[0]["attrs"]["remote"] is True
            assert client[0]["attrs"]["method"] == "MaxAvailableReplicasBatch"
            server = [
                s for s in server_tracer.ring.dump(w)
                if s["name"] == "estimator.serve"
            ]
            assert len(server) == 1
            sspan = server[0]
            assert sspan["wave"] == w
            assert sspan["trace_id"] == client[0]["trace_id"]
            assert sspan["attrs"]["remote_parent"] == client[0]["span_id"]
            assert sspan["attrs"]["caller"] == "plane"
            # the server-side window fits inside the client window
            assert sspan["duration_s"] <= client[0]["duration_s"] + 0.05
        finally:
            conn.close()
            srv.stop()

    def test_unary_fallback_keeps_context_per_attempt(self, server_tracer):
        """The PR 4 negotiated fallback (call_future pipelining) still
        carries context: every per-profile server span lands under the
        caller's wave with a DISTINCT client span as its parent."""
        from karmada_tpu.estimator.grpc_transport import (
            EstimatorGrpcServer,
            GrpcEstimatorConnection,
            RemoteAccurateEstimator,
        )

        srv = server_tracer(
            "estimator",
            lambda: EstimatorGrpcServer(
                _estimator_service(), enable_batch=False
            ),
        )
        port = srv.start()
        conn = GrpcEstimatorConnection(
            "c1", f"127.0.0.1:{port}", timeout_seconds=5.0
        )
        est = RemoteAccurateEstimator("c1", conn, lambda: list(DIMS))
        try:
            w = tracer.begin_wave("test")
            with tracer.span("estimator.refresh"):
                batch = np.asarray(
                    [[1000, 1 << 20, 1], [2000, 1 << 21, 1],
                     [3000, 1 << 22, 1]],
                    np.int64,
                )
                out = est.max_available_replicas(None, batch)
            tracer.end_wave()
            assert conn.supports_batch is False  # negotiated
            assert (np.asarray(out) >= 0).all()
            deadline = time.time() + 5
            while time.time() < deadline:
                server = [
                    s for s in server_tracer.ring.dump(w)
                    if s["name"] == "estimator.serve"
                    and s["attrs"].get("method") == "MaxAvailableReplicas"
                ]
                client = [
                    s for s in tracer.dump(w)
                    if s["name"] == "estimator.rpc"
                    and s["attrs"].get("method") == "MaxAvailableReplicas"
                ]
                if len(server) >= 3 and len(client) >= 3:
                    break
                time.sleep(0.05)  # manual spans close from done callbacks
            assert len(server) == 3 and len(client) == 3
            parents = [s["attrs"]["remote_parent"] for s in server]
            assert sorted(parents) == sorted(
                s["span_id"] for s in client
            ), "each server span re-parents under exactly one client span"
        finally:
            conn.close()
            srv.stop()

    def test_context_survives_reconnect_reprobe(self, server_tracer):
        """A wire failure resets the batch negotiation; the re-probing
        call on the transparently-reconnected channel still carries the
        trace context (the metadata rides every wire attempt, probes
        included)."""
        from karmada_tpu.estimator.grpc_transport import (
            EstimatorGrpcServer,
            GrpcEstimatorConnection,
        )
        from karmada_tpu.estimator.service import GetGenerationsRequest

        srv1 = server_tracer(
            "estimator", lambda: EstimatorGrpcServer(_estimator_service())
        )
        port = srv1.start()
        conn = GrpcEstimatorConnection(
            "c1", f"127.0.0.1:{port}", timeout_seconds=2.0
        )
        try:
            conn.call("GetGenerations", GetGenerationsRequest())
            assert conn.supports_batch is True
            srv1.stop(grace=0)
            with pytest.raises(Exception):
                conn.call("GetGenerations", GetGenerationsRequest())
            assert conn.supports_batch is None  # re-probe armed
            # the server returns at the SAME address (its channel
            # reconnects transparently underneath)
            try:
                srv2 = server_tracer(
                    "estimator",
                    lambda: EstimatorGrpcServer(
                        _estimator_service(), f"127.0.0.1:{port}"
                    ),
                )
            except RuntimeError:
                pytest.skip("port not rebindable on this host")
            srv2.start()
            try:
                w = tracer.begin_wave("test")
                with tracer.span("estimator.refresh"):
                    # the reconnect rides the channel's own backoff —
                    # retry until it lands (each failed attempt is its
                    # own client span; assertions read the LAST pair)
                    deadline = time.time() + 10
                    while True:
                        try:
                            conn.call(
                                "GetGenerations", GetGenerationsRequest()
                            )
                            break
                        except Exception:  # noqa: BLE001 — backoff
                            if time.time() > deadline:
                                raise
                            time.sleep(0.2)
                tracer.end_wave()
                assert conn.supports_batch is True  # re-probed
                client = [
                    s for s in tracer.dump(w)
                    if s["name"] == "estimator.rpc"
                ]
                serve = [
                    s for s in server_tracer.ring.dump(w)
                    if s["name"] == "estimator.serve"
                ]
                assert serve and client
                assert serve[-1]["attrs"]["remote_parent"] == (
                    client[-1]["span_id"]
                )
            finally:
                srv2.stop()
        finally:
            conn.close()

    def test_breaker_open_records_no_rpc_span(self):
        from karmada_tpu.estimator.grpc_transport import (
            GrpcEstimatorConnection,
        )
        from karmada_tpu.estimator.service import GetGenerationsRequest
        from karmada_tpu.utils.backoff import CircuitBreakerOpen

        conn = GrpcEstimatorConnection(
            "c1", "127.0.0.1:1", timeout_seconds=0.2
        )
        try:
            w = tracer.begin_wave("test")
            # trip the breaker on the dead endpoint
            for _ in range(10):
                try:
                    conn.call("GetGenerations", GetGenerationsRequest())
                except Exception:  # noqa: BLE001 — wire failure expected
                    pass
            before = len([
                s for s in tracer.dump(w) if s["name"] == "estimator.rpc"
            ])
            assert conn.breaker.engaged()
            with pytest.raises(CircuitBreakerOpen):
                conn.call("GetGenerations", GetGenerationsRequest())
            tracer.end_wave()
            after = len([
                s for s in tracer.dump(w) if s["name"] == "estimator.rpc"
            ])
            assert after == before, "a fast-failed call is not an RPC span"
        finally:
            conn.close()

    def test_inproc_connection_records_serve_span(self):
        from karmada_tpu.estimator.service import (
            EstimatorConnection,
            MaxAvailableReplicasRequest,
        )

        conn = EstimatorConnection("c1", _estimator_service())
        w = tracer.begin_wave("test")
        with tracer.span("estimator.refresh") as parent:
            conn.call(
                "MaxAvailableReplicas",
                MaxAvailableReplicasRequest(
                    cluster="c1", resource_request={"cpu": 1000}
                ),
            )
        tracer.end_wave()
        serve = [
            s for s in tracer.dump(w) if s["name"] == "estimator.serve"
        ]
        assert len(serve) == 1
        # same process: nests naturally, no remote re-parent marker
        assert serve[0]["parent_id"] == parent.span_id
        assert "caller" not in serve[0]["attrs"]


# --------------------------------------------------------------------------
# solver channel propagation + retry discipline
# --------------------------------------------------------------------------


class TestSolverPropagation:
    def test_retry_spans_are_distinct_parents(self, server_tracer):
        """The FAILED_PRECONDITION re-sync path: each wire attempt is its
        own client span, so the two server-side solver.solve spans (the
        stale one and the retried one) re-parent under DIFFERENT client
        spans — a retried RPC never double-records under one parent."""
        from karmada_tpu.solver import (
            RemoteSolver,
            SolverGrpcServer,
            SolverService,
        )
        from karmada_tpu.utils.builders import synthetic_fleet

        clusters = synthetic_fleet(4)
        srv = server_tracer(
            "solver", lambda: SolverGrpcServer(SolverService())
        )
        port = srv.start()
        client = RemoteSolver(
            f"127.0.0.1:{port}",
            timeout_seconds=60.0,
            cluster_source=lambda: clusters,
        )
        try:
            from karmada_tpu.utils.builders import dynamic_weight_placement
            from karmada_tpu.scheduler import BindingProblem

            problems = [
                BindingProblem(
                    key="b0",
                    placement=dynamic_weight_placement(),
                    replicas=3,
                    requests={"cpu": 100},
                    gvk="apps/v1/Deployment",
                )
            ]
            w = tracer.begin_wave("test")
            with tracer.span("scheduler.pass"):
                # the engine resolves the module-global tracer at call
                # time (function-level imports); in a real sidecar that
                # IS the sidecar's ring — point it there for the call so
                # engine spans land beside the handler spans. The solver
                # CLIENT bound the real global at module import, so its
                # spans keep landing in the plane ring.
                tracing.tracer = server_tracer.ring
                try:
                    results = client.schedule(problems)  # no sync: retry
                finally:
                    tracing.tracer = tracer
            tracer.end_wave()
            assert results and results[0].success
            score_spans = [
                s for s in tracer.dump(w)
                if s["name"] == "solver.rpc"
                and s["attrs"].get("method") == "ScoreAndAssign"
            ]
            sync_spans = [
                s for s in tracer.dump(w)
                if s["name"] == "solver.rpc"
                and s["attrs"].get("method") == "SyncClusters"
            ]
            assert [s["attrs"]["attempt"] for s in score_spans] == [1, 2]
            assert len(sync_spans) == 1
            solve = [
                s for s in server_tracer.ring.dump(w)
                if s["name"] == "solver.solve"
            ]
            sync = [
                s for s in server_tracer.ring.dump(w)
                if s["name"] == "solver.sync"
            ]
            assert len(solve) == 2 and len(sync) == 1
            assert solve[0]["attrs"]["error"] == "stale_snapshot"
            parents = {s["attrs"]["remote_parent"] for s in solve}
            assert parents == {s["span_id"] for s in score_spans}
            assert sync[0]["attrs"]["remote_parent"] == (
                sync_spans[0]["span_id"]
            )
            # engine spans recorded in the sidecar ring nest under the
            # solve handler span — the caller's wave reaches the kernels
            retried = next(
                s for s in solve if "error" not in s["attrs"]
            )
            nested = [
                s for s in server_tracer.ring.dump(w)
                if s["parent_id"] == retried["span_id"]
            ]
            assert nested, "engine spans must nest under solver.solve"
        finally:
            client.close()
            srv.stop()


# --------------------------------------------------------------------------
# bus channel propagation
# --------------------------------------------------------------------------


class TestBusPropagation:
    def test_apply_and_watch_spans(self, server_tracer):
        from karmada_tpu.bus.service import StoreBusServer, StoreReplica
        from karmada_tpu.utils import Store
        from karmada_tpu.utils.builders import new_deployment

        srv = server_tracer("bus", lambda: StoreBusServer(Store()))
        port = srv.start()
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        try:
            assert replica.wait_synced(10)
            w = tracer.begin_wave("test")
            with tracer.span("settle"):
                with tracer.span("controller.binding"):
                    replica.apply(new_deployment("d1", replicas=2))
            tracer.end_wave()
            client = [
                s for s in tracer.dump(w) if s["name"] == "bus.rpc"
            ]
            assert len(client) == 1
            assert client[0]["attrs"]["method"] == "Apply"
            server = [
                s for s in server_tracer.ring.dump(w)
                if s["name"] == "bus.apply"
            ]
            assert len(server) == 1
            assert server[0]["attrs"]["remote_parent"] == (
                client[0]["span_id"]
            )
            assert server[0]["attrs"]["caller"] == "plane"
            # the boot Watch replay recorded a bus.watch span (wave 0 —
            # the replica connected outside any wave)
            watch = [
                s for s in server_tracer.ring.dump()
                if s["name"] == "bus.watch"
            ]
            assert watch and watch[0]["attrs"]["replayed"] == 0
        finally:
            replica.close()
            srv.stop()


# --------------------------------------------------------------------------
# the stitcher
# --------------------------------------------------------------------------


class TestStitcher:
    def _plane_and_peer(self, server_tracer):
        """One wave whose estimator RPC crossed into the peer ring."""
        from karmada_tpu.estimator.grpc_transport import (
            EstimatorGrpcServer,
            GrpcEstimatorConnection,
        )
        from karmada_tpu.estimator.service import (
            MaxAvailableReplicasBatchRequest,
        )

        srv = server_tracer(
            "estimator", lambda: EstimatorGrpcServer(_estimator_service())
        )
        port = srv.start()
        conn = GrpcEstimatorConnection(
            "c1", f"127.0.0.1:{port}", timeout_seconds=5.0
        )
        try:
            w = tracer.begin_wave("test")
            with tracer.span("settle"):
                with tracer.span("estimator.refresh"):
                    conn.call(
                        "MaxAvailableReplicasBatch",
                        MaxAvailableReplicasBatchRequest(
                            clusters=["c1"], dims=DIMS,
                            rows=[[1000, 1 << 20, 1]],
                        ),
                    )
            tracer.end_wave()
        finally:
            conn.close()
            srv.stop()
        return w

    def test_stitch_reparents_and_computes_channels(self, server_tracer):
        w = self._plane_and_peer(server_tracer)
        local = trace_debug_doc(tracer_obj=tracer)
        peer = trace_debug_doc(tracer_obj=server_tracer.ring)
        doc = stitch_dumps(local, {"estimator": peer}, wave=w)
        assert doc["procs"] == ["estimator", "plane"]
        assert len(doc["waves"]) == 1
        summary = doc["waves"][0]
        assert summary["stitched"] is True
        assert summary["wave"] == w
        # total is the CALLER-side wall (the settle root) — the
        # re-parented remote span must not inflate it
        settle = next(
            s for s in local["spans"] if s["name"] == "settle"
        )
        assert summary["total_s"] == pytest.approx(
            settle["duration_s"], abs=1e-6
        )
        assert "estimator.serve" in summary["phases"]
        assert set(summary["process_s"]) == {"estimator", "plane"}
        ch = summary["channels"]["estimator"]
        assert ch["rpcs"] == 1
        assert ch["server_s"] > 0
        assert ch["network_s"] >= 0
        assert ch["client_s"] == pytest.approx(
            ch["server_s"] + ch["network_s"], abs=1e-5
        )
        # full attribution: every span's self time telescopes under the
        # root, so coverage stays near 1 even across processes
        assert 0.9 <= summary["coverage"] <= 1.0001

    def test_orphaned_server_span_never_inflates_total(self):
        """A handler span whose client span fell off the ring must not
        become a root (total_s is the caller-side wall)."""
        spans = [
            {"name": "settle", "wave": 1, "span_id": 1, "parent_id": None,
             "trace_id": "t", "duration_s": 1.0, "attrs": {},
             "proc": "plane"},
            {"name": "estimator.serve", "wave": 1, "span_id": 1,
             "parent_id": None, "trace_id": "t", "duration_s": 0.4,
             "attrs": {"remote_parent": 999, "caller": "plane"},
             "proc": "estimator"},
        ]
        summary = tracing.stitch_spans(spans, 1, "t")
        assert summary["total_s"] == pytest.approx(1.0)
        assert summary["phases"]["estimator.serve"] == pytest.approx(0.4)

    def test_wave_summary_stitched_pulls_registered_peers(
        self, server_tracer
    ):
        """wave_summary(stitched=True) fetches every registered peer's
        /debug/traces over HTTP and answers the stitched shape."""
        from karmada_tpu.utils.metrics import MetricsServer

        w = self._plane_and_peer(server_tracer)
        # serve the PEER ring at a metrics port: monkey-build a server
        # whose /debug/traces answers the peer's doc
        peer_doc = trace_debug_doc(tracer_obj=server_tracer.ring)

        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps(peer_doc).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            tracing.register_peer(
                "estimator", f"127.0.0.1:{httpd.server_address[1]}"
            )
            summary = tracer.wave_summary(w, stitched=True)
            assert summary["stitched"] is True
            assert "estimator" in summary["process_s"]
            assert summary["channels"]["estimator"]["rpcs"] == 1
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_unreachable_peer_skipped(self):
        docs = tracing.fetch_peer_dumps({"dead": "127.0.0.1:1"},
                                        timeout=0.2)
        assert docs == {}

    def test_peers_from_env(self, monkeypatch):
        monkeypatch.setenv(
            "KARMADA_TPU_TRACE_PEERS",
            "solver=127.0.0.1:1001, bus=127.0.0.1:1002,bad-entry,=x",
        )
        added = tracing.register_peers_from_env()
        assert added == {
            "solver": "127.0.0.1:1001", "bus": "127.0.0.1:1002",
        }
        assert tracing.peers() == added


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------


@pytest.fixture()
def flight_env(tmp_path, monkeypatch):
    monkeypatch.setenv("KARMADA_TPU_FLIGHT_DIR", str(tmp_path))
    return tmp_path / "flight.jsonl"


class TestFlightRecorder:
    def _wave(self, tr, sleep=0.0):
        w = tr.begin_wave("test")
        with tr.span("settle"):
            if sleep:
                time.sleep(sleep)
        return tr.end_wave(), w

    def test_disarmed_by_default(self, flight_env, monkeypatch):
        monkeypatch.delenv("KARMADA_TPU_TRACE_SLO_SECONDS", raising=False)
        tr = WaveTracer()
        self._wave(tr, sleep=0.01)
        assert not flight_env.exists()

    def test_fires_on_slo_breach(self, flight_env, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0.001")
        tr = WaveTracer()
        closed, w = self._wave(tr, sleep=0.02)
        assert closed == w
        records = tracing.load_flight_records(str(flight_env))
        assert len(records) == 1
        rec = records[0]
        assert rec["wave"] == w
        assert any(r.startswith("slo:") for r in rec["reasons"])
        assert rec["spans"] and rec["summary"]["stitched"] is True

    def test_healthy_wave_writes_nothing(self, flight_env, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "60")
        tr = WaveTracer()
        self._wave(tr)
        assert not flight_env.exists()

    def test_fires_on_degraded_pass(self, flight_env, monkeypatch):
        from karmada_tpu.utils.metrics import degraded_passes

        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "60")
        tr = WaveTracer()
        w = tr.begin_wave("test")
        with tr.span("settle"):
            degraded_passes.inc(channel="estimator")
        tr.end_wave()
        records = tracing.load_flight_records(str(flight_env))
        assert [r["wave"] for r in records] == [w]
        assert records[0]["reasons"] == ["degraded-pass"]
        delta = records[0]["metrics_delta"]
        assert "karmada_tpu_degraded_passes_total" in delta

    def test_fires_on_breaker_transition_span(self, flight_env,
                                              monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "60")
        tr = WaveTracer()
        w = tr.begin_wave("test")
        with tr.span("settle"):
            tr.record("channel.breaker", 0.0, channel="solver",
                      from_state="closed", to_state="open")
        tr.end_wave()
        records = tracing.load_flight_records(str(flight_env))
        assert records[0]["wave"] == w
        assert "breaker-transition" in records[0]["reasons"]

    def test_disk_ring_cap(self, flight_env, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0.0001")
        monkeypatch.setenv("KARMADA_TPU_FLIGHT_CAP", "2")
        tr = WaveTracer()
        waves = [self._wave(tr, sleep=0.002)[0] for _ in range(4)]
        records = tracing.load_flight_records(str(flight_env))
        assert [r["wave"] for r in records] == waves[-2:]

    def test_analyze_rerenders_identically(self, flight_env, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0.001")
        tr = WaveTracer()
        w = tr.begin_wave("test")
        with tr.span("settle"):
            with tr.span("scheduler.pass"):
                time.sleep(0.01)
        tr.end_wave()
        from karmada_tpu.cli import cmd_trace_analyze

        doc = cmd_trace_analyze(str(flight_env), wave=w)
        assert doc["identical"] is True
        assert doc["wave"] == w
        assert "scheduler.pass" in doc["summary"]["phases"]
        assert f"wave {w}" in doc["table"]

    def test_recorder_failure_never_aborts_the_wave(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0.0001")
        monkeypatch.setenv("KARMADA_TPU_FLIGHT_DIR", "/dev/null/nope")
        tr = WaveTracer()
        closed, w = self._wave(tr, sleep=0.002)
        assert closed == w  # no raise


# --------------------------------------------------------------------------
# CLI surfaces
# --------------------------------------------------------------------------


class TestCliTrace:
    def test_dump_stitch_with_explicit_peer(self, server_tracer):
        from karmada_tpu.cli import cmd_trace_dump

        helper = TestStitcher()
        w = helper._plane_and_peer(server_tracer)
        peer_doc = trace_debug_doc(tracer_obj=server_tracer.ring)

        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps(peer_doc).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            doc = cmd_trace_dump(
                stitch=True, wave=w,
                peers=f"estimator=127.0.0.1:{httpd.server_address[1]}",
            )
            assert doc["procs"] == ["estimator", "plane"]
            assert doc["waves"][0]["channels"]["estimator"]["rpcs"] == 1
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_dump_stitch_no_peers_is_local_only(self):
        from karmada_tpu.cli import cmd_trace_dump

        w = tracer.begin_wave("test")
        with tracer.span("settle"):
            pass
        tracer.end_wave()
        doc = cmd_trace_dump(stitch=True, wave=w)
        assert doc["procs"] == ["plane"]
        assert doc["waves"][0]["stitched"] is True

    def test_analyze_missing_record_errors(self, tmp_path):
        from karmada_tpu.cli import cmd_trace_analyze

        empty = tmp_path / "flight.jsonl"
        empty.write_text("")
        with pytest.raises(ValueError):
            cmd_trace_analyze(str(empty))

    def test_cli_main_trace_analyze(self, tmp_path, monkeypatch, capsys):
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0.0001")
        monkeypatch.setenv("KARMADA_TPU_FLIGHT_DIR", str(tmp_path))
        tr = WaveTracer()
        tr.begin_wave("test")
        with tr.span("settle"):
            time.sleep(0.002)
        tr.end_wave()
        from karmada_tpu.cli import main

        rc = main(["trace", "analyze", str(tmp_path / "flight.jsonl")])
        assert rc == 0
        out = capsys.readouterr().out
        assert json.loads(out)["identical"] is True
