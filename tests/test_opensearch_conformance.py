"""OpenSearch wire-conformance fixtures (VERDICT r4 next #8).

Golden request shapes for every call the reference client issues
(pkg/search/backendstore/opensearch.go:118-284): index create with the
exact mapping const, per-document PUT /{index}/_doc/{uid} with the
reference's document shape (metadata flattened, RFC3339 creation
timestamp, the resource.karmada.io/cached-from-cluster annotation,
spec/status as JSON strings), and DELETE /{index}/_doc/{uid}. The
transcript is captured from OUR client against a recording endpoint and
checked field for field — then the same flows replay against the stand-in
OpenSearchServer to prove behavior (this file is the falsifiable fixture
the round-4 verdict asked for; against a real node the same recorder
assertions apply unchanged).
"""

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.search.opensearch import (
    CACHE_SOURCE_ANNOTATION,
    DEFAULT_PREFIX,
    OpenSearchBackend,
    OpenSearchServer,
    index_name,
    rfc3339,
)

# the reference's mapping const, transcribed from opensearch.go:41-116
GOLDEN_MAPPING = {
    "settings": {"index": {"number_of_shards": 1, "number_of_replicas": 0}},
    "mappings": {
        "properties": {
            "apiVersion": {"type": "text"},
            "kind": {"type": "text"},
            "metadata": {
                "properties": {
                    "annotations": {"type": "object", "enabled": False},
                    "creationTimestamp": {"type": "text"},
                    "deletionTimestamp": {"type": "text"},
                    "labels": {"type": "object", "enabled": False},
                    "name": {
                        "type": "text",
                        "fields": {
                            "keyword": {"type": "keyword", "ignore_above": 256}
                        },
                    },
                    "namespace": {
                        "type": "text",
                        "fields": {
                            "keyword": {"type": "keyword", "ignore_above": 256}
                        },
                    },
                    "ownerReferences": {"type": "text"},
                    "resourceVersion": {
                        "type": "text",
                        "fields": {
                            "keyword": {"type": "keyword", "ignore_above": 256}
                        },
                    },
                }
            },
            "spec": {"type": "object", "enabled": False},
            "status": {"type": "object", "enabled": False},
        }
    },
}

RFC3339_RE = re.compile(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z$")


class Recorder:
    """Accept-everything endpoint recording (method, path, body)."""

    def __init__(self):
        self.calls: list[tuple[str, str, bytes]] = []
        rec = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _handle(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                rec.calls.append((self.command, self.path, body))
                out = json.dumps({"acknowledged": True, "result": "created",
                                  "errors": False, "items": []}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            do_PUT = do_POST = do_DELETE = do_GET = _handle

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def deployment(uid="uid-123"):
    return Resource(
        api_version="apps/v1",
        kind="Deployment",
        meta=ObjectMeta(
            name="web", namespace="default", uid=uid,
            labels={"app": "web"},
            annotations={"team": "infra"},
            creation_timestamp=1700000000.0,
        ),
        spec={"replicas": 3},
        status={"readyReplicas": 3},
    )


@pytest.fixture()
def recorder():
    rec = Recorder()
    try:
        yield rec
    finally:
        rec.stop()


class TestWireConformance:
    def test_index_create_request(self, recorder):
        be = OpenSearchBackend(f"127.0.0.1:{recorder.port}")
        be.upsert("member1", deployment())
        be.flush()
        method, path, body = recorder.calls[0]
        # opensearchapi.IndicesCreateRequest -> PUT /{prefix}-{kind,lower}
        assert (method, path) == ("PUT", f"/{DEFAULT_PREFIX}-deployment")
        assert DEFAULT_PREFIX == "kubernetes"  # opensearch.go:39
        assert json.loads(body) == GOLDEN_MAPPING

    def test_document_upsert_request(self, recorder):
        be = OpenSearchBackend(f"127.0.0.1:{recorder.port}")
        be.upsert("member1", deployment())
        be.flush()
        doc_calls = [
            c for c in recorder.calls
            if "_doc" in c[1] or c[1] == "/_bulk"
        ]
        assert doc_calls, recorder.calls
        method, path, body = doc_calls[0]
        if path == "/_bulk":  # batched flush: NDJSON action+source lines
            lines = [json.loads(ln) for ln in body.decode().splitlines()]
            action = lines[0]["index"]
            assert action["_index"] == f"{DEFAULT_PREFIX}-deployment"
            assert action["_id"] == "uid-123"  # DocumentID = UID
            doc = lines[1]
        else:  # IndexRequest -> PUT /{index}/_doc/{uid}
            assert method in ("PUT", "POST")
            assert path == f"/{DEFAULT_PREFIX}-deployment/_doc/uid-123"
            doc = json.loads(body)
        # document shape, opensearch.go:203-218
        assert doc["apiVersion"] == "apps/v1"
        assert doc["kind"] == "Deployment"
        md = doc["metadata"]
        assert md["name"] == "web"
        assert md["namespace"] == "default"
        assert RFC3339_RE.match(md["creationTimestamp"])
        assert md["creationTimestamp"] == "2023-11-14T22:13:20Z"
        assert md["labels"] == {"app": "web"}
        # the cache-source annotation is stamped over the object's own
        assert md["annotations"]["team"] == "infra"
        assert (
            md["annotations"][CACHE_SOURCE_ANNOTATION] == "member1"
        )
        assert (
            CACHE_SOURCE_ANNOTATION
            == "resource.karmada.io/cached-from-cluster"
        )  # well_known_constants.go:35
        assert md["deletionTimestamp"] is None
        # spec/status ship as JSON STRINGS (json.Marshal into the doc)
        assert json.loads(doc["spec"]) == {"replicas": 3}
        assert json.loads(doc["status"]) == {"readyReplicas": 3}

    def test_document_delete_request(self, recorder):
        be = OpenSearchBackend(f"127.0.0.1:{recorder.port}")
        dep = deployment()
        be.upsert("member1", dep)
        be.flush()
        recorder.calls.clear()
        be.delete("member1", "apps/v1/Deployment", "default", "web")
        be.flush()
        dels = [
            c for c in recorder.calls if c[0] == "DELETE" or c[1] == "/_bulk"
        ]
        assert dels, recorder.calls
        method, path, body = dels[0]
        if path == "/_bulk":
            lines = [json.loads(ln) for ln in body.decode().splitlines()]
            action = lines[0]["delete"]
            assert action["_index"] == f"{DEFAULT_PREFIX}-deployment"
            assert action["_id"] == "uid-123"
        else:  # DeleteRequest -> DELETE /{index}/_doc/{uid}
            assert path == f"/{DEFAULT_PREFIX}-deployment/_doc/uid-123"

    def test_zero_creation_timestamp_is_go_zero_time(self):
        # Go's zero metav1.Time formats as year one — unset timestamps must
        # render exactly as the reference client would send them
        assert rfc3339(0.0) == "0001-01-01T00:00:00Z"
        assert rfc3339(None) == "0001-01-01T00:00:00Z"

    def test_index_name_convention(self):
        assert index_name("Deployment") == "kubernetes-deployment"
        assert index_name("Pod") == "kubernetes-pod"


class TestReplayAgainstStandIn:
    """The same client flows against the in-repo OpenSearch stand-in node:
    behavioral proof that the recorded wire shapes are accepted and
    queryable (swap the URL for a real node and this class still passes)."""

    @pytest.fixture()
    def node(self):
        srv = OpenSearchServer()
        port = srv.start()
        try:
            yield f"127.0.0.1:{port}"
        finally:
            srv.stop()

    def test_upsert_search_delete_roundtrip(self, node):
        be = OpenSearchBackend(node)
        be.upsert("member1", deployment())
        be.flush()
        hits = be.search("name:web")
        assert len(hits) == 1
        assert hits[0]["name"] == "web"
        assert hits[0]["cluster"] == "member1"
        # idempotent re-create of the index is tolerated (already-exists)
        be2 = OpenSearchBackend(node)
        be2.upsert("member2", deployment(uid="uid-456"))
        be2.flush()
        assert be2.count() == 2
        be.delete("member1", "apps/v1/Deployment", "default", "web")
        be.flush()
        assert be.count() == 1
