"""The vectorized-numpy host baseline must match the pure-Python oracle
(refimpl.divider) placement-for-placement across all four strategies and
Steady/Fresh/scale cohorts — it is only a legitimate baseline if it computes
the same thing."""

import numpy as np
import pytest

from karmada_tpu import refimpl as R
from karmada_tpu.refimpl.divider_np import assign_batch_np


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_numpy_divider_matches_python_oracle(seed):
    rng = np.random.default_rng(seed)
    b, c = 200, 40
    strategy = rng.integers(0, 4, b).astype(np.int32)
    replicas = rng.integers(0, 60, b).astype(np.int32)
    candidates = rng.random((b, c)) < 0.7
    static_w = (rng.integers(0, 5, (b, c)) * (rng.random((b, c)) < 0.5)).astype(
        np.int32
    )
    avail_raw = rng.integers(0, 50, (b, c)).astype(np.int32)
    prev = (rng.integers(0, 20, (b, c)) * (rng.random((b, c)) < 0.15)).astype(
        np.int32
    )
    fresh = rng.random(b) < 0.25

    got, unsched = assign_batch_np(
        strategy, replicas, candidates, static_w, avail_raw, prev, fresh
    )

    for i in range(b):
        cand_idx = np.flatnonzero(candidates[i]).tolist()
        prob = R.DivisionProblem(
            replicas=int(replicas[i]),
            strategy=int(strategy[i]),
            candidates=cand_idx,
            available=[int(avail_raw[i, j]) for j in cand_idx],
            static_weights=[int(static_w[i, j]) for j in cand_idx],
            prev={int(j): int(prev[i, j]) for j in np.flatnonzero(prev[i])}
            or None,
            fresh=bool(fresh[i]),
        )
        try:
            want = R.assign_replicas(prob)
            assert not unsched[i], i
            want_row = np.zeros(c, np.int32)
            for j, n in want.items():
                want_row[j] = n
            assert np.array_equal(got[i], want_row), (
                i, int(strategy[i]), got[i].tolist(), want_row.tolist(),
            )
        except R.UnschedulableError:
            assert unsched[i], i
