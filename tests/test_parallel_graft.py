"""Driver-contract regression tests: graft entry + sharded solver step."""

import sys

import numpy as np
import jax

sys.path.insert(0, "/root/repo")


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.assignment.shape == (256, 128)
        assert (np.asarray(out.assignment) >= 0).all()

    def test_dryrun_multichip_8(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)  # 4x2 mesh: binding + cluster sharding

    def test_dryrun_multichip_odd(self):
        import __graft_entry__ as g

        g.dryrun_multichip(3)


class TestShardedStep:
    def test_sharded_matches_unsharded(self):
        from karmada_tpu.parallel.solver import (
            default_mesh,
            make_sharded_step,
            schedule_step,
        )
        import __graft_entry__ as g

        args = g._example_args(b=64, c=32)
        mesh = default_mesh(8, cluster_axis=2)
        sharded = make_sharded_step(mesh, shard_clusters=True)
        a = sharded(*args)
        b = schedule_step(*args)
        np.testing.assert_array_equal(
            np.asarray(a.assignment), np.asarray(b.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(a.unschedulable), np.asarray(b.unschedulable)
        )

    def test_interned_step_matches_plain(self):
        import jax.numpy as jnp
        from karmada_tpu.parallel import schedule_step, schedule_step_interned
        import __graft_entry__ as g

        args = g._example_args(b=64, c=32)
        (available_cap, has_summary, requests), rest = args[:3], args[3:]
        profiles, inv = np.unique(np.asarray(requests), axis=0,
                                  return_inverse=True)
        plain = schedule_step(*args)
        interned = schedule_step_interned(
            available_cap, has_summary, jnp.asarray(profiles),
            jnp.asarray(inv.astype(np.int32)), *rest,
        )
        np.testing.assert_array_equal(
            np.asarray(plain.assignment), np.asarray(interned.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.unschedulable), np.asarray(interned.unschedulable)
        )
