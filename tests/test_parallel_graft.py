"""Driver-contract regression tests: graft entry + sharded solver step."""

import sys

import numpy as np
import jax

sys.path.insert(0, "/root/repo")


class TestGraftEntry:
    def test_entry_compiles_and_runs(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.assignment.shape == (256, 128)
        assert (np.asarray(out.assignment) >= 0).all()

    def test_dryrun_multichip_8(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)  # 4x2 mesh: binding + cluster sharding

    def test_dryrun_multichip_odd(self):
        import __graft_entry__ as g

        g.dryrun_multichip(3)


class TestShardedStep:
    def test_sharded_matches_unsharded(self):
        from karmada_tpu.parallel.solver import (
            default_mesh,
            make_sharded_step,
            schedule_step,
        )
        import __graft_entry__ as g

        args = g._example_args(b=64, c=32)
        mesh = default_mesh(8, cluster_axis=2)
        sharded = make_sharded_step(mesh, shard_clusters=True)
        a = sharded(*args)
        b = schedule_step(*args)
        np.testing.assert_array_equal(
            np.asarray(a.assignment), np.asarray(b.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(a.unschedulable), np.asarray(b.unschedulable)
        )

    def test_interned_step_matches_plain(self):
        import jax.numpy as jnp
        from karmada_tpu.parallel import schedule_step, schedule_step_interned
        import __graft_entry__ as g

        args = g._example_args(b=64, c=32)
        (available_cap, has_summary, requests), rest = args[:3], args[3:]
        profiles, inv = np.unique(np.asarray(requests), axis=0,
                                  return_inverse=True)
        plain = schedule_step(*args)
        interned = schedule_step_interned(
            available_cap, has_summary, jnp.asarray(profiles),
            jnp.asarray(inv.astype(np.int32)), *rest,
        )
        np.testing.assert_array_equal(
            np.asarray(plain.assignment), np.asarray(interned.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.unschedulable), np.asarray(interned.unschedulable)
        )


class TestBenchShardedStorm:
    def test_config5_shards_on_virtual_mesh(self, tmp_path):
        """bench.py config 5 must run sharded over the 8-device virtual CPU
        mesh with identical placements (the v5e-8 deployment shape)."""
        import os
        import json
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        proc = subprocess.run(
            [
                sys.executable, "/root/repo/bench.py", "--cpu",
                "--kernel-only",
                "--bindings", "512", "--chunk", "256", "--clusters", "64",
                "--repeats", "1", "--sample", "48",
            ],
            capture_output=True, text=True, timeout=600, env=env,
            cwd="/root/repo",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "# mesh: 8 devices over the binding axis" in proc.stderr
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result["unit"] == "s" and result["value"] > 0

    def test_2d_sharding_placement_identity_at_10k_clusters(self):
        """Placement identity under binding x cluster (2D) sharding at 10k
        clusters (VERDICT r1 #6): the c-axis sort collectives must not
        change a single placement."""
        import os
        import json
        import subprocess

        env = dict(os.environ)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        proc = subprocess.run(
            [
                sys.executable, "/root/repo/bench.py", "--cpu",
                "--shard", "4x2",
                "--bindings", "256", "--clusters", "10000", "--repeats", "1",
            ],
            capture_output=True, text=True, timeout=600, env=env,
            cwd="/root/repo",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result["identical"] is True
        assert "# identity under 4x2 sharding: True" in proc.stderr

    def test_engine_bench_verifies_on_cpu(self):
        """bench.py config 5 engine path at toy scale: every verification
        tier (numpy full-set, oracle sample, mixed strategies) must be
        mismatch-free."""
        import os
        import json
        import subprocess

        proc = subprocess.run(
            [
                sys.executable, "/root/repo/bench.py", "--cpu",
                "--bindings", "512", "--chunk", "256", "--clusters", "64",
                "--repeats", "1", "--sample", "48", "--mix-sample", "64",
            ],
            capture_output=True, text=True, timeout=600,
            env=dict(os.environ), cwd="/root/repo",
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result["verified_mismatches"] == 0
        assert result["verified_rows"] >= 512 + 48 + 64
