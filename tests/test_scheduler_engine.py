"""TensorScheduler end-to-end: filters, affinity groups, spread selection,
assignment — mirroring the reference's scheduler core test strategy
(fabricated clusters, exact TargetCluster assertions)."""

import numpy as np
import pytest

from karmada_tpu.api import (
    ClusterAffinity,
    ClusterAffinityTerm,
    LabelSelector,
    Placement,
    SpreadConstraint,
    Taint,
    Toleration,
)
from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.utils.builders import (
    aggregated_placement,
    duplicated_placement,
    dynamic_weight_placement,
    new_cluster,
    static_weight_placement,
    synthetic_fleet,
)
from karmada_tpu.utils.quantity import parse_resource_list

REQ = parse_resource_list({"cpu": "1", "memory": "2Gi"})


def make_snapshot(clusters):
    return ClusterSnapshot(clusters)


class TestFilters:
    def test_cluster_names_affinity(self):
        snap = make_snapshot([new_cluster(f"m{i}") for i in range(4)])
        sched = TensorScheduler(snap)
        pl = duplicated_placement(
            cluster_affinity=ClusterAffinity(cluster_names=["m1", "m3"])
        )
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=2, gvk="apps/v1/Deployment")]
        )
        assert res.clusters == {"m1": 2, "m3": 2}

    def test_label_selector_affinity(self):
        clusters = [
            new_cluster("a", labels={"env": "prod", "tier": "t1"}),
            new_cluster("b", labels={"env": "dev"}),
            new_cluster("c", labels={"env": "prod"}),
        ]
        sched = TensorScheduler(make_snapshot(clusters))
        pl = duplicated_placement(
            cluster_affinity=ClusterAffinity(
                label_selector=LabelSelector(match_labels={"env": "prod"})
            )
        )
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=1, gvk="apps/v1/Deployment")]
        )
        assert set(res.clusters) == {"a", "c"}

    def test_taint_filter_and_toleration(self):
        taint = Taint(key="k", value="v", effect="NoSchedule")
        clusters = [new_cluster("ok"), new_cluster("tainted", taints=[taint])]
        sched = TensorScheduler(make_snapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=duplicated_placement(), replicas=1,
                            gvk="apps/v1/Deployment")]
        )
        assert set(res.clusters) == {"ok"}
        pl = duplicated_placement(
            cluster_tolerations=[Toleration(key="k", operator="Exists")]
        )
        [res] = sched.schedule(
            [BindingProblem(key="b2", placement=pl, replicas=1, gvk="apps/v1/Deployment")]
        )
        assert set(res.clusters) == {"ok", "tainted"}

    def test_tainted_cluster_lenient_when_already_placed(self):
        taint = Taint(key="k", value="v", effect="NoExecute")
        clusters = [new_cluster("a"), new_cluster("b", taints=[taint])]
        sched = TensorScheduler(make_snapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=dynamic_weight_placement(), replicas=4,
                            gvk="apps/v1/Deployment", prev={"b": 2})]
        )
        # b keeps being a candidate because it already holds replicas
        assert "b" in res.clusters

    def test_api_enablement(self):
        clusters = [
            new_cluster("with", api_enablements=["apps/v1/Deployment"]),
            new_cluster("without", api_enablements=["v1/ConfigMap"]),
        ]
        sched = TensorScheduler(make_snapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=duplicated_placement(), replicas=1,
                            gvk="apps/v1/Deployment")]
        )
        assert set(res.clusters) == {"with"}

    def test_eviction_filter(self):
        clusters = [new_cluster("a"), new_cluster("b")]
        sched = TensorScheduler(make_snapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=duplicated_placement(), replicas=1,
                            gvk="apps/v1/Deployment", evict_clusters=("a",))]
        )
        assert set(res.clusters) == {"b"}


class TestAffinityGroups:
    def test_ordered_groups_fallback(self):
        clusters = [
            new_cluster("primary", cpu="2"),  # too small for 8 x 1cpu
            new_cluster("backup", cpu="100"),
        ]
        pl = dynamic_weight_placement(
            cluster_affinities=[
                ClusterAffinityTerm(affinity_name="primary", cluster_names=["primary"]),
                ClusterAffinityTerm(affinity_name="backup", cluster_names=["backup"]),
            ]
        )
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=8,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert res.success and res.affinity_name == "backup"
        assert res.clusters == {"backup": 8}

    def test_first_group_wins_when_it_fits(self):
        clusters = [new_cluster("primary"), new_cluster("backup")]
        pl = dynamic_weight_placement(
            cluster_affinities=[
                ClusterAffinityTerm(affinity_name="primary", cluster_names=["primary"]),
                ClusterAffinityTerm(affinity_name="backup", cluster_names=["backup"]),
            ]
        )
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=2,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert res.affinity_name == "primary" and res.clusters == {"primary": 2}


class TestAssignmentStrategies:
    def test_static_weight(self):
        clusters = [new_cluster(n) for n in ("a", "b", "c")]
        pl = static_weight_placement({"a": 3, "b": 2, "c": 1})
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=12,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert res.clusters == {"a": 6, "b": 4, "c": 2}

    def test_dynamic_weight_proportional_to_capacity(self):
        clusters = [
            new_cluster("small", cpu="10", memory="20Gi", allocated={"cpu": 5}),
            new_cluster("big", cpu="20", memory="40Gi", allocated={"cpu": 5}),
        ]
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=dynamic_weight_placement(), replicas=10,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        # availability 5 vs 15 -> weights give 2 (floor 2.5) + remainder rules
        assert sum(res.clusters.values()) == 10
        assert res.clusters["big"] > res.clusters["small"]

    def test_aggregated_packs_fewest(self):
        clusters = [
            new_cluster("a", cpu="6"),
            new_cluster("b", cpu="30"),
            new_cluster("c", cpu="10"),
        ]
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=aggregated_placement(), replicas=8,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert res.clusters == {"b": 8}

    def test_zero_replica_binding_selects_all(self):
        clusters = [new_cluster("a"), new_cluster("b")]
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=duplicated_placement(), replicas=0,
                            gvk="apps/v1/Deployment")]
        )
        assert res.success and res.clusters == {}
        assert set(res.feasible) == {"a", "b"}

    def test_unschedulable_reports_error(self):
        clusters = [new_cluster("tiny", cpu="1")]
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=dynamic_weight_placement(), replicas=50,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert not res.success and "not enough" in res.error


class TestSpreadConstraints:
    def _regional_clusters(self):
        return [
            new_cluster("r1a", region="r1", zone="r1-z1", cpu="50"),
            new_cluster("r1b", region="r1", zone="r1-z2", cpu="40"),
            new_cluster("r2a", region="r2", zone="r2-z1", cpu="30"),
            new_cluster("r3a", region="r3", zone="r3-z1", cpu="20"),
        ]

    def test_cluster_spread_max_groups(self):
        pl = dynamic_weight_placement(
            spread_constraints=[
                SpreadConstraint(spread_by_field="cluster", min_groups=1, max_groups=2)
            ]
        )
        sched = TensorScheduler(ClusterSnapshot(self._regional_clusters()))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=10,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert res.success and len(res.clusters) <= 2
        assert sum(res.clusters.values()) == 10

    def test_cluster_spread_min_groups_fit_error(self):
        pl = dynamic_weight_placement(
            spread_constraints=[
                SpreadConstraint(spread_by_field="cluster", min_groups=9, max_groups=9)
            ]
        )
        sched = TensorScheduler(ClusterSnapshot(self._regional_clusters()))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=2,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert not res.success

    def test_region_spread(self):
        pl = dynamic_weight_placement(
            spread_constraints=[
                SpreadConstraint(spread_by_field="region", min_groups=2, max_groups=2),
                SpreadConstraint(spread_by_field="cluster", min_groups=2, max_groups=3),
            ]
        )
        sched = TensorScheduler(ClusterSnapshot(self._regional_clusters()))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=12,
                            requests=REQ, gvk="apps/v1/Deployment")]
        )
        assert res.success
        regions = {n[:2] for n in res.clusters}
        assert len(regions) == 2
        assert sum(res.clusters.values()) == 12

    def test_missing_region_field_filtered(self):
        clusters = [
            new_cluster("with-region", region="r1"),
            new_cluster("no-region"),
        ]
        pl = duplicated_placement(
            spread_constraints=[
                SpreadConstraint(spread_by_field="region", min_groups=1, max_groups=1),
                SpreadConstraint(spread_by_field="cluster", min_groups=1, max_groups=5),
            ]
        )
        sched = TensorScheduler(ClusterSnapshot(clusters))
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=1, gvk="apps/v1/Deployment")]
        )
        assert set(res.clusters) == {"with-region"}


class TestBatch:
    def test_mixed_batch_matches_individual(self):
        fleet = synthetic_fleet(40, seed=3)
        snap = ClusterSnapshot(fleet)
        placements = [
            duplicated_placement(),
            static_weight_placement({c.name: (i % 5) + 1 for i, c in enumerate(fleet[:10])}),
            dynamic_weight_placement(),
            aggregated_placement(),
        ]
        problems = [
            BindingProblem(
                key=f"b{i}",
                placement=placements[i % 4],
                replicas=(i % 7) + 1,
                requests=REQ,
                gvk="apps/v1/Deployment",
                prev={fleet[i % 40].name: (i % 3)} if i % 2 else {},
            )
            for i in range(64)
        ]
        sched_batch = TensorScheduler(snap)
        batch_results = sched_batch.schedule(problems)
        for p, want in zip(problems, batch_results):
            [got] = TensorScheduler(snap).schedule([p])
            assert got.clusters == want.clusters, p.key
            assert got.error == want.error, p.key


class TestRandomizedBatchIsolation:
    """Fuzz: batched scheduling must equal per-binding scheduling for ANY
    mix of strategies, spread constraints, affinities, prev placements and
    evictions — catches cross-binding contamination in the batched kernels
    and the fast-path gates (which are chosen from CHUNK maxima and must
    never change per-binding results)."""

    @pytest.mark.parametrize("seed", range(4))
    def test_random_fleet_and_policies(self, seed):
        rng = np.random.default_rng(200 + seed)
        fleet = synthetic_fleet(int(rng.integers(8, 30)), seed=seed)
        snap = ClusterSnapshot(fleet)
        names = [c.name for c in fleet]

        def random_placement():
            kind = rng.integers(0, 5)
            if kind == 0:
                return duplicated_placement()
            if kind == 1:
                weights = {
                    n: int(rng.integers(1, 6))
                    for n in rng.choice(names, size=rng.integers(1, 5),
                                        replace=False)
                }
                return static_weight_placement(weights)
            if kind == 2:
                return dynamic_weight_placement()
            if kind == 3:
                return aggregated_placement()
            return dynamic_weight_placement(
                spread_constraints=[
                    SpreadConstraint(spread_by_field="cluster",
                                     min_groups=1,
                                     max_groups=int(rng.integers(1, 6))),
                ]
            )

        placements = [random_placement() for _ in range(6)]
        problems = []
        for i in range(48):
            prev = {}
            if rng.random() < 0.5:
                for n in rng.choice(names, size=rng.integers(1, 4),
                                    replace=False):
                    prev[str(n)] = int(rng.integers(1, 9))
            problems.append(BindingProblem(
                key=f"b{i}",
                placement=placements[int(rng.integers(0, len(placements)))],
                replicas=int(rng.integers(0, 30)),
                requests=REQ,
                gvk="apps/v1/Deployment",
                prev=prev,
                evict_clusters=tuple(
                    rng.choice(names, size=rng.integers(0, 2), replace=False)
                ),
                fresh=bool(rng.random() < 0.2),
            ))

        batch = TensorScheduler(snap).schedule(problems)
        for p, want in zip(problems, batch):
            [got] = TensorScheduler(snap).schedule([p])
            assert got.clusters == want.clusters, (seed, p.key)
            assert got.error == want.error, (seed, p.key)
            rs = p.placement.replica_scheduling if p.placement else None
            divided = rs is not None and rs.replica_scheduling_type == "Divided"
            if want.success and p.replicas > 0 and want.clusters and divided:
                # Divided placements preserve the replica total; Duplicated
                # broadcasts the full count everywhere by design
                assert sum(want.clusters.values()) == p.replicas, (seed, p.key)


class TestLabelOnlySpreadRefused:
    def test_spread_by_label_is_fit_error(self):
        # the reference supports only cluster/region grouping
        # (select_clusters.go:58); label-only constraints must FitError,
        # not silently pass every feasible cluster
        fleet = synthetic_fleet(6, seed=9)
        snap = ClusterSnapshot(fleet)
        placement = dynamic_weight_placement(
            spread_constraints=[
                SpreadConstraint(spread_by_label="topology.io/rack",
                                 min_groups=2),
            ]
        )
        [res] = TensorScheduler(snap).schedule([
            BindingProblem(key="b", placement=placement, replicas=4,
                           requests=REQ, gvk="apps/v1/Deployment")
        ])
        assert not res.success


class TestPlacementCacheLifetime:
    def test_cache_pins_placement_against_id_reuse(self):
        # Regression: the compiled-placement cache is keyed by id(placement).
        # If the cache did not hold a strong reference, a GC'd Placement's
        # address could be reused by a NEW Placement, silently serving the
        # stale compiled mask. Holding the reference makes reuse impossible.
        import gc
        import weakref

        snap = make_snapshot([new_cluster(f"m{i}") for i in range(4)])
        sched = TensorScheduler(snap)
        pl = duplicated_placement(
            cluster_affinity=ClusterAffinity(cluster_names=["m1"])
        )
        ref = weakref.ref(pl)
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=pl, replicas=1,
                            gvk="apps/v1/Deployment")]
        )
        assert res.clusters == {"m1": 1}
        del pl, res
        gc.collect()
        assert ref() is not None, "cache must pin the Placement it compiled"

    def test_fresh_placements_never_reuse_stale_masks(self):
        # churn placements aggressively; every new Placement must compile its
        # own mask (under the old id()-keyed cache without pinning, CPython's
        # allocator reuse made this flaky-wrong)
        import gc

        snap = make_snapshot([new_cluster(f"m{i}") for i in range(4)])
        sched = TensorScheduler(snap)
        for i in range(20):
            want = f"m{i % 4}"
            pl = duplicated_placement(
                cluster_affinity=ClusterAffinity(cluster_names=[want])
            )
            [res] = sched.schedule(
                [BindingProblem(key="b", placement=pl, replicas=1,
                                gvk="apps/v1/Deployment")]
            )
            assert res.clusters == {want: 1}, i
            del pl
            gc.collect()


class TestMaskTokenSwapSafety:
    """update_snapshot keeps compiled masks only when the FILTER fields are
    truly unchanged: a renamed label value that lands on the same interned
    bit id must still invalidate (review finding: vocab string tables are
    part of the token, not just the bit patterns)."""

    def _snap(self, env):
        from karmada_tpu.utils.builders import new_cluster

        clusters = [new_cluster(f"m{i}", cpu="50", memory="100Gi") for i in range(3)]
        for cl in clusters:
            cl.meta.labels = {"env": env}
        return ClusterSnapshot(clusters)

    def test_label_rename_invalidates_compiled_masks(self):
        from karmada_tpu.api.policy import LabelSelector

        s1 = self._snap("prod")
        engine = TensorScheduler(s1)
        pl = dynamic_weight_placement(
            cluster_affinity=ClusterAffinity(
                label_selector=LabelSelector(match_labels={"env": "prod"})
            )
        )
        p = BindingProblem(key="b", placement=pl, replicas=3,
                           requests={"cpu": 100}, gvk="apps/v1/Deployment")
        res = engine.schedule([p])[0]
        assert res.success and sum(res.clusters.values()) == 3
        # relabel every cluster env=blue: same interned bit layout,
        # different vocabulary -> the selector must stop matching
        s2 = self._snap("blue")
        assert s1.mask_token != s2.mask_token
        assert engine.update_snapshot(s2)
        res2 = engine.schedule([p])[0]
        assert not res2.success, "stale compiled mask survived the relabel"

    def test_availability_only_swap_keeps_token(self):
        s1 = self._snap("prod")
        s2 = self._snap("prod")
        for cl in s2.clusters:
            cl.status.resource_summary.allocated["cpu"] = 1000
        s2b = ClusterSnapshot(s2.clusters)
        assert s1.mask_token == s2b.mask_token


class TestTinyBatchHostFastPath:
    """Small batches (configs 1-2 scale) divide on host numpy instead of
    paying device round-trips; placements must be identical to the device
    path (forced here via a no-answer extra estimator, which disables the
    fast path without changing merge results)."""

    def test_small_batch_identity_device_vs_host(self):
        rng = np.random.default_rng(3)
        clusters = synthetic_fleet(40, seed=6)
        snap = ClusterSnapshot(clusters)
        pls = [
            dynamic_weight_placement(),
            duplicated_placement(),
            static_weight_placement(
                {c.name: (i % 3) + 1 for i, c in enumerate(clusters[:8])}
            ),
            aggregated_placement(),
        ]
        req = parse_resource_list({"cpu": "250m", "memory": "512Mi"})
        for trial in range(10):
            problems = [
                BindingProblem(
                    key=f"t{trial}b{i}", placement=pls[int(rng.integers(0, 4))],
                    replicas=int(rng.integers(0, 40)), requests=req,
                    gvk="apps/v1/Deployment",
                    prev={
                        clusters[int(j)].name: int(rng.integers(1, 9))
                        for j in rng.choice(40, int(rng.integers(0, 4)), replace=False)
                    },
                    fresh=bool(rng.random() < 0.2),
                )
                for i in range(int(rng.integers(1, 24)))
            ]
            host_eng = TensorScheduler(snap)
            got = host_eng._schedule_host(
                problems, [host_eng._compiled(p.placement) for p in problems]
            )
            # no-answer extra estimator: merge-identical, but disables the
            # host_small gate so the device kernels run
            dev_eng = TensorScheduler(
                snap,
                extra_estimators=[
                    lambda reqs, reps: np.full(
                        (len(reqs), len(clusters)), -1, np.int32
                    )
                ],
            )
            want = dev_eng._schedule_host(
                problems, [dev_eng._compiled(p.placement) for p in problems]
            )
            for w, g in zip(want, got):
                assert w.success == g.success, (trial, w.key, w.error, g.error)
                assert dict(w.clusters) == dict(g.clusters), (trial, w.key)
                assert sorted(w.feasible) == sorted(g.feasible), (trial, w.key)
