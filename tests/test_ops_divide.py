"""Golden equivalence: TPU division kernels == pure-Python oracle.

The identical-placement guarantee (BASELINE.md) is enforced here with
randomized problems across every strategy/mode cohort, plus the estimator
min-merge kernel.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from karmada_tpu import refimpl as R
from karmada_tpu.ops import (
    divide_replicas,
    general_estimate,
    merge_estimates,
    take_by_weight_batch,
)


def kernel_solve(
    problems: list[R.DivisionProblem],
    num_clusters: int,
    wide: bool = True,
    fast: tuple | None = None,
):
    """Pack oracle problems into dense arrays and run the batch kernel."""
    b = len(problems)
    c = num_clusters
    strategy = np.zeros(b, np.int32)
    replicas = np.zeros(b, np.int32)
    cand = np.zeros((b, c), bool)
    static_w = np.zeros((b, c), np.int32)
    avail = np.zeros((b, c), np.int32)
    prev = np.zeros((b, c), np.int32)
    fresh = np.zeros(b, bool)
    for i, p in enumerate(problems):
        strategy[i] = p.strategy
        replicas[i] = p.replicas
        cand[i, list(p.candidates)] = True
        if p.static_weights is not None:
            static_w[i, list(p.candidates)] = p.static_weights
        if p.available is not None:
            avail[i, list(p.candidates)] = p.available
        for idx, r in (p.prev or {}).items():
            prev[i, idx] = r
        fresh[i] = p.fresh
    res = divide_replicas(
        jnp.asarray(strategy), jnp.asarray(replicas), jnp.asarray(cand),
        jnp.asarray(static_w), jnp.asarray(avail), jnp.asarray(prev),
        jnp.asarray(fresh), wide=wide, fast=fast,
    )
    return np.asarray(res.assignment), np.asarray(res.unschedulable)


def oracle_solve(problems: list[R.DivisionProblem], num_clusters: int):
    out = np.zeros((len(problems), num_clusters), np.int32)
    unsched = np.zeros(len(problems), bool)
    for i, p in enumerate(problems):
        try:
            for idx, r in R.assign_replicas(p).items():
                out[i, idx] = r
        except R.UnschedulableError:
            unsched[i] = True
    return out, unsched


def random_problem(rng: np.random.Generator, c: int) -> R.DivisionProblem:
    strategy = int(rng.integers(0, 4))
    n_cand = int(rng.integers(1, c + 1))
    candidates = sorted(rng.choice(c, size=n_cand, replace=False).tolist())
    replicas = int(rng.integers(0, 40))
    prev = {}
    if rng.random() < 0.6:  # previously scheduled (possibly on non-candidates)
        n_prev = int(rng.integers(1, c + 1))
        for idx in rng.choice(c, size=n_prev, replace=False):
            prev[int(idx)] = int(rng.integers(0, 15))
    return R.DivisionProblem(
        replicas=replicas,
        strategy=strategy,
        candidates=candidates,
        static_weights=[int(w) for w in rng.integers(0, 5, size=n_cand)],
        available=[int(a) for a in rng.integers(0, 25, size=n_cand)],
        prev=prev or None,
        fresh=bool(rng.random() < 0.25),
    )


class TestKernelOracleEquivalence:
    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_batches(self, seed):
        rng = np.random.default_rng(seed)
        c = int(rng.integers(2, 12))
        problems = [random_problem(rng, c) for _ in range(64)]
        got, got_unsched = kernel_solve(problems, c)
        want, want_unsched = oracle_solve(problems, c)
        np.testing.assert_array_equal(got_unsched, want_unsched)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_batches_narrow_fast(self, seed):
        """The int32 fast path (wide=False) and the packed-key top_k
        dispense (fast=...) must stay placement-identical under the bounds
        the packing layer gates on: weights <= 40 (6b incl. fresh sums),
        prev <= 14 (4b), c <= 12 (4b), replicas <= 39 -> k_top covers
        min(max replicas, c)."""
        rng = np.random.default_rng(1000 + seed)
        c = int(rng.integers(2, 12))
        problems = [random_problem(rng, c) for _ in range(64)]
        want, want_unsched = oracle_solve(problems, c)
        for fast in (None, (6, 4, c, True), (6, 4, c, False)):
            got, got_unsched = kernel_solve(problems, c, wide=False, fast=fast)
            np.testing.assert_array_equal(got_unsched, want_unsched)
            np.testing.assert_array_equal(got, want)

    def test_large_values_no_overflow(self):
        # weight * replicas products beyond int32: 2e6 avail, 30k replicas
        p = R.DivisionProblem(
            replicas=30_000,
            strategy=R.DYNAMIC_WEIGHT,
            candidates=[0, 1, 2],
            available=[2_000_000, 1_500_000, 1_000_000],
        )
        got, gu = kernel_solve([p], 3)
        want, wu = oracle_solve([p], 3)
        np.testing.assert_array_equal(got, want)
        assert not gu[0] and not wu[0]
        assert got.sum() == 30_000


class TestDispenseBatch:
    def test_matches_oracle(self):
        rng = np.random.default_rng(7)
        b, c = 32, 9
        num = rng.integers(0, 50, size=b).astype(np.int32)
        w = rng.integers(0, 8, size=(b, c)).astype(np.int32)
        last = rng.integers(0, 10, size=(b, c)).astype(np.int32)
        init = rng.integers(0, 5, size=(b, c)).astype(np.int32)
        got = np.asarray(
            take_by_weight_batch(
                jnp.asarray(num), jnp.asarray(w), jnp.asarray(last), jnp.asarray(init)
            )
        )
        for i in range(b):
            weights = [(j, int(w[i, j]), int(last[i, j])) for j in range(c)]
            want = R.take_by_weight(
                int(num[i]), weights, {j: int(init[i, j]) for j in range(c)}
            )
            np.testing.assert_array_equal(
                got[i], [want.get(j, 0) for j in range(c)]
            )


class TestProfileInterning:
    def test_gather_matches_direct_indexing(self):
        from karmada_tpu.ops.estimate import gather_profile_rows

        rng = np.random.default_rng(3)
        # include sentinel-like extremes: the 16-bit matmul split must keep
        # every int32 exact (MAX_INT32, -1 no-answer, zeros)
        table = rng.integers(0, 2**31 - 1, size=(6, 37), dtype=np.int32)
        table[0, :3] = [2**31 - 1, -1, 0]
        idx = rng.integers(0, 6, size=50).astype(np.int32)
        got = np.asarray(gather_profile_rows(jnp.asarray(table), jnp.asarray(idx)))
        np.testing.assert_array_equal(got, table[idx])

    def test_interned_equals_plain_estimate(self):
        from karmada_tpu.ops.estimate import general_estimate_interned

        rng = np.random.default_rng(4)
        cap = jnp.asarray(rng.integers(0, 1 << 40, size=(13, 4)), jnp.int64)
        profiles = jnp.asarray(
            rng.integers(1, 1 << 30, size=(5, 4)), jnp.int64
        )
        prof_idx = jnp.asarray(rng.integers(0, 5, size=29), jnp.int32)
        got = np.asarray(general_estimate_interned(cap, profiles, prof_idx))
        want = np.asarray(general_estimate(cap, profiles[prof_idx]))
        np.testing.assert_array_equal(got, want)


class TestEstimate:
    def test_general_estimate(self):
        # 2 clusters x 3 dims (cpu-milli, memory, pods); 2 bindings
        cap = jnp.asarray(
            [[4000, 8 << 30, 100], [1000, 2 << 30, 3]], dtype=jnp.int64
        )
        req = jnp.asarray(
            [[500, 1 << 30, 1], [0, 0, 1]], dtype=jnp.int64
        )
        got = np.asarray(general_estimate(cap, req))
        np.testing.assert_array_equal(got[0], [8, 2])  # min(8, 8, 100)=8; min(2,2,3)=2
        np.testing.assert_array_equal(got[1], [100, 3])  # pods-only

    def test_negative_available_clamps_to_zero(self):
        cap = jnp.asarray([[-500, 10]], dtype=jnp.int64)
        req = jnp.asarray([[250, 1]], dtype=jnp.int64)
        assert np.asarray(general_estimate(cap, req))[0, 0] == 0

    def test_merge_matches_oracle(self):
        replicas = jnp.asarray([10, 0, 7], jnp.int32)
        e1 = jnp.asarray([[5, -1], [5, 5], [-1, -1]], jnp.int32)
        e2 = jnp.asarray([[7, -1], [1, 1], [-1, 3]], jnp.int32)
        got = np.asarray(merge_estimates(replicas, (e1, e2)))
        want = [
            R.merge_estimates(10, [[5, -1], [7, -1]], 2),
            R.merge_estimates(0, [[5, 5], [1, 1]], 2),
            R.merge_estimates(7, [[-1, -1], [-1, 3]], 2),
        ]
        np.testing.assert_array_equal(got, want)


class TestFastPathBoundaries:
    """Adversarial inputs at the packed-key bit boundaries: max-value
    weights, all-equal ties, remainder rank at k_top, zero weights."""

    def _compare(self, num, w, last, wide_ref=True, fast=None):
        from karmada_tpu.ops import take_by_weight, take_by_weight_fast

        c = len(w)
        args = (
            jnp.asarray(num, jnp.int32), jnp.asarray(w, jnp.int32),
            jnp.asarray(last, jnp.int32), jnp.zeros(c, jnp.int32),
        )
        want = np.asarray(take_by_weight(*args, wide_ref))
        got = np.asarray(take_by_weight_fast(*args, *fast))
        np.testing.assert_array_equal(got, want)

    def test_weights_at_bit_ceiling(self):
        # w_bits=10: every weight at 1023 (max representable), heavy ties
        c = 17
        self._compare(100, [1023] * c, [0] * c, fast=(10, 4, 16, True))

    def test_remainder_rank_equals_k_top(self):
        # num chosen so remain lands exactly at the k_top boundary
        w = [7, 7, 7, 7, 7, 7, 7, 7]
        self._compare(12, w, [0] * 8, fast=(4, 4, 8, True))

    def test_last_tiebreak_at_ceiling(self):
        w = [5] * 12
        last = [15, 0, 15, 0, 15, 0, 15, 0, 15, 0, 15, 0]  # l_bits=4 max
        self._compare(7, w, last, fast=(4, 4, 8, True))

    def test_all_zero_weights_return_init(self):
        self._compare(9, [0] * 6, [3] * 6, fast=(4, 4, 8, True))

    def test_int32_div_path_without_f32(self):
        # div_f32=False exercises the plain integer floor-div in the fast
        # kernel (products above 2^24 would use it)
        self._compare(1000, [900, 800, 700, 600], [0] * 4,
                      fast=(10, 4, 4, False))

    def test_randomized_boundary_sweep(self):
        rng = np.random.default_rng(77)
        for _ in range(40):
            c = int(rng.integers(1, 33))
            w_bits = int(rng.integers(1, 12))
            l_bits = int(rng.integers(1, 8))
            if w_bits + l_bits + max(1, (c - 1).bit_length()) > 31:
                continue
            wmax = (1 << w_bits) - 1
            lmax = (1 << l_bits) - 1
            w = rng.integers(0, wmax + 1, size=c)
            last = rng.integers(0, lmax + 1, size=c)
            num = int(rng.integers(0, 2 * wmax + 2))
            k_top = min(c, 1 << max(1, max(1, num) - 1).bit_length())
            div_f32 = wmax * max(num, 1) < 2**24
            self._compare(num, w.tolist(), last.tolist(),
                          fast=(w_bits, l_bits, k_top, div_f32))
