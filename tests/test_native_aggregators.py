"""Per-kind native status aggregation (resource_test.go analogue;
native/aggregatestatus.go:123-645): Service/Ingress LB merge, Pod phase
precedence, PVC phase, PDB counter sums, HPA sums, CronJob actives."""

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.work import AggregatedStatusItem
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.interpreter.native import register_native_interpreters


def make_interp() -> ResourceInterpreter:
    interp = ResourceInterpreter()
    register_native_interpreters(interp)
    return interp


def res(api_version, kind, spec=None, status=None):
    return Resource(
        api_version=api_version, kind=kind,
        meta=ObjectMeta(name="x", namespace="default"),
        spec=spec or {}, status=status or {},
    )


def item(cluster, status):
    return AggregatedStatusItem(cluster_name=cluster, status=status, applied=True)


class TestLoadBalancerMerge:
    def test_service_lb_collects_vips_with_member_hostname(self):
        interp = make_interp()
        svc = res("v1", "Service", spec={"type": "LoadBalancer"})
        out = interp.aggregate_status(svc, [
            item("m1", {"loadBalancer": {"ingress": [{"ip": "10.0.0.1"}]}}),
            item("m2", {"loadBalancer": {"ingress": [
                {"ip": "10.0.0.2", "hostname": "lb.example.com"}]}}),
        ])
        ing = out.status["loadBalancer"]["ingress"]
        assert ing == [
            {"ip": "10.0.0.1", "hostname": "m1"},
            {"ip": "10.0.0.2", "hostname": "lb.example.com"},
        ]

    def test_clusterip_service_untouched(self):
        interp = make_interp()
        svc = res("v1", "Service", spec={"type": "ClusterIP"},
                  status={"x": 1})
        out = interp.aggregate_status(svc, [item("m1", {"loadBalancer": {}})])
        assert out.status == {"x": 1}

    def test_ingress_merges_like_service(self):
        interp = make_interp()
        ing = res("networking.k8s.io/v1", "Ingress")
        out = interp.aggregate_status(ing, [
            item("m1", {"loadBalancer": {"ingress": [{"ip": "1.2.3.4"}]}}),
        ])
        assert out.status["loadBalancer"]["ingress"][0]["hostname"] == "m1"


class TestPodAggregate:
    def test_phase_precedence_failed_wins(self):
        interp = make_interp()
        pod = res("v1", "Pod")
        out = interp.aggregate_status(pod, [
            item("m1", {"phase": "Running"}),
            item("m2", {"phase": "Failed"}),
        ])
        assert out.status["phase"] == "Failed"

    def test_missing_status_counts_pending(self):
        interp = make_interp()
        pod = res("v1", "Pod")
        out = interp.aggregate_status(pod, [
            item("m1", {"phase": "Running"}),
            item("m2", None),
        ])
        assert out.status["phase"] == "Pending"

    def test_container_statuses_concatenate(self):
        interp = make_interp()
        pod = res("v1", "Pod")
        out = interp.aggregate_status(pod, [
            item("m1", {"phase": "Running", "containerStatuses": [
                {"ready": True, "state": {"running": {}}, "noise": 1}]}),
            item("m2", {"phase": "Running", "initContainerStatuses": [
                {"ready": False, "state": {"waiting": {}}}]}),
        ])
        assert out.status["containerStatuses"] == [
            {"ready": True, "state": {"running": {}}}]
        assert out.status["initContainerStatuses"] == [
            {"ready": False, "state": {"waiting": {}}}]


class TestPvcPdbHpaCron:
    def test_pvc_lost_wins(self):
        interp = make_interp()
        pvc = res("v1", "PersistentVolumeClaim")
        out = interp.aggregate_status(pvc, [
            item("m1", {"phase": "Bound"}), item("m2", {"phase": "Lost"}),
        ])
        assert out.status["phase"] == "Lost"

    def test_pvc_pending_propagates(self):
        interp = make_interp()
        pvc = res("v1", "PersistentVolumeClaim")
        out = interp.aggregate_status(pvc, [
            item("m1", {"phase": "Bound"}), item("m2", {"phase": "Pending"}),
        ])
        assert out.status["phase"] == "Pending"

    def test_pdb_sums_and_namespaces_disrupted_pods(self):
        interp = make_interp()
        pdb = res("policy/v1", "PodDisruptionBudget")
        out = interp.aggregate_status(pdb, [
            item("m1", {"currentHealthy": 2, "desiredHealthy": 2,
                        "expectedPods": 3, "disruptionsAllowed": 1,
                        "disruptedPods": {"p1": "t1"}}),
            item("m2", {"currentHealthy": 1, "desiredHealthy": 2,
                        "expectedPods": 3, "disruptionsAllowed": 0}),
        ])
        assert out.status["currentHealthy"] == 3
        assert out.status["expectedPods"] == 6
        assert out.status["disruptedPods"] == {"m1/p1": "t1"}

    def test_hpa_sums_replicas(self):
        interp = make_interp()
        hpa = res("autoscaling/v2", "HorizontalPodAutoscaler")
        out = interp.aggregate_status(hpa, [
            item("m1", {"currentReplicas": 3, "desiredReplicas": 4}),
            item("m2", {"currentReplicas": 2, "desiredReplicas": 2}),
        ])
        assert out.status["currentReplicas"] == 5
        assert out.status["desiredReplicas"] == 6

    def test_cronjob_actives_and_latest_times(self):
        interp = make_interp()
        cj = res("batch/v1", "CronJob")
        out = interp.aggregate_status(cj, [
            item("m1", {"active": [{"name": "j1"}],
                        "lastScheduleTime": "2026-07-30T01:00:00Z"}),
            item("m2", {"active": [{"name": "j2"}],
                        "lastScheduleTime": "2026-07-30T02:00:00Z",
                        "lastSuccessfulTime": "2026-07-30T01:30:00Z"}),
        ])
        assert [a["name"] for a in out.status["active"]] == ["j1", "j2"]
        assert out.status["lastScheduleTime"] == "2026-07-30T02:00:00Z"
        assert out.status["lastSuccessfulTime"] == "2026-07-30T01:30:00Z"

    def test_cronjob_times_mixed_rfc3339_formats(self):
        # members may emit Z vs +00:00 offsets or fractional seconds;
        # comparison must be chronological, not lexicographic ("+" < "Z"
        # would make the offset form always lose against Z)
        interp = make_interp()
        cj = res("batch/v1", "CronJob")
        out = interp.aggregate_status(cj, [
            item("m1", {"lastScheduleTime": "2026-07-30T03:00:00+00:00"}),
            item("m2", {"lastScheduleTime": "2026-07-30T02:59:59.500Z"}),
        ])
        assert out.status["lastScheduleTime"] == "2026-07-30T03:00:00+00:00"
