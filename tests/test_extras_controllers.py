"""Tests: dependencies distribution, namespace sync, workload rebalancer,
federated resource quota, cluster-scoped bindings."""

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.policy import (
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
    StaticClusterAssignment,
)
from karmada_tpu.controllers import (
    ObjectReferenceSelector,
    WorkloadRebalancer,
    WorkloadRebalancerSpec,
    execution_namespace,
)
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.utils.builders import (
    duplicated_placement,
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)


def make_plane(n=2, **kw):
    cp = ControlPlane(**kw)
    for i in range(1, n + 1):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.settle()
    return cp


def nginx_policy(placement, propagate_deps=False):
    return PropagationPolicy(
        meta=ObjectMeta(name="p", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=placement,
            propagate_deps=propagate_deps,
        ),
    )


class TestDependenciesDistributor:
    def test_configmap_follows_workload(self):
        cp = make_plane(2)
        dep = new_deployment("app", replicas=2)
        dep.spec["template"]["spec"]["volumes"] = [
            {"name": "cfg", "configMap": {"name": "app-config"}}
        ]
        cm = Resource(
            api_version="v1",
            kind="ConfigMap",
            meta=ObjectMeta(name="app-config", namespace="default"),
            spec={"data": {"k": "v"}},
        )
        cp.store.apply(cm)
        cp.store.apply(dep)
        cp.store.apply(nginx_policy(dynamic_weight_placement(), propagate_deps=True))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        placed = {tc.name for tc in rb.spec.clusters}
        attached = cp.store.get("ResourceBinding", "default/app-config-configmap")
        assert attached is not None
        assert {tc.name for tc in attached.spec.clusters} == placed
        # configmap physically lands on the member clusters
        for name in placed:
            assert (
                cp.members.get(name).get("v1/ConfigMap", "default", "app-config")
                is not None
            )

    def test_attached_removed_when_parent_deleted(self):
        cp = make_plane(1)
        dep = new_deployment("app", replicas=1)
        dep.spec["template"]["spec"]["volumes"] = [
            {"name": "cfg", "configMap": {"name": "c1"}}
        ]
        cp.store.apply(
            Resource(api_version="v1", kind="ConfigMap",
                     meta=ObjectMeta(name="c1", namespace="default"))
        )
        cp.store.apply(dep)
        cp.store.apply(nginx_policy(duplicated_placement(), propagate_deps=True))
        cp.settle()
        assert cp.store.get("ResourceBinding", "default/c1-configmap") is not None
        cp.store.delete("Resource", "default/app")
        cp.settle()
        assert cp.store.get("ResourceBinding", "default/c1-configmap") is None

    def test_adopted_binding_survives_parent_cleanup(self):
        """A binding that loses its depended-by label (adopted as an
        independent binding) must drop out of the attachment index — parent
        cleanup may not delete it."""
        from karmada_tpu.controllers.dependencies import DEPENDED_BY_LABEL

        cp = make_plane(1)
        dep = new_deployment("app", replicas=1)
        dep.spec["template"]["spec"]["volumes"] = [
            {"name": "cfg", "configMap": {"name": "c1"}}
        ]
        cp.store.apply(
            Resource(api_version="v1", kind="ConfigMap",
                     meta=ObjectMeta(name="c1", namespace="default"))
        )
        cp.store.apply(dep)
        cp.store.apply(nginx_policy(duplicated_placement(), propagate_deps=True))
        cp.settle()
        attached = cp.store.get("ResourceBinding", "default/c1-configmap")
        assert attached is not None
        # adoption: the label is removed, the binding becomes independent
        del attached.meta.labels[DEPENDED_BY_LABEL]
        cp.store.apply(attached)
        cp.store.delete("Resource", "default/app")
        cp.settle()
        assert cp.store.get("ResourceBinding", "default/c1-configmap") is not None


class TestWorkBuildCache:
    def test_template_label_only_edit_rebuilds_works(self):
        """Metadata-only template edits bump neither generation nor any
        binding field; the Work build cache must still rebuild (its token
        hashes labels/annotations, not the generation)."""
        cp = make_plane(2)
        cp.store.apply(new_deployment("app", replicas=4))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        template = cp.store.get("Resource", "default/app")
        template.meta.labels["team"] = "payments"  # no generation bump
        cp.store.apply(template)
        cp.settle()
        works = [
            w for w in cp.store.list("Work")
            if w.meta.name.endswith("app-deployment")
        ]
        assert works
        from karmada_tpu.controllers.propagation import work_manifests

        for w in works:
            # works may be template-delta rendered: rehydrate to inspect
            manifest = work_manifests(cp.store, w)[0]
            assert manifest.meta.labels.get("team") == "payments"


class TestNamespaceSync:
    def test_namespace_propagates_to_all_members(self):
        cp = make_plane(2)
        cp.store.apply(
            Resource(api_version="v1", kind="Namespace", meta=ObjectMeta(name="team-a"))
        )
        cp.settle()
        for m in ("member1", "member2"):
            assert cp.members.get(m).get("v1/Namespace", "", "team-a") is not None

    def test_reserved_namespaces_skipped(self):
        cp = make_plane(1)
        cp.store.apply(
            Resource(api_version="v1", kind="Namespace",
                     meta=ObjectMeta(name="kube-system"))
        )
        cp.settle()
        assert cp.members.get("member1").get("v1/Namespace", "", "kube-system") is None


class TestWorkloadRebalancer:
    def test_triggers_fresh_reschedule(self):
        clock = [5000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in (1, 2):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("app", replicas=4))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert rb.spec.reschedule_triggered_at is None
        clock[0] += 10
        cp.store.apply(
            WorkloadRebalancer(
                meta=ObjectMeta(name="rb1"),
                spec=WorkloadRebalancerSpec(
                    workloads=[ObjectReferenceSelector(kind="Deployment", name="app")]
                ),
            )
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert rb.spec.reschedule_triggered_at == clock[0]
        assert rb.status.last_scheduled_time is not None
        rebalancer = cp.store.get("WorkloadRebalancer", "rb1")
        assert rebalancer.status.observed_workloads[0]["result"] == "Successful"
        assert rebalancer.status.finish_time == clock[0]

    def test_same_length_inplace_edit_retriggers(self):
        # Store.apply does not auto-bump generation, so a writer that
        # swaps a target IN PLACE (same workload count, same generation)
        # used to be indistinguishable from our own status echo — the
        # content digest must re-trigger it
        clock = [5000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in (1, 2):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("app", replicas=4))
        cp.store.apply(new_deployment("app2", replicas=4))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        clock[0] += 10
        cp.store.apply(
            WorkloadRebalancer(
                meta=ObjectMeta(name="rb-edit"),
                spec=WorkloadRebalancerSpec(
                    workloads=[ObjectReferenceSelector(kind="Deployment", name="app")]
                ),
            )
        )
        cp.settle()
        t_first = clock[0]
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert rb.spec.reschedule_triggered_at == t_first
        # same-length in-place edit: app -> app2, no generation bump
        clock[0] += 10
        reb = cp.store.get("WorkloadRebalancer", "rb-edit")
        reb.spec.workloads[0] = ObjectReferenceSelector(
            kind="Deployment", name="app2"
        )
        cp.store.apply(reb)
        cp.settle()
        rb2 = cp.store.get("ResourceBinding", "default/app2-deployment")
        assert rb2.spec.reschedule_triggered_at == clock[0]
        # the echo gate still holds once the edit is observed: more
        # settles must not re-trigger anything
        clock[0] += 10
        cp.settle()
        rb2 = cp.store.get("ResourceBinding", "default/app2-deployment")
        assert rb2.spec.reschedule_triggered_at == clock[0] - 10

    def test_legacy_status_without_digest_not_retriggered(self):
        # a checkpoint written by a pre-digest build unpickles statuses
        # WITHOUT observed_spec_digest (Store.restore bypasses __init__):
        # the echo gate must fall back to the old length compare — no
        # AttributeError, and no boot-time re-trigger of every finished
        # rebalancer
        clock = [5000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in (1, 2):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("app", replicas=4))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        clock[0] += 10
        cp.store.apply(
            WorkloadRebalancer(
                meta=ObjectMeta(name="rb-legacy"),
                spec=WorkloadRebalancerSpec(
                    workloads=[ObjectReferenceSelector(kind="Deployment", name="app")]
                ),
            )
        )
        cp.settle()
        t_first = clock[0]
        # simulate the restored legacy object: strip the new field
        reb = cp.store.get("WorkloadRebalancer", "rb-legacy")
        del reb.status.observed_spec_digest
        clock[0] += 10
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert rb.spec.reschedule_triggered_at == t_first

    def test_ttl_after_finished_cleans_up(self):
        clock = [5000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        cp.join_cluster(new_cluster("member1", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        cp.store.apply(
            WorkloadRebalancer(
                meta=ObjectMeta(name="rb-ttl"),
                spec=WorkloadRebalancerSpec(
                    workloads=[ObjectReferenceSelector(kind="Deployment",
                                                       name="app")],
                    ttl_seconds_after_finished=60,
                ),
            )
        )
        cp.settle()
        assert cp.store.get("WorkloadRebalancer", "rb-ttl") is not None
        clock[0] += 59
        cp.settle()
        assert cp.store.get("WorkloadRebalancer", "rb-ttl") is not None
        clock[0] += 2
        cp.settle()
        # TTL elapsed after finish -> auto-deleted
        # (workloadrebalancer_controller.go:99-107)
        assert cp.store.get("WorkloadRebalancer", "rb-ttl") is None


class TestFederatedResourceQuota:
    def test_static_assignments_propagate_and_live_accounting(self):
        cp = make_plane(2)
        cp.store.apply(
            FederatedResourceQuota(
                meta=ObjectMeta(name="quota", namespace="default"),
                spec=FederatedResourceQuotaSpec(
                    overall={"cpu": 10_000},
                    static_assignments=[
                        StaticClusterAssignment(cluster_name="member1",
                                                hard={"cpu": 6000}),
                        StaticClusterAssignment(cluster_name="member2",
                                                hard={"cpu": 4000}),
                    ],
                ),
            )
        )
        cp.settle()
        q1 = cp.members.get("member1").get("v1/ResourceQuota", "default", "quota")
        assert q1 is not None and q1.spec["hard"]["cpu"] == 6000
        # overall_used is recomputed LIVE from bound ResourceBindings
        # (the reference's FRQ status controller), not member-reported
        # quota statuses: a scheduled workload's assigned replicas x
        # per-replica request lands in status in the same settle wave
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.store.apply(new_deployment("quotad", replicas=3, cpu="500m"))
        cp.settle()
        frq = cp.store.get("FederatedResourceQuota", "default/quota")
        assert frq.status.overall_used == {"cpu": 1500}
        assert frq.status.overall == {"cpu": 10_000}
        # scale down -> usage follows in the next wave
        cp.store.apply(new_deployment("quotad", replicas=1, cpu="500m"))
        cp.settle()
        frq = cp.store.get("FederatedResourceQuota", "default/quota")
        assert frq.status.overall_used == {"cpu": 500}


class TestClusterScopedBindings:
    def test_cluster_role_propagates_via_crb(self):
        from karmada_tpu.api.policy import ClusterPropagationPolicy

        cp = make_plane(2)
        role = Resource(
            api_version="rbac.authorization.k8s.io/v1",
            kind="ClusterRole",
            meta=ObjectMeta(name="viewer"),
            spec={"rules": [{"apiGroups": [""], "resources": ["pods"],
                             "verbs": ["get", "list"]}]},
        )
        for m in cp.members.names():
            cp.members.get(m).api_enablements.append(
                "rbac.authorization.k8s.io/v1/ClusterRole"
            )
        # refresh cluster status with new enablements
        cp.settle()
        cp.store.apply(role)
        cp.store.apply(
            ClusterPropagationPolicy(
                meta=ObjectMeta(name="roles"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(
                            api_version="rbac.authorization.k8s.io/v1",
                            kind="ClusterRole",
                        )
                    ],
                    placement=duplicated_placement(),
                ),
            )
        )
        cp.settle()
        crb = cp.store.get("ClusterResourceBinding", "viewer-clusterrole")
        assert crb is not None
        for m in ("member1", "member2"):
            assert (
                cp.members.get(m).get(
                    "rbac.authorization.k8s.io/v1/ClusterRole", "", "viewer"
                )
                is not None
            )

    def test_fresh_uses_plane_clock(self):
        """Regression: last_scheduled_time must come from the plane clock.
        With wall time leaking in, a fake-clock rescheduleTriggeredAt could
        never exceed it and Fresh silently degraded to a steady no-op."""
        clock = [7000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        cp.join_cluster(new_cluster("small", cpu="4", memory="200Gi"))
        cp.store.apply(new_deployment("app", replicas=4, cpu="1"))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert {tc.name for tc in rb.spec.clusters} == {"small"}

        # a much larger cluster joins; Steady mode keeps placements...
        cp.join_cluster(new_cluster("big", cpu="400", memory="800Gi"))
        clock[0] += 10
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert {tc.name for tc in rb.spec.clusters} == {"small"}

        # ...until a rebalancer triggers Fresh, which must actually fire
        # (fake trigger time > fake last_scheduled_time) and redistribute
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name="go-fresh"),
            spec=WorkloadRebalancerSpec(workloads=[
                ObjectReferenceSelector(kind="Deployment", name="app")]),
        ))
        clock[0] += 10
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert "big" in {tc.name for tc in rb.spec.clusters}

    def test_ttl_not_applied_while_new_work_pending(self):
        """A spec update adding workloads must clear finish_time so the TTL
        sweep cannot delete a rebalancer with pending work."""
        clock = [5000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        cp.join_cluster(new_cluster("member1", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name="rb-grow"),
            spec=WorkloadRebalancerSpec(
                workloads=[ObjectReferenceSelector(kind="Deployment",
                                                   name="app")],
                ttl_seconds_after_finished=60,
            ),
        ))
        cp.settle()
        r = cp.store.get("WorkloadRebalancer", "rb-grow")
        assert r.status.finish_time == clock[0]
        # add a workload that stays Pending (no such binding)
        clock[0] += 50
        r.spec.workloads.append(
            ObjectReferenceSelector(kind="Deployment", name="ghost"))
        r.status.observed_workloads = []  # force re-reconcile content change
        cp.store.apply(r)
        cp.settle()
        r = cp.store.get("WorkloadRebalancer", "rb-grow")
        pending = [o for o in r.status.observed_workloads
                   if o["result"] == "Pending"]
        if pending:
            assert r.status.finish_time is None
            clock[0] += 100
            cp.settle()
            assert cp.store.get("WorkloadRebalancer", "rb-grow") is not None

    def test_ttl_restarts_from_latest_finish(self):
        """A spec update re-processes the rebalancer; finish_time restamps
        at the new completion, so the TTL measures from the LATEST finish
        (and the defensive reset keeps a hypothetical pending state alive —
        our in-proc results are always terminal, reference: Successful or
        Failed)."""
        clock = [5000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        cp.join_cluster(new_cluster("member1", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name="rb-grow"),
            spec=WorkloadRebalancerSpec(
                workloads=[ObjectReferenceSelector(kind="Deployment",
                                                   name="app")],
                ttl_seconds_after_finished=60,
            ),
        ))
        cp.settle()
        first_finish = cp.store.get("WorkloadRebalancer", "rb-grow").status.finish_time
        assert first_finish == clock[0]


