"""ISSUE 20: incremental (dirty-row) scheduling — churn cost
proportional to churn size on the resident mesh state.

Coverage map:
- delta-vs-full placement identity across mesh sizes 1/2/4/8 (the
  conftest 8-virtual-CPU-device mesh), with the per-pass breakdown
  proving the delta path dispatched exactly the churn set;
- row-coupled kernel forcing: an armed preemption plane disables the
  delta solve entirely (full passes, identical placements), and a
  quota-bearing wave routes its changed rows through a COMPLETE scoped
  admission kernel — unchanged denials replay, the working remaining is
  debited for the changed rows' delta demand only;
- stale dirty sets: unknown keys are dropped (safe superset semantics),
  and a dirty set carried across an engine restart onto a different
  mesh shape degrades to a full pass, never a wrong placement;
- the controller plumbing: problem-cache identity <=> content, dirty
  keys accumulated per wave, and the descheduler's dry solve riding the
  delta path without debiting the live quota plane;
- chaos-seeded churn: a PR 7 fault-injection cluster kill lands mid
  churn sequence; placements must exclude the dead member, preserve
  totals, and match a delta-disabled full re-solve bit for bit.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

import karmada_tpu.scheduler.fleet as fleet_mod
from karmada_tpu import cli as _cli
from karmada_tpu.api import (
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
    Placement,
    ReplicaSchedulingStrategy,
)
from karmada_tpu.estimator.accurate import NodeState
from karmada_tpu.parallel.mesh import scheduling_mesh
from karmada_tpu.scheduler import (
    BindingProblem,
    ClusterSnapshot,
    TensorScheduler,
)
from karmada_tpu.scheduler.quota import QuotaSnapshot
from karmada_tpu.utils import faultinject
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
    synthetic_fleet,
)
from karmada_tpu.utils.member import MemberCluster
from karmada_tpu.utils.quantity import parse_resource_list

C = 48


@pytest.fixture(scope="module")
def snap():
    return ClusterSnapshot(synthetic_fleet(C, seed=7, taint_fraction=0.08))


def build_problems(snap, n, *, seed=3, with_dup=True, prefix="d"):
    """A mixed batch (the test_mesh_sharding shape): Divided rows with
    prev placements plus Duplicated and zero-replica rows, so the delta
    replay covers every result kind the mirrors encode."""
    pl = dynamic_weight_placement()
    pl_dup = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Duplicated"
        )
    )
    profiles = [
        parse_resource_list(
            {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
        )
        for p in range(4)
    ]
    rng = np.random.default_rng(seed)
    names = snap.names
    out = []
    for i in range(n):
        if with_dup and i % 19 == 0:
            out.append(
                BindingProblem(
                    key=f"{prefix}{i}", placement=pl_dup,
                    replicas=int(rng.integers(0, 5)),
                    requests=profiles[i % 4], gvk="apps/v1/Deployment",
                )
            )
            continue
        prev = (
            {
                names[int(j)]: int(rng.integers(1, 20))
                for j in rng.choice(C, 3, replace=False)
            }
            if rng.random() < 0.7
            else {}
        )
        out.append(
            BindingProblem(
                key=f"{prefix}{i}", placement=pl,
                replicas=int(rng.integers(1, 100)),
                requests=profiles[i % 4], gvk="apps/v1/Deployment",
                prev=prev, fresh=bool(rng.random() < 0.05),
            )
        )
    return out


def churned(problems, rng, count):
    """Replace ``count`` random rows with new objects whose replicas
    changed (bounded so Divided rows stay on the same kernel shapes).
    Returns (new list, changed positions)."""
    idx = np.sort(rng.choice(len(problems), count, replace=False))
    out = list(problems)
    for i in idx:
        p = out[int(i)]
        out[int(i)] = dataclasses.replace(p, replicas=(p.replicas % 39) + 1)
    return out, idx


def full_solve(engine, problems):
    """One pass with the delta path killed (the KARMADA_TPU_DELTA_SOLVE
    switch is read per pass) — the full-solve oracle side."""
    saved = os.environ.get("KARMADA_TPU_DELTA_SOLVE")
    os.environ["KARMADA_TPU_DELTA_SOLVE"] = "0"
    try:
        return engine.schedule(problems)
    finally:
        if saved is None:
            os.environ.pop("KARMADA_TPU_DELTA_SOLVE", None)
        else:
            os.environ["KARMADA_TPU_DELTA_SOLVE"] = saved


def decoded(results):
    return [
        (r.key, dict(r.clusters), r.success, r.error,
         tuple(sorted(r.feasible)))
        for r in results
    ]


def dirty_dispatched(engine) -> int:
    return int(engine._fleet.last_breakdown.get("dirty_rows", 0))


# --------------------------------------------------------------------------
# delta vs full identity, across mesh shapes
# --------------------------------------------------------------------------


class TestDeltaVsFullIdentity:
    @pytest.mark.parametrize("devices", (1, 2, 4, 8))
    def test_identity_across_mesh_sizes(self, snap, devices):
        """The same churn sequence through a delta engine and a
        delta-disabled full engine on every mesh shape the conftest
        virtual devices can host: placements bit-identical each round,
        and the delta engine's breakdown proves each round dispatched
        exactly the churn set."""
        mesh = scheduling_mesh(devices) if devices > 1 else None
        delta_eng = TensorScheduler(snap, mesh=mesh, trace_manifest="")
        full_eng = TensorScheduler(snap, mesh=mesh, trace_manifest="")
        delta_eng.fleet_threshold = 1
        full_eng.fleet_threshold = 1
        problems = build_problems(snap, 512)
        assert decoded(delta_eng.schedule(problems)) == decoded(
            full_solve(full_eng, problems)
        )
        rng = np.random.default_rng(100 + devices)
        for rnd in range(2):
            problems, idx = churned(problems, rng, 20)
            ref = decoded(full_solve(full_eng, problems))
            got = decoded(delta_eng.schedule(problems))
            assert got == ref, f"mesh={devices} round={rnd}"
            assert dirty_dispatched(delta_eng) == len(idx), (
                f"mesh={devices} round={rnd}: delta pass did not engage "
                "on exactly the churn set"
            )
        assert delta_eng._fleet is not None
        if devices > 1:
            assert delta_eng._fleet._mesh is mesh

    @pytest.mark.parametrize("legacy", (False, True), ids=("dense", "legacy"))
    def test_identity_on_both_resident_paths(self, snap, legacy, monkeypatch):
        """Single-device, both resident layouts: the legacy
        entry-resident path maintains the same host mirrors the replay
        reads, so the delta contract is layout-independent."""
        if legacy:
            monkeypatch.setattr(fleet_mod, "DENSE_RESIDENT_MAX_BYTES", 0)
        delta_eng = TensorScheduler(snap, trace_manifest="")
        full_eng = TensorScheduler(snap, trace_manifest="")
        delta_eng.fleet_threshold = 1
        full_eng.fleet_threshold = 1
        problems = build_problems(snap, 300, prefix=f"r{int(legacy)}_")
        delta_eng.schedule(problems)
        full_solve(full_eng, problems)
        rng = np.random.default_rng(7)
        for rnd in range(3):
            problems, idx = churned(problems, rng, 9)
            ref = decoded(full_solve(full_eng, problems))
            got = decoded(delta_eng.schedule(problems))
            assert got == ref, f"legacy={legacy} round={rnd}"
            assert dirty_dispatched(delta_eng) == len(idx)


# --------------------------------------------------------------------------
# row-coupled kernels force (scoped) full passes
# --------------------------------------------------------------------------


class TestCoupledKernelForcing:
    def test_armed_preemption_forces_full_pass(self, snap):
        """preempt_select ranks victims ACROSS rows: an armed scarcity
        plane must take the full path (dirty_rows == 0) with placements
        still identical; disarming re-enables the delta pass."""
        eng = TensorScheduler(snap, trace_manifest="")
        ref = TensorScheduler(snap, trace_manifest="")
        eng.fleet_threshold = 1
        ref.fleet_threshold = 1
        problems = build_problems(snap, 300, with_dup=False, prefix="p")
        eng.schedule(problems)
        full_solve(ref, problems)
        rng = np.random.default_rng(23)

        eng.set_preemption(lambda exclude: [])
        problems, idx = churned(problems, rng, 8)
        got = decoded(eng.schedule(problems))
        assert got == decoded(full_solve(ref, problems))
        assert dirty_dispatched(eng) == 0, (
            "armed preemption must force the full pass"
        )

        eng.set_preemption(None)
        problems, idx = churned(problems, rng, 8)
        got = decoded(eng.schedule(problems))
        assert got == decoded(full_solve(ref, problems))
        assert dirty_dispatched(eng) == len(idx)

    def test_quota_churn_runs_scoped_admission(self, snap):
        """quota_admit is row_coupled (per-namespace FIFO cumsum): a
        churned quota wave re-admits its changed rows through a COMPLETE
        kernel over their own sub-batch against the working remaining.
        Unchanged denials replay exactly; the debit covers only the
        changed rows' delta demand (the PR 14 working-remaining restore
        contract, extended to the delta path)."""
        dims = ["cpu", "memory", "pods"]
        problems = build_problems(snap, 320, with_dup=False, prefix="q")
        for i, p in enumerate(problems):
            p.namespace = "ns0" if i % 2 == 0 else "ns1"
            p.prev = {}  # fresh demand so admission actually gates
        # ns0 tight (denials), ns1 roomy (every churned row re-admits)
        remaining = np.array(
            [[200_000, 2 << 33, 500], [2**50, 2**50, 2**50]], np.int64
        )

        def quota():
            return QuotaSnapshot(
                dims=dims, ns_index={"ns0": 0, "ns1": 1},
                remaining=remaining.copy(), cap_index={},
                cluster_caps=np.zeros((0, C, 3), np.int64),
                generation=1, cap_token=0,
            )

        eng = TensorScheduler(snap, trace_manifest="")
        eng.fleet_threshold = 1
        eng.set_quota(quota())
        first = eng.schedule(problems)
        denied_before = {r.key for r in first if not r.success}
        assert denied_before, "quota never denied anything"
        r1 = eng.quota.remaining.copy()

        # churn ns1 (roomy) rows only: the denial partition is unchanged
        rng = np.random.default_rng(31)
        ns1_pos = [i for i, p in enumerate(problems) if p.namespace == "ns1"]
        idx = np.sort(rng.choice(ns1_pos, 10, replace=False))
        out = list(problems)
        for i in idx:
            p = out[int(i)]
            out[int(i)] = dataclasses.replace(
                p, replicas=(p.replicas % 39) + 1
            )
        second = eng.schedule(out)

        # unchanged denials replayed exactly, nothing new denied
        assert {r.key for r in second if not r.success} == denied_before
        # the tight namespace was not re-charged for replayed rows
        r2 = eng.quota.remaining
        assert np.array_equal(r2[0], r1[0])
        # the roomy namespace was debited EXACTLY the changed rows'
        # delta demand (prev == {} so delta == the new replica count)
        q = eng.quota
        expect = np.zeros(len(dims), np.int64)
        for i in idx:
            p = out[int(i)]
            expect += q.demand_row(p.requests, p.replicas)
        assert np.array_equal(r1[1] - r2[1], expect)
        # and the admission kernel actually ran scoped: a "Q" trace
        # whose row pad is the CHANGED sub-batch pow2, not the wave's
        sub_pad = 1 << max(0, (len(idx) - 1).bit_length())
        assert any(
            k[0] == "Q" and k[1] == sub_pad for k in eng._engine_traces
        )


# --------------------------------------------------------------------------
# stale dirty sets
# --------------------------------------------------------------------------


class TestStaleDirtySet:
    def test_unknown_dirty_keys_are_dropped(self, snap):
        """Dirty keys are advisory positions on top of the id diff: a
        key the wave does not carry only over-dispatches when it maps —
        an unknown key maps nowhere and must be ignored, results
        unchanged."""
        eng = TensorScheduler(snap, trace_manifest="")
        eng.fleet_threshold = 1
        problems = build_problems(snap, 300, prefix="s")
        base = decoded(eng.schedule(problems))
        again = decoded(
            eng.schedule(problems, dirty_keys={"ghost/one", "ghost/two"})
        )
        assert again == base
        # every named key was unknown: nothing was dispatched
        assert dirty_dispatched(eng) == 0

    def test_dirty_keys_force_redispatch_without_content_change(self, snap):
        """A caller-declared dirty key re-dispatches its row even when
        the problem object is identical — the safe-superset contract
        (estimator pings invalidate rows without touching the spec)."""
        eng = TensorScheduler(snap, trace_manifest="")
        eng.fleet_threshold = 1
        problems = build_problems(snap, 300, with_dup=False, prefix="f")
        base = decoded(eng.schedule(problems))
        dirty = {problems[3].key, problems[117].key}
        again = decoded(eng.schedule(problems, dirty_keys=dirty))
        assert again == base
        assert dirty_dispatched(eng) == len(dirty)

    def test_stale_dirty_set_across_mesh_shape_change(self, snap):
        """A controller restart carries its accumulated dirty set onto a
        freshly built engine with a DIFFERENT mesh shape: the first pass
        has no armed batch, so the stale set degrades to a full pass —
        identical placements, never a partial solve against a resident
        state that does not exist."""
        eng_a = TensorScheduler(
            snap, mesh=scheduling_mesh(2), trace_manifest=""
        )
        eng_a.fleet_threshold = 1
        problems = build_problems(snap, 512, prefix="m")
        eng_a.schedule(problems)
        rng = np.random.default_rng(47)
        problems, idx = churned(problems, rng, 12)
        ref = decoded(eng_a.schedule(problems))
        stale = {problems[int(i)].key for i in idx}

        eng_b = TensorScheduler(
            snap, mesh=scheduling_mesh(4), trace_manifest=""
        )
        eng_b.fleet_threshold = 1
        got = decoded(eng_b.schedule(problems, dirty_keys=stale))
        assert got == ref
        assert dirty_dispatched(eng_b) == 0  # full pass: no armed batch
        # the same stale set against the NOW-armed batch over-dispatches
        # exactly those rows — and answers the same placements
        got2 = decoded(eng_b.schedule(problems, dirty_keys=stale))
        assert got2 == ref
        assert dirty_dispatched(eng_b) == len(stale)


# --------------------------------------------------------------------------
# controller plumbing
# --------------------------------------------------------------------------


def small_plane():
    cp = _cli.cmd_init()
    members = {}
    for name, cpu in (("c0", 64), ("c1", 64), ("c2", 64)):
        caps = {"cpu": str(cpu), "memory": "100Gi", "pods": 1000}
        m = MemberCluster(name)
        m.nodes = [NodeState(
            name=f"{name}-n0", allocatable=parse_resource_list(caps)
        )]
        members[name] = m
        cp.join_cluster(new_cluster(name, **caps), m)
    cp.settle()
    cp.store.apply(PropagationPolicy(
        meta=ObjectMeta(name="pol", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment"
            )],
            placement=dynamic_weight_placement(),
        ),
    ))
    return cp, members


class TestControllerDirtyPlumbing:
    def test_problem_cache_identity_iff_content(self):
        """Identity <=> content, the delta plumbing's contract: an
        unchanged binding answers the SAME object across waves (no dirty
        mark); a content move replaces it and marks the key dirty."""
        cp, _members = small_plane()
        cp.store.apply(new_deployment("w0", replicas=4, cpu="1",
                                      memory="1Gi"))
        cp.settle()
        key = "default/w0-deployment"
        rb = cp.store.get("ResourceBinding", key)
        sched = cp.scheduler
        # sync the cache to the settled state first (the committed
        # placement updated prev, which IS a content move), then prove
        # stability: rebuilt-but-equal answers the same object, no mark
        p1 = sched._problem_for(key, rb, False)
        sched._dirty_problem_keys.clear()
        p2 = sched._problem_for(key, rb, False)
        assert p2 is p1
        assert key not in sched._dirty_problem_keys
        rb.spec.replicas += 3
        p3 = sched._problem_for(key, rb, False)
        assert p3 is not p1 and p3.replicas == p1.replicas + 3
        assert key in sched._dirty_problem_keys

    def test_dry_solve_delta_leaves_no_trace(self):
        """The descheduler's scoring seam on the delta path: a dry solve
        carrying dirty keys still restores the quota working remaining
        and re-arms provenance (PR 14's contract, extended)."""
        from karmada_tpu.utils.explainstore import ExplainStore

        cp, _members = small_plane()
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="default"),
            spec=FederatedResourceQuotaSpec(overall={"cpu": 100000}),
        ))
        cp.store.apply(new_deployment("w0", replicas=4, cpu="1",
                                      memory="1Gi"))
        cp.settle()
        key = "default/w0-deployment"
        rb = cp.store.get("ResourceBinding", key)
        rb.spec.replicas += 2  # positive delta demand: a leak WOULD debit
        problem = cp.scheduler._problem_for(key, rb, True)
        engine = cp.scheduler._inproc_engine()
        store = ExplainStore(cap=4)
        engine.set_explain(store)
        cp.scheduler._ensure_engine_quota(engine)
        before = engine.quota.remaining.copy()
        res = cp.scheduler.dry_solve([problem], dirty_keys={key})
        assert res[0].success
        assert np.array_equal(engine.quota.remaining, before)
        assert store.debug_doc(proc="t")["waves"] == []
        assert engine.explain is store


# --------------------------------------------------------------------------
# chaos-seeded churn
# --------------------------------------------------------------------------


class TestChaosChurn:
    def teardown_method(self):
        faultinject.disarm()

    def test_seeded_cluster_kill_mid_churn(self, monkeypatch):
        """A PR 7 seeded fault (cluster.health=down) lands in the middle
        of a churn sequence: the snapshot swap invalidates the resident
        base, fresh placements must avoid the tainted member, totals
        hold for churned bindings, and the settled plane's placements
        match a delta-disabled full re-solve of every binding bit for
        bit."""
        cp, _members = small_plane()
        n_bindings = 6
        for i in range(6):
            cp.store.apply(new_deployment(
                f"w{i}", replicas=6 + i, cpu="1", memory="1Gi"
            ))
        cp.settle()

        def placements():
            out = {}
            for i in range(n_bindings):
                rb = cp.store.get(
                    "ResourceBinding", f"default/w{i}-deployment"
                )
                out[rb.meta.namespace + "/" + rb.meta.name] = {
                    tc.name: tc.replicas for tc in rb.spec.clusters
                }
            return out

        # churn round 1 (healthy plane)
        for i in (0, 2, 4):
            d = new_deployment(f"w{i}", replicas=10 + i, cpu="1",
                               memory="1Gi")
            cp.store.apply(d)
        cp.settle()

        # the seeded kill fires mid-sequence
        faultinject.arm("cluster.health=down,match=c1", seed=11)
        cp.settle()
        mid = placements()
        # churn round 2 lands while c1 is down: two existing bindings
        # scale up, and one brand-new binding arrives with no prev
        for i in (1, 3):
            cp.store.apply(new_deployment(
                f"w{i}", replicas=12 + i, cpu="1", memory="1Gi"
            ))
        cp.store.apply(new_deployment("w6", replicas=9, cpu="1",
                                      memory="1Gi"))
        n_bindings = 7
        cp.settle()
        after = placements()
        # NotReady stamps the NoSchedule taint. The engine's Steady
        # semantics credit prev, so bindings that already hold replicas
        # on c1 keep it as a weighted member; the hard contract is that
        # totals hold for every churned binding and that a FRESH
        # placement (no prev credit anywhere) never lands on the
        # tainted member.
        for i in (1, 3):
            key = f"default/w{i}-deployment"
            assert sum(after[key].values()) == 12 + i, after[key]
        w6 = after["default/w6-deployment"]
        assert "c1" not in w6, w6
        assert sum(w6.values()) == 9, w6

        # recovery: disarm, re-judge health, settle
        faultinject.disarm()
        cp.settle()

        # the settled plane vs a delta-disabled full re-solve: Steady
        # semantics credit prev, so a full solve of the same problems
        # answers the committed placements exactly
        monkeypatch.setenv("KARMADA_TPU_DELTA_SOLVE", "0")
        sched = cp.scheduler
        final = placements()
        for i in range(n_bindings):
            key = f"default/w{i}-deployment"
            rb = cp.store.get("ResourceBinding", key)
            problem = sched._problem_for(key, rb, False)
            res = sched.dry_solve([problem])
            assert res[0].success, (key, res[0].error)
            assert dict(res[0].clusters) == final[key], key
