"""Unified channel resilience (utils.backoff): decorrelated-jitter policy,
deadline budgets, the circuit breaker's closed/open/half-open machine and
its metrics, and the bus channel's adoption (bounded write-through with
breaker fast-fail)."""

from __future__ import annotations

import random
import threading

import pytest

from karmada_tpu.utils import backoff
from karmada_tpu.utils.metrics import channel_retries, circuit_state


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestDeadline:
    def test_budget_counts_down(self):
        clk = FakeClock()
        d = backoff.Deadline(10.0, clock=clk)
        assert d.remaining() == 10.0
        clk.t = 4.0
        assert d.remaining() == 6.0
        assert not d.expired
        clk.t = 11.0
        assert d.expired and d.remaining() == 0.0

    def test_attempt_timeout_caps_and_floors(self):
        clk = FakeClock()
        d = backoff.Deadline(10.0, clock=clk)
        assert d.attempt_timeout(3.0) == 3.0
        clk.t = 8.5
        assert d.attempt_timeout(3.0) == pytest.approx(1.5)
        clk.t = 20.0
        assert d.attempt_timeout(3.0) == 0.001  # floor, never 0


class TestBackoffPolicy:
    def test_decorrelated_jitter_bounds(self):
        policy = backoff.BackoffPolicy(base=0.1, cap=1.0)
        sleeps = policy.sleeps(random.Random(42))
        prev = policy.base
        for _ in range(50):
            s = next(sleeps)
            assert policy.base <= s <= min(policy.cap, max(prev * 3, policy.base))
            prev = s

    def test_env_tuned_default_policy(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_BACKOFF_BASE", "0.2")
        monkeypatch.setenv("KARMADA_TPU_BACKOFF_CAP", "7.5")
        p = backoff.default_policy()
        assert p.base == 0.2 and p.cap == 7.5
        monkeypatch.setenv("KARMADA_TPU_BACKOFF_BASE", "junk")
        assert backoff.default_policy().base == 0.05  # bad value -> default


class TestCircuitBreaker:
    def _breaker(self, clk, threshold=3, reset=5.0):
        return backoff.CircuitBreaker(
            "test-chan", failure_threshold=threshold, reset_seconds=reset,
            clock=clk,
        )

    def test_closed_to_open_to_half_open_to_closed(self):
        clk = FakeClock()
        b = self._breaker(clk)
        assert b.state == backoff.CLOSED and b.allow()
        for _ in range(3):
            b.record_failure()
        assert b.state == backoff.OPEN
        assert not b.allow() and b.engaged()
        assert circuit_state.value(channel="test-chan") == backoff.OPEN
        clk.t = 6.0  # past the reset window
        assert not b.engaged()  # non-consuming: probe still available
        assert b.allow()  # takes the single probe slot
        assert b.state == backoff.HALF_OPEN
        assert not b.allow()  # concurrent callers stay rejected
        b.record_success()
        assert b.state == backoff.CLOSED
        assert circuit_state.value(channel="test-chan") == backoff.CLOSED

    def test_half_open_failure_reopens_and_restarts_window(self):
        clk = FakeClock()
        b = self._breaker(clk)
        for _ in range(3):
            b.record_failure()
        clk.t = 6.0
        assert b.allow()
        b.record_failure()
        assert b.state == backoff.OPEN
        clk.t = 10.0  # window restarted at t=6: still open
        assert not b.allow()
        clk.t = 11.5
        assert b.allow()
        b.record_success()
        assert b.state == backoff.CLOSED

    def test_success_resets_failure_streak(self):
        clk = FakeClock()
        b = self._breaker(clk)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == backoff.CLOSED  # never 3 consecutive

    def test_engaged_never_consumes_the_probe(self):
        clk = FakeClock()
        b = self._breaker(clk)
        for _ in range(3):
            b.record_failure()
        clk.t = 6.0
        for _ in range(10):
            assert not b.engaged()
        assert b.allow()  # probe still there after 10 engaged() checks

    def test_thread_safety_smoke(self):
        clk = FakeClock()
        b = self._breaker(clk, threshold=5)

        def hammer():
            for i in range(200):
                if b.allow():
                    (b.record_success if i % 3 else b.record_failure)()

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert b.state in (backoff.CLOSED, backoff.OPEN, backoff.HALF_OPEN)


class TestCallWithResilience:
    def test_retries_then_succeeds_and_counts(self):
        calls = []
        before = channel_retries.value(channel="retry-chan")

        def fn(timeout):
            calls.append(timeout)
            if len(calls) < 3:
                raise ValueError("flaky")
            return "ok"

        out = backoff.call_with_resilience(
            fn,
            channel="retry-chan",
            policy=backoff.BackoffPolicy(
                base=0.001, cap=0.002, attempt_timeout=0.5, max_attempts=4
            ),
            deadline=backoff.Deadline(5.0),
            retryable=(ValueError,),
            sleep=lambda s: None,
        )
        assert out == "ok" and len(calls) == 3
        assert channel_retries.value(channel="retry-chan") == before + 2

    def test_budget_exhaustion_wraps_last_error(self):
        def fn(timeout):
            raise ValueError("down")

        with pytest.raises(backoff.DeadlineExceeded) as exc:
            backoff.call_with_resilience(
                fn,
                channel="x",
                policy=backoff.BackoffPolicy(
                    base=0.001, cap=0.001, attempt_timeout=0.1,
                    max_attempts=2,
                ),
                deadline=backoff.Deadline(1.0),
                retryable=(ValueError,),
                sleep=lambda s: None,
            )
        assert isinstance(exc.value.cause, ValueError)

    def test_breaker_open_fast_fails_without_attempt(self):
        clk = FakeClock()
        b = backoff.CircuitBreaker("fast", clock=clk, failure_threshold=1)
        b.record_failure()
        calls = []
        with pytest.raises(backoff.CircuitBreakerOpen):
            backoff.call_with_resilience(
                lambda t: calls.append(t),
                channel="fast",
                policy=backoff.BackoffPolicy(attempt_timeout=0.1),
                breaker=b,
            )
        assert not calls

    def test_non_retryable_resolves_breaker_admission(self):
        clk = FakeClock()
        b = backoff.CircuitBreaker("probe", clock=clk, failure_threshold=1)
        b.record_failure()
        clk.t = 10.0  # half-open window

        with pytest.raises(KeyError):
            backoff.call_with_resilience(
                lambda t: (_ for _ in ()).throw(KeyError("bug")),
                channel="probe",
                policy=backoff.BackoffPolicy(attempt_timeout=0.1),
                breaker=b,
                retryable=(ValueError,),
            )
        # the probe slot was resolved (as failure), not leaked
        assert b.state == backoff.OPEN
        clk.t = 20.0
        assert b.allow()  # a fresh probe is available


class TestBusChannelResilience:
    """The store-bus write-through under the unified policy: explicit
    timeouts on every RPC (GL007), one overall budget, breaker fast-fail
    as backpressure."""

    def _bus(self):
        from karmada_tpu.bus.service import StoreBusServer
        from karmada_tpu.utils import Store

        store = Store()
        srv = StoreBusServer(store)
        port = srv.start()
        return store, srv, port

    def test_write_through_and_bounded_failure(self):
        import time as _time

        from karmada_tpu.bus.service import StoreReplica
        from karmada_tpu.utils.builders import new_deployment

        store, srv, port = self._bus()
        replica = StoreReplica(
            f"127.0.0.1:{port}", timeout_seconds=2.0
        )
        replica.start()
        try:
            assert replica.wait_synced(5.0)
            replica.apply(new_deployment("through-bus", replicas=1))
            assert store.get("Resource", "default/through-bus") is not None

            # bus dies: the write fails within ~1x the budget, not 3x
            srv.stop(0)
            t0 = _time.perf_counter()
            with pytest.raises(Exception):
                replica.apply(new_deployment("after-death", replicas=1))
            assert _time.perf_counter() - t0 < 2.0 * 2.5
            # consecutive failures open the breaker -> instant fast-fail
            for _ in range(4):
                with pytest.raises(Exception):
                    replica.apply(new_deployment("x", replicas=1))
            assert replica.breaker.state == backoff.OPEN
            t0 = _time.perf_counter()
            with pytest.raises(backoff.CircuitBreakerOpen):
                replica.apply(new_deployment("y", replicas=1))
            assert _time.perf_counter() - t0 < 0.5  # zero RPC burned
        finally:
            replica.close()
