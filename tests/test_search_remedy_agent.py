"""Search/proxy, remedy, pull-mode agent, metrics adapter tests."""

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.cluster import PULL
from karmada_tpu.api.core import Condition, ObjectMeta, Resource, set_condition
from karmada_tpu.api.policy import ClusterAffinity
from karmada_tpu.controllers.remedy import (
    DecisionMatch,
    Remedy,
    RemedySpec,
    REMEDY_ACTIONS_ANNOTATION,
)
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.search import ProxyRequest
from karmada_tpu.search.registry import ResourceRegistry, ResourceRegistrySpec
from karmada_tpu.utils.builders import (
    duplicated_placement,
    new_cluster,
    new_deployment,
)


def member_pod(name, ns="default", phase="Running"):
    return Resource(
        api_version="v1",
        kind="Pod",
        meta=ObjectMeta(name=name, namespace=ns, labels={"app": "web"}),
        spec={"containers": []},
        status={"phase": phase},
    )


def make_plane(n=2):
    cp = ControlPlane()
    for i in range(1, n + 1):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.settle()
    return cp


class TestSearchAndProxy:
    def test_registry_caches_member_resources(self):
        cp = make_plane()
        cp.members.get("member1").apply(member_pod("p1"))
        cp.members.get("member2").apply(member_pod("p2"))
        cp.store.apply(
            ResourceRegistry(
                meta=ObjectMeta(name="pods"),
                spec=ResourceRegistrySpec(
                    resource_selectors=[{"apiVersion": "v1", "kind": "Pod"}]
                ),
            )
        )
        cp.settle()
        resp = cp.proxy.connect(ProxyRequest(verb="list", gvk="v1/Pod"))
        assert resp.served_by == "cache"
        assert {(c, o.meta.name) for c, o in resp.items} == {
            ("member1", "p1"), ("member2", "p2"),
        }

    def test_cluster_proxy_passthrough(self):
        cp = make_plane()
        cp.members.get("member2").apply(member_pod("direct"))
        resp = cp.proxy.connect(
            ProxyRequest(verb="get", gvk="v1/Pod", namespace="default",
                         name="direct", cluster="member2")
        )
        assert resp.served_by == "cluster" and resp.obj.meta.name == "direct"

    def test_karmada_fallback_serves_templates(self):
        cp = make_plane()
        cp.store.apply(new_deployment("tmpl"))
        resp = cp.proxy.connect(
            ProxyRequest(verb="get", gvk="apps/v1/Deployment",
                         namespace="default", name="tmpl")
        )
        assert resp.served_by == "karmada" and resp.obj.meta.name == "tmpl"


class TestRemedy:
    def test_traffic_control_applied_on_condition(self):
        cp = make_plane()
        cp.store.apply(
            Remedy(
                meta=ObjectMeta(name="dns-remedy"),
                spec=RemedySpec(
                    cluster_affinity=ClusterAffinity(cluster_names=["member1"]),
                    decision_matches=[
                        DecisionMatch(
                            cluster_condition_type="ServiceDomainNameResolutionReady",
                            cluster_condition_status="False",
                        )
                    ],
                ),
            )
        )
        cp.settle()
        cluster = cp.store.get("Cluster", "member1")
        assert REMEDY_ACTIONS_ANNOTATION not in cluster.meta.annotations
        set_condition(
            cluster.status.conditions,
            Condition(type="ServiceDomainNameResolutionReady", status=False),
        )
        cp.store.apply(cluster)
        cp.settle()
        cluster = cp.store.get("Cluster", "member1")
        assert cluster.meta.annotations[REMEDY_ACTIONS_ANNOTATION] == "TrafficControl"
        # condition recovers -> action removed
        set_condition(
            cluster.status.conditions,
            Condition(type="ServiceDomainNameResolutionReady", status=True),
        )
        cp.store.apply(cluster)
        cp.settle()
        cluster = cp.store.get("Cluster", "member1")
        assert REMEDY_ACTIONS_ANNOTATION not in cluster.meta.annotations


class TestPullModeAgent:
    def test_agent_applies_works_and_reports_status(self):
        cp = ControlPlane()
        push = new_cluster("pusher", cpu="100", memory="200Gi")
        pull = new_cluster("puller", cpu="100", memory="200Gi")
        pull.spec.sync_mode = PULL
        cp.join_cluster(push)
        cp.join_cluster(pull)
        cp.settle()
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="p", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    placement=duplicated_placement(),
                ),
            )
        )
        cp.settle()
        # the pull cluster got the deployment via its agent, not the pusher path
        obj = cp.members.get("puller").get("apps/v1/Deployment", "default", "app")
        assert obj is not None and obj.spec["replicas"] == 2
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert {i.cluster_name for i in rb.status.aggregated_status} >= {"puller"}


class TestMetricsAdapter:
    def test_weighted_merge(self):
        cp = make_plane()
        cp.members.get("member1").pod_metrics["default/web"] = {
            "pods": 3, "cpu_utilization": 90.0,
        }
        cp.members.get("member2").pod_metrics["default/web"] = {
            "pods": 1, "cpu_utilization": 10.0,
        }
        assert cp.metrics_adapter.merged_utilization("default/web") == 70.0

    def test_external_metric_sum(self):
        cp = make_plane()
        cp.members.get("member1").external_metric_series.append(
            {"namespace": "", "metric": "queue_depth", "value": 5}
        )
        cp.members.get("member2").external_metric_series.append(
            {"namespace": "", "metric": "queue_depth", "value": 7}
        )
        assert cp.metrics_adapter.external_metric_sum("queue_depth") == 12


class TestPullClusterLease:
    """Lease-based failure detection for Pull clusters: the plane never
    probes the member; Ready degrades only when the agent's lease goes
    stale past the grace period, and recovers on the next renewal."""

    def _pull_plane(self):
        from karmada_tpu import cli

        clock = [50_000.0]
        cp = cli.cmd_init(clock=lambda: clock[0])
        cli.cmd_join(cp, "pusher")
        token = cli.cmd_token_create(cp)
        cli.cmd_register(cp, "puller", token=token)
        cp.settle()
        return cp, clock

    def test_lease_renewed_keeps_ready(self, ):
        cp, clock = self._pull_plane()
        lease = cp.store.get("Lease", "puller")
        assert lease is not None and lease.renew_time == clock[0]
        cluster = cp.store.get("Cluster", "puller")
        ready = next(c for c in cluster.status.conditions if c.type == "Ready")
        assert ready.status and ready.reason == "AgentLeaseRenewed"

    def test_dead_agent_degrades_after_grace_only(self):
        cp, clock = self._pull_plane()
        cp.members.get("puller").reachable = False  # agent cut off
        # within the grace period the plane still believes the lease
        clock[0] += 60
        cp.settle()
        cluster = cp.store.get("Cluster", "puller")
        ready = next(c for c in cluster.status.conditions if c.type == "Ready")
        assert ready.status
        # past the grace period the cluster degrades and gets tainted
        clock[0] += 120
        cp.settle()
        cluster = cp.store.get("Cluster", "puller")
        ready = next(c for c in cluster.status.conditions if c.type == "Ready")
        assert not ready.status and ready.reason == "AgentLeaseExpired"
        assert any(t.key == "cluster.karmada.io/not-ready"
                   for t in cluster.spec.taints)
        # agent comes back -> lease renews -> Ready + untainted
        cp.members.get("puller").reachable = True
        clock[0] += 10
        cp.settle()
        cluster = cp.store.get("Cluster", "puller")
        ready = next(c for c in cluster.status.conditions if c.type == "Ready")
        assert ready.status and ready.reason == "AgentLeaseRenewed"
        assert not cluster.spec.taints
