"""Metrics adapter: the three metrics API flavors + FederatedHPA via
selector-filtered custom metrics across 3 members (VERDICT r1 #7).

Ref: pkg/metricsadapter/provider/{resourcemetrics,custommetrics,
externalmetrics}.go — by-name and by-selector queries with object AND
metric label selectors, namespaced/root scoping, per-cluster list union,
ListAllMetrics discovery union. The external flavor is stubbed in the
reference (externalmetrics.go:38) and implemented here."""

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.autoscaling import (
    FederatedHPA,
    FederatedHPASpec,
    MetricSpec,
    ScaleTargetRef,
)
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import LabelSelector, LabelSelectorRequirement
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.metricsadapter import MetricsAdapter
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)


def three_member_plane():
    cp = ControlPlane()
    for i in (1, 2, 3):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    return cp


class TestResourceMetrics:
    def test_pod_metrics_by_name_and_selector(self):
        cp = three_member_plane()
        cp.members.get("member1").pod_metrics_detail["default/web-1"] = {
            "cpu": 250, "memory": 1 << 28, "labels": {"app": "web"},
        }
        cp.members.get("member2").pod_metrics_detail["default/web-1"] = {
            "cpu": 400, "labels": {"app": "web"},
        }
        cp.members.get("member2").pod_metrics_detail["default/db-1"] = {
            "cpu": 900, "labels": {"app": "db"},
        }
        adapter = MetricsAdapter(cp.members)
        by_name = adapter.resources.pod_metrics_by_name("default", "web-1")
        assert {(s.cluster, s.value) for s in by_name} == {
            ("member1", 250.0), ("member2", 400.0),
        }
        by_sel = adapter.resources.pod_metrics_by_selector(
            "default", {"app": "web"}
        )
        assert len(by_sel) == 2
        assert all(s.labels["app"] == "web" for s in by_sel)

    def test_node_metrics_by_selector(self):
        cp = three_member_plane()
        cp.members.get("member1").node_metrics["n1"] = {
            "cpu": 4000, "labels": {"pool": "gpu"},
        }
        cp.members.get("member3").node_metrics["n9"] = {
            "cpu": 1000, "labels": {"pool": "cpu"},
        }
        adapter = MetricsAdapter(cp.members)
        got = adapter.resources.node_metrics_by_selector({"pool": "gpu"})
        assert [(s.cluster, s.object_name) for s in got] == [("member1", "n1")]
        assert len(adapter.resources.node_metrics_by_name("n9")) == 1


class TestCustomMetrics:
    def _seed(self, cp):
        cp.members.get("member1").custom_metric_series.extend([
            {"resource": "pods", "namespaced": True, "namespace": "default",
             "object": "web-1", "metric": "http_requests",
             "value": 30.0, "labels": {"verb": "GET"},
             "object_labels": {"app": "web"}},
            {"resource": "pods", "namespaced": True, "namespace": "default",
             "object": "web-1", "metric": "http_requests",
             "value": 5.0, "labels": {"verb": "POST"},
             "object_labels": {"app": "web"}},
            {"resource": "namespaces", "namespaced": False, "namespace": "",
             "object": "default", "metric": "ns_cost", "value": 12.0},
        ])
        cp.members.get("member2").custom_metric_series.append(
            {"resource": "pods", "namespaced": True, "namespace": "default",
             "object": "web-2", "metric": "http_requests",
             "value": 50.0, "labels": {"verb": "GET"},
             "object_labels": {"app": "web"}},
        )
        cp.members.get("member3").custom_metric_series.append(
            {"resource": "pods", "namespaced": True, "namespace": "other",
             "object": "web-9", "metric": "http_requests",
             "value": 999.0, "labels": {"verb": "GET"},
             "object_labels": {"app": "web"}},
        )

    def test_by_name_with_metric_selector(self):
        cp = three_member_plane()
        self._seed(cp)
        adapter = MetricsAdapter(cp.members)
        got = adapter.custom.get_metric_by_name(
            "pods", "default", "web-1", "http_requests",
            metric_selector={"verb": "GET"},
        )
        assert [(s.cluster, s.value) for s in got] == [("member1", 30.0)]

    def test_by_selector_unions_clusters_and_respects_namespace(self):
        cp = three_member_plane()
        self._seed(cp)
        adapter = MetricsAdapter(cp.members)
        got = adapter.custom.get_metric_by_selector(
            "pods", "default", "http_requests",
            object_selector={"app": "web"},
            metric_selector={"verb": "GET"},
        )
        # member3's series lives in another namespace and must not leak
        assert {(s.cluster, s.object_name, s.value) for s in got} == {
            ("member1", "web-1", 30.0), ("member2", "web-2", 50.0),
        }
        # match-expression selectors work too
        sel = LabelSelector(match_expressions=[
            LabelSelectorRequirement(key="verb", operator="In",
                                     values=["GET", "PUT"])
        ])
        got2 = adapter.custom.get_metric_by_selector(
            "pods", "default", "http_requests", metric_selector=sel
        )
        assert len(got2) == 2

    def test_root_scoped_and_list_all(self):
        cp = three_member_plane()
        self._seed(cp)
        adapter = MetricsAdapter(cp.members)
        root = adapter.custom.get_metric_by_name(
            "namespaces", "", "default", "ns_cost"
        )
        assert [s.value for s in root] == [12.0]
        infos = adapter.custom.list_all_metrics()
        assert {(i.group_resource, i.metric, i.namespaced) for i in infos} == {
            ("pods", "http_requests", True), ("namespaces", "ns_cost", False),
        }


class TestExternalMetrics:
    def test_namespaced_external_with_selector(self):
        cp = three_member_plane()
        cp.members.get("member1").external_metric_series.extend([
            {"namespace": "default", "metric": "queue_depth", "value": 5,
             "labels": {"queue": "orders"}},
            {"namespace": "default", "metric": "queue_depth", "value": 100,
             "labels": {"queue": "audit"}},
        ])
        cp.members.get("member2").external_metric_series.append(
            {"namespace": "default", "metric": "queue_depth", "value": 7,
             "labels": {"queue": "orders"}},
        )
        cp.members.get("member3").external_metric_series.append(
            {"namespace": "other", "metric": "queue_depth", "value": 999,
             "labels": {"queue": "orders"}},
        )
        adapter = MetricsAdapter(cp.members)
        assert adapter.external.external_metric_sum(
            "default", "queue_depth", {"queue": "orders"}
        ) == 12
        assert ("default", "queue_depth") in (
            adapter.external.list_all_external_metrics()
        )


class TestFederatedHPACustomMetrics:
    def test_hpa_scales_on_selector_filtered_custom_metric(self):
        """FederatedHPA e2e driven by a selector-filtered custom metric
        across 3 members (VERDICT r1 #7 done-criterion)."""
        clock = [0.0]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in (1, 2, 3):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("web", replicas=3))
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="p", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    placement=dynamic_weight_placement(),
                ),
            )
        )
        cp.settle()
        # per-pod http_requests across the three members; the "audit"
        # series must be excluded by the metric selector
        for i, (member, val) in enumerate(
            [("member1", 120.0), ("member2", 80.0), ("member3", 100.0)]
        ):
            cp.members.get(member).custom_metric_series.extend([
                {"resource": "pods", "namespaced": True,
                 "namespace": "default", "object": f"web-{i}",
                 "metric": "http_requests", "value": val,
                 "labels": {"path": "api"}},
                {"resource": "pods", "namespaced": True,
                 "namespace": "default", "object": f"web-{i}",
                 "metric": "http_requests", "value": 10_000.0,
                 "labels": {"path": "healthz"}},
            ])
        cp.store.apply(
            FederatedHPA(
                meta=ObjectMeta(name="web-hpa", namespace="default"),
                spec=FederatedHPASpec(
                    scale_target_ref=ScaleTargetRef(kind="Deployment", name="web"),
                    min_replicas=1,
                    max_replicas=10,
                    metrics=[
                        MetricSpec(
                            type="Pods",
                            metric_name="http_requests",
                            metric_selector={"path": "api"},
                            target_average_value=50.0,
                        )
                    ],
                    stabilization_window_seconds=0,
                ),
            )
        )
        clock[0] += 30
        cp.settle()
        template = cp.store.get("Resource", "default/web")
        # sum(api series) = 300; target 50/pod -> 6 replicas
        assert template.spec["replicas"] == 6
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        assert sum(tc.replicas for tc in rb.spec.clusters) == 6
