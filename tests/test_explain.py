"""Placement provenance plane (ISSUE 13): kernel-vs-oracle identity,
stage-bit semantics under quota and chaos, the ExplainStore ring, the
/debug/explain + CLI surfaces, the unschedulable-reason taxonomy with
transition dedup, and the flight-record "why" attachment."""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from karmada_tpu.api.cluster import NO_EXECUTE, Taint
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    ClusterAffinityTerm,
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
    LabelSelector,
    StaticClusterAssignment,
)
from karmada_tpu.ops.explain import (
    N_STAGES,
    TOPK_COLS,
    explain_pass,
    topk_width,
)
from karmada_tpu.parallel.mesh import scheduling_mesh
from karmada_tpu.refimpl.explain_np import explain_batch_np
from karmada_tpu.scheduler import (
    QUOTA_EXCEEDED_ERROR,
    BindingProblem,
    ClusterSnapshot,
    TensorScheduler,
    build_quota_snapshot,
)
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
)
from karmada_tpu.utils.explainstore import (
    ExplainCapture,
    ExplainStore,
    render_explanation,
    render_worst_table,
)
from karmada_tpu.utils.quantity import parse_resource_list
from karmada_tpu.utils.reasons import (
    REASONS,
    STAGE_REASONS,
    TransitionDedup,
    classify_error,
)

CPU_REQ = parse_resource_list({"cpu": "1"})


def group_term(group: str) -> ClusterAffinityTerm:
    return ClusterAffinityTerm(
        affinity_name=f"grp-{group}",
        label_selector=LabelSelector(match_labels={"group": group}),
    )


def random_inputs(rng, b, c):
    return dict(
        aff_ok=rng.random((b, c)) < 0.8,
        taint_ok=rng.random((b, c)) < 0.9,
        api_ok=rng.random((b, c)) < 0.95,
        spread_ok=rng.random((b, c)) < 0.85,
        avail=rng.integers(-1, 60, (b, c)).astype(np.int32),
        caps=np.where(
            rng.random((b, c)) < 0.2,
            rng.integers(0, 4, (b, c)),
            2**31 - 1,
        ).astype(np.int32),
        admitted=rng.random(b) < 0.8,
        dynamic=rng.random(b) < 0.7,
        replicas=rng.integers(0, 12, b).astype(np.int32),
        assignment=rng.integers(0, 6, (b, c)).astype(np.int32),
        prev=rng.integers(0, 6, (b, c)).astype(np.int32),
        preempted=rng.random((b, c)) < 0.1,
    )


# --------------------------------------------------------------------------
# kernel vs the shared-free numpy oracle
# --------------------------------------------------------------------------


class TestKernelOracleIdentity:
    def test_bit_layout_matches_taxonomy(self):
        assert N_STAGES == len(STAGE_REASONS) <= 8
        assert TOPK_COLS == 5
        for code in STAGE_REASONS:
            assert REASONS[code].kind == "stage"

    def test_randomized_grid_bit_identical(self):
        """Random shapes (incl. padded-tail-shaped odd sizes) across the
        bucket grid: the vectorized kernel and the per-binding reference
        loop must agree bit for bit on masks AND top-k summaries."""
        rng = np.random.default_rng(42)
        for _ in range(12):
            b = int(rng.integers(1, 48))
            c = int(rng.integers(2, 48))
            k = topk_width(c)
            args = random_inputs(rng, b, c)
            m_dev, t_dev = explain_pass(*args.values(), k=k)
            m_np, t_np = explain_batch_np(*args.values(), k=k)
            assert np.array_equal(np.asarray(m_dev), m_np)
            assert np.array_equal(np.asarray(t_dev), t_np)

    @pytest.mark.parametrize("devices", (1, 2, 4, 8))
    def test_mesh_identity(self, devices):
        """The sharded dispatch (mesh 1/2/4/8 over the conftest virtual
        devices) answers bit-identical masks/top-k to the oracle and the
        single-device form — padded tails included (b=24 does not divide
        8 evenly per shard boundary alignment, b=32 does)."""
        rng = np.random.default_rng(devices)
        mesh = scheduling_mesh(devices)
        for b in (8, 32):
            c = 16
            k = topk_width(c)
            args = random_inputs(rng, b, c)
            m_np, t_np = explain_batch_np(*args.values(), k=k)
            m_dev, t_dev = explain_pass(
                *args.values(), k=k, mesh=mesh, shard_c=False
            )
            assert np.array_equal(np.asarray(m_dev), m_np)
            assert np.array_equal(np.asarray(t_dev), t_np)

    def test_topk_order_and_mask_column(self):
        """Deterministic ordering: assigned desc, then avail desc, then
        index asc; the 5th column is the candidate's own mask byte."""
        aff = np.ones((1, 4), bool)
        args = dict(
            aff_ok=aff, taint_ok=aff.copy(), api_ok=aff.copy(),
            spread_ok=np.array([[True, True, False, True]]),
            avail=np.array([[5, 9, 9, 0]], np.int32),
            caps=np.full((1, 4), 2**31 - 1, np.int32),
            admitted=np.array([True]),
            dynamic=np.array([True]),
            replicas=np.array([3], np.int32),
            assignment=np.array([[0, 3, 0, 0]], np.int32),
            prev=np.zeros((1, 4), np.int32),
            preempted=np.zeros((1, 4), bool),
        )
        _m, topk = explain_pass(*args.values(), k=4)
        topk = np.asarray(topk)[0]
        # assigned row first, then avail 9 (idx 2), avail 5 (idx 0), 0
        assert topk[:, 0].tolist() == [1, 2, 0, 3]
        spread_bit = 1 << STAGE_REASONS.index("SpreadConstraintUnsatisfied")
        avail_bit = 1 << STAGE_REASONS.index("NoAvailableReplicas")
        assert topk[1, 4] == spread_bit  # idx 2 excluded by spread
        assert topk[3, 4] == avail_bit  # idx 3 has zero availability


# --------------------------------------------------------------------------
# the ExplainStore ring
# --------------------------------------------------------------------------


def toy_capture(wave, keys=("ns/a",), error="", rank=0):
    b = len(keys)
    return ExplainCapture(
        wave=wave,
        names=("c0", "c1"),
        keys=list(keys),
        masks=np.zeros((b, 2), np.uint8),
        topk=np.zeros((b, 2, TOPK_COLS), np.int32),
        group_rank=np.full(b, rank, np.int32),
        errors=[error] * b,
        assignment=np.zeros((b, 2), np.int32),
    )


class TestExplainStore:
    def test_wave_ring_evicts_whole_waves_counted(self):
        store = ExplainStore(cap=2)
        for wave in (1, 1, 2, 3):  # wave 1 has TWO captures (two chunks)
            store.add(toy_capture(wave))
        assert store.evicted == 2  # both wave-1 chunks left together
        assert sorted({c.wave for c in store.captures()}) == [2, 3]
        store.clear()
        assert store.captures() == [] and store.evicted == 0

    def test_zero_cap_disables(self):
        store = ExplainStore(cap=0)
        store.add(toy_capture(1))
        assert not store.enabled and store.captures() == []

    def test_binding_lookup_newest_wins_and_wave_pin(self):
        store = ExplainStore(cap=4)
        store.add(toy_capture(1, keys=("ns/a",), error="old"))
        store.add(toy_capture(2, keys=("ns/a",), error=""))
        assert store.explain_binding("ns/a")["wave"] == 2
        assert store.explain_binding("ns/a", wave=1)["error"] == "old"
        assert store.explain_binding("ns/zzz") is None

    def test_worst_orders_denied_before_displaced(self):
        store = ExplainStore(cap=4)
        store.add(toy_capture(5, keys=("ns/ok",), error=""))
        store.add(toy_capture(5, keys=("ns/displaced",), rank=1))
        store.add(
            toy_capture(5, keys=("ns/denied",), error=QUOTA_EXCEEDED_ERROR)
        )
        worst = store.worst(5, k=8)
        assert [w["binding"] for w in worst] == [
            "ns/denied", "ns/displaced",
        ]
        ctx = store.worst_context(5)
        assert ctx["summary"]["wave"] == 5
        table = render_worst_table(ctx)
        assert "ns/denied" in table and "QuotaExceeded" in table

    def test_worst_newest_capture_wins_over_stale_denial(self):
        """A binding denied in an early pass but SCHEDULED by a later
        pass of the same wave must not surface its stale denial — the
        newest capture wins the key unconditionally."""
        store = ExplainStore(cap=4)
        store.add(
            toy_capture(7, keys=("ns/b",), error=QUOTA_EXCEEDED_ERROR)
        )
        store.add(toy_capture(7, keys=("ns/b",), error=""))
        assert store.worst(7) == []

    def test_decode_assignment_complete_beyond_topk(self):
        """The decoded assignment comes from the sparse full-assignment
        store, never the top-k slice: a wide placement assigned on more
        clusters than k reports them all."""
        clusters = [
            new_cluster(f"m{i:02d}", cpu="1000", memory="2000Gi")
            for i in range(12)
        ]
        eng, store = make_engine(clusters)
        from karmada_tpu.utils.builders import duplicated_placement

        res = eng.schedule([
            problem("d/wide", replicas=2, placement=duplicated_placement())
        ])
        assert len(res[0].clusters) == 12
        doc = store.explain_binding("d/wide")
        assert doc["assignment"] == res[0].clusters
        assert len(doc["candidates"]) == 8  # the summary stays top-k

    def test_debug_doc_shapes(self):
        store = ExplainStore(cap=4)
        store.add(toy_capture(3))
        doc = store.debug_doc(proc="plane")
        assert doc["waves"] == [3] and "summary" in doc and "worst" in doc
        doc_b = store.debug_doc(binding="ns/a")
        assert doc_b["binding"]["binding"] == "ns/a"
        json.dumps(doc)  # the HTTP surface serializes this verbatim
        json.dumps(doc_b)


# --------------------------------------------------------------------------
# engine captures: explain-under-quota and explain-under-chaos
# --------------------------------------------------------------------------


def make_engine(clusters, quota=None):
    snap = ClusterSnapshot(clusters)
    eng = TensorScheduler(snap, trace_manifest="")
    store = ExplainStore(cap=8)
    eng.set_explain(store)
    if quota is not None:
        eng.set_quota(build_quota_snapshot([quota], snap, generation=1))
    return eng, store


def frq(ns, overall, static=()):
    return FederatedResourceQuota(
        meta=ObjectMeta(name="q", namespace=ns),
        spec=FederatedResourceQuotaSpec(
            overall=dict(overall), static_assignments=list(static)
        ),
    )


def problem(key, ns="", replicas=2, placement=None, prev=None, evict=()):
    return BindingProblem(
        key=key,
        placement=placement or dynamic_weight_placement(),
        replicas=replicas,
        requests=CPU_REQ,
        gvk="apps/v1/Deployment",
        prev=dict(prev or {}),
        evict_clusters=tuple(evict),
        namespace=ns,
    )


class TestEngineCaptureStageBits:
    def test_admission_denial_carries_exactly_its_bit(self):
        """A binding denied by batched FIFO admission explains with the
        QuotaExceeded stage bit on EVERY cluster and nothing else (the
        clusters themselves were feasible)."""
        clusters = [
            new_cluster(f"m{i}", cpu="1000", memory="2000Gi")
            for i in range(4)
        ]
        eng, store = make_engine(clusters, quota=frq("a", {"cpu": 0}))
        res = eng.schedule([problem("a/b0", ns="a")])
        assert res[0].error == QUOTA_EXCEEDED_ERROR
        doc = store.explain_binding("a/b0")
        assert doc["reason"] == "QuotaExceeded"
        assert set(doc["stages"]) == {"QuotaExceeded"}
        assert doc["stages"]["QuotaExceeded"]["count"] == 4
        assert doc["clusters_feasible"] == 0

    def test_static_cap_carries_cap_bit_not_admission(self):
        """A cluster capped to zero by a static assignment explains with
        QuotaCapExceeded on THAT cluster; the binding still admits."""
        clusters = [
            new_cluster(f"m{i}", cpu="1000", memory="2000Gi")
            for i in range(3)
        ]
        q = frq(
            "a", {"cpu": 100000},
            static=[StaticClusterAssignment(
                cluster_name="m0", hard={"cpu": 0}
            )],
        )
        eng, store = make_engine(clusters, quota=q)
        res = eng.schedule([problem("a/b0", ns="a", replicas=4)])
        assert res[0].success and "m0" not in res[0].clusters
        doc = store.explain_binding("a/b0")
        assert set(doc["stages"]) == {"QuotaCapExceeded"}
        assert doc["stages"]["QuotaCapExceeded"]["clusters"] == ["m0"]

    def test_noexecute_taint_and_eviction_carry_taint_bit(self):
        """An untolerated NoExecute taint — and an active graceful
        eviction — both explain as the taints/NoExecute stage, exactly
        that bit on exactly those clusters."""
        clusters = [
            new_cluster("m0", cpu="1000", memory="2000Gi",
                        taints=[Taint(key="down", effect=NO_EXECUTE)]),
            new_cluster("m1", cpu="1000", memory="2000Gi"),
            new_cluster("m2", cpu="1000", memory="2000Gi"),
        ]
        eng, store = make_engine(clusters)
        res = eng.schedule([
            problem("d/tainted"),
            problem("d/evicted", evict=["m1"]),
        ])
        assert all(r.success for r in res)
        tainted = store.explain_binding("d/tainted")
        assert set(tainted["stages"]) == {"TaintUntolerated"}
        assert tainted["stages"]["TaintUntolerated"]["clusters"] == ["m0"]
        evicted = store.explain_binding("d/evicted")
        # m0 by its taint, m1 by the NoExecute eviction task
        assert set(evicted["stages"]) == {"TaintUntolerated"}
        assert evicted["stages"]["TaintUntolerated"]["clusters"] == [
            "m0", "m1",
        ]
        assert "m1" not in res[1].clusters

    def test_failover_displacement_explains_group_rank(self):
        """A PR 7-style failover wave: the primary affinity group's
        clusters are evicted, the binding reschedules onto the fallback
        group — the capture records group_rank 1 and the primary
        clusters excluded by AffinityMismatch (of the SELECTED group's
        view) + TaintUntolerated (the evictions)."""
        clusters = [
            new_cluster(f"p{i}", cpu="1000", memory="2000Gi",
                        labels={"group": "primary"})
            for i in range(2)
        ] + [
            new_cluster(f"f{i}", cpu="1000", memory="2000Gi",
                        labels={"group": "fallback"})
            for i in range(2)
        ]
        pl = dynamic_weight_placement(
            cluster_affinities=[
                group_term("primary"), group_term("fallback"),
            ]
        )
        eng, store = make_engine(clusters)
        res = eng.schedule([
            problem(
                "d/displaced", replicas=4, placement=pl,
                prev={"p0": 2, "p1": 2}, evict=["p0", "p1"],
            ),
        ])
        assert res[0].success
        assert set(res[0].clusters) <= {"f0", "f1"}
        doc = store.explain_binding("d/displaced")
        assert doc["group_rank"] == 1
        assert set(doc["stages"]["AffinityMismatch"]["clusters"]) == {
            "p0", "p1",
        }
        assert set(doc["stages"]["TaintUntolerated"]["clusters"]) == {
            "p0", "p1",
        }

    def test_cap_zeroed_primary_group_rank_matches_solve(self):
        """A static-assignment cap that zeroes the primary affinity
        group's clusters displaces the binding onto the fallback group
        — the capture's group selection must consume the SAME cap-folded
        availability the ranked solve ranks on, so group_rank names the
        group that actually placed."""
        clusters = [
            new_cluster(f"p{i}", cpu="1000", memory="2000Gi",
                        labels={"group": "primary"})
            for i in range(2)
        ] + [
            new_cluster(f"f{i}", cpu="1000", memory="2000Gi",
                        labels={"group": "fallback"})
            for i in range(2)
        ]
        q = frq(
            "a", {"cpu": 100000},
            static=[
                StaticClusterAssignment(cluster_name="p0", hard={"cpu": 0}),
                StaticClusterAssignment(cluster_name="p1", hard={"cpu": 0}),
            ],
        )
        pl = dynamic_weight_placement(
            cluster_affinities=[
                group_term("primary"), group_term("fallback"),
            ]
        )
        eng, store = make_engine(clusters, quota=q)
        res = eng.schedule(
            [problem("a/capped", ns="a", replicas=4, placement=pl)]
        )
        assert res[0].success and set(res[0].clusters) <= {"f0", "f1"}
        doc = store.explain_binding("a/capped")
        assert doc["group_rank"] == 1
        assert doc["assignment"] == res[0].clusters

    def test_cap_zero_ring_skips_the_dispatch(self):
        """KARMADA_TPU_EXPLAIN_CAP=0 disables the store; an armed engine
        must not pay the capture dispatch for a ring that drops
        everything."""
        clusters = [new_cluster("m0", cpu="1000", memory="2000Gi")]
        eng, _store = make_engine(clusters)
        dead = ExplainStore(cap=0)
        eng.set_explain(dead)
        eng.schedule([problem("d/x")])
        assert dead.captures() == []
        assert not any(k[0] == "E" for k in eng._engine_traces), (
            "explain kernel dispatched for a disabled ring"
        )

    def test_stage_masks_compose_to_pack_chunk_feasibility(self):
        """Drift guard for the duplicated packing algebra: AND-folding
        the capture's FILTER-stage bits (affinity/taint/API/spread — the
        stages _pack_chunk composes into `feasible`) must reproduce
        _pack_chunk's output bit for bit over a batch exercising taints,
        evictions, already-placed leniency, unknown GVKs and incomplete
        enablements."""
        clusters = [
            new_cluster("m0", cpu="1000", memory="2000Gi"),
            new_cluster("m1", cpu="1000", memory="2000Gi",
                        taints=[Taint(key="t", effect=NO_EXECUTE)]),
            new_cluster("m2", cpu="1000", memory="2000Gi",
                        api_enablements=(), complete_enablements=True),
            new_cluster("m3", cpu="1000", memory="2000Gi",
                        complete_enablements=False),
        ]
        eng, store = make_engine(clusters)
        probs = [
            problem("d/plain"),
            problem("d/lenient", prev={"m1": 1, "m2": 1, "m3": 1}),
            problem("d/evicted", evict=["m0"]),
            BindingProblem(
                key="d/unknown-gvk",
                placement=dynamic_weight_placement(),
                replicas=2, requests=CPU_REQ, gvk="weird/v9/Thing",
            ),
        ]
        eng.schedule(probs)
        cap = store.captures()[-1]
        compiled = [eng._compiled(p.placement) for p in probs]
        feasible, *_rest = eng._pack_chunk(probs, compiled, 0)
        filter_bits = np.uint8(0)
        for code in (
            "AffinityMismatch", "TaintUntolerated", "ApiNotEnabled",
            "SpreadConstraintUnsatisfied",
        ):
            filter_bits |= np.uint8(1 << STAGE_REASONS.index(code))
        masks = cap.uniq_masks[cap.mask_inv]
        assert np.array_equal((masks & filter_bits) == 0, feasible)

    def test_disarmed_engine_captures_nothing(self):
        clusters = [new_cluster("m0", cpu="1000", memory="2000Gi")]
        eng, store = make_engine(clusters)
        eng.set_explain(None)
        eng.schedule([problem("d/x")])
        assert store.captures() == []

    def test_capture_survives_batch_identity_replay(self):
        """The replay fast path returns cached results; an armed engine
        still captures the pass (provenance is per PASS, not per fresh
        solve)."""
        clusters = [
            new_cluster(f"m{i}", cpu="1000", memory="2000Gi")
            for i in range(3)
        ]
        eng, store = make_engine(clusters)
        probs = [problem(f"d/b{i}") for i in range(4)]
        eng.schedule(probs)
        n1 = len(store.captures())
        eng.schedule(probs)  # identity replay
        assert len(store.captures()) == 2 * n1

    def test_explain_trace_recorded_in_manifest(self, tmp_path):
        manifest = str(tmp_path / "manifest.json")
        clusters = [new_cluster("m0", cpu="1000", memory="2000Gi")]
        snap = ClusterSnapshot(clusters)
        eng = TensorScheduler(snap, trace_manifest=manifest)
        eng.set_explain(ExplainStore(cap=4))
        eng.schedule([problem("d/x")])
        data = json.loads(open(manifest).read())
        kernels = {r["kernel"] for r in data["records"]}
        assert "explain_pass" in kernels
        from karmada_tpu.scheduler.prewarm import TraceManifest, replay

        stats = replay(TraceManifest(manifest), expand=False)
        assert stats["compiled"] >= 1 and stats["failed"] == 0


# --------------------------------------------------------------------------
# reasons taxonomy + transition dedup
# --------------------------------------------------------------------------


class TestTransitionDedup:
    def test_once_per_reason_generation(self):
        d = TransitionDedup()
        assert d.observe("k", "QuotaExceeded", 1)
        assert not d.observe("k", "QuotaExceeded", 1)  # re-enqueue
        assert d.observe("k", "QuotaExceeded", 2)  # new generation
        assert d.observe("k", "NoClusterFit", 2)  # reason changed
        d.forget("k")
        assert d.observe("k", "NoClusterFit", 2)  # transition via forget

    def test_cap_resets_wholesale(self):
        d = TransitionDedup(cap=2)
        assert d.observe("a", "X", 1) and d.observe("b", "X", 1)
        assert d.observe("c", "X", 1)  # full: reset, then record
        assert d.observe("a", "X", 1)  # over-counts once, never grows

    def test_classifier_total(self):
        assert classify_error("") == "Success"
        assert classify_error("weird new failure") == "Unschedulable"


class TestControllerReasonCounters:
    def test_quota_denial_counts_once_per_generation(self):
        from karmada_tpu import cli as _cli
        from karmada_tpu.api import (
            PropagationPolicy,
            PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.utils.builders import new_deployment
        from karmada_tpu.utils.metrics import unschedulable_total

        base = unschedulable_total.value(reason="QuotaExceeded")
        cp = _cli.cmd_init()
        cp.join_cluster(new_cluster("m0", cpu="1000", memory="2000Gi"))
        cp.settle()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="teamq"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(
                        api_version="apps/v1", kind="Deployment"
                    )
                ],
                placement=dynamic_weight_placement(),
            ),
        ))
        cp.store.apply(frq("teamq", {"cpu": 0}))
        cp.store.apply(
            new_deployment("denied", namespace="teamq", replicas=2)
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "teamq/denied-deployment")
        cond = [c for c in rb.status.conditions if c.type == "Scheduled"]
        assert cond and cond[0].status is False
        assert cond[0].reason == "QuotaExceeded"
        after_first = unschedulable_total.value(reason="QuotaExceeded")
        assert after_first == base + 1
        # re-enqueue within the same binding generation: parked, no count
        cp.scheduler.worker.enqueue(
            ("ResourceBinding", "teamq/denied-deployment")
        )
        cp.settle()
        assert unschedulable_total.value(
            reason="QuotaExceeded"
        ) == after_first
        # a quota EVENT that re-denies the UNCHANGED binding is the same
        # ongoing denial — still one count
        cp.store.apply(frq("teamq", {"cpu": 0}))
        cp.settle()
        assert unschedulable_total.value(
            reason="QuotaExceeded"
        ) == after_first
        # the binding's own spec changing (scale) is a new generation:
        # a re-denial then counts again
        cp.store.apply(
            new_deployment("denied", namespace="teamq", replicas=3)
        )
        cp.settle()
        assert unschedulable_total.value(
            reason="QuotaExceeded"
        ) == after_first + 1


# --------------------------------------------------------------------------
# surfaces: /debug/explain, the CLI verb, top columns, flight records
# --------------------------------------------------------------------------


class TestSurfaces:
    def _armed_engine_with_denial(self):
        clusters = [
            new_cluster(f"m{i}", cpu="1000", memory="2000Gi")
            for i in range(2)
        ]
        eng, store = make_engine(clusters, quota=frq("a", {"cpu": 0}))
        eng.schedule([problem("a/denied", ns="a"), problem("d/ok")])
        return eng, store

    def test_debug_explain_endpoint(self, monkeypatch):
        from karmada_tpu.utils import explainstore as expl
        from karmada_tpu.utils.metrics import MetricsServer

        _eng, store = self._armed_engine_with_denial()
        monkeypatch.setattr(expl, "_STORE", store)
        srv = MetricsServer()
        srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/explain"
                "?binding=a/denied",
                timeout=5,
            ) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["binding"]["reason"] == "QuotaExceeded"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/explain", timeout=5
            ) as resp:
                summary = json.loads(resp.read().decode())
            assert summary["summary"]["verdicts"]["QuotaExceeded"] == 1
            assert summary["worst"][0]["binding"] == "a/denied"
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/explain?wave=zap",
                    timeout=5,
                )
            assert err.value.code == 400
        finally:
            srv.stop()

    def test_cli_explain_placement_and_render(self, monkeypatch):
        from karmada_tpu import cli as _cli
        from karmada_tpu.utils import explainstore as expl

        _eng, store = self._armed_engine_with_denial()
        monkeypatch.setattr(expl, "_STORE", store)
        doc = _cli.cmd_explain_placement("a/denied")
        text = render_explanation(doc["binding"])
        assert "QuotaExceeded" in text and "candidate" in text
        # the field-docs form keeps working through main()
        rc = _cli.main(["explain", "PropagationPolicy.spec"])
        assert rc == 0

    def test_flight_record_carries_worst_explanations(
        self, tmp_path, monkeypatch
    ):
        from karmada_tpu.utils import explainstore as expl
        from karmada_tpu.utils.tracing import (
            WaveTracer,
            analyze_record,
            load_flight_records,
        )
        from karmada_tpu.utils import tracing as trc

        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0.00001")
        monkeypatch.setenv("KARMADA_TPU_FLIGHT_DIR", str(tmp_path))
        tracer_obj = WaveTracer()
        monkeypatch.setattr(trc, "tracer", tracer_obj)
        clusters = [
            new_cluster(f"m{i}", cpu="1000", memory="2000Gi")
            for i in range(2)
        ]
        eng, store = make_engine(clusters, quota=frq("a", {"cpu": 0}))
        monkeypatch.setattr(expl, "_STORE", store)
        wave = tracer_obj.begin_wave("test")
        with tracer_obj.span("scheduler.pass"):
            eng.schedule([problem("a/denied", ns="a")])
        closed = tracer_obj.end_wave()
        assert closed == wave
        records = load_flight_records(str(tmp_path / "flight.jsonl"))
        rec = records[-1]
        assert rec["wave"] == wave
        worst = rec["explain"]["worst"]
        assert worst[0]["binding"] == "a/denied"
        assert worst[0]["reason"] == "QuotaExceeded"
        analysis = analyze_record(rec)
        assert analysis["identical"]
        assert "explain: wave" in analysis["table"]
        assert "a/denied" in analysis["table"]

    def test_top_json_carries_device_bytes_and_unschedulable(self):
        from karmada_tpu import cli as _cli
        from karmada_tpu.utils.metrics import (
            MetricsServer,
            device_bytes,
            unschedulable_total,
        )

        device_bytes.set(
            1234567, kind="packed_grid", bucket="t", platform="cpu"
        )
        unschedulable_total.inc(reason="NoClusterFit")
        srv = MetricsServer()
        srv.start()
        try:
            doc = _cli.cmd_plane_top(metrics=f"127.0.0.1:{srv.port}")
            entry = next(iter(doc["procs"].values()))
            assert entry["device_bytes"] >= 1234567
            assert entry["unschedulable_total"] >= 1
            assert "NoClusterFit" in entry["unschedulable_by_reason"]
            text = _cli.render_top(doc)
            assert "unsched/denied" in text
        finally:
            srv.stop()

    def test_history_row_samples_unschedulable(self):
        from karmada_tpu.utils.history import WaveHistory
        from karmada_tpu.utils.metrics import unschedulable_total
        from karmada_tpu.utils.tracing import WaveTracer

        tr = WaveTracer()
        hist = WaveHistory(cap=8)
        tr.begin_wave("t")
        row0 = hist.sample(tr, tr.current_wave)  # seeds the baseline
        unschedulable_total.inc(reason="InsufficientReplicas")
        row = hist.sample(tr, tr.current_wave)
        assert row0["unschedulable"] == 0
        assert row["unschedulable"] == 1
