"""Quota enforcement plane (ISSUE 8): FederatedResourceQuota as tensor
constraints in the Assign path, live usage accounting, denial conditions,
and the quota-capped HPA-surge scenario."""

import numpy as np
import pytest

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
    FederatedResourceQuotaStatus,
    StaticClusterAssignment,
)
from karmada_tpu.api.work import SCHEDULED
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.scheduler import (
    QUOTA_EXCEEDED_ERROR,
    BindingProblem,
    ClusterSnapshot,
    TensorScheduler,
    build_quota_snapshot,
)
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)
from karmada_tpu.utils.quantity import parse_resource_list
from karmada_tpu.webhook.chain import (
    ValidationError,
    validate_federated_resource_quota,
)

CPU_REQ = parse_resource_list({"cpu": "1"})


def frq(ns, overall, static=(), used=None):
    q = FederatedResourceQuota(
        meta=ObjectMeta(name="q", namespace=ns),
        spec=FederatedResourceQuotaSpec(
            overall=dict(overall), static_assignments=list(static)
        ),
    )
    if used is not None:
        q.status = FederatedResourceQuotaStatus(
            overall=dict(overall), overall_used=dict(used)
        )
    return q


def problem(key, ns, replicas, prev=None):
    return BindingProblem(
        key=key, placement=dynamic_weight_placement(), replicas=replicas,
        requests=CPU_REQ, gvk="apps/v1/Deployment",
        prev=dict(prev or {}), namespace=ns,
    )


class TestEngineAdmission:
    def setup_method(self):
        self.snap = ClusterSnapshot(
            [new_cluster(f"m{i}", cpu="1000", memory="2000Gi") for i in range(4)]
        )

    def test_fifo_denial_and_unquotad_passthrough(self):
        eng = TensorScheduler(self.snap, chunk_size=1024)
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 5000})], self.snap, generation=1
        ))
        ps = [problem(f"a/b{i}", "a", 2) for i in range(4)] + [
            problem("z/b0", "z", 2)
        ]
        res = eng.schedule(ps)
        assert [r.error for r in res] == [
            "", "", QUOTA_EXCEEDED_ERROR, QUOTA_EXCEEDED_ERROR, "",
        ]
        assert sum(res[0].clusters.values()) == 2

    def test_delta_demand_admits_steady_reschedule(self):
        """A binding already holding its replicas has zero delta demand:
        re-scheduling the same wave against a fully-used quota must not
        deny it (usage is recomputed from bound state, not double-charged
        per pass)."""
        eng = TensorScheduler(self.snap, chunk_size=1024)
        # remaining 0: used == limit
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 4000}, used={"cpu": 4000})],
            self.snap, generation=1,
        ))
        held = problem("a/held", "a", 2, prev={"m0": 1, "m1": 1})
        fresh_new = problem("a/new", "a", 2)
        res = eng.schedule([held, fresh_new])
        assert res[0].error == ""  # delta 0: admitted
        assert res[1].error == QUOTA_EXCEEDED_ERROR  # delta 2 cpu: denied

    def test_denied_partition_replays_until_generation_bump(self):
        eng = TensorScheduler(self.snap, chunk_size=1024)
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 3000})], self.snap, generation=1
        ))
        ps = [problem(f"a/b{i}", "a", 2) for i in range(3)]
        res1 = eng.schedule(ps)
        assert [bool(r.success) for r in res1] == [True, False, False]
        # same wave, same generation: the quota cache replays the
        # partition (and the admitted sub-list identity is stable)
        res2 = eng.schedule(ps)
        assert [r.error for r in res2] == [r.error for r in res1]
        # generation bump with a raised quota re-admits
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 60000})], self.snap, generation=2
        ))
        res3 = eng.schedule(ps)
        assert all(r.success for r in res3)

    def test_static_caps_bound_placement_host_and_fleet(self):
        """The static-assignment cap tensor bounds per-cluster replicas
        identically on the host-small path and the device-resident fleet
        path (cap rows fold into interned profile slots)."""
        q = build_quota_snapshot(
            [frq("c", {"cpu": 10_000_000},
                 static=[StaticClusterAssignment(
                     cluster_name="m0", hard={"cpu": 3000})])],
            self.snap, generation=1,
        )
        fleet_eng = TensorScheduler(self.snap, chunk_size=1024)
        fleet_eng.set_quota(q)
        many = [problem(f"c/f{i}", "c", 8) for i in range(300)]
        rf = fleet_eng.schedule(many)
        assert fleet_eng._fleet is not None  # fleet path engaged
        assert all(r.success for r in rf)
        assert all(r.clusters.get("m0", 0) <= 3 for r in rf)
        host_eng = TensorScheduler(self.snap, chunk_size=1024)
        host_eng.set_quota(q)
        for i in (0, 7, 150, 299):
            r1 = host_eng.schedule([problem(f"c/f{i}", "c", 8)])[0]
            assert r1.clusters == rf[i].clusters

    def test_cap_change_drops_fleet_but_generation_bump_does_not(self):
        eng = TensorScheduler(self.snap, chunk_size=1024)
        eng.set_quota(build_quota_snapshot(
            [frq("c", {"cpu": 10_000_000})], self.snap, generation=1
        ))
        many = [problem(f"c/f{i}", "c", 4) for i in range(300)]
        eng.schedule(many)
        fleet = eng._fleet
        assert fleet is not None
        # generation-only bump (remaining moved): the table survives
        eng.set_quota(build_quota_snapshot(
            [frq("c", {"cpu": 9_000_000})], self.snap, generation=2
        ))
        assert eng._fleet is fleet
        # disarming a CAP-FREE quota bakes nothing into the profile
        # slots: the table survives the toggle both ways
        eng.set_quota(None)
        assert eng._fleet is fleet
        eng.set_quota(build_quota_snapshot(
            [frq("c", {"cpu": 9_000_000})], self.snap, generation=2
        ))
        assert eng._fleet is fleet
        # cap content change: profile slots embed cap rows — rebuild
        eng.set_quota(build_quota_snapshot(
            [frq("c", {"cpu": 10_000_000},
                 static=[StaticClusterAssignment(
                     cluster_name="m1", hard={"cpu": 1000})])],
            self.snap, generation=3,
        ))
        assert eng._fleet is None


def quota_plane(n_clusters=4, overall=None):
    cp = ControlPlane()
    for i in range(n_clusters):
        cp.join_cluster(
            new_cluster(f"m{i}", cpu="1000", memory="2000Gi", pods=10000)
        )
    cp.settle()
    cp.store.apply(PropagationPolicy(
        meta=ObjectMeta(name="pol", namespace="teamA"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=dynamic_weight_placement(),
        ),
    ))
    if overall is not None:
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="teamA"),
            spec=FederatedResourceQuotaSpec(overall=dict(overall)),
        ))
    return cp


def scheduled_condition(cp, key):
    rb = cp.store.get("ResourceBinding", key)
    return next(c for c in rb.status.conditions if c.type == SCHEDULED)


class TestQuotaPlane:
    def test_denial_condition_usage_accounting_and_raise(self):
        cp = quota_plane(overall={"cpu": 5000})
        for i in range(4):
            cp.store.apply(
                new_deployment(f"w{i}", namespace="teamA", replicas=2, cpu="1")
            )
        cp.settle()
        conds = [
            scheduled_condition(cp, f"teamA/w{i}-deployment") for i in range(4)
        ]
        assert [c.status for c in conds] == [True, True, False, False]
        assert conds[2].reason == "QuotaExceeded"
        # live accounting from bound ResourceBindings only
        q = cp.store.get("FederatedResourceQuota", "teamA/q")
        assert q.status.overall_used == {"cpu": 4000}
        from karmada_tpu.utils.metrics import quota_denied, quota_used

        assert quota_denied.value(namespace="teamA") >= 2
        assert quota_used.value(namespace="teamA", resource="cpu") == 4000
        # raising the quota clears the denials WITHOUT re-packing the
        # admitted fleet: only the denied bindings re-solve
        solves0 = cp.scheduler._engine.solve_batches
        q.spec.overall = {"cpu": 20000}
        cp.store.apply(q)
        cp.settle()
        for i in range(4):
            assert scheduled_condition(
                cp, f"teamA/w{i}-deployment"
            ).status, i
        assert cp.scheduler._engine.solve_batches - solves0 <= 2
        assert cp.scheduler._quota_denied == {}
        q = cp.store.get("FederatedResourceQuota", "teamA/q")
        assert q.status.overall_used == {"cpu": 8000}

    def test_denied_binding_skips_requeue_until_generation(self):
        """A denied binding parks: re-enqueuing it within the same quota
        generation never reaches the engine (no per-pass retry storm)."""
        cp = quota_plane(overall={"cpu": 1000})
        cp.store.apply(
            new_deployment("big", namespace="teamA", replicas=8, cpu="1")
        )
        cp.settle()
        assert (
            scheduled_condition(cp, "teamA/big-deployment").reason
            == "QuotaExceeded"
        )
        solves0 = cp.scheduler._engine.solve_batches
        cp.scheduler.worker.enqueue(
            ("ResourceBinding", "teamA/big-deployment")
        )
        cp.settle()
        assert cp.scheduler._engine.solve_batches == solves0

    def test_enforcement_kill_switch(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_QUOTA_ENFORCEMENT", "0")
        cp = quota_plane(overall={"cpu": 1000})
        cp.store.apply(
            new_deployment("big", namespace="teamA", replicas=8, cpu="1")
        )
        cp.settle()
        assert scheduled_condition(cp, "teamA/big-deployment").status

    def test_usage_counts_pods_implicitly(self):
        cp = quota_plane(overall={"pods": 100})
        cp.store.apply(
            new_deployment("w", namespace="teamA", replicas=3, cpu="1")
        )
        cp.settle()
        q = cp.store.get("FederatedResourceQuota", "teamA/q")
        assert q.status.overall_used == {"pods": 3}


class TestQuotaShrinkValidation:
    def test_shrink_below_usage_rejected(self):
        q = frq("a", {"cpu": 1000}, used={"cpu": 4000})
        q.status.overall = {"cpu": 8000}  # last-reconciled spec differs
        with pytest.raises(ValidationError, match="cannot shrink"):
            validate_federated_resource_quota(q)

    def test_shrink_above_usage_allowed(self):
        q = frq("a", {"cpu": 5000}, used={"cpu": 4000})
        q.status.overall = {"cpu": 8000}
        validate_federated_resource_quota(q)

    def test_status_controller_write_with_over_usage_allowed(self):
        """The status controller records over-usage (bindings bound before
        the FRQ existed) with status.overall synced to spec.overall — that
        write must pass: only a CHANGED overall is a shrink."""
        q = frq("a", {"cpu": 1000}, used={"cpu": 4000})  # status.overall
        # synced by the controller in the same reconcile
        validate_federated_resource_quota(q)

    def test_fresh_create_without_status_allowed(self):
        validate_federated_resource_quota(frq("a", {"cpu": 1000}))


class TestHpaSurgePath:
    """ISSUE 8 satellite: a simultaneous multi-binding rescale through the
    scale-up dispense cohort — engine.solve_batches stays O(chunks) and
    scale-ups credit surviving placements."""

    def _surge_plane(self, n_workloads):
        cp = ControlPlane()
        for i in range(4):
            cp.join_cluster(
                new_cluster(f"m{i}", cpu="4000", memory="8000Gi", pods=100000)
            )
        cp.settle()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="pol", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        ))
        for i in range(n_workloads):
            cp.store.apply(
                new_deployment(f"s{i}", replicas=2, cpu="100m")
            )
        cp.settle()
        return cp

    def test_cron_surge_is_batched_and_credits_survivors(self):
        import calendar

        base = calendar.timegm((2026, 1, 1, 8, 59, 30, 0, 0, 0))
        clock = [float(base)]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in range(4):
            cp.join_cluster(
                new_cluster(f"m{i}", cpu="4000", memory="8000Gi", pods=100000)
            )
        cp.settle()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="pol", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        ))
        n = 40
        for i in range(n):
            cp.store.apply(new_deployment(f"s{i}", replicas=2, cpu="100m"))
        from karmada_tpu.api.autoscaling import (
            CronFederatedHPA,
            CronFederatedHPARule,
            CronFederatedHPASpec,
            ScaleTargetRef,
        )

        for i in range(n):
            cp.store.apply(CronFederatedHPA(
                meta=ObjectMeta(name=f"cron{i}", namespace="default"),
                spec=CronFederatedHPASpec(
                    scale_target_ref=ScaleTargetRef(
                        kind="Deployment", name=f"s{i}"
                    ),
                    rules=[CronFederatedHPARule(
                        name="surge", schedule="0 9 * * *",
                        target_replicas=10,
                    )],
                ),
            ))
        cp.settle()
        before = {}
        for i in range(n):
            rb = cp.store.get("ResourceBinding", f"default/s{i}-deployment")
            assert sum(tc.replicas for tc in rb.spec.clusters) == 2
            before[i] = {tc.name: tc.replicas for tc in rb.spec.clusters}
        solves0 = cp.scheduler._engine.solve_batches
        clock[0] += 40  # crosses 09:00: every cron rule fires this tick
        cp.settle()
        surge_solves = cp.scheduler._engine.solve_batches - solves0
        # one simultaneous 40-binding rescale = O(chunks) batched solves,
        # never one per binding
        assert surge_solves <= 4, surge_solves
        for i in range(n):
            rb = cp.store.get("ResourceBinding", f"default/s{i}-deployment")
            after = {tc.name: tc.replicas for tc in rb.spec.clusters}
            assert sum(after.values()) == 10
            # scale-up cohort: surviving placements are credited (init =
            # previous), so no previously-placed cluster loses replicas
            for name, prev_reps in before[i].items():
                assert after.get(name, 0) >= prev_reps, (i, before[i], after)

    def test_replica_calculator_drives_scale_up_through_binding(self):
        """The per-pod replica calculator path (FederatedHPA over
        workload_pods) feeds the same scale-up dispense: the binding's
        replicas follow the calculator's proposal and survivors keep
        their placements."""
        from karmada_tpu.api.autoscaling import (
            FederatedHPA,
            FederatedHPASpec,
            MetricSpec,
            ScaleTargetRef,
        )

        clock = [0.0]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in (1, 2):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        ))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        before = {tc.name: tc.replicas for tc in rb.spec.clusters}
        # every pod at 90% of a 500m request against a 45% target -> 2x
        for tc in rb.spec.clusters:
            cp.members.get(tc.name).workload_pods["default/web"] = [
                {"name": f"{tc.name}-p{j}", "request": 500, "value": 450}
                for j in range(tc.replicas)
            ]
        cp.store.apply(FederatedHPA(
            meta=ObjectMeta(name="web-hpa", namespace="default"),
            spec=FederatedHPASpec(
                scale_target_ref=ScaleTargetRef(kind="Deployment", name="web"),
                min_replicas=1, max_replicas=16,
                metrics=[MetricSpec(
                    resource_name="cpu", target_average_utilization=45
                )],
                stabilization_window_seconds=0,
            ),
        ))
        clock[0] += 30
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        after = {tc.name: tc.replicas for tc in rb.spec.clusters}
        assert sum(after.values()) == 8, after
        for name, prev_reps in before.items():
            assert after.get(name, 0) >= prev_reps

    def test_surge_respects_quota(self):
        """A surge into a tight quota admits up to the remaining headroom
        and denies the rest with QuotaExceeded — the bench scenario at
        test scale."""
        import calendar

        base = calendar.timegm((2026, 1, 1, 8, 59, 30, 0, 0, 0))
        clock = [float(base)]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in range(4):
            cp.join_cluster(
                new_cluster(f"m{i}", cpu="4000", memory="8000Gi", pods=100000)
            )
        cp.settle()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="pol", namespace="teamA"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        ))
        # 8 workloads x 2 replicas x 1 cpu = 16 cpu bound; quota 24 cpu:
        # a surge to 4 replicas each (delta 2 cpu per workload) admits 4
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="teamA"),
            spec=FederatedResourceQuotaSpec(overall={"cpu": 24000}),
        ))
        from karmada_tpu.api.autoscaling import (
            CronFederatedHPA,
            CronFederatedHPARule,
            CronFederatedHPASpec,
            ScaleTargetRef,
        )

        for i in range(8):
            cp.store.apply(
                new_deployment(f"s{i}", namespace="teamA", replicas=2, cpu="1")
            )
            cp.store.apply(CronFederatedHPA(
                meta=ObjectMeta(name=f"cron{i}", namespace="teamA"),
                spec=CronFederatedHPASpec(
                    scale_target_ref=ScaleTargetRef(
                        kind="Deployment", name=f"s{i}"
                    ),
                    rules=[CronFederatedHPARule(
                        name="surge", schedule="0 9 * * *",
                        target_replicas=4,
                    )],
                ),
            ))
        cp.settle()
        q = cp.store.get("FederatedResourceQuota", "teamA/q")
        assert q.status.overall_used == {"cpu": 16000}
        clock[0] += 40
        cp.settle()
        scaled = denied = 0
        for i in range(8):
            rb = cp.store.get("ResourceBinding", f"teamA/s{i}-deployment")
            total = sum(tc.replicas for tc in rb.spec.clusters)
            cond = next(
                c for c in rb.status.conditions if c.type == SCHEDULED
            )
            if total == 4:
                scaled += 1
                assert cond.status
            else:
                assert total == 2  # denied surge keeps the held replicas
                assert cond.reason == "QuotaExceeded"
                denied += 1
        assert scaled == 4 and denied == 4, (scaled, denied)
        q = cp.store.get("FederatedResourceQuota", "teamA/q")
        assert q.status.overall_used == {"cpu": 24000}


class TestQuotaStatusVerb:
    def test_in_proc_and_http_status(self):
        from karmada_tpu.cli import cmd_quota_status
        from karmada_tpu.utils.metrics import (
            MetricsServer,
            quota_denied,
            quota_limit,
            quota_used,
        )

        quota_limit.set(5000, namespace="verbNS", resource="cpu")
        quota_used.set(4000, namespace="verbNS", resource="cpu")
        quota_denied.inc(3, namespace="verbNS")
        doc = cmd_quota_status()
        entry = doc["namespaces"]["verbNS"]
        assert entry["resources"]["cpu"] == {"limit": 5000, "used": 4000}
        assert entry["denied_total"] == 3
        srv = MetricsServer()
        port = srv.start()
        try:
            remote = cmd_quota_status(f"127.0.0.1:{port}")
        finally:
            srv.stop()
        assert remote["namespaces"]["verbNS"] == entry


class TestQuotaPrewarm:
    def test_admission_traces_record_and_replay(self, tmp_path):
        """The engine-side quota kernels ledger like the fleet solve
        family: a fresh admission dispatch records its compile inputs to
        the trace manifest, and prewarm replay compiles the record in a
        jax-free-boot fashion."""
        from karmada_tpu.scheduler.prewarm import TraceManifest, replay

        snap = ClusterSnapshot(
            [new_cluster(f"m{i}", cpu="1000", memory="2000Gi") for i in range(4)]
        )
        manifest = TraceManifest(str(tmp_path / "m.json"))
        eng = TensorScheduler(
            snap, chunk_size=1024, trace_manifest=manifest
        )
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 10_000_000},
                 static=[StaticClusterAssignment(
                     cluster_name="m0", hard={"cpu": 1000})])],
            snap, generation=1,
        ))
        # fleet-sized batch: the caps kernel dispatches on the device
        # profile-table fold (tiny batches take the numpy caps mirror)
        ps = [problem(f"a/b{i}", "a", 1) for i in range(300)]
        eng.schedule(ps)
        assert eng.last_pass_new_trace  # fresh admission trace this pass
        kernels = {r["kernel"] for r in manifest.records}
        assert "quota_admit" in kernels, kernels
        assert "quota_cluster_caps" in kernels, kernels
        stats = replay(manifest, expand=False)
        assert stats["failed"] == 0 and stats["compiled"] >= 2, stats


class TestReviewRegressions:
    """Regression coverage for the review findings on the quota plane."""

    def test_cross_pass_debit_within_generation(self):
        """Consecutive engine passes within ONE quota generation share a
        debited remaining: pass 2 cannot re-admit the budget pass 1
        spent (multi-batch drains before the usage recompute)."""
        snap = ClusterSnapshot(
            [new_cluster(f"m{i}", cpu="1000", memory="2000Gi") for i in range(4)]
        )
        eng = TensorScheduler(snap, chunk_size=1024)
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 4000})], snap, generation=1
        ))
        r1 = eng.schedule([problem("a/x", "a", 2)])  # 2 cpu: admitted
        assert r1[0].success
        # 3 cpu > the 2 cpu left after the debit: denied, even though the
        # snapshot generation never moved
        r2 = eng.schedule([problem("a/y", "a", 3)])
        assert r2[0].error == QUOTA_EXCEEDED_ERROR
        # a fresh generation rebuilds remaining from recomputed usage
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 4000}, used={"cpu": 2000})],
            snap, generation=2,
        ))
        r3 = eng.schedule([problem("a/y", "a", 2)])
        assert r3[0].success

    def test_denied_binding_retries_on_own_spec_change(self):
        """A parked denial must unpark when the BINDING's spec changes
        (scale-down to fit): its own usage is unchanged, so no quota
        event would ever retry it otherwise."""
        cp = quota_plane(overall={"cpu": 3000})
        cp.store.apply(
            new_deployment("big", namespace="teamA", replicas=8, cpu="1")
        )
        cp.settle()
        assert (
            scheduled_condition(cp, "teamA/big-deployment").reason
            == "QuotaExceeded"
        )
        cp.store.apply(
            new_deployment("big", namespace="teamA", replicas=2, cpu="1")
        )
        cp.settle()
        cond = scheduled_condition(cp, "teamA/big-deployment")
        assert cond.status, cond
        rb = cp.store.get("ResourceBinding", "teamA/big-deployment")
        assert sum(tc.replicas for tc in rb.spec.clusters) == 2

    def test_frq_delete_retires_gauges(self):
        from karmada_tpu.utils.metrics import quota_limit, quota_used

        cp = quota_plane(overall={"cpu": 5000})
        cp.store.apply(
            new_deployment("w", namespace="teamA", replicas=2, cpu="1")
        )
        cp.settle()
        assert quota_limit.value(namespace="teamA", resource="cpu") == 5000
        cp.store.delete("FederatedResourceQuota", "teamA/q")
        cp.settle()
        assert quota_limit.value(namespace="teamA", resource="cpu") == 0
        assert quota_used.value(namespace="teamA", resource="cpu") == 0

    def test_quota_waves_route_around_engines_without_quota(self):
        """An engine with no quota channel (the solver sidecar shape)
        must not serve a quota'd wave: routing falls back to the in-proc
        engine instead of silently skipping enforcement."""
        cp = quota_plane(overall={"cpu": 1000})

        class QuotalessEngine:  # the sidecar client surface: no set_quota
            pass

        wave = [problem("teamA/x", "teamA", 2)]
        routed = cp.scheduler._route_engine_for_quota(QuotalessEngine(), wave)
        assert hasattr(routed, "set_quota")  # the in-proc TensorScheduler
        # and with enforcement disabled the sidecar engine passes through
        import os

        os.environ["KARMADA_TPU_QUOTA_ENFORCEMENT"] = "0"
        try:
            dummy = QuotalessEngine()
            assert cp.scheduler._route_engine_for_quota(dummy, wave) is dummy
        finally:
            os.environ.pop("KARMADA_TPU_QUOTA_ENFORCEMENT", None)

    def test_failed_solve_charges_nothing(self):
        """A pass that dies mid-solve must not leave its demand debited
        (the worker bisects and retries with rebuilt problem objects):
        the retry re-admits against the uncharged remaining."""
        snap = ClusterSnapshot(
            [new_cluster(f"m{i}", cpu="1000", memory="2000Gi") for i in range(4)]
        )
        eng = TensorScheduler(snap, chunk_size=1024)
        eng.set_quota(build_quota_snapshot(
            [frq("a", {"cpu": 2000})], snap, generation=1
        ))
        boom = RuntimeError("mid-solve death")
        inner = eng._schedule_inner

        def dying(problems):
            raise boom

        eng._schedule_inner = dying
        with pytest.raises(RuntimeError):
            eng.schedule([problem("a/x", "a", 2)])
        eng._schedule_inner = inner
        # retry with REBUILT objects (the bisect shape): still admits
        r = eng.schedule([problem("a/x", "a", 2)])
        assert r[0].success, r[0].error
        # and the committed wave IS charged: the next distinct wave in
        # the same generation sees the debited remaining
        r2 = eng.schedule([problem("a/y", "a", 1)])
        assert r2[0].error == QUOTA_EXCEEDED_ERROR

    def test_partial_frq_delete_and_resource_drop_retire_gauges(self):
        from karmada_tpu.utils.metrics import quota_limit

        cp = quota_plane(overall={"cpu": 5000, "memory": 1 << 30})
        cp.store.apply(
            new_deployment("w", namespace="teamA", replicas=2, cpu="1")
        )
        cp.settle()
        assert quota_limit.value(namespace="teamA", resource="memory") > 0
        # spec edit dropping a resource retires its samples
        q = cp.store.get("FederatedResourceQuota", "teamA/q")
        q.spec.overall = {"cpu": 5000}
        cp.store.apply(q)
        cp.settle()
        assert quota_limit.value(namespace="teamA", resource="memory") == 0
        assert quota_limit.value(namespace="teamA", resource="cpu") == 5000
        # partial delete: a second FRQ dies, the survivor's sweep drops
        # the dead quota's samples
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="q2", namespace="teamA"),
            spec=FederatedResourceQuotaSpec(overall={"pods": 50}),
        ))
        cp.settle()
        assert quota_limit.value(namespace="teamA", resource="pods") == 50
        cp.store.delete("FederatedResourceQuota", "teamA/q2")
        cp.settle()
        assert quota_limit.value(namespace="teamA", resource="pods") == 0
        assert quota_limit.value(namespace="teamA", resource="cpu") == 5000

    def test_solver_routing_scoped_to_quotad_waves(self):
        """One namespace's FRQ must not cost every other namespace the
        sidecar: only waves containing quota'd-namespace bindings
        reroute."""
        cp = quota_plane(overall={"cpu": 5000})

        class QuotalessEngine:
            pass

        dummy = QuotalessEngine()
        quota_wave = [problem("teamA/x", "teamA", 2)]
        other_wave = [problem("teamB/x", "teamB", 2)]
        assert cp.scheduler._route_engine_for_quota(dummy, other_wave) is dummy
        routed = cp.scheduler._route_engine_for_quota(dummy, quota_wave)
        assert hasattr(routed, "set_quota")

    def test_fresh_frq_over_existing_usage_counts_live(self):
        """An FRQ created over a namespace with EXISTING bound usage must
        enforce from live bindings in the same settle — its status hasn't
        been reconciled yet, and trusting the empty overall_used would
        admit a full extra budget nothing ever revokes."""
        cp = quota_plane()  # no FRQ yet
        cp.store.apply(
            new_deployment("old", namespace="teamA", replicas=4, cpu="1")
        )
        cp.settle()  # 4 cpu bound, unquota'd
        # quota equal to existing usage + a new same-size deployment in
        # ONE settle: the new one must be denied
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="teamA"),
            spec=FederatedResourceQuotaSpec(overall={"cpu": 4000}),
        ))
        cp.store.apply(
            new_deployment("new", namespace="teamA", replicas=4, cpu="1")
        )
        cp.settle()
        assert scheduled_condition(cp, "teamA/old-deployment").status
        cond = scheduled_condition(cp, "teamA/new-deployment")
        assert cond.reason == "QuotaExceeded", cond

    def test_solver_fallback_refreshes_engine_quota(self):
        """The solver transport-failure fallback must not enforce a
        STALE QuotaSnapshot retained on the in-proc engine from an
        earlier quota wave."""
        cp = quota_plane(overall={"cpu": 1000})
        cp.store.apply(
            new_deployment("big", namespace="teamA", replicas=8, cpu="1")
        )
        cp.settle()
        assert (
            scheduled_condition(cp, "teamA/big-deployment").reason
            == "QuotaExceeded"
        )
        # the in-proc engine retains the tight snapshot; disable
        # enforcement and drive the solver-fallback path directly
        import os

        engine = cp.scheduler._inproc_engine()
        assert engine.quota is not None

        class DeadSolver:
            def schedule(self, problems):
                raise ConnectionError("sidecar down")

            def sync_clusters(self, clusters):
                pass

        cp.scheduler.solver = DeadSolver()
        cp.scheduler._solver_synced = True
        os.environ["KARMADA_TPU_QUOTA_ENFORCEMENT"] = "0"
        try:
            # a spec change re-gates the binding; enforcement is off, so
            # the wave takes the DeadSolver -> in-proc fallback, which
            # must clear the engine's retained tight snapshot
            cp.store.apply(
                new_deployment("big", namespace="teamA", replicas=6, cpu="1")
            )
            cp.settle()
        finally:
            os.environ.pop("KARMADA_TPU_QUOTA_ENFORCEMENT", None)
            cp.scheduler.solver = None
        assert scheduled_condition(cp, "teamA/big-deployment").status

    def test_spread_selection_sees_capped_availability(self):
        """Group selection must rank spread groups on the same cap-folded
        availability the divide uses: a capped primary group that cannot
        fit loses to an uncapped group that can."""
        from karmada_tpu.api.policy import (
            ClusterAffinity,
            ClusterPreferences,
            Placement,
            ReplicaSchedulingStrategy,
            SpreadConstraint,
            StaticClusterWeight,
        )

        snap = ClusterSnapshot(
            [new_cluster(f"m{i}", cpu="1000", memory="2000Gi") for i in range(4)]
        )
        eng = TensorScheduler(snap, chunk_size=1024)
        # m0+m1 capped to 1 cpu each for namespace "c": 8 replicas of
        # 1 cpu cannot fit a 2-cluster group drawn from them
        eng.set_quota(build_quota_snapshot(
            [frq("c", {"cpu": 10_000_000},
                 static=[
                     StaticClusterAssignment(cluster_name="m0",
                                             hard={"cpu": 1000}),
                     StaticClusterAssignment(cluster_name="m1",
                                             hard={"cpu": 1000}),
                 ])],
            snap, generation=1,
        ))
        pl = Placement(
            spread_constraints=[SpreadConstraint(
                spread_by_field="cluster", min_groups=2, max_groups=2,
            )],
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type="Divided",
                replica_division_preference="Weighted",
                weight_preference=ClusterPreferences(dynamic_weight="AvailableReplicas"),
            ),
        )
        p = BindingProblem(
            key="c/spread", placement=pl, replicas=8, requests=CPU_REQ,
            gvk="apps/v1/Deployment", namespace="c",
        )
        res = eng.schedule([p])[0]
        assert res.success, res.error
        placed = res.clusters
        assert sum(placed.values()) == 8
        # the capped clusters cannot carry more than 1 each; the
        # selection must have favored uncapped capacity
        assert placed.get("m0", 0) <= 1 and placed.get("m1", 0) <= 1, placed
