"""Store/watch/worker runtime tests (ref analogue: informer + AsyncWorker)."""

from karmada_tpu.api import Cluster, ObjectMeta
from karmada_tpu.utils import ADDED, DELETED, MODIFIED, DONE, Runtime, Store


def make_cluster(name: str) -> Cluster:
    return Cluster(meta=ObjectMeta(name=name))


class TestStore:
    def test_apply_get_list(self):
        s = Store()
        s.apply(make_cluster("m1"))
        s.apply(make_cluster("m2"))
        assert s.get("Cluster", "m1").name == "m1"
        assert {c.name for c in s.list("Cluster")} == {"m1", "m2"}

    def test_watch_events_and_replay(self):
        s = Store()
        s.apply(make_cluster("m1"))
        events = []
        s.watch("Cluster", events.append)
        assert [e.type for e in events] == [ADDED]  # replay
        s.apply(make_cluster("m1"))
        s.delete("Cluster", "m2")  # no-op
        s.delete("Cluster", "m1")
        assert [e.type for e in events] == [ADDED, MODIFIED, DELETED]

    def test_finalizer_blocks_delete(self):
        s = Store()
        c = make_cluster("m1")
        c.meta.finalizers.append("karmada.io/cluster-controller")
        s.apply(c)
        s.delete("Cluster", "m1")
        assert s.get("Cluster", "m1") is not None
        assert s.get("Cluster", "m1").meta.deletion_timestamp is not None
        c.meta.finalizers.clear()
        s.finalize(c)
        assert s.get("Cluster", "m1") is None

    def test_resource_version_monotonic(self):
        s = Store()
        a = s.apply(make_cluster("a"))
        b = s.apply(make_cluster("b"))
        assert b.meta.resource_version > a.meta.resource_version


class TestRuntime:
    def test_run_until_settled(self):
        rt = Runtime()
        seen = []

        def reconcile(key):
            seen.append(key)
            if key == "a" and seen.count("a") == 1:
                w.enqueue("b")  # cascading work
            return DONE

        w = rt.new_worker("test", reconcile)
        w.enqueue("a")
        steps = rt.run_until_settled()
        assert steps == 2 and seen == ["a", "b"]

    def test_requeue_retries(self):
        rt = Runtime()
        attempts = []

        def reconcile(key):
            attempts.append(key)
            if len(attempts) < 3:
                raise RuntimeError("transient")
            return DONE

        w = rt.new_worker("flaky", reconcile)
        w.enqueue("x")
        rt.run_until_settled()
        assert len(attempts) == 3
