"""Store/watch/worker runtime tests (ref analogue: informer + AsyncWorker)."""

from karmada_tpu.api import Cluster, ObjectMeta
from karmada_tpu.utils import ADDED, DELETED, MODIFIED, DONE, Runtime, Store


def make_cluster(name: str) -> Cluster:
    return Cluster(meta=ObjectMeta(name=name))


class TestStore:
    def test_apply_get_list(self):
        s = Store()
        s.apply(make_cluster("m1"))
        s.apply(make_cluster("m2"))
        assert s.get("Cluster", "m1").name == "m1"
        assert {c.name for c in s.list("Cluster")} == {"m1", "m2"}

    def test_watch_events_and_replay(self):
        s = Store()
        s.apply(make_cluster("m1"))
        events = []
        s.watch("Cluster", events.append)
        assert [e.type for e in events] == [ADDED]  # replay
        s.apply(make_cluster("m1"))
        s.delete("Cluster", "m2")  # no-op
        s.delete("Cluster", "m1")
        assert [e.type for e in events] == [ADDED, MODIFIED, DELETED]

    def test_finalizer_blocks_delete(self):
        s = Store()
        c = make_cluster("m1")
        c.meta.finalizers.append("karmada.io/cluster-controller")
        s.apply(c)
        s.delete("Cluster", "m1")
        assert s.get("Cluster", "m1") is not None
        assert s.get("Cluster", "m1").meta.deletion_timestamp is not None
        c.meta.finalizers.clear()
        s.finalize(c)
        assert s.get("Cluster", "m1") is None

    def test_resource_version_monotonic(self):
        s = Store()
        a = s.apply(make_cluster("a"))
        b = s.apply(make_cluster("b"))
        assert b.meta.resource_version > a.meta.resource_version


class TestRuntime:
    def test_run_until_settled(self):
        rt = Runtime()
        seen = []

        def reconcile(key):
            seen.append(key)
            if key == "a" and seen.count("a") == 1:
                w.enqueue("b")  # cascading work
            return DONE

        w = rt.new_worker("test", reconcile)
        w.enqueue("a")
        steps = rt.run_until_settled()
        assert steps == 2 and seen == ["a", "b"]

    def test_requeue_retries(self):
        rt = Runtime()
        attempts = []

        def reconcile(key):
            attempts.append(key)
            if len(attempts) < 3:
                raise RuntimeError("transient")
            return DONE

        w = rt.new_worker("flaky", reconcile)
        w.enqueue("x")
        rt.run_until_settled()
        assert len(attempts) == 3

    def test_batch_failure_isolates_poisoned_key(self):
        # One bad key in a batch must not burn retries for (or drop) the
        # healthy keys riding in the same batch.
        from karmada_tpu.utils.worker import DONE, REQUEUE, Runtime

        done = []

        def reconcile(key):
            if key == "poison":
                raise RuntimeError("bad binding")
            done.append(key)
            return DONE

        def reconcile_batch(keys):
            if "poison" in keys:
                raise RuntimeError("engine pass blew up")
            return {k: reconcile(k) for k in keys}

        rt = Runtime()
        w = rt.new_worker("batch", reconcile, reconcile_batch=reconcile_batch)
        for k in ("a", "poison", "b", "c"):
            w.enqueue(k)
        rt.run_until_settled()
        assert sorted(done) == ["a", "b", "c"]
        # healthy keys were reconciled exactly once, not retried to death
        assert len(done) == 3


class TestCheckpointResume:
    """SURVEY §5 checkpoint/resume: the store is the durable source of
    truth; a snapshot + replay into a fresh control plane resumes exactly
    (idempotent reconcilers, Steady assignment preserves placements)."""

    def test_round_trip_preserves_objects(self, tmp_path):
        from karmada_tpu.utils.store import Store
        from karmada_tpu.api.core import ObjectMeta, Resource

        s = Store()
        s.apply(Resource(api_version="v1", kind="ConfigMap",
                         meta=ObjectMeta(name="a", namespace="ns"),
                         spec={"data": {"k": "v"}}))
        path = str(tmp_path / "snap.bin")
        assert s.checkpoint(path) == 1
        s2 = Store()
        seen = []
        s2.watch("Resource", lambda e: seen.append((e.type, e.key)),
                 replay=False)
        assert s2.restore(path) == 1
        assert seen == [("Added", "ns/a")]
        got = s2.get("Resource", "ns/a")
        assert got.spec["data"] == {"k": "v"}

    def test_control_plane_resume_preserves_placements(self, tmp_path):
        from karmada_tpu import cli
        from karmada_tpu.api import (
            PropagationPolicy, PropagationSpec, ResourceSelector)
        from karmada_tpu.api.core import ObjectMeta
        from karmada_tpu.utils.builders import (
            dynamic_weight_placement, new_deployment)

        cp = cli.cmd_local_up(2)
        cp.store.apply(new_deployment("web", replicas=6))
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                     kind="Deployment")],
                placement=dynamic_weight_placement())))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        before = {tc.name: tc.replicas for tc in rb.spec.clusters}
        path = str(tmp_path / "plane.bin")
        cp.store.checkpoint(path)

        # a NEW plane restores the snapshot and settles: Steady assignment
        # must keep the previous placements (no churn on resume)
        cp2 = cli.cmd_local_up(2)
        cp2.store.restore(path)
        cp2.settle()
        rb2 = cp2.store.get("ResourceBinding", "default/web-deployment")
        after = {tc.name: tc.replicas for tc in rb2.spec.clusters}
        assert after == before
        assert cp2.members.get("member1").get(
            "apps/v1/Deployment", "default", "web") is not None
