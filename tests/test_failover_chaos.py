"""Chaos-hardened failover plane (ISSUE 7): deterministic fault injection,
tensorized ordered failover against the per-binding numpy oracle, graceful-
eviction deadline edges, and per-channel degraded modes.

Layers under test:
- utils.faultinject: seeded determinism, the cluster.health injection
  point driving the SAME condition->taint->NoExecute-eviction machinery a
  real outage does, and the fired-event log as a replay script.
- ops.masks.affinity_group_rank / first_fit_group +
  TensorScheduler._schedule_chunk_ranked: ordered ClusterAffinities
  fallback as ONE batched solve, placement-identical to
  refimpl.failover_np's per-binding retry-loop oracle.
- controllers.failover.GracefulEvictionController deadline edges and
  ApplicationFailoverController state preservation across a double
  reschedule.
- degraded modes: a dead solver sidecar fails over to the in-proc engine
  (observable via karmada_tpu_degraded_passes_total).
"""

from __future__ import annotations

import numpy as np
import pytest

from karmada_tpu.api import (
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    ApplicationFailoverBehavior,
    ClusterAffinityTerm,
    FailoverBehavior,
    LabelSelector,
)
from karmada_tpu.api.work import (
    AggregatedStatusItem,
    GracefulEvictionTask,
    ResourceBinding,
    TargetCluster,
)
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.ops import masks as mops
from karmada_tpu.refimpl.failover_np import replay_failover, solve_one_ordered
from karmada_tpu.scheduler import (
    BindingProblem,
    ClusterSnapshot,
    TensorScheduler,
)
from karmada_tpu.scheduler.snapshot import compile_placement
from karmada_tpu.utils import faultinject
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)
from karmada_tpu.utils.features import (
    FAILOVER,
    STATEFUL_FAILOVER_INJECTION,
    feature_gate,
)


@pytest.fixture(autouse=True)
def _disarmed():
    yield
    faultinject.disarm()


def group_term(group: str) -> ClusterAffinityTerm:
    return ClusterAffinityTerm(
        affinity_name=f"grp-{group}",
        label_selector=LabelSelector(match_labels={"group": group}),
    )


def ordered_policy(name="chaos-policy", ns="default"):
    return PropagationPolicy(
        meta=ObjectMeta(name=name, namespace=ns),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=dynamic_weight_placement(
                cluster_affinities=[
                    group_term("primary"), group_term("fallback"),
                ]
            ),
        ),
    )


# --------------------------------------------------------------------------
# deterministic fault injection
# --------------------------------------------------------------------------


class TestFaultInjection:
    def test_disarmed_is_none_and_allocation_free(self):
        assert faultinject.fault_point("estimator.rpc", "x") is None
        assert faultinject.injector() is None

    def test_seeded_decisions_replay_bit_identically(self):
        spec = "estimator.rpc=error,rate=0.4,count=50"
        logs = []
        for _ in range(2):
            inj = faultinject.arm(spec, seed=1234)
            for i in range(200):
                inj.fire("estimator.rpc", f"call{i}")
            logs.append([(e.seq, e.point, e.key) for e in inj.log])
        assert logs[0] == logs[1]
        assert 0 < len(logs[0]) <= 50
        # a different seed produces a different firing pattern
        inj = faultinject.arm(spec, seed=99)
        for i in range(200):
            inj.fire("estimator.rpc", f"call{i}")
        assert [(e.seq, e.point, e.key) for e in inj.log] != logs[0]

    def test_match_count_after_and_actions(self):
        inj = faultinject.arm(
            "solver.rpc=drop,match=Score,count=2;"
            "cluster.health=down,match=member2;"
            "bus.rpc=delay,delay=0.001,after=1"
        )
        assert inj.fire("solver.rpc", "SyncClusters") is None
        assert inj.fire("solver.rpc", "ScoreAndAssign").action == "drop"
        assert inj.fire("solver.rpc", "ScoreAndAssign").action == "drop"
        assert inj.fire("solver.rpc", "ScoreAndAssign") is None  # count=2
        assert inj.fire("cluster.health", "member1") is None
        assert inj.fire("cluster.health", "member2").action == "down"
        assert inj.fire("bus.rpc", "Apply") is None  # after=1
        assert inj.fire("bus.rpc", "Apply").action == "delay"

    def test_injected_error_is_grpc_shaped(self):
        import grpc

        err = faultinject.injected_error("solver.rpc", "Score")
        assert isinstance(err, faultinject.FaultError)
        assert isinstance(err, grpc.RpcError)
        assert err.code() == grpc.StatusCode.UNAVAILABLE

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError):
            faultinject.parse_spec("estimator.rpc=explode")
        with pytest.raises(ValueError):
            faultinject.parse_spec("estimator.rpc=error,bogus=1")


# --------------------------------------------------------------------------
# tensorized ordered failover vs the per-binding oracle
# --------------------------------------------------------------------------


def make_grouped_snapshot(n_primary=3, n_fallback=3, primary_cpu="4",
                          fallback_cpu="4000"):
    clusters = [
        new_cluster(f"p{i}", cpu=primary_cpu, memory="400Gi",
                    labels={"group": "primary"})
        for i in range(n_primary)
    ] + [
        new_cluster(f"f{i}", cpu=fallback_cpu, memory="4000Gi",
                    labels={"group": "fallback"})
        for i in range(n_fallback)
    ]
    clusters.sort(key=lambda c: c.name)
    return ClusterSnapshot(clusters)


class TestRankedOrderedFailover:
    def test_affinity_group_rank(self):
        terms = np.array(
            [[True, False, True], [False, True, True]], bool
        )  # T=2, C=3
        rank = mops.affinity_group_rank(terms)
        assert rank.tolist() == [0, 1, 0]
        assert mops.affinity_group_rank(np.zeros((2, 3), bool)).tolist() == [
            2, 2, 2,
        ]

    def test_batch_matches_per_binding_oracle(self):
        """Randomized multi-term batch through the engine's ranked path ==
        the refimpl per-binding ordered retry loop (which re-derives fit
        by RUNNING the divider per group, sharing no selection code)."""
        rng = np.random.default_rng(7)
        snap = make_grouped_snapshot(4, 4, primary_cpu="8", fallback_cpu="64")
        pl = dynamic_weight_placement(
            cluster_affinities=[group_term("primary"), group_term("fallback")]
        )
        problems = []
        for i in range(240):
            reps = int(rng.integers(1, 30))
            prev = {}
            if i % 3 == 0:  # some rows carry previous placements
                prev = {f"p{int(rng.integers(0, 4))}": max(1, reps // 2)}
            problems.append(
                BindingProblem(
                    key=f"b{i}",
                    placement=pl,
                    replicas=reps,
                    requests={"cpu": 1000},
                    gvk="apps/v1/Deployment",
                    prev=prev,
                    fresh=bool(i % 5 == 0),
                )
            )
        eng = TensorScheduler(snap)
        res = eng.schedule(problems)
        solves_before = eng.solve_batches
        assert solves_before >= 1

        cp = compile_placement(pl, snap)
        term_masks = np.stack([m for _, m in cp.terms])
        c = snap.num_clusters
        for p, r in zip(problems, res):
            reqs = np.zeros((1, len(snap.dims)), np.int64)
            reqs[0, snap.dim_index("cpu")] = 1000
            reqs[0, snap.dim_index("pods")] = 1
            avail = eng._availability_np(
                reqs, np.asarray([p.replicas], np.int32)
            )[0]
            prev_row = np.zeros(c, np.int32)
            for n, v in p.prev.items():
                prev_row[snap.index[n]] = v
            base = cp.taint_ok & cp.spread_field_ok
            a, ti, err = solve_one_ordered(
                term_masks, base, cp.strategy, p.replicas,
                cp.static_weights, avail, prev_row, p.fresh,
            )
            want = (
                {}
                if a is None
                else {
                    snap.names[j]: int(a[j]) for j in np.flatnonzero(a > 0)
                }
            )
            assert r.clusters == want, (p.key, r.clusters, want, r.error, err)
            if a is not None:
                assert r.affinity_name == cp.terms[ti][0]

    def test_fallback_engaged_only_when_primary_cannot_fit(self):
        snap = make_grouped_snapshot(2, 2, primary_cpu="4", fallback_cpu="400")
        pl = dynamic_weight_placement(
            cluster_affinities=[group_term("primary"), group_term("fallback")]
        )
        eng = TensorScheduler(snap)
        small, big = (
            BindingProblem(key="small", placement=pl, replicas=2,
                           requests={"cpu": 1000}, gvk="apps/v1/Deployment"),
            BindingProblem(key="big", placement=pl, replicas=100,
                           requests={"cpu": 1000}, gvk="apps/v1/Deployment"),
        )
        res = {r.key: r for r in eng.schedule([small, big])}
        assert set(res["small"].clusters) <= {"p0", "p1"}
        assert res["small"].affinity_name == "grp-primary"
        assert set(res["big"].clusters) <= {"f0", "f1"}
        assert res["big"].affinity_name == "grp-fallback"

    def test_displaced_wave_is_one_batched_solve(self):
        """A failover wave (evicted rows, multi-term placements) must ride
        ONE batched solve per chunk — not a solve per binding."""
        snap = make_grouped_snapshot(3, 3, primary_cpu="64",
                                     fallback_cpu="64")
        pl = dynamic_weight_placement(
            cluster_affinities=[group_term("primary"), group_term("fallback")]
        )
        problems = [
            BindingProblem(
                key=f"d{i}", placement=pl, replicas=4,
                requests={"cpu": 1000}, gvk="apps/v1/Deployment",
                prev={"p1": 2}, evict_clusters=("p0",),
            )
            for i in range(500)
        ]
        eng = TensorScheduler(snap)
        res = eng.schedule(problems)
        assert eng.solve_batches == 1  # 500 displaced rows, one chunk solve
        for r in res:
            assert r.success
            assert "p0" not in r.clusters  # evicted cluster masked out

    def test_multi_term_with_spread_keeps_round_loop(self):
        """Multi-term + spread constraints is the partition the ranked
        path must NOT claim: selection there is a per-term group search."""
        from karmada_tpu.api.policy import SpreadConstraint

        clusters = [
            new_cluster(f"s{i}", cpu="64", memory="400Gi",
                        labels={"group": "primary"}, region=f"r{i % 2}")
            for i in range(4)
        ]
        snap = ClusterSnapshot(sorted(clusters, key=lambda c: c.name))
        pl = dynamic_weight_placement(
            cluster_affinities=[group_term("primary"), group_term("fallback")],
            spread_constraints=[
                SpreadConstraint(
                    spread_by_field="region", min_groups=2, max_groups=2
                )
            ],
        )
        problems = [
            BindingProblem(key=f"sp{i}", placement=pl, replicas=4,
                           requests={"cpu": 1000}, gvk="apps/v1/Deployment")
            for i in range(8)
        ]
        res = TensorScheduler(snap).schedule(problems)
        for r in res:
            assert r.success, r.error
            assert len({snap.clusters[snap.index[n]].spec.region
                        for n in r.clusters}) == 2


# --------------------------------------------------------------------------
# graceful-eviction deadline edges (ISSUE 7 satellite)
# --------------------------------------------------------------------------


class TestGracefulEvictionEdges:
    def _plane(self, clock, timeout=50.0):
        cp = ControlPlane(clock=lambda: clock[0], eviction_timeout=timeout)
        return cp

    def test_task_past_grace_purged_even_with_pending_replacement(self):
        """A task whose grace window expired is dropped even though the
        replacement cluster never reported Healthy (evictiontask.go
        timeout arm beats the health arm)."""
        feature_gate.set(FAILOVER, True)
        clock = [1000.0]
        try:
            cp = self._plane(clock, timeout=50.0)
            rb = ResourceBinding(meta=ObjectMeta(name="app", namespace="default"))
            rb.spec.replicas = 2
            rb.spec.clusters = [TargetCluster(name="m2", replicas=2)]
            rb.spec.graceful_eviction_tasks = [
                GracefulEvictionTask(
                    from_cluster="m1", replicas=2, reason="test",
                    creation_timestamp=clock[0],
                )
            ]
            # replacement m2 is still Pending: applied=False, no health
            rb.status.aggregated_status = [
                AggregatedStatusItem(cluster_name="m2", applied=False,
                                     health="Unknown")
            ]
            cp.store.apply(rb)
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/app")
            assert rb.spec.graceful_eviction_tasks  # within grace: kept
            clock[0] += 51.0  # default timeout exceeded
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/app")
            assert not rb.spec.graceful_eviction_tasks
        finally:
            feature_gate.set(FAILOVER, False)

    def test_per_task_grace_overrides_controller_timeout(self):
        feature_gate.set(FAILOVER, True)
        clock = [500.0]
        try:
            cp = self._plane(clock, timeout=600.0)
            rb = ResourceBinding(meta=ObjectMeta(name="fast", namespace="default"))
            rb.spec.replicas = 1
            rb.spec.clusters = [TargetCluster(name="m2", replicas=1)]
            rb.spec.graceful_eviction_tasks = [
                GracefulEvictionTask(
                    from_cluster="m1", replicas=1, reason="test",
                    grace_period_seconds=5,
                    creation_timestamp=clock[0],
                )
            ]
            cp.store.apply(rb)
            clock[0] += 6.0  # past the TASK grace, far within controller's
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/fast")
            assert not rb.spec.graceful_eviction_tasks
        finally:
            feature_gate.set(FAILOVER, False)

    def test_preserve_state_survives_double_reschedule(self):
        """StatefulFailoverInjection: a binding that fails over TWICE
        during one eviction window keeps each hop's preserved state on its
        own task (the first task's labels must not be clobbered by the
        second eviction)."""
        feature_gate.set(FAILOVER, True)
        feature_gate.set(STATEFUL_FAILOVER_INJECTION, True)
        clock = [2000.0]
        try:
            cp = self._plane(clock)
            rb = ResourceBinding(meta=ObjectMeta(name="stateful", namespace="default"))
            rb.spec.replicas = 2
            rb.spec.scheduler_name = "nobody"  # keep the scheduler out
            rb.spec.failover = FailoverBehavior(
                application=ApplicationFailoverBehavior(
                    decision_conditions_toleration_seconds=10,
                    state_preservation={"phase": ".phase"},
                )
            )
            rb.spec.clusters = [TargetCluster(name="m1", replicas=2)]
            rb.status.aggregated_status = [
                AggregatedStatusItem(
                    cluster_name="m1", applied=True, health="Unhealthy",
                    status={"phase": "hop1"},
                )
            ]
            cp.store.apply(rb)
            cp.settle()
            clock[0] += 11.0
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/stateful")
            tasks = {t.from_cluster: t for t in rb.spec.graceful_eviction_tasks}
            assert tasks["m1"].preserved_label_state == {"phase": "hop1"}

            # rescheduled onto m2, which then ALSO degrades mid-eviction
            rb.spec.clusters = [TargetCluster(name="m2", replicas=2)]
            rb.status.aggregated_status = [
                AggregatedStatusItem(
                    cluster_name="m2", applied=True, health="Unhealthy",
                    status={"phase": "hop2"},
                )
            ]
            cp.store.apply(rb)
            cp.settle()
            clock[0] += 11.0
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/stateful")
            tasks = {t.from_cluster: t for t in rb.spec.graceful_eviction_tasks}
            assert set(tasks) == {"m1", "m2"}
            assert tasks["m1"].preserved_label_state == {"phase": "hop1"}
            assert tasks["m2"].preserved_label_state == {"phase": "hop2"}
        finally:
            feature_gate.set(STATEFUL_FAILOVER_INJECTION, False)
            feature_gate.set(FAILOVER, False)


# --------------------------------------------------------------------------
# chaos e2e: seeded cluster kill -> ordered failover -> oracle parity
# --------------------------------------------------------------------------


class TestChaosPlane:
    def _grouped_plane(self, clock):
        cp = ControlPlane(clock=lambda: clock[0])
        for i in range(1, 3):
            cp.join_cluster(
                new_cluster(f"member{i}", cpu="100", memory="200Gi",
                            labels={"group": "primary"})
            )
        for i in range(3, 5):
            cp.join_cluster(
                new_cluster(f"member{i}", cpu="100", memory="200Gi",
                            labels={"group": "fallback"})
            )
        cp.settle()
        return cp

    def test_seeded_cluster_kill_replays_to_oracle_placements(self):
        feature_gate.set(FAILOVER, True)
        clock = [3000.0]
        try:
            cp = self._grouped_plane(clock)
            cp.store.apply(new_deployment("web", replicas=8))
            cp.store.apply(ordered_policy())
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/web-deployment")
            before = {tc.name: tc.replicas for tc in rb.spec.clusters}
            assert set(before) <= {"member1", "member2"}
            assert sum(before.values()) == 8

            # arm the seeded kill: member2 flips NotReady at the next
            # heartbeat — the exact mid-wave failure the chaos bench fires
            inj = faultinject.arm("cluster.health=down,match=member2", seed=3)
            clock[0] += 60
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/web-deployment")
            after = {tc.name: tc.replicas for tc in rb.spec.clusters}
            assert "member2" not in after
            assert sum(after.values()) == 8
            # ordered fallback honored: the surviving primary serves first
            assert rb.status.scheduler_observed_affinity_name == "grp-primary"

            # oracle replay from (event log, pre-kill placements, final
            # availability): placements must match bit-for-bit
            engine = cp.scheduler._engine
            snap = engine.snapshot
            pl = ordered_policy().spec.placement
            cp_compiled = compile_placement(pl, snap)
            reqs = np.zeros((1, len(snap.dims)), np.int64)
            pods = snap.dim_index("pods")
            if pods is not None:
                reqs[0, pods] = 1
            avail = engine._availability_np(
                reqs, np.asarray([8], np.int32)
            )[0]
            key = "default/web-deployment"
            want = replay_failover(
                inj.log,
                snap.names,
                {key: before},
                {key: np.stack([m for _, m in cp_compiled.terms])},
                {key: cp_compiled.taint_ok & cp_compiled.spread_field_ok},
                {key: cp_compiled.strategy},
                {key: 8},
                {key: cp_compiled.static_weights},
                {key: avail},
            )
            assert want[key] == after
        finally:
            feature_gate.set(FAILOVER, False)

    def test_primary_wipeout_falls_back_in_group_order(self):
        feature_gate.set(FAILOVER, True)
        clock = [4000.0]
        try:
            cp = self._grouped_plane(clock)
            cp.store.apply(new_deployment("web", replicas=6))
            cp.store.apply(ordered_policy())
            cp.settle()
            faultinject.arm(
                "cluster.health=down,match=member1;"
                "cluster.health=down,match=member2",
                seed=11,
            )
            clock[0] += 60
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/web-deployment")
            after = {tc.name: tc.replicas for tc in rb.spec.clusters}
            assert set(after) <= {"member3", "member4"}
            assert sum(after.values()) == 6
            assert rb.status.scheduler_observed_affinity_name == "grp-fallback"
            # recovery: disarm, members heal, primary group takes back over
            # on the next reschedule trigger
            faultinject.disarm()
            clock[0] += 60
            cp.settle()
            cluster2 = cp.store.get("Cluster", "member2")
            assert not any(
                t.effect == "NoExecute" for t in cluster2.spec.taints
            )
        finally:
            feature_gate.set(FAILOVER, False)


# --------------------------------------------------------------------------
# degraded mode: solver sidecar down -> in-proc fallback
# --------------------------------------------------------------------------


class TestSolverDegradedMode:
    def test_dead_sidecar_falls_back_to_inproc_solve(self):
        from karmada_tpu.solver.client import RemoteSolver
        from karmada_tpu.utils.metrics import degraded_passes

        solver = RemoteSolver("127.0.0.1:1", timeout_seconds=1.0)
        before = degraded_passes.value(channel="solver")
        cp = ControlPlane(solver=solver)
        for i in (1, 2):
            cp.join_cluster(
                new_cluster(f"member{i}", cpu="100", memory="200Gi")
            )
        cp.settle()
        cp.store.apply(new_deployment("app", replicas=4))
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="p", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(
                            api_version="apps/v1", kind="Deployment"
                        )
                    ],
                    placement=dynamic_weight_placement(),
                ),
            )
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
        assert sum(placed.values()) == 4  # scheduled despite the dead sidecar
        assert degraded_passes.value(channel="solver") > before
        solver.close()
