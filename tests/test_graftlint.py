"""graftlint tier-1 gate + per-rule fixture corpus.

The gate: the analyzer runs over the full ``karmada_tpu/`` + ``tools/``
tree and must report ZERO non-baselined findings — trace discipline, the
env-flag registry, lock discipline and import hygiene are machine-checked
invariants, not review conventions. The fixture tests pin each rule's
detection (bad fixture fires, good fixture stays silent) so a rule can
never silently stop firing.

No jax import anywhere on this path: graftlint is pure-AST.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools import graftlint  # noqa: E402
from tools.graftlint import core as gl_core  # noqa: E402

FIXTURES = REPO / "tests" / "graftlint_fixtures"

#: role overrides per rule: fixtures live outside the package tree, so the
#: path-derived roles must be forced onto them
FIXTURE_ROLES = {
    "GL001": {gl_core.ROLE_JIT},
    "GL002": {gl_core.ROLE_LEDGER},
    "GL003": set(),
    "GL004": set(),
    "GL005": {gl_core.ROLE_ENTRY, gl_core.ROLE_OPS},
    "GL006": set(),
    "GL007": set(),
    "GL008": set(),
    "GL009": set(),
    "GL010": set(),
    "GL011": set(),
    "GL012": set(),
    "GL013": {gl_core.ROLE_HOTPATH},
}


def lint_fixture(name: str, roles: set) -> list:
    path = FIXTURES / name
    rel = path.relative_to(REPO).as_posix()
    result = graftlint.run(
        [rel], root=REPO, baseline=None, roles_override={rel: roles}
    )
    return result.findings


# -- the tier-1 gate ---------------------------------------------------------


def test_full_tree_zero_findings():
    result = graftlint.run(root=REPO, baseline="auto")
    assert result.checked_files > 100
    assert not result.findings, (
        "graftlint findings on the committed tree:\n"
        + "\n".join(f.render() for f in result.findings)
    )
    assert not result.baseline_errors, "\n".join(result.baseline_errors)
    assert not result.unused_baseline, (
        "baseline entries no finding matches — remove them: "
        f"{result.unused_baseline}"
    )


def test_baseline_entries_are_justified():
    entries, errors = gl_core.load_baseline(REPO / "graftlint_baseline.json")
    assert not errors, "\n".join(errors)
    for ent in entries:
        assert ent["justification"].strip()


# -- per-rule fixture corpus -------------------------------------------------


@pytest.mark.parametrize("rule_id", sorted(FIXTURE_ROLES))
def test_bad_fixture_fires(rule_id):
    roles = FIXTURE_ROLES[rule_id]
    findings = lint_fixture(f"{rule_id.lower()}_bad.py", roles)
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its bad fixture"
    others = [f for f in findings if f.rule != rule_id]
    assert not others, (
        f"unexpected cross-rule findings on {rule_id} bad fixture:\n"
        + "\n".join(f.render() for f in others)
    )


@pytest.mark.parametrize("rule_id", sorted(FIXTURE_ROLES))
def test_good_fixture_is_silent(rule_id):
    roles = FIXTURE_ROLES[rule_id]
    findings = lint_fixture(f"{rule_id.lower()}_good.py", roles)
    assert not findings, (
        f"{rule_id} good fixture flagged:\n"
        + "\n".join(f.render() for f in findings)
    )


def test_gl001_catches_each_pattern():
    findings = lint_fixture("gl001_bad.py", FIXTURE_ROLES["GL001"])
    details = {f.detail for f in findings}
    assert "if:n" in details
    assert "while:x" in details
    assert "float:x" in details
    assert "print" in details
    assert "time.time" in details
    assert ".item" in details
    assert "os.environ" in details


def test_gl007_catches_each_pattern():
    """ISSUE 11 satellite: the batched write stub (ApplyBatch — one unary
    RPC per write SET) and the with_call form are call sites GL007 must
    bound; the WatchBatch frame stream stays exempt like unary watch."""
    findings = lint_fixture("gl007_bad.py", FIXTURE_ROLES["GL007"])
    details = {f.detail for f in findings}
    assert "stub:self._sync" in details
    assert "future:self._score" in details
    assert "stub:self._apply_batch" in details, (
        "batched stub called with metadata but no timeout not flagged"
    )
    assert "with_call:self._apply_batch" in details, (
        "with_call form not flagged"
    )
    assert "stub:score" in details
    assert "urlopen" in details


def test_gl006_catches_each_pattern():
    findings = lint_fixture("gl006_bad.py", FIXTURE_ROLES["GL006"])
    details = {f.detail for f in findings}
    assert "requests_total" in details, "unprefixed family not flagged"
    assert "dup:karmada_tpu_dup_total" in details, (
        "duplicate family registration not flagged"
    )


def test_gl006_registry_families_unique_and_prefixed():
    """The live registry is GL006's ground truth: every family defined in
    the package must satisfy the rule the linter enforces statically."""
    from karmada_tpu.utils.metrics import registry

    names = [name for name, _type, _help in registry.families()]
    assert len(names) == len(set(names)), "duplicate family in registry"
    for name in names:
        assert name.startswith(("karmada_tpu_", "karmada_scheduler_")), name


def test_gl008_catches_each_pattern():
    findings = lint_fixture("gl008_bad.py", FIXTURE_ROLES["GL008"])
    details = {f.detail for f in findings}
    assert "rogue.span" in details, "unregistered span() literal not flagged"
    assert "another.rogue" in details, "unregistered record() not flagged"
    assert "rogue.serve" in details, "unregistered server_span() not flagged"
    assert "dynamic:rogue." in details, (
        "dynamic name with unregistered family prefix not flagged"
    )
    assert "dynamic:" in details, (
        "dynamic name with no literal head not flagged"
    )


def test_gl009_catches_each_pattern():
    findings = lint_fixture("gl009_bad.py", FIXTURE_ROLES["GL009"])
    details = {f.detail for f in findings}
    assert "ghost:metric:karmada_tpu_ghost_total" in details, (
        "unregistered metric-family source not flagged"
    )
    assert "rogue:span:rogue.phase" in details, (
        "unregistered span source not flagged"
    )
    assert "bogus:buckets.raw" in details, (
        "source outside the metric:/span: grammar not flagged"
    )


def test_gl009_live_registry_resolves():
    """The live HISTORY_SERIES registry is GL009's ground truth: every
    declared source must satisfy the rule the linter enforces — a span
    source resolves through the taxonomy matcher, a metric source names
    a registered family."""
    from karmada_tpu.utils.history import HISTORY_SERIES
    from karmada_tpu.utils.metrics import registry
    from karmada_tpu.utils.tracing import span_name_registered

    families = {name for name, _t, _h in registry.families()}
    for series in HISTORY_SERIES.values():
        kind, sep, ref = series.source.partition(":")
        assert sep, series
        if kind == "span":
            assert span_name_registered(ref), series
        else:
            assert kind == "metric", series
            assert ref in families, series


def test_gl008_taxonomy_covers_live_names():
    """The registry GL008 enforces must itself stay well-formed: every
    family key renders into the docs table and the wildcard matcher
    resolves the dynamic controller family."""
    from karmada_tpu.utils.tracing import (
        SPAN_NAMES,
        render_span_table,
        span_name_registered,
    )

    assert span_name_registered("controller.scheduler")
    assert span_name_registered("settle")
    assert not span_name_registered("rogue.span")
    table = render_span_table()
    for name in SPAN_NAMES:
        assert f"`{name}`" in table


def test_gl010_catches_each_pattern():
    findings = lint_fixture("gl010_bad.py", FIXTURE_ROLES["GL010"])
    details = {f.detail for f in findings}
    assert "RogueReason" in details, (
        "unregistered Condition reason literal not flagged"
    )
    assert "AnotherRogue" in details, (
        "unregistered .inc(reason=...) label not flagged"
    )


def test_gl010_live_registry_resolves():
    """The live taxonomy is GL010's ground truth: the stage order must
    match the kernel's bit layout, every known emission constant must be
    registered, and the classifier answers registered codes only."""
    from karmada_tpu.api.work import (
        EVICTION_REASON_APPLICATION_FAILURE,
        EVICTION_REASON_PREEMPTED,
        EVICTION_REASON_TAINT_UNTOLERATED,
    )
    from karmada_tpu.scheduler.quota import QUOTA_EXCEEDED_REASON
    from karmada_tpu.utils.reasons import (
        REASONS,
        STAGE_REASONS,
        classify_error,
        reason_registered,
        render_reasons_table,
    )

    for i, code in enumerate(STAGE_REASONS):
        assert REASONS[code].stage_bit == i
        assert REASONS[code].kind == "stage"
    for const in (
        QUOTA_EXCEEDED_REASON,
        EVICTION_REASON_TAINT_UNTOLERATED,
        EVICTION_REASON_APPLICATION_FAILURE,
        EVICTION_REASON_PREEMPTED,
        "Preempted",
        "RebalanceTriggered",
    ):
        assert reason_registered(const), const
    for err, code in (
        ("", "Success"),
        ("namespace quota exceeded", "QuotaExceeded"),
        ("no clusters fit the placement", "NoClusterFit"),
        ("clusters available replicas are not enough",
         "InsufficientReplicas"),
        ("no affinity group fits", "NoAffinityGroupFits"),
        ("something else entirely", "Unschedulable"),
    ):
        assert classify_error(err) == code
        assert reason_registered(classify_error(err))
    table = render_reasons_table()
    for code in REASONS:
        assert f"`{code}`" in table


def test_gl003_resolves_constant_keys():
    findings = lint_fixture("gl003_bad.py", FIXTURE_ROLES["GL003"])
    names = {f.detail for f in findings}
    assert "KARMADA_TPU_NOT_REGISTERED" in names
    assert "KARMADA_TPU_ALSO_NOT_REGISTERED" in names, (
        "indirect read through a module constant was not resolved"
    )
    assert "KARMADA_TPU_ALIASED_GETENV" in names, (
        "`from os import getenv` read slipped past the registry gate"
    )
    assert "KARMADA_TPU_ALIASED_ENVIRON" in names, (
        "`from os import environ` read slipped past the registry gate"
    )


def test_gl011_catches_each_pattern():
    findings = lint_fixture("gl011_bad.py", FIXTURE_ROLES["GL011"])
    by_detail = {f.detail: f for f in findings}
    assert "_by_key" in by_detail, "lock-free dict read not flagged"
    assert "_order" in by_detail, "lock-free list read not flagged"
    assert by_detail["_by_key"].anchor.endswith("snapshot")
    # one finding per (method, attr): newest() reads _order twice
    assert len([f for f in findings if f.detail == "_order"]) == 1


def test_gl012_catches_each_pattern():
    findings = lint_fixture("gl012_bad.py", FIXTURE_ROLES["GL012"])
    details = {f.detail for f in findings}
    assert "Deadline:for" in details, "Deadline in for loop not flagged"
    assert "BackoffPolicy:while" in details, (
        "BackoffPolicy in while loop not flagged"
    )


def test_gl013_catches_each_pattern():
    findings = lint_fixture("gl013_bad.py", FIXTURE_ROLES["GL013"])
    details = {f.detail for f in findings}
    assert "_memo" in details, "grow-only dict not flagged"
    assert "_events" in details, "uncapped deque not flagged"


def test_gl013_needs_hotpath_role():
    """Outside the worker/controller scope the rule stays silent — a
    short-lived CLI helper cannot leak for months."""
    findings = lint_fixture("gl013_bad.py", set())
    assert not [f for f in findings if f.rule == "GL013"]


# -- suppression + baseline workflow ----------------------------------------


def test_inline_suppression(tmp_path):
    src = FIXTURES / "gl004_bad.py"
    bad = src.read_text()
    suppressed = bad.replace(
        "        self._n = 0  # BAD: lock-free write of a lock-guarded attr",
        "        self._n = 0  # graftlint: disable=GL004",
    ).replace(
        "        self._items.clear()  # BAD: lock-free in-place mutation",
        "        # graftlint: disable=GL004\n        self._items.clear()",
    )
    assert suppressed != bad
    target = tmp_path / "suppressed.py"
    target.write_text(suppressed)
    result = graftlint.run([str(target)], root=REPO, baseline=None)
    assert not result.findings
    assert result.suppressed_count == 2


def test_file_level_suppression(tmp_path):
    target = tmp_path / "filewide.py"
    target.write_text(
        "# graftlint: disable-file=GL003\n"
        "import os\n"
        "V = os.environ.get('KARMADA_TPU_TOTALLY_BOGUS')\n"
    )
    result = graftlint.run([str(target)], root=REPO, baseline=None)
    assert not result.findings
    assert result.suppressed_count == 1


def test_baseline_grandfathers_with_justification(tmp_path):
    rel = (FIXTURES / "gl003_bad.py").relative_to(REPO).as_posix()
    raw = graftlint.run([rel], root=REPO, baseline=None)
    assert raw.findings
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "entries": [
            {
                "rule": f.rule, "path": f.path, "anchor": f.anchor,
                "detail": f.detail,
                "justification": "fixture: grandfathered for the test",
            }
            for f in raw.findings
        ],
    }))
    config = gl_core.default_config(REPO)
    result = gl_core.Linter(config).run([rel], baseline=baseline)
    assert not result.findings
    assert len(result.baselined) == len(raw.findings)

    # an entry with no justification is itself an error, never a pass
    baseline.write_text(json.dumps({
        "version": 1,
        "entries": [{
            "rule": "GL003", "path": rel, "anchor": "read",
            "detail": "KARMADA_TPU_NOT_REGISTERED", "justification": "",
        }],
    }))
    result = gl_core.Linter(config).run([rel], baseline=baseline)
    assert result.baseline_errors
    assert not result.ok


def test_write_baseline_preserves_justifications(tmp_path):
    """Regenerating the baseline must carry over hand-written
    justifications for entries whose identity still matches."""
    rel = (FIXTURES / "gl003_bad.py").relative_to(REPO).as_posix()
    raw = graftlint.run([rel], root=REPO, baseline=None)
    assert len(raw.findings) >= 2
    baseline = tmp_path / "baseline.json"
    gl_core.write_baseline(baseline, raw.findings)
    entries = json.loads(baseline.read_text())["entries"]
    assert all(e["justification"] == "" for e in entries)

    entries[0]["justification"] = "written by a human, must survive"
    baseline.write_text(json.dumps({"version": 1, "entries": entries}))
    gl_core.write_baseline(baseline, raw.findings)
    rewritten = json.loads(baseline.read_text())["entries"]
    assert len(rewritten) == len(entries)
    by_id = {
        (e["rule"], e["path"], e["anchor"], e["detail"]):
            e["justification"]
        for e in rewritten
    }
    key = (entries[0]["rule"], entries[0]["path"], entries[0]["anchor"],
           entries[0]["detail"])
    assert by_id[key] == "written by a human, must survive"


# -- surfaces: module CLI, karmadactl verb, docs drift gate ------------------


# the CLI-surface tests prove argument plumbing + output shape only, so
# they lint ONE small file — the full-tree sweep already runs in-process
# in test_full_tree_zero_findings
_CLI_TARGET = "karmada_tpu/utils/quantity.py"


def test_module_cli_json():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--format", "json",
         _CLI_TARGET],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["checked_files"] == 1


def test_cli_lint_verb(capsys):
    from karmada_tpu import cli

    rc = cli.main(["lint", "--format", "json", _CLI_TARGET])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert payload["checked_files"] == 1


def test_env_table_in_sync_with_registry():
    """The docs half of GL003: OPERATIONS.md env table is generated from
    ENV_FLAGS and docs_from_bench fails loudly on drift."""
    sys.path.insert(0, str(REPO / "tools"))
    import docs_from_bench

    docs_from_bench.check_env_table()  # raises SystemExit on drift

    from karmada_tpu.utils.flags import ENV_FLAGS, render_env_table

    table = render_env_table()
    ops = (REPO / "docs" / "OPERATIONS.md").read_text()
    for name in ENV_FLAGS:
        assert name in table
        assert name in ops


def test_env_table_drift_fails_loudly(tmp_path, monkeypatch):
    sys.path.insert(0, str(REPO / "tools"))
    import docs_from_bench

    docs_dir = tmp_path / "docs"
    docs_dir.mkdir()
    (docs_dir / "OPERATIONS.md").write_text(
        "<!-- envflags:begin -->\n| stale | table |\n<!-- envflags:end -->\n"
    )
    monkeypatch.setattr(docs_from_bench, "ROOT", tmp_path)
    with pytest.raises(SystemExit, match="drifted"):
        docs_from_bench.check_env_table()
