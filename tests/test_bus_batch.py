"""Columnar bus channel (ISSUE 11): batched ApplyBatch / WatchBatch wire
protocol parity against the per-object unary path.

The contract under test: plane state is IDENTICAL batched vs unary — the
batch protocol changes the wire unit (a write SET per RPC, an event FRAME
per stream message), never the semantics. Mixed-version negotiation
(UNIMPLEMENTED → unary fallback, re-probe after reconnect), CAS-once
conflict isolation inside a batch, per-batch fault injection, per-event
queue-age accounting, template-delta rehydration byte-equivalence, and
namespace-sharded worker drains all live here.
"""

import time

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.bus.service import StoreBusServer, StoreReplica
from karmada_tpu.utils import DONE, Store
from karmada_tpu.utils.store import ConflictError


def _cm(name, payload, ns="ns"):
    return Resource(
        api_version="v1", kind="ConfigMap",
        meta=ObjectMeta(name=name, namespace=ns),
        spec={"payload": payload},
    )


def _canon(doc: dict) -> dict:
    """Semantic canonical form of a jsonable Resource doc: identity noise
    (resource_version bumps from re-applies, per-plane random uids and
    permanent-id stamps, wall-clock timestamps) stripped — what must be
    IDENTICAL between the batched/template-delta and unary/full planes."""
    import copy

    doc = copy.deepcopy(doc)
    meta = doc.get("meta") or {}
    for k in ("resource_version", "uid", "creation_timestamp"):
        meta.pop(k, None)
    for bag in ("labels", "annotations"):
        d = meta.get(bag) or {}
        for k in list(d):
            if "permanent-id" in k:
                del d[k]
    return doc


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


@pytest.fixture()
def bus():
    store = Store()
    server = StoreBusServer(store, "127.0.0.1:0")
    port = server.start()
    yield store, port
    server.stop()


@pytest.fixture()
def old_bus():
    """An old-build server shape: ApplyBatch/WatchBatch unregistered, so
    batched calls answer UNIMPLEMENTED and clients negotiate the unary
    fallback per connection."""
    store = Store()
    server = StoreBusServer(store, "127.0.0.1:0", enable_batch=False)
    port = server.start()
    yield store, port
    server.stop()


class TestApplyBatch:
    def test_batched_write_set_roundtrip(self, bus):
        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        objs = [_cm(f"a{i}", i) for i in range(300)]
        errors = replica.apply_many(objs)
        assert errors == []
        # the probe pinned the batched protocol for this connection
        assert replica.supports_batch is True
        # the PRIMARY assigned versions (the caller's objects stay
        # unstamped — StoreReplica.apply semantics: the echo, not the
        # response, is the commit signal)
        assert store.get("Resource", "ns/a0").meta.resource_version > 0
        assert store.get("Resource", "ns/a299").spec["payload"] == 299
        # the mirror converges through the (batched) watch stream
        assert _wait(
            lambda: replica.store.get("Resource", "ns/a299") is not None
        )
        replica.close()

    def test_cas_conflict_isolated_to_conflicting_op(self, bus):
        """A CAS loser surfaces ConflictError on exactly the conflicting
        object; every other op of the batch commits."""
        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        assert replica.apply_many([_cm("c0", 0), _cm("c1", 1)]) == []
        good_rv = store.get("Resource", "ns/c1").meta.resource_version
        loser = _cm("c0", 100)
        winner = _cm("c1", 101)
        plain = _cm("c2", 102)
        errors = replica.apply_many(
            [loser, winner, plain], expected_rvs=[10_000, good_rv, None]
        )
        assert len(errors) == 1
        obj, exc = errors[0]
        assert obj is loser and isinstance(exc, ConflictError)
        assert store.get("Resource", "ns/c0").spec["payload"] == 0
        assert store.get("Resource", "ns/c1").spec["payload"] == 101
        assert store.get("Resource", "ns/c2").spec["payload"] == 102
        replica.close()

    def test_delete_many(self, bus):
        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        assert replica.apply_many([_cm(f"d{i}", i) for i in range(4)]) == []
        errors = replica.delete_many(
            [("Resource", "ns/d0"), ("Resource", "ns/d1", True)]
        )
        assert errors == []
        assert store.get("Resource", "ns/d0") is None
        assert store.get("Resource", "ns/d1") is None
        assert store.get("Resource", "ns/d2") is not None
        replica.close()

    def test_env_kill_switch_forces_unary(self, bus, monkeypatch):
        """KARMADA_TPU_BUS_BATCH=0 is the mixed-version escape hatch: the
        batched protocol is never even probed."""
        monkeypatch.setenv("KARMADA_TPU_BUS_BATCH", "0")
        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        assert replica.apply_many([_cm(f"u{i}", i) for i in range(5)]) == []
        assert replica.supports_batch is None  # never probed
        assert store.get("Resource", "ns/u4") is not None
        replica.close()

    def test_batch_size_histogram_observed(self, bus):
        from karmada_tpu.utils.metrics import bus_batch_size

        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        before = (bus_batch_size.summary() or {"count": 0})["count"]
        assert replica.apply_many([_cm(f"h{i}", i) for i in range(64)]) == []
        after = (bus_batch_size.summary() or {"count": 0})["count"]
        # at least the served ApplyBatch observed its op count
        assert after > before
        replica.close()


class TestMixedVersionNegotiation:
    def test_old_server_pins_unary_fallback(self, old_bus):
        store, port = old_bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()  # watch fell back to unary stream
        objs = [_cm(f"m{i}", i) for i in range(20)]
        assert replica.apply_many(objs) == []
        # UNIMPLEMENTED pinned the per-object fallback — and the write
        # set still committed whole
        assert replica.supports_batch is False
        assert replica._watch_supports_batch is False
        assert store.get("Resource", "ns/m19").spec["payload"] == 19
        assert store.get("Resource", "ns/m0").meta.resource_version > 0
        # deletes ride the same pin
        assert replica.delete_many([("Resource", "ns/m0")]) == []
        assert store.get("Resource", "ns/m0") is None
        replica.close()

    def test_wire_failure_resets_pin_and_reprobes(self, old_bus):
        """An old server pins the unary fallback; when the connection
        breaks and a NEW (batch-capable) build comes back on the same
        address, the client re-probes instead of staying unary forever."""
        store, port = old_bus
        replica = StoreReplica(
            f"127.0.0.1:{port}", timeout_seconds=2.0
        )
        replica.start()
        assert replica.wait_synced()
        assert replica.apply_many([_cm("r0", 0)]) == []
        assert replica.supports_batch is False

        # the old build dies mid-flight: the next write sees a wire
        # failure, which RESETS the negotiation pin
        store2 = Store()
        server2 = StoreBusServer(store2, "127.0.0.1:0")  # new build
        try:
            # find the old server through the fixture teardown ordering:
            # stop it by severing at the address level is not possible
            # here, so emulate the upgrade with a fresh replica whose
            # pin was carried into a wire failure
            with pytest.raises(Exception):
                bad = StoreReplica("127.0.0.1:1", timeout_seconds=0.5)
                bad.supports_batch = False  # pinned by an old server
                try:
                    bad.apply(_cm("x", 1))
                finally:
                    # unary wire failure resets the batch pin
                    assert bad.supports_batch is None
                    bad.close()
            # a batch-capable server answers the re-probe batched
            port2 = server2.start()
            replica2 = StoreReplica(f"127.0.0.1:{port2}")
            replica2.start()
            assert replica2.wait_synced()
            assert replica2.apply_many([_cm("r1", 1)]) == []
            assert replica2.supports_batch is True
            replica2.close()
        finally:
            server2.stop()
        replica.close()

    def test_mid_set_unimplemented_falls_back_for_remainder_only(
        self, bus, monkeypatch
    ):
        """A server replaced by an old build BETWEEN chunks of one write
        set: the committed chunks must not replay unary (duplicate
        writes; a committed CAS op would surface the caller's own write
        as a false conflict) — only the uncommitted remainder falls
        back."""
        import grpc

        monkeypatch.setenv("KARMADA_TPU_BUS_BATCH", "3")
        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()

        class Unimplemented(grpc.RpcError):
            def code(self):
                return grpc.StatusCode.UNIMPLEMENTED

            def details(self):
                return "unimplemented"

        real = replica._apply_batch
        calls = [0]

        def flaky(req, timeout=None, metadata=None):
            calls[0] += 1
            if calls[0] >= 2:  # the "new build" died after chunk 1
                raise Unimplemented()
            return real(req, timeout=timeout, metadata=metadata)

        replica._apply_batch = flaky
        objs = [_cm(f"ms{i}", i) for i in range(7)]  # 3 batched + 4 unary
        assert replica.apply_many(objs) == []
        assert calls[0] == 2  # chunk 1 committed, chunk 2 negotiated
        assert replica.supports_batch is False
        for i in range(7):
            assert store.get("Resource", f"ns/ms{i}").spec["payload"] == i
        replica.close()

    def test_batch_wire_failure_resets_pin(self):
        """A wire failure on the BATCH path re-probes too (the server
        behind the reconnected channel may be a different build)."""
        replica = StoreReplica("127.0.0.1:1", timeout_seconds=0.5)
        replica.supports_batch = True  # pinned by a batched success
        with pytest.raises(Exception):
            replica.apply_many([_cm("x", 1)])
        assert replica.supports_batch is None
        replica.close()


class TestWatchBatchParity:
    def test_batched_and_unary_mirrors_identical(self, bus):
        """One primary, one batch-capable server, one old-build server:
        the batched replica and the negotiated-unary replica converge to
        IDENTICAL mirrors through replay + live tail."""
        store, port = bus
        old = StoreBusServer(store, "127.0.0.1:0", enable_batch=False)
        old_port = old.start()
        # replayed state
        for i in range(30):
            store.apply(_cm(f"pre{i}", i))
        batched = StoreReplica(f"127.0.0.1:{port}")
        unary = StoreReplica(f"127.0.0.1:{old_port}")
        batched.start()
        unary.start()
        try:
            assert batched.wait_synced()
            assert unary.wait_synced()
            # live tail: modifications, adds, deletes interleaved
            for i in range(30):
                store.apply(_cm(f"pre{i}", i + 1000))
            for i in range(30, 60):
                store.apply(_cm(f"pre{i}", i))
            for i in range(0, 10):
                store.delete("Resource", f"ns/pre{i}", force=True)

            def snapshot(st):
                return {
                    (type(o).__name__, o.meta.namespaced_name):
                        (o.meta.resource_version, o.spec)
                    for o in st.list("Resource")
                }

            want = snapshot(store)
            assert _wait(lambda: snapshot(batched.store) == want, 10.0)
            assert _wait(lambda: snapshot(unary.store) == want, 10.0)
            assert batched._watch_supports_batch is True
            assert unary._watch_supports_batch is False
        finally:
            batched.close()
            unary.close()
            old.stop()

    def test_reconnect_replays_batched_and_heals_gap(self):
        store = Store()
        server = StoreBusServer(store, "127.0.0.1:0")
        port = server.start()
        store.apply(_cm("g0", 0))
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        try:
            assert _wait(
                lambda: replica.store.get("Resource", "ns/g0") is not None
            )
            server.stop(grace=0)
            store.apply(_cm("g1", 1))  # written while disconnected
            server2 = StoreBusServer(store, f"127.0.0.1:{port}")
            server2.start()
            try:
                assert _wait(
                    lambda: replica.store.get("Resource", "ns/g1")
                    is not None,
                    timeout=10.0,
                )
                # the reconnected stream re-negotiated batched
                assert replica._watch_supports_batch is True
            finally:
                server2.stop()
        finally:
            replica.close()

    def test_event_age_recorded_per_event_not_per_frame(self, bus):
        """Satellite: a coalesced frame of N events must record N queue-
        age observations — batching cannot fake a low queue age."""
        from karmada_tpu.utils.metrics import bus_event_age_seconds

        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        before = (bus_event_age_seconds.summary() or {"count": 0})["count"]
        n = 40
        # one batched delivery sweep: the flush timer coalesces the burst
        store.apply_many([_cm(f"age{i}", i) for i in range(n)])
        assert _wait(
            lambda: replica.store.get("Resource", f"ns/age{n - 1}")
            is not None
        )
        # the stream has observed one age per delivered event (>= n new
        # observations for this subscriber)
        assert _wait(
            lambda: (bus_event_age_seconds.summary() or {"count": 0})[
                "count"
            ] - before >= n
        )
        replica.close()


class TestFaultInjectionPerBatch:
    def test_fault_fires_per_batch_attempt(self, bus):
        """The PR 7 seam fires once per BATCH attempt (the batch is the
        wire unit now), and the resilience retry commits the set."""
        from karmada_tpu.utils import faultinject

        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        faultinject.arm("bus.rpc=error,count=1,match=ApplyBatch", seed=7)
        try:
            errors = replica.apply_many(
                [_cm(f"f{i}", i) for i in range(50)]
            )
            assert errors == []
            inj = faultinject.injector()
            fired = [e for e in inj.log if e.point == "bus.rpc"]
            assert len(fired) == 1  # one injection for the whole batch
            assert fired[0].key == "ApplyBatch"
        finally:
            faultinject.disarm()
        assert store.get("Resource", "ns/f49") is not None
        replica.close()


class TestTemplateDeltaRendering:
    def _plane(self, n_deploys=6, n_clusters=3):
        from karmada_tpu import cli as _cli
        from karmada_tpu.api import (
            PropagationPolicy,
            PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.utils.builders import (
            new_cluster,
            new_deployment,
            static_weight_placement,
        )

        cp = _cli.cmd_init()
        for i in range(1, n_clusters + 1):
            cp.join_cluster(
                new_cluster(f"member{i}", cpu="100", memory="200Gi")
            )
        cp.settle()
        # static 2:1:1 division with enough replicas to spread: every
        # binding lands Works on ALL clusters with DIFFERENT replica
        # counts, so the per-cluster template patches genuinely differ
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment"
                )],
                placement=static_weight_placement({
                    f"member{i}": (2 if i == 1 else 1)
                    for i in range(1, n_clusters + 1)
                }),
            ),
        ))
        for i in range(n_deploys):
            cp.store.apply(
                new_deployment(f"app{i}", replicas=8 + i,
                               image="docker.io/nginx:1.25")
            )
        cp.settle()
        return cp

    @staticmethod
    def _member_state(cp):
        """Canonical member-side applied objects: the plane's OUTPUT."""
        from karmada_tpu.utils.codec import to_jsonable

        out = {}
        for name in cp.members.names():
            member = cp.members.get(name)
            for obj in member.list():
                doc = _canon(to_jsonable(obj))
                out[(name, obj.meta.namespace, obj.meta.name)] = doc
        return out

    def test_works_are_template_delta_and_rehydration_byte_equivalent(
        self, monkeypatch
    ):
        """Tentpole (c) acceptance: template-delta rehydration is byte-
        equivalent to full rendering, and the member-side applied state
        is identical under either representation."""
        from karmada_tpu.utils.codec import to_jsonable

        cp = self._plane()
        works = cp.store.list("Work")
        delta = [
            w for w in works
            if w.spec.workload_template is not None
            and w.spec.workload_template.digest
        ]
        assert delta, "no Work rendered template-delta"
        # one content-addressed template per workload family, shared
        digests = {w.spec.workload_template.digest for w in delta}
        for d in digests:
            assert cp.store.get("WorkloadTemplate", d) is not None
        assert len(digests) < len(delta)
        state_delta = self._member_state(cp)

        # rehydrate each delta Work and compare against the full render
        # the SAME plane produces with the kill switch thrown
        from karmada_tpu.controllers.propagation import work_manifests

        rehydrated = {
            w.meta.namespaced_name: [
                to_jsonable(m) for m in work_manifests(cp.store, w)
            ]
            for w in delta
        }
        monkeypatch.setenv("KARMADA_TPU_BUS_TEMPLATE_DELTA", "0")
        # flipping the kill switch changes the build fingerprint: every
        # binding re-renders its Works full on the next reconcile
        for kind in ("ResourceBinding",):
            for rb in cp.store.list(kind):
                cp.binding_controller.worker.enqueue(
                    (kind, rb.meta.namespaced_name)
                )
        cp.settle()
        full_works = cp.store.list("Work")
        full = {
            w.meta.namespaced_name: [
                to_jsonable(m) for m in w.spec.workload
            ]
            for w in full_works
            if w.spec.workload
        }
        for key, docs in rehydrated.items():
            assert key in full
            assert docs == full[key], f"rehydration diverged for {key}"
        # the member-side plane output is identical too
        assert self._member_state(cp) == state_delta
        # the orphaned templates were garbage-collected once nothing
        # referenced them
        assert _wait(
            lambda: not cp.store.list("WorkloadTemplate"), timeout=2.0
        ) or not cp.store.list("WorkloadTemplate")

    def test_override_matched_target_full_renders(self):
        """Per-target fallback: a cluster matched by an override rule
        full-renders while the rest of the fleet stays delta."""
        from karmada_tpu.api.policy import (
            ImageOverrider,
            OverridePolicy,
            OverrideSpec,
            Overriders,
            RuleWithCluster,
        )
        from karmada_tpu.api.policy import ClusterAffinity
        from karmada_tpu.controllers.propagation import (
            execution_namespace,
            work_manifests,
        )

        from karmada_tpu.api import ResourceSelector

        cp = self._plane(n_deploys=2)
        cp.store.apply(OverridePolicy(
            meta=ObjectMeta(name="ov", namespace="default"),
            spec=OverrideSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment"
                )],
                override_rules=[RuleWithCluster(
                    target_cluster=ClusterAffinity(
                        cluster_names=["member1"]
                    ),
                    overriders=Overriders(image_overrider=[ImageOverrider(
                        component="Registry", operator="replace",
                        value="override.example.com",
                    )]),
                )],
            ),
        ))
        for rb in cp.store.list("ResourceBinding"):
            cp.binding_controller.worker.enqueue(
                ("ResourceBinding", rb.meta.namespaced_name)
            )
        cp.settle()
        by_cluster: dict[str, list] = {}
        for w in cp.store.list("Work"):
            ns = w.meta.namespace
            for cl in ("member1", "member2", "member3"):
                if ns == execution_namespace(cl):
                    by_cluster.setdefault(cl, []).append(w)
        assert all(
            w.spec.workload and w.spec.workload_template is None
            for w in by_cluster.get("member1", [])
        ), "override-matched target must full-render"
        others = by_cluster.get("member2", []) + by_cluster.get(
            "member3", []
        )
        assert any(
            w.spec.workload_template is not None for w in others
        ), "unmatched targets should stay template-delta"
        # and every work still rehydrates to a manifest
        for w in cp.store.list("Work"):
            assert work_manifests(cp.store, w), w.meta.namespaced_name

    def test_template_gc_on_binding_delete(self):
        cp = self._plane(n_deploys=2)
        assert cp.store.list("WorkloadTemplate")
        for dep in list(cp.store.list("Resource")):
            if dep.kind == "Deployment":
                cp.store.delete(
                    "Resource", dep.meta.namespaced_name, force=True
                )
        cp.settle()
        # the app Works are gone (system Works — cluster RBAC sync etc. —
        # are not the binding controller's and stay)
        assert not [
            w for w in cp.store.list("Work")
            if ".app" in w.meta.name or w.meta.name.startswith("default.")
        ]
        assert not cp.store.list("WorkloadTemplate"), (
            "unreferenced templates must be collected"
        )

    def test_work_delivered_before_template_parks_then_applies(self):
        """Bus replay can deliver a Work before its WorkloadTemplate on a
        mid-stream join: the consumer parks on the digest and the
        template watch unparks it."""
        from karmada_tpu.api.work import (
            Work,
            WorkSpec,
            WorkloadTemplate,
            WorkloadTemplateRef,
        )
        from karmada_tpu.controllers.propagation import TemplateRehydrator
        from karmada_tpu.utils.codec import to_jsonable

        store = Store()
        manifest = Resource(
            api_version="apps/v1", kind="Deployment",
            meta=ObjectMeta(name="app", namespace="default"),
            spec={"replicas": 1, "template": {"x": 1}},
        )
        doc = to_jsonable(manifest)
        ref = WorkloadTemplateRef(
            digest="d1", api_version="apps/v1", kind="Deployment",
            namespace="default", name="app", patch={"replicas": 5},
        )
        work = Work(
            meta=ObjectMeta(name="w", namespace="karmada-es-m1"),
            spec=WorkSpec(workload_template=ref),
        )
        rehydrator = TemplateRehydrator(store)
        assert rehydrator.manifests(work) is None  # parked: no template
        store.apply(WorkloadTemplate(
            meta=ObjectMeta(name="d1"), manifest=doc
        ))
        out = rehydrator.manifests(work)
        assert out is not None and out[0].spec["replicas"] == 5
        assert out[0].spec["template"] == {"x": 1}
        # memoized render: same object identity on re-reconcile
        assert rehydrator.manifests(work)[0] is out[0]


class TestPlaneOverBusParity:
    """End-to-end: the whole controller fleet writing through a real gRPC
    bus — batched vs forced-unary planes converge to identical state."""

    def _run_plane(self, n=12, c=3):
        from karmada_tpu import cli as _cli
        from karmada_tpu.api import (
            PropagationPolicy,
            PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.bus.agent import ReplicaStoreFacade
        from karmada_tpu.utils.builders import (
            dynamic_weight_placement,
            new_cluster,
            new_deployment,
        )

        primary = Store()
        server = StoreBusServer(primary, "127.0.0.1:0")
        port = server.start()
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced(10)
        cp = _cli.cmd_init(store=ReplicaStoreFacade(replica))
        try:
            for i in range(1, c + 1):
                cp.join_cluster(
                    new_cluster(f"member{i}", cpu="100", memory="200Gi")
                )
            self._settle(cp)
            cp.store.apply(PropagationPolicy(
                meta=ObjectMeta(name="p", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[ResourceSelector(
                        api_version="apps/v1", kind="Deployment"
                    )],
                    placement=dynamic_weight_placement(),
                ),
            ))
            for i in range(n):
                cp.store.apply(
                    new_deployment(f"app{i}", replicas=(i % 4) + 1)
                )
            self._settle(cp)

            def works_match_placements() -> bool:
                self._settle(cp)
                want = sum(
                    len(rb.spec.clusters)
                    for rb in primary.list("ResourceBinding")
                )
                have = sum(
                    1 for w in primary.list("Work")
                    if ".app" in w.meta.name
                )
                return want > 0 and have == want

            assert _wait(works_match_placements, timeout=30.0), (
                "works never converged to the scheduled placements"
            )
            return self._state(cp, primary)
        finally:
            replica.close()
            server.stop()

    @staticmethod
    def _settle(cp):
        """Settle through the write-echo stream: a settle's writes become
        locally visible via the bus echo, which can land after
        run_until_settled returns."""
        cp.settle()
        idle = 0
        deadline = time.time() + 30
        while idle < 3 and time.time() < deadline:
            time.sleep(0.05)
            if cp.settle() == 0:
                idle += 1
            else:
                idle = 0
        assert idle >= 3, "plane never settled through echoes"

    @staticmethod
    def _state(cp, primary):
        """Timestamp-free canonical plane state: binding placements and
        REHYDRATED work manifests (representation-independent)."""
        from karmada_tpu.controllers.propagation import work_manifests
        from karmada_tpu.utils.codec import to_jsonable

        placements = {
            rb.meta.namespaced_name: sorted(
                (tc.name, tc.replicas) for tc in rb.spec.clusters
            )
            for rb in primary.list("ResourceBinding")
        }
        manifests = {}
        for w in primary.list("Work"):
            docs = work_manifests(primary, w)
            assert docs, f"work {w.meta.namespaced_name} has no manifest"
            manifests[w.meta.namespaced_name] = [
                _canon(to_jsonable(m)) for m in docs
            ]
        return placements, manifests

    def test_final_state_identical_batched_vs_unary(self, monkeypatch):
        batched = self._run_plane()
        monkeypatch.setenv("KARMADA_TPU_BUS_BATCH", "0")
        monkeypatch.setenv("KARMADA_TPU_BUS_TEMPLATE_DELTA", "0")
        unary = self._run_plane()
        assert batched[0] == unary[0], "binding placements diverged"
        assert batched[1] == unary[1], (
            "rehydrated work manifests diverged between batched "
            "template-delta and unary full rendering"
        )


class TestWorkerNamespaceSharding:
    def test_batch_drain_holds_one_shard_only(self):
        from karmada_tpu.utils import Runtime

        seen: list[list] = []

        def reconcile(key):
            return DONE

        def reconcile_batch(keys):
            seen.append(list(keys))
            return {k: DONE for k in keys}

        rt = Runtime()
        w = rt.new_worker(
            "t", reconcile, reconcile_batch=reconcile_batch,
            shard_fn=lambda key: key.partition("/")[0],
        )
        for i in range(4):
            w.enqueue(f"ns-a/k{i}")
            w.enqueue(f"ns-b/k{i}")
        while len(w):
            w.process_one()
        assert seen, "batched drains never ran"
        for batch in seen:
            tokens = {k.partition("/")[0] for k in batch}
            assert len(tokens) == 1, (
                f"a batch drain mixed ownership domains: {batch}"
            )
        drained = {k for b in seen for k in b}
        assert drained == {
            f"ns-{t}/k{i}" for t in "ab" for i in range(4)
        }

    def test_sharded_enqueue_dedup_and_len(self):
        from karmada_tpu.utils import Runtime

        rt = Runtime()
        w = rt.new_worker(
            "t2", lambda k: DONE,
            shard_fn=lambda key: key.partition("/")[0],
        )
        w.enqueue("a/1")
        w.enqueue("a/1")  # dedup
        w.enqueue("b/2")
        assert len(w) == 2
        assert w.process_one() is True
        assert w.process_one() is True
        assert w.process_one() is False
        assert len(w) == 0
