"""Accelerator-owning solver sidecar e2e (opt-in).

The flagship deployment shape (docs/OPERATIONS.md) dedicates the
accelerator to the solver sidecar while every other component stays on
CPU jax. The accelerator tunnel is SINGLE-CLIENT per machine, so this
e2e must be the only claimant — it is gated behind
``KARMADA_TPU_TPU_SOLVER_E2E=1`` and skipped in the normal suite (which
runs many processes concurrently). Run it alone:

    KARMADA_TPU_TPU_SOLVER_E2E=1 python -m pytest \
        tests/test_tpu_solver_localup.py -x -q

Ref: the reference's scheduler Deployment runs as its own pod
(operator/pkg/controller/karmada — scheduler workload); here "its own
pod" becomes "its own process owning the chip".
"""

from __future__ import annotations

import os
import time

import pytest

from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.bus.service import StoreReplica
from karmada_tpu.localup import LocalUp
from karmada_tpu.utils.builders import dynamic_weight_placement, new_deployment
from tests.test_localup_processes import wait_for

pytestmark = pytest.mark.skipif(
    os.environ.get("KARMADA_TPU_TPU_SOLVER_E2E") != "1",
    reason="single-client accelerator tunnel: opt-in via "
    "KARMADA_TPU_TPU_SOLVER_E2E=1 (run this file alone)",
)


def test_solver_owns_accelerator_and_schedules():
    platform = os.environ.get("KARMADA_TPU_SOLVER_PLATFORM", "axon,cpu")
    record: dict = {"platform_policy": platform}
    t_start = time.time()
    with LocalUp(
        members=2, pull=(), solver_platform=platform
    ) as lu:
        record["startup_wall_s"] = round(time.time() - t_start, 2)
        # the sidecar reported its resolved backend: must be the
        # accelerator, not a silent CPU fallback
        assert lu.solver_backend not in ("", "cpu"), lu.solver_backend
        record["solver_backend"] = lu.solver_backend
        replica = StoreReplica(f"127.0.0.1:{lu.endpoints['bus']}")
        replica.start()
        assert replica.wait_synced(10)
        try:
            replica.apply(
                PropagationPolicy(
                    meta=ObjectMeta(name="tpu-policy", namespace="default"),
                    spec=PropagationSpec(
                        resource_selectors=[
                            ResourceSelector(
                                api_version="apps/v1", kind="Deployment"
                            )
                        ],
                        placement=dynamic_weight_placement(),
                    ),
                )
            )

            def divided(name, total):
                def check():
                    rb = replica.store.get(
                        "ResourceBinding", f"default/{name}-deployment"
                    )
                    if rb is None or not rb.spec.clusters:
                        return False
                    return (
                        sum(tc.replicas for tc in rb.spec.clusters) == total
                    )

                return check

            # first schedule: pays whatever accelerator init/compile the
            # persistent cache does not cover
            t0 = time.time()
            replica.apply(new_deployment("tpu-solved", replicas=12))
            assert wait_for(divided("tpu-solved", 12), timeout=180), (
                "weighted division never reached the binding through the "
                "accelerator-backed solver"
            )
            record["first_schedule_wall_s"] = round(time.time() - t0, 2)

            # warm schedule: the steady-state sidecar latency
            t0 = time.time()
            replica.apply(new_deployment("tpu-warm", replicas=7))
            assert wait_for(divided("tpu-warm", 7), timeout=60)
            record["warm_schedule_wall_s"] = round(time.time() - t0, 2)
            record["total_wall_s"] = round(time.time() - t_start, 2)
            out = os.environ.get("KARMADA_TPU_TPU_E2E_RECORD")
            if out:
                import json

                with open(out, "w") as f:
                    json.dump(record, f, indent=1)
            print(f"# TPU e2e record: {record}")
        finally:
            replica.close()


if __name__ == "__main__":
    os.environ.setdefault("KARMADA_TPU_TPU_SOLVER_E2E", "1")
    t0 = time.time()
    test_solver_owns_accelerator_and_schedules()
    print(f"TPU-solver e2e OK in {time.time() - t0:.1f}s")
