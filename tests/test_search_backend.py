"""Search backend store: inverted-index documents (opensearch.go analogue)."""

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.search.backend import InvertedIndexBackend
from karmada_tpu.search.registry import ResourceRegistry, ResourceRegistrySpec
from karmada_tpu.utils.builders import new_cluster


def deploy(name, ns="default", labels=None):
    return Resource(
        api_version="apps/v1",
        kind="Deployment",
        meta=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        spec={"replicas": 1},
    )


class TestInvertedIndex:
    def test_upsert_search_and_field_scopes(self):
        be = InvertedIndexBackend()
        be.upsert("m1", deploy("web-frontend", labels={"app": "web"}))
        be.upsert("m2", deploy("web-frontend", labels={"app": "web"}))
        be.upsert("m1", deploy("db", ns="prod", labels={"app": "db"}))
        assert be.count() == 3
        assert len(be.search("web")) == 2
        assert len(be.search("kind:deployment")) == 3
        assert len(be.search("label:app=db")) == 1
        assert [d["cluster"] for d in be.search("web cluster:m2")] == ["m2"]
        assert len(be.search("namespace:prod")) == 1
        # prefix
        assert len(be.search("front*")) == 2
        # conjunction with no overlap
        assert be.search("web namespace:prod") == []

    def test_upsert_replaces_and_delete_drops_terms(self):
        be = InvertedIndexBackend()
        be.upsert("m1", deploy("api", labels={"tier": "gold"}))
        assert len(be.search("label:tier=gold")) == 1
        be.upsert("m1", deploy("api", labels={"tier": "silver"}))
        assert be.search("label:tier=gold") == []
        assert len(be.search("label:tier=silver")) == 1
        be.delete("m1", "apps/v1/Deployment", "default", "api")
        assert be.count() == 0
        assert be.search("api") == []

    def test_drop_cluster(self):
        be = InvertedIndexBackend()
        be.upsert("m1", deploy("a"))
        be.upsert("m2", deploy("a"))
        be.drop_cluster("m1")
        assert [d["cluster"] for d in be.search("a")] == ["m2"]

    def test_cluster_scope_filter(self):
        be = InvertedIndexBackend()
        be.upsert("m1", deploy("a"))
        be.upsert("m2", deploy("a"))
        assert len(be.search("a", clusters=["m1"])) == 1


class TestRegistryBackendRouting:
    def test_opensearch_registry_feeds_indexer(self):
        cp = ControlPlane()
        cp.join_cluster(new_cluster("member1", cpu="10", memory="10Gi"))
        cp.join_cluster(new_cluster("member2", cpu="10", memory="10Gi"))
        cp.settle()
        for name in ("member1", "member2"):
            cp.members.get(name).apply(deploy(f"app-{name}", labels={"team": "core"}))
        cp.store.apply(
            ResourceRegistry(
                meta=ObjectMeta(name="indexed"),
                spec=ResourceRegistrySpec(
                    resource_selectors=[{"apiVersion": "apps/v1", "kind": "Deployment"}],
                    backend="opensearch",
                ),
            )
        )
        cp.settle()
        hits = cp.search.search("label:team=core")
        assert {d["cluster"] for d in hits} == {"member1", "member2"}
        # tokenized name search: "app" AND "member1"
        hits = cp.search.search("app member1 kind:deployment")
        assert [d["name"] for d in hits] == ["app-member1"]

    def test_cache_registry_does_not_index(self):
        cp = ControlPlane()
        cp.join_cluster(new_cluster("member1", cpu="10", memory="10Gi"))
        cp.settle()
        cp.members.get("member1").apply(deploy("plain"))
        cp.store.apply(
            ResourceRegistry(
                meta=ObjectMeta(name="cached"),
                spec=ResourceRegistrySpec(
                    resource_selectors=[{"apiVersion": "apps/v1", "kind": "Deployment"}],
                ),
            )
        )
        cp.settle()
        # cache serves it, the indexer stays empty
        assert cp.search.cache.get("apps/v1/Deployment", "default", "plain") is not None
        assert cp.search.search("plain") == []


class TestIndexerLifecycle:
    def test_member_deletion_removes_document(self):
        cp = ControlPlane()
        cp.join_cluster(new_cluster("member1", cpu="10", memory="10Gi"))
        cp.settle()
        member = cp.members.get("member1")
        member.apply(deploy("ephemeral"))
        cp.store.apply(
            ResourceRegistry(
                meta=ObjectMeta(name="idx"),
                spec=ResourceRegistrySpec(
                    resource_selectors=[{"apiVersion": "apps/v1", "kind": "Deployment"}],
                    backend="opensearch",
                ),
            )
        )
        cp.settle()
        assert len(cp.search.search("ephemeral")) == 1
        member.delete("apps/v1/Deployment", "default", "ephemeral")
        cp.search_controller_sweep() if hasattr(cp, "search_controller_sweep") else cp.search.worker.enqueue("idx")
        cp.settle()
        assert cp.search.search("ephemeral") == []

    def test_registry_deletion_removes_documents(self):
        cp = ControlPlane()
        cp.join_cluster(new_cluster("member1", cpu="10", memory="10Gi"))
        cp.settle()
        cp.members.get("member1").apply(deploy("tracked"))
        cp.store.apply(
            ResourceRegistry(
                meta=ObjectMeta(name="idx"),
                spec=ResourceRegistrySpec(
                    resource_selectors=[{"apiVersion": "apps/v1", "kind": "Deployment"}],
                    backend="opensearch",
                ),
            )
        )
        cp.settle()
        assert len(cp.search.search("tracked")) == 1
        cp.store.delete("ResourceRegistry", "idx")
        cp.settle()
        assert cp.search.search("tracked") == []
