"""The remaining reference admission handlers (webhook.go:161-183 full set):
OP mutate, Work/RB/MCS permanent-id mutators + manifest prune, FederatedHPA
defaults, MCI validation, interpreter-webhook-config validation, deletion
protection on Delete."""

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.work import Work, WorkSpec
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.interpreter.webhook import (
    InterpreterWebhook,
    ResourceInterpreterWebhookConfiguration,
    RuleWithOperations,
    WebhookClientConfig,
)
from karmada_tpu.webhook.chain import (
    DELETION_PROTECTION_LABEL,
    PERMANENT_ID_LABEL,
    ValidationError,
    default_admission_chain,
    mutate_work,
    validate_interpreter_webhook_configuration,
    validate_multicluster_ingress,
)


def webhook_config(**overrides):
    kw = dict(
        name="hooks.example.io",
        client_config=WebhookClientConfig(url="https://hooks.example:8443/interpret"),
        rules=[RuleWithOperations(operations=["InterpretHealth"])],
    )
    kw.update(overrides)
    return ResourceInterpreterWebhookConfiguration(
        meta=ObjectMeta(name="cfg"), webhooks=[InterpreterWebhook(**kw)]
    )


class TestInterpreterWebhookConfigValidation:
    def test_valid_config_passes(self):
        validate_interpreter_webhook_configuration(webhook_config())

    def test_duplicate_names_denied(self):
        config = webhook_config()
        config.webhooks.append(config.webhooks[0])
        with pytest.raises(ValidationError, match="duplicate"):
            validate_interpreter_webhook_configuration(config)

    def test_missing_url_denied(self):
        with pytest.raises(ValidationError, match="clientConfig.url"):
            validate_interpreter_webhook_configuration(
                webhook_config(client_config=WebhookClientConfig())
            )

    def test_unknown_operation_denied(self):
        with pytest.raises(ValidationError, match="unsupported operations"):
            validate_interpreter_webhook_configuration(
                webhook_config(rules=[RuleWithOperations(operations=["Mangle"])])
            )


class TestMultiClusterIngressValidation:
    def test_valid_rules(self):
        validate_multicluster_ingress(
            type("MCI", (), {"spec": type("S", (), {"rules": [
                {"http": {"paths": [{"path": "/api", "pathType": "Prefix",
                                     "backend": {"service": {"name": "web"}}}]}}
            ]})()})()
        )

    def test_bad_path_type_denied(self):
        with pytest.raises(ValidationError, match="pathType"):
            validate_multicluster_ingress(
                type("MCI", (), {"spec": type("S", (), {"rules": [
                    {"http": {"paths": [{"path": "/x", "pathType": "Regex",
                                         "backend": {"service": {"name": "w"}}}]}}
                ]})()})()
            )

    def test_relative_path_denied(self):
        with pytest.raises(ValidationError, match="absolute"):
            validate_multicluster_ingress(
                type("MCI", (), {"spec": type("S", (), {"rules": [
                    {"http": {"paths": [{"path": "x", "pathType": "Prefix",
                                         "backend": {"service": {"name": "w"}}}]}}
                ]})()})()
            )


class TestWorkMutation:
    def test_permanent_id_and_manifest_prune(self):
        manifest = Resource(
            api_version="apps/v1", kind="Deployment",
            meta=ObjectMeta(name="m", namespace="default", uid="uid-raw",
                            resource_version=42, creation_timestamp=123.0),
            spec={"replicas": 1},
            status={"readyReplicas": 1},
        )
        work = Work(meta=ObjectMeta(name="w", namespace="exec-m1"),
                    spec=WorkSpec(workload=[manifest]))
        mutate_work(work)
        assert work.meta.labels[PERMANENT_ID_LABEL]
        first_id = work.meta.labels[PERMANENT_ID_LABEL]
        # pruning acts on a copy in the work; the caller's object is intact
        pruned = work.spec.workload[0]
        assert pruned.status == {}
        assert pruned.meta.uid == "" and pruned.meta.resource_version == 0
        assert manifest.status == {"readyReplicas": 1}
        assert manifest.meta.uid == "uid-raw"
        mutate_work(work)  # idempotent: id sticks
        assert work.meta.labels[PERMANENT_ID_LABEL] == first_id


class TestDeletionProtection:
    def test_protected_template_survives_delete(self):
        cp = ControlPlane()
        protected = Resource(
            api_version="v1", kind="ConfigMap",
            meta=ObjectMeta(name="keep", namespace="default",
                            labels={DELETION_PROTECTION_LABEL: "Always"}),
        )
        cp.store.apply(protected)
        with pytest.raises(ValidationError, match="protected"):
            cp.store.delete("Resource", "default/keep")
        assert cp.store.get("Resource", "default/keep") is not None
        # removing the label unlocks deletion
        protected.meta.labels.pop(DELETION_PROTECTION_LABEL)
        cp.store.apply(protected)
        cp.store.delete("Resource", "default/keep")
        assert cp.store.get("Resource", "default/keep") is None

    def test_lenient_value_allows_delete(self):
        cp = ControlPlane()
        obj = Resource(
            api_version="v1", kind="ConfigMap",
            meta=ObjectMeta(name="soft", namespace="default",
                            labels={DELETION_PROTECTION_LABEL: "Never"}),
        )
        cp.store.apply(obj)
        cp.store.delete("Resource", "default/soft")
        assert cp.store.get("Resource", "default/soft") is None


class TestPermanentIdMutators:
    def test_binding_and_mcs_get_ids_through_the_chain(self):
        chain = default_admission_chain()
        from karmada_tpu.api.networking import MultiClusterService
        from karmada_tpu.api.work import ResourceBinding

        rb = ResourceBinding(meta=ObjectMeta(name="b", namespace="default"))
        chain.admit("ResourceBinding", rb)
        assert rb.meta.labels[PERMANENT_ID_LABEL]
        mcs = MultiClusterService(meta=ObjectMeta(name="s", namespace="default"))
        chain.admit("MultiClusterService", mcs)
        assert mcs.meta.labels[PERMANENT_ID_LABEL]

    def test_override_policy_selector_namespace_defaulted(self):
        chain = default_admission_chain()
        from karmada_tpu.api.policy import OverridePolicy

        op = OverridePolicy(meta=ObjectMeta(name="op", namespace="team-a"))
        sel = type("Sel", (), {"namespace": ""})()
        op.spec.resource_selectors = [sel]
        chain.admit("OverridePolicy", op)
        assert sel.namespace == "team-a"


class TestMutationSafety:
    def test_work_prune_does_not_corrupt_aliased_store_object(self):
        """NamespaceSync aliases live store objects into Work.spec.workload;
        pruning must act on copies."""
        cp = ControlPlane()
        cp.join_cluster(__import__("karmada_tpu.utils.builders", fromlist=["new_cluster"]).new_cluster("member1", cpu="10", memory="10Gi"))
        cp.settle()
        ns = Resource(api_version="v1", kind="Namespace",
                      meta=ObjectMeta(name="team-x"), status={"phase": "Active"})
        cp.store.apply(ns)
        cp.settle()
        stored = cp.store.get("Resource", "team-x")
        assert stored.meta.uid  # live object untouched by work pruning
        assert stored.status == {"phase": "Active"}

    def test_fhpa_explicit_zero_still_denied(self):
        from karmada_tpu.api.autoscaling import FederatedHPA, FederatedHPASpec, ScaleTargetRef

        chain = default_admission_chain()
        hpa = FederatedHPA(
            meta=ObjectMeta(name="h", namespace="default"),
            spec=FederatedHPASpec(min_replicas=0, max_replicas=5,
                                  scale_target_ref=ScaleTargetRef(name="web")),
        )
        with pytest.raises(ValidationError, match="minReplicas"):
            chain.admit("FederatedHPA", hpa)
