"""ISSUE 12: per-wave telemetry history, the device-byte ledger and the
perf-regression guard.

Covers the tentpole surfaces — the end_wave sampler (row schema, engine
pass-stat aggregation, counter deltas), ring-cap eviction accounting
under a multi-thread open/close-wave hammer (no torn rows), the
``/debug/history?window=N`` pagination contract, breach context on the
flight path — plus the satellites: ``coverage_degraded`` surfacing,
bucket-interpolated ``Histogram.quantile`` against exact synthetic
values (and its exposition-parser twin), and benchguard fixture
semantics (synthetic 2x regression fires non-zero, within-band noise
passes, missing metric is a loud error, never a silent pass)."""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from karmada_tpu.utils.history import (  # noqa: E402
    HISTORY_SERIES,
    ROW_IDENTITY_FIELDS,
    WaveHistory,
    render_breach_table,
    render_history_schema_table,
    render_history_table,
)
from karmada_tpu.utils.tracing import (  # noqa: E402
    WaveTracer,
    render_attribution_table,
    stitch_dumps,
    trace_debug_doc,
)

#: row keys every sampled row must carry, fully formed (torn-row check)
_REQUIRED_KEYS = tuple(name for name, _ in ROW_IDENTITY_FIELDS) + tuple(
    HISTORY_SERIES
)


def _one_wave(tr: WaveTracer, *, bindings: int = 50, packed: int = 5):
    tr.begin_wave("test")
    with tr.span("settle"):
        with tr.span("scheduler.pass") as sp:
            sp.attrs["bindings"] = bindings
            with tr.span("scheduler.solve") as sv:
                sv.attrs["rows_packed"] = packed
                sv.attrs["rows_replayed"] = bindings - packed
    return tr.end_wave()


class TestWaveSampling:
    def test_row_schema_complete(self):
        tr = WaveTracer(capacity=256)
        wave = _one_wave(tr, bindings=70, packed=7)
        row = tr.history.row_for(wave)
        assert row is not None
        for key in _REQUIRED_KEYS:
            assert key in row, f"row missing {key}"
        assert row["wave"] == wave
        assert row["bindings"] == 70
        assert row["rows_packed"] == 7
        assert row["rows_replayed"] == 63
        assert row["solve_batches"] == 1
        assert row["wall_s"] > 0
        assert row["stitched"] is False

    def test_sampler_failure_never_aborts_the_wave(self, monkeypatch):
        tr = WaveTracer(capacity=64)
        monkeypatch.setattr(
            type(tr.history), "_build_row",
            lambda self, t, w: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        wave = _one_wave(tr)  # must not raise
        assert wave > 0
        assert tr.history.rows() == []

    def test_cap_zero_disables_sampling(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_HISTORY_CAP", "0")
        tr = WaveTracer(capacity=64)
        _one_wave(tr)
        assert not tr.history.enabled
        assert tr.history.rows() == []

    def test_digests_exact_quantiles(self):
        h = WaveHistory(cap=16)
        for i, wall in enumerate([1.0, 2.0, 3.0, 4.0]):
            h._rows.append(
                {"wave": i, "wall_s": wall, "phases": {"settle": wall}}
            )
        d = h.digests()
        assert d["window"] == 4
        assert d["series"]["wall_s"]["p50"] == pytest.approx(2.5)
        assert d["series"]["wall_s"]["p95"] == pytest.approx(3.85)
        assert d["series"]["phases.settle"]["p50"] == pytest.approx(2.5)

    def test_breach_context_excludes_breaching_row(self):
        tr = WaveTracer(capacity=256)
        for _ in range(4):
            wave = _one_wave(tr)
        ctx = tr.history.breach_context(wave)
        assert ctx["row"]["wave"] == wave
        assert ctx["recent"]["window"] == 3
        table = render_breach_table(ctx)
        assert f"wave {wave} vs last 3" in table
        assert "wall_s" in table

    def test_history_table_marks_degraded_coverage(self):
        rows = [{
            "wave": 9, "wall_s": 1.0, "coverage": 0.5,
            "coverage_degraded": True, "bindings_s": 10.0,
        }]
        assert "50.0!" in render_history_table(rows)


class TestConcurrencyHammer:
    def test_no_torn_rows_and_counted_evictions(self, monkeypatch):
        """Multi-thread open/close-wave + sample hammer: every row in
        the ring is COMPLETE (built before append, read under the
        lock), the ring never exceeds its cap, and evictions are
        counted exactly."""
        monkeypatch.setenv("KARMADA_TPU_HISTORY_CAP", "8")
        tr = WaveTracer(capacity=512)
        h = tr.history
        assert h.cap == 8
        errors: list = []
        n_threads, per_thread = 4, 25

        def writer(tid: int):
            try:
                for _ in range(per_thread):
                    _one_wave(tr)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        def reader():
            try:
                for _ in range(200):
                    for row in h.rows():
                        for key in _REQUIRED_KEYS:
                            assert key in row, f"torn row: no {key}"
                    h.digests(window=4)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [
            threading.Thread(target=writer, args=(i,))
            for i in range(n_threads)
        ] + [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # begin_wave while another thread's wave is open reuses no id:
        # each begin mints a fresh wave, but an end_wave can close a
        # wave another thread opened — sampled counts CLOSES, bounded
        # by the number of begin/end pairs
        assert 0 < h.sampled <= n_threads * per_thread
        assert len(h.rows()) == min(h.sampled, 8)
        assert h.evicted == max(h.sampled - 8, 0)

    def test_rows_returns_copies(self):
        tr = WaveTracer(capacity=64)
        wave = _one_wave(tr)
        tr.history.rows()[0]["wall_s"] = -1
        assert tr.history.row_for(wave)["wall_s"] != -1


class TestDebugHistoryEndpoint:
    def test_window_pagination_and_digests(self):
        from karmada_tpu.utils.metrics import MetricsServer
        from karmada_tpu.utils.tracing import tracer

        tracer.clear()
        try:
            for _ in range(6):
                _one_wave(tracer)
            srv = MetricsServer()
            port = srv.start()
            try:
                def get(query: str) -> dict:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/debug/history{query}",
                        timeout=10,
                    ) as resp:
                        return json.loads(resp.read().decode())

                full = get("")
                assert len(full["rows"]) == 6
                assert full["sampled"] == 6
                assert full["digests"]["window"] == 6

                page = get("?window=2")
                assert len(page["rows"]) == 2
                assert page["digests"]["window"] == 2
                # pagination keeps the NEWEST rows
                assert (
                    page["rows"][-1]["wave"] == full["rows"][-1]["wave"]
                )

                one = get(f"?wave={full['rows'][0]['wave']}")
                assert len(one["rows"]) == 1

                lean = get("?window=3&digests=0")
                assert "digests" not in lean

                with pytest.raises(urllib.error.HTTPError) as err:
                    get("?window=bogus")
                assert err.value.code == 400
            finally:
                srv.stop()
        finally:
            tracer.clear()

    def test_top_aggregates_endpoint(self):
        from karmada_tpu import cli
        from karmada_tpu.utils.metrics import MetricsServer, settle_seconds
        from karmada_tpu.utils.tracing import tracer

        tracer.clear()
        try:
            for _ in range(3):
                _one_wave(tracer, bindings=40)
            settle_seconds.observe(0.25)
            srv = MetricsServer()
            port = srv.start()
            try:
                doc = cli.cmd_plane_top(
                    metrics=f"127.0.0.1:{port}", window=4
                )
                (name, entry), = doc["procs"].items()
                assert entry["rows"], "no history rows fetched"
                assert "settle_p50_s" in entry
                table = cli.render_top(doc)
                assert "bind/s" in table
            finally:
                srv.stop()
        finally:
            tracer.clear()


class TestCoverageDegraded:
    def test_local_summary_flags_dropped_waves(self):
        tr = WaveTracer(capacity=16)
        tr.begin_wave("t")
        with tr.span("settle"):
            for i in range(40):  # outgrow the ring mid-wave
                tr.record("scheduler.pack", 0.001)
        wave = tr.end_wave()
        s = tr.wave_summary(wave)
        assert s["dropped"] > 0
        assert s["coverage_degraded"] is True
        assert "DEGRADED" in render_attribution_table(s)
        # the sampled row carries the flag too
        assert tr.history.row_for(wave)["coverage_degraded"] is True

    def test_healthy_summary_not_degraded(self):
        tr = WaveTracer(capacity=256)
        wave = _one_wave(tr)
        s = tr.wave_summary(wave)
        assert s["coverage_degraded"] is False
        assert "DEGRADED" not in render_attribution_table(s)

    def test_stitched_summary_carries_device_and_compile(self):
        """Stitched rows must not read zeros for series the local rows
        populate: stitch_spans computes device_s/compile_s with the
        local summary's rule (kind attr / compile flag)."""
        from karmada_tpu.utils.tracing import stitch_spans

        spans = [
            {"name": "settle", "wave": 1, "span_id": 1,
             "parent_id": None, "trace_id": "t", "duration_s": 1.0,
             "attrs": {}, "proc": "plane"},
            {"name": "kernel.device", "wave": 1, "span_id": 2,
             "parent_id": 1, "trace_id": "t", "duration_s": 0.25,
             "attrs": {"kind": "device", "compile": True},
             "proc": "plane"},
        ]
        s = stitch_spans(spans, 1, "t")
        assert s["device_s"] == pytest.approx(0.25)
        assert s["compile_s"] == pytest.approx(0.25)

    def test_stitch_handoff_consumed_once(self):
        tr = WaveTracer(capacity=64)
        wave = _one_wave(tr)
        doc = {"waves": [], "spans": [], "procs": [], "dropped": {}}
        with tr._lock:
            tr._stitch_handoff = (wave, doc)
        assert tr.consume_stitch_handoff(wave) is doc
        assert tr.consume_stitch_handoff(wave) is None  # one-shot
        with tr._lock:
            tr._stitch_handoff = (wave, doc)
        assert tr.consume_stitch_handoff(wave + 1) is None  # wrong wave

    def test_stitched_summary_sums_peer_drops(self):
        tr = WaveTracer(capacity=16)
        tr.begin_wave("t")
        with tr.span("settle"):
            for _ in range(40):
                tr.record("scheduler.pack", 0.001)
        wave = tr.end_wave()
        local = trace_debug_doc(tracer_obj=tr)
        doc = stitch_dumps(local, {}, wave=wave)
        (stitched,) = doc["waves"]
        assert stitched["dropped"] == tr.wave_summary(wave)["dropped"]
        assert stitched["coverage_degraded"] is True
        assert "DEGRADED" in render_attribution_table(stitched)


class TestHistogramQuantile:
    def test_exact_interpolation_on_synthetic_observations(self):
        from karmada_tpu.utils.metrics import Histogram

        h = Histogram("karmada_tpu_test_q_seconds", buckets=(1, 2, 4, 8))
        for v in (0.5, 1.5, 3.0, 6.0):
            h.observe(v)
        # ranks: q*4 → interpolate within the landing bucket
        assert h.quantile(0.25) == pytest.approx(1.0)  # first bucket: 0→1
        assert h.quantile(0.5) == pytest.approx(2.0)
        assert h.quantile(0.75) == pytest.approx(4.0)
        assert h.quantile(0.875) == pytest.approx(6.0)  # mid (4, 8]
        assert h.quantile(1.0) == pytest.approx(8.0)
        assert h.quantile(0.5, missing="labels") is None

    def test_rank_beyond_last_bound_answers_highest_finite(self):
        from karmada_tpu.utils.metrics import Histogram

        h = Histogram("karmada_tpu_test_q2_seconds", buckets=(1, 2))
        h.observe(50.0)  # lands in +Inf only
        assert h.quantile(0.5) == pytest.approx(2.0)

    def test_exposition_parser_matches_live_histogram(self):
        """The CLI path (exposition text → shared bucket_quantile) and
        the in-process Histogram.quantile must answer identically."""
        from karmada_tpu import cli
        from karmada_tpu.utils.metrics import Registry

        reg = Registry()
        h = reg.histogram(
            "karmada_tpu_test_q3_seconds", "t", buckets=(0.1, 1, 5, 10)
        )
        for v in (0.05, 0.5, 0.7, 2.0, 3.0, 7.0, 30.0):
            h.observe(v)
        text = reg.render()
        for q in (0.1, 0.5, 0.9, 0.99):
            parsed = cli.exposition_quantile(
                text, "karmada_tpu_test_q3_seconds", q
            )
            assert parsed[()] == pytest.approx(h.quantile(q)), q


# --------------------------------------------------------------------------
# benchguard
# --------------------------------------------------------------------------

from tools import benchguard  # noqa: E402


def _write(path: Path, record: dict) -> Path:
    path.write_text(json.dumps(record))
    return path


_BASELINE = {
    "metric": "observability_wave_20kx512",
    "value": 4.0,
    "coverage_vs_wall": 0.98,
    "bindings_s": 5000.0,
}


class TestBenchguard:
    def test_synthetic_2x_regression_fires_nonzero(self, tmp_path):
        _write(tmp_path / "BENCH_OBS_r01.json", _BASELINE)
        fresh = _write(
            tmp_path / "fresh.json",
            {**_BASELINE, "value": 8.0, "bindings_s": 2500.0},
        )
        code, report = benchguard.check_record(fresh, root=tmp_path)
        assert code == 1
        verdicts = {v["metric"]: v["verdict"] for v in report["verdicts"]}
        assert verdicts["value"] == "regression"  # 2.0 >= band 2.0 FIRES
        assert verdicts["bindings_s"] == "regression"
        assert verdicts["coverage_vs_wall"] == "ok"
        assert "REGRESSION" in report["table"]

    def test_within_band_noise_passes(self, tmp_path):
        _write(tmp_path / "BENCH_OBS_r01.json", _BASELINE)
        fresh = _write(
            tmp_path / "fresh.json",
            {**_BASELINE, "value": 4.8, "bindings_s": 4200.0,
             "coverage_vs_wall": 0.95},
        )
        code, report = benchguard.check_record(fresh, root=tmp_path)
        assert code == 0, report["table"]
        assert all(
            v["verdict"] in ("ok", "improved", "baseline-missing",
                             "absent")
            for v in report["verdicts"]
        )

    def test_missing_metric_is_a_loud_error(self, tmp_path):
        _write(tmp_path / "BENCH_OBS_r01.json", _BASELINE)
        fresh_rec = {**_BASELINE, "value": 4.1}
        del fresh_rec["coverage_vs_wall"]
        fresh = _write(tmp_path / "fresh.json", fresh_rec)
        code, report = benchguard.check_record(fresh, root=tmp_path)
        assert code == 1
        verdicts = {v["metric"]: v["verdict"] for v in report["verdicts"]}
        assert verdicts["coverage_vs_wall"] == "missing"

    def test_baseline_predating_a_metric_passes_but_is_reported(
        self, tmp_path
    ):
        old = dict(_BASELINE)
        del old["bindings_s"]
        _write(tmp_path / "BENCH_OBS_r01.json", old)
        fresh = _write(tmp_path / "fresh.json", dict(_BASELINE))
        code, report = benchguard.check_record(fresh, root=tmp_path)
        assert code == 0
        verdicts = {v["metric"]: v["verdict"] for v in report["verdicts"]}
        assert verdicts["bindings_s"] == "baseline-missing"

    def test_improvement_is_reported_not_failed(self, tmp_path):
        _write(tmp_path / "BENCH_OBS_r01.json", _BASELINE)
        fresh = _write(
            tmp_path / "fresh.json",
            {**_BASELINE, "value": 1.0, "bindings_s": 20000.0},
        )
        code, report = benchguard.check_record(fresh, root=tmp_path)
        assert code == 0
        verdicts = {v["metric"]: v["verdict"] for v in report["verdicts"]}
        assert verdicts["value"] == "improved"

    def test_newest_committed_record_baselines(self, tmp_path):
        _write(tmp_path / "BENCH_OBS_r01.json",
               {**_BASELINE, "value": 100.0})
        _write(tmp_path / "BENCH_OBS_r02.json", _BASELINE)
        fresh = _write(tmp_path / "fresh.json",
                       {**_BASELINE, "value": 4.2})
        code, report = benchguard.check_record(fresh, root=tmp_path)
        assert code == 0
        assert report["baseline"].endswith("BENCH_OBS_r02.json")

    def test_no_committed_baseline_refuses_loudly(self, tmp_path):
        fresh = _write(tmp_path / "fresh.json", dict(_BASELINE))
        with pytest.raises(SystemExit, match="no committed BENCH_"):
            benchguard.check_record(fresh, root=tmp_path)

    def test_unknown_family_refuses_loudly(self, tmp_path):
        fresh = _write(
            tmp_path / "fresh.json",
            {"metric": "mystery_tier_1x1", "value": 1.0},
        )
        with pytest.raises(SystemExit, match="no guard spec"):
            benchguard.check_record(fresh, root=tmp_path)

    def test_checked_record_never_baselines_itself(self, tmp_path):
        fresh = _write(tmp_path / "BENCH_OBS_r03.json", dict(_BASELINE))
        with pytest.raises(SystemExit, match="no committed BENCH_"):
            benchguard.check_record(fresh, root=tmp_path)

    def test_churn_tier_guard_lifecycle(self, tmp_path):
        """The incremental-solve guard across its adoption arc: a
        baseline predating the churn series passes (baseline-missing),
        a fresh record that DROPS the required 1% tier fails loudly
        (missing), and once both sides carry it the band fires on a
        drift back toward full-solve cost."""
        engine = {
            "metric": "p50_engine_schedule_100kx5000_dynamic_weight",
            "value": 0.31,
        }
        # committed trajectory predates the churn series entirely
        _write(tmp_path / "BENCH_r01.json", engine)
        fresh = _write(
            tmp_path / "fresh.json",
            {**engine, "scale1m_churn1pct_p50": 0.8},
        )
        code, report = benchguard.check_record(fresh, root=tmp_path)
        assert code == 0
        verdicts = {v["metric"]: v["verdict"] for v in report["verdicts"]}
        assert verdicts["scale1m_churn1pct_p50"] == "baseline-missing"

        # a default record that stops carrying the 1% tier means the
        # delta path (or its measurement) silently died: required fires
        dropped = _write(tmp_path / "dropped.json", dict(engine))
        code, report = benchguard.check_record(dropped, root=tmp_path)
        assert code == 1
        verdicts = {v["metric"]: v["verdict"] for v in report["verdicts"]}
        assert verdicts["scale1m_churn1pct_p50"] == "missing"

        # with a churn-carrying baseline, a 4x drift back toward
        # full-solve cost is a regression; the unrequired 0.1%/10%
        # tiers ride along without failing when absent
        _write(
            tmp_path / "BENCH_r02.json",
            {**engine, "scale1m_churn1pct_p50": 0.8},
        )
        slow = _write(
            tmp_path / "slow.json",
            {**engine, "scale1m_churn1pct_p50": 3.2},
        )
        code, report = benchguard.check_record(slow, root=tmp_path)
        assert code == 1
        verdicts = {v["metric"]: v["verdict"] for v in report["verdicts"]}
        assert verdicts["scale1m_churn1pct_p50"] == "regression"
        assert verdicts["scale1m_churn0p1pct_p50"] == "absent"

    def test_cli_exit_codes(self, tmp_path):
        _write(tmp_path / "BENCH_OBS_r01.json", _BASELINE)
        good = _write(tmp_path / "fresh.json", dict(_BASELINE))
        bad = _write(
            tmp_path / "slow.json", {**_BASELINE, "value": 9.0}
        )
        assert benchguard.main(
            [str(good), "--root", str(tmp_path)]
        ) == 0
        assert benchguard.main(
            [str(bad), "--root", str(tmp_path), "--format", "json"]
        ) == 1


class TestFlightHistoryContext:
    def test_breach_record_carries_history_and_analyzes(
        self, tmp_path, monkeypatch
    ):
        """A seeded SLO breach attaches the breaching wave's history row
        + recent-window digests, and trace analyze renders the
        breach-vs-recent table identically offline."""
        from karmada_tpu.utils import tracing as trc

        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "10000")
        monkeypatch.setenv("KARMADA_TPU_FLIGHT_DIR", str(tmp_path))
        tr = WaveTracer(capacity=256)
        for _ in range(3):
            _one_wave(tr)
        # the breaching wave: force the SLO under its wall
        tr.begin_wave("breach")
        with tr.span("settle"):
            time.sleep(0.02)
        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0.001")
        wave = tr.end_wave()
        records = trc.load_flight_records(tmp_path / "flight.jsonl")
        rec = records[-1]
        assert rec["wave"] == wave
        assert rec["history"]["row"]["wave"] == wave
        assert rec["history"]["recent"]["window"] == 3
        analysis = trc.analyze_record(rec)
        assert analysis["identical"] is True
        assert f"history: wave {wave} vs last 3" in analysis["table"]

    def test_analyze_tolerates_pre_upgrade_records(self, tmp_path,
                                                   monkeypatch):
        """A flight record whose summary predates the coverage_degraded/
        dropped keys must still report identical=True — a schema
        ADDITION is not a purity failure — while a genuinely divergent
        summary still fails."""
        from karmada_tpu.utils import tracing as trc

        monkeypatch.setenv("KARMADA_TPU_TRACE_SLO_SECONDS", "0")
        monkeypatch.setenv("KARMADA_TPU_FLIGHT_DIR", str(tmp_path))
        tr = WaveTracer(capacity=64)
        _one_wave(tr)
        rec = trc.load_flight_records(tmp_path / "flight.jsonl")[-1]
        old = dict(rec)
        old["summary"] = {
            k: v for k, v in rec["summary"].items()
            if k not in ("coverage_degraded", "dropped")
        }
        assert trc.analyze_record(old)["identical"] is True
        divergent = dict(old)
        divergent["summary"] = {
            **old["summary"], "total_s": old["summary"]["total_s"] + 1
        }
        assert trc.analyze_record(divergent)["identical"] is False


class TestDeviceBytesLedger:
    def test_steady_passes_hold_resident_bytes_constant(self):
        """The ledger answers exact nbytes, steady passes keep it
        constant, and the gauge's samples sum to the same total with
        honest platform labels."""
        from karmada_tpu.scheduler import (
            BindingProblem,
            ClusterSnapshot,
            TensorScheduler,
        )
        from karmada_tpu.utils.builders import (
            dynamic_weight_placement,
            synthetic_fleet,
        )
        from karmada_tpu.utils.metrics import device_bytes as gauge
        from karmada_tpu.utils.quantity import parse_resource_list

        req = parse_resource_list({"cpu": "250m", "memory": "512Mi"})
        snap = ClusterSnapshot(synthetic_fleet(40, seed=3))
        pl = dynamic_weight_placement()
        problems = [
            BindingProblem(
                key=f"b{i}", placement=pl, replicas=(i % 6) + 1,
                requests=req, gvk="apps/v1/Deployment",
            )
            for i in range(300)
        ]
        eng = TensorScheduler(snap, trace_manifest="")
        eng.schedule(problems)
        first = eng.device_bytes()
        assert first["packed_grid"] > 0
        assert first["slot_tables"] > 0
        eng.schedule(problems)
        assert eng.device_bytes() == first, "steady pass moved the ledger"
        samples = gauge.samples()
        total = sum(
            v for k, v in samples.items()
            if dict(k).get("kind") in first
        )
        assert int(total) == sum(first.values())
        platforms = {dict(k).get("platform") for k in samples}
        assert platforms <= {"cpu"}, (
            "forced-host bytes must label platform=cpu, never a device "
            f"platform: {platforms}"
        )
        # the history row picks the level up off the gauge
        tr = WaveTracer(capacity=64)
        wave = _one_wave(tr)
        assert tr.history.row_for(wave)["device_bytes"] >= int(total)


def test_schema_table_lists_every_series():
    table = render_history_schema_table()
    for name in HISTORY_SERIES:
        assert f"`{name}`" in table
    for name, _ in ROW_IDENTITY_FIELDS:
        assert f"`{name}`" in table
