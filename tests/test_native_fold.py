"""Native host-loop extension (karmada_tpu.native): identity vs the
numpy fallback, compiled on demand with the baked toolchain."""

import numpy as np
import pytest

from karmada_tpu import native


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


def test_decoders_match_numpy(rng):
    raw3 = rng.integers(0, 256, 3 * 50_000).astype(np.uint8)
    want3 = (
        raw3[0::3].astype(np.int32)
        | (raw3[1::3].astype(np.int32) << 8)
        | (raw3[2::3].astype(np.int32) << 16)
    )
    assert np.array_equal(native.decode3(raw3), want3)
    raw2 = rng.integers(0, 256, 2 * 50_000).astype(np.uint8)
    want2 = raw2[0::2].astype(np.int32) | (raw2[1::2].astype(np.int32) << 8)
    assert np.array_equal(native.decode2(raw2), want2)


def test_fold_matches_numpy_referent(rng):
    cap, k = 3000, 24
    for rep in range(25):
        mirror_c = rng.integers(0, 9, (cap, k)).astype(np.int32)
        mirror_np = mirror_c.copy()
        n = int(rng.integers(1, 500))
        rows = rng.choice(cap, n, replace=False).astype(np.int64)
        # every other repetition draws counts PAST the mirror width so the
        # clamp branch runs: the row keeps only its first k entries while
        # the stream offset advances by the full count
        hi = k + 1 if rep % 2 == 0 else k + 5
        counts = rng.integers(0, hi, n).astype(np.int64)
        stream = rng.integers(1, 1 << 20, int(counts.sum())).astype(np.int32)
        native.fold_entries(mirror_c, rows, counts, stream)
        total = int(counts.sum())
        mirror_np[rows] = 0
        fr = np.repeat(rows, counts)
        st = np.cumsum(counts) - counts
        cols = np.arange(total) - np.repeat(st, counts)
        ok = cols < k
        mirror_np[fr[ok], cols[ok]] = stream[:total][ok]
        assert np.array_equal(mirror_c, mirror_np)


def test_fallback_paths_are_equivalent(rng, monkeypatch):
    """With the library gated off, the same calls produce identical
    results through the numpy forms."""
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    raw3 = rng.integers(0, 256, 3 * 5000).astype(np.uint8)
    want3 = (
        raw3[0::3].astype(np.int32)
        | (raw3[1::3].astype(np.int32) << 8)
        | (raw3[2::3].astype(np.int32) << 16)
    )
    assert np.array_equal(native.decode3(raw3), want3)
    mirror = rng.integers(0, 9, (100, 8)).astype(np.int32)
    rows = np.array([3, 50], np.int64)
    counts = np.array([2, 0], np.int64)
    stream = np.array([11, 12], np.int32)
    native.fold_entries(mirror, rows, counts, stream)
    assert list(mirror[3]) == [11, 12, 0, 0, 0, 0, 0, 0]
    assert not mirror[50].any()


def test_apply_deltas_matches_dict_referent(rng):
    """Merge semantics: newcount 0 removes, existing updates, new inserts
    in site order; rows clamp at k_res; native and fallback agree."""
    cap, k = 500, 16
    for rep in range(40):
        n_sites = int(rng.integers(20, 120))
        mirror_c = np.zeros((cap, k), np.int32)
        rows = rng.choice(cap, int(rng.integers(1, 60)), replace=False)
        rows = rows.astype(np.int64)
        ref: dict = {}
        for r in rows:
            sites = np.sort(rng.choice(n_sites, int(rng.integers(0, k + 1)),
                                       replace=False))
            cnts = rng.integers(1, 200, len(sites))
            run = [(int(s) << 8) | int(c) for s, c in zip(sites, cnts)]
            mirror_c[r, : len(run)] = run
            ref[int(r)] = dict(zip(map(int, sites), map(int, cnts)))
        mirror_np = mirror_c.copy()
        dcounts = rng.integers(0, 10, len(rows)).astype(np.int64)
        stream = []
        for r, nd in zip(rows, dcounts):
            dsites = np.sort(rng.choice(n_sites, int(nd), replace=False))
            for s in dsites:
                # ~1/3 removals (newcount 0), else a set/insert
                c = 0 if rng.random() < 0.33 else int(rng.integers(1, 200))
                stream.append((int(s) << 9) | (c + 1))
                if c:
                    ref[int(r)][int(s)] = c
                else:
                    ref[int(r)].pop(int(s), None)
        stream = np.asarray(stream, np.int32)
        native.apply_deltas(mirror_c, rows, dcounts, stream)
        # fallback path on a copy
        import karmada_tpu.native as nat

        saved = (nat._LIB, nat._TRIED)
        try:
            nat._LIB, nat._TRIED = None, True
            nat.apply_deltas(mirror_np, rows, dcounts, stream)
        finally:
            nat._LIB, nat._TRIED = saved
        assert np.array_equal(mirror_c, mirror_np)
        for r in rows:
            want = [
                (s << 8) | c for s, c in sorted(ref[int(r)].items())
            ][:k]
            got = [int(v) for v in mirror_c[r] if v != 0]
            assert got == want, (r, got, want)
