"""Native host-loop extension (karmada_tpu.native): identity vs the
numpy fallback, compiled on demand with the baked toolchain."""

import numpy as np
import pytest

from karmada_tpu import native


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


def test_decoders_match_numpy(rng):
    raw3 = rng.integers(0, 256, 3 * 50_000).astype(np.uint8)
    want3 = (
        raw3[0::3].astype(np.int32)
        | (raw3[1::3].astype(np.int32) << 8)
        | (raw3[2::3].astype(np.int32) << 16)
    )
    assert np.array_equal(native.decode3(raw3), want3)
    raw2 = rng.integers(0, 256, 2 * 50_000).astype(np.uint8)
    want2 = raw2[0::2].astype(np.int32) | (raw2[1::2].astype(np.int32) << 8)
    assert np.array_equal(native.decode2(raw2), want2)


def test_fold_matches_numpy_referent(rng):
    cap, k = 3000, 24
    for rep in range(25):
        mirror_c = rng.integers(0, 9, (cap, k)).astype(np.int32)
        mirror_np = mirror_c.copy()
        n = int(rng.integers(1, 500))
        rows = rng.choice(cap, n, replace=False).astype(np.int64)
        # every other repetition draws counts PAST the mirror width so the
        # clamp branch runs: the row keeps only its first k entries while
        # the stream offset advances by the full count
        hi = k + 1 if rep % 2 == 0 else k + 5
        counts = rng.integers(0, hi, n).astype(np.int64)
        stream = rng.integers(1, 1 << 20, int(counts.sum())).astype(np.int32)
        native.fold_entries(mirror_c, rows, counts, stream)
        total = int(counts.sum())
        mirror_np[rows] = 0
        fr = np.repeat(rows, counts)
        st = np.cumsum(counts) - counts
        cols = np.arange(total) - np.repeat(st, counts)
        ok = cols < k
        mirror_np[fr[ok], cols[ok]] = stream[:total][ok]
        assert np.array_equal(mirror_c, mirror_np)


def test_fallback_paths_are_equivalent(rng, monkeypatch):
    """With the library gated off, the same calls produce identical
    results through the numpy forms."""
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    raw3 = rng.integers(0, 256, 3 * 5000).astype(np.uint8)
    want3 = (
        raw3[0::3].astype(np.int32)
        | (raw3[1::3].astype(np.int32) << 8)
        | (raw3[2::3].astype(np.int32) << 16)
    )
    assert np.array_equal(native.decode3(raw3), want3)
    mirror = rng.integers(0, 9, (100, 8)).astype(np.int32)
    rows = np.array([3, 50], np.int64)
    counts = np.array([2, 0], np.int64)
    stream = np.array([11, 12], np.int32)
    native.fold_entries(mirror, rows, counts, stream)
    assert list(mirror[3]) == [11, 12, 0, 0, 0, 0, 0, 0]
    assert not mirror[50].any()
