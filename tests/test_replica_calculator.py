"""Table-driven FederatedHPA replica-calculator tests.

Mirrors the reference's calculator tables case by case
(pkg/controllers/federatedhpa/replica_calculator_test.go:114-281 resource,
:284-455 raw resource, :457-628 metric, :630-815 plain-metric grouping,
:829-1010 object / object-per-pod; metrics/utilization_test.go:67-140
ratio helpers) over the PodSample model.
"""

import pytest

from karmada_tpu.controllers.replica_calculator import (
    MetricsError,
    PodSample,
    ReplicaCalculator,
    group_pods,
    metric_usage_ratio,
    resource_utilization_ratio,
)


def pod(name, request=100, value=None, **kw):
    return PodSample(name=name, request=request, value=value, **kw)


def unready_pod(name, request=100, value=None):
    # createUnreadyPod (replica_calculator_test.go:818-827): Ready=False,
    # transition at pod start -> never been ready within the initial delay
    return PodSample(
        name=name, request=request, value=value, ready=False,
        start_age=1e9, transition_age=1e9,
    )


CALC = ReplicaCalculator(tolerance=0.1)


# -- GetResourceReplicas (replica_calculator_test.go:114-281) --------------

RESOURCE_CASES = [
    # (name, current, target_util, pods, want_replicas, want_util, want_raw)
    ("scale up", 2, 50,
     [pod("pod1", 100, 150), pod("pod2", 100, 150)], 6, 150, 150),
    ("scale down", 4, 50,
     [pod(f"pod{i}", 100, 50) for i in range(1, 5)], 4, 50, 50),
    ("no change within tolerance", 2, 50,
     [pod("pod1", 100, 52), pod("pod2", 100, 48)], 2, 50, 50),
    ("scale up with unready pods", 3, 50,
     [pod("pod1", 100, 150), pod("pod2", 100, 150),
      unready_pod("pod3", 100)], 6, 150, 150),
]


@pytest.mark.parametrize(
    "name,current,target,pods,want_n,want_util,want_raw", RESOURCE_CASES
)
def test_get_resource_replicas(name, current, target, pods, want_n,
                               want_util, want_raw):
    n, util, raw = CALC.get_resource_replicas(current, target, "cpu", pods)
    assert (n, util, raw) == (want_n, want_util, want_raw), name


def test_get_resource_replicas_calibration():
    # "Scale with calibration": calibration 0.5 doubles the proposal
    pods = [pod("pod1", 100, 150), pod("pod2", 100, 150)]
    n, util, raw = CALC.get_resource_replicas(2, 50, "cpu", pods, 0.5)
    assert (n, util, raw) == (12, 150, 150)


def test_get_resource_replicas_errors():
    with pytest.raises(MetricsError):
        CALC.get_resource_replicas(2, 50, "cpu", [])
    with pytest.raises(MetricsError):  # no metrics for any pod
        CALC.get_resource_replicas(
            2, 50, "cpu", [pod("pod1", 100), pod("pod2", 100)]
        )


def test_get_resource_replicas_missing_request():
    with pytest.raises(MetricsError):
        CALC.get_resource_replicas(
            2, 50, "cpu",
            [pod("pod1", 100, 150), pod("pod2", None, 150)],
        )


# -- GetRawResourceReplicas (:284-455) -------------------------------------

RAW_CASES = [
    ("scale up", 2, 100,
     [pod("pod1", 100, 150), pod("pod2", 100, 150)], 1.0, 3, 150),
    ("scale down", 4, 100,
     [pod(f"pod{i}", 100, 50) for i in range(1, 5)], 1.0, 2, 50),
    ("no change", 2, 100,
     [pod("pod1", 100, 100), pod("pod2", 100, 100)], 1.0, 2, 100),
    ("calibration", 2, 100,
     [pod("pod1", 100, 150), pod("pod2", 100, 150)], 0.8, 4, 150),
]


@pytest.mark.parametrize(
    "name,current,target,pods,cal,want_n,want_usage", RAW_CASES
)
def test_get_raw_resource_replicas(name, current, target, pods, cal,
                                   want_n, want_usage):
    n, usage = CALC.get_raw_resource_replicas(
        current, target, "cpu", pods, cal
    )
    assert (n, usage) == (want_n, want_usage), name


# -- GetMetricReplicas (:457-628) ------------------------------------------

METRIC_CASES = [
    ("scale up", 2, 10, {"pod1": 15, "pod2": 15},
     [pod("pod1"), pod("pod2")], 1.0, 3, 15),
    ("scale down", 4, 20, {f"pod{i}": 10 for i in range(1, 5)},
     [pod(f"pod{i}") for i in range(1, 5)], 1.0, 2, 10),
    ("no change", 2, 15, {"pod1": 15, "pod2": 15},
     [pod("pod1"), pod("pod2")], 1.0, 2, 15),
    ("calibration", 2, 10, {"pod1": 15, "pod2": 15},
     [pod("pod1"), pod("pod2")], 0.8, 4, 15),
]


@pytest.mark.parametrize(
    "name,current,target,metrics,pods,cal,want_n,want_usage", METRIC_CASES
)
def test_get_metric_replicas(name, current, target, metrics, pods, cal,
                             want_n, want_usage):
    n, usage = CALC.get_metric_replicas(current, target, metrics, pods, cal)
    assert (n, usage) == (want_n, want_usage), name


# -- calcPlainMetricReplicas grouping behaviors (:630-815) ------------------


def test_plain_scale_up_with_unready_holds():
    # ratio 1.5 > 1 with an unready pod: backfill 0 -> new ratio 1.0 is
    # within tolerance -> keep current (the reference expects 3, NOT 5)
    n, usage = CALC.get_metric_replicas(
        3, 10, {"pod1": 15, "pod2": 15},
        [pod("pod1"), pod("pod2"), unready_pod("pod3")],
    )
    assert (n, usage) == (3, 15)


def test_plain_scale_down_with_missing_pods():
    # ratio 0.5 < 1 with a missing pod: backfill the target -> new ratio
    # (5+5+10)/3/10 = 0.667 -> ceil(0.667 * 3) = 2
    n, usage = CALC.get_metric_replicas(
        3, 10, {"pod1": 5, "pod2": 5},
        [pod("pod1"), pod("pod2"), pod("pod3")],
    )
    assert (n, usage) == (2, 5)


def test_plain_no_ready_metrics_errors():
    with pytest.raises(MetricsError):
        CALC.get_metric_replicas(
            2, 10, {}, [unready_pod("pod1"), unready_pod("pod2")]
        )
    with pytest.raises(MetricsError):
        CALC.get_metric_replicas(2, 10, {}, [])


def test_group_pods_phases():
    pods = [
        pod("ok", value=10),
        PodSample(name="failed", phase="Failed", value=10),
        PodSample(name="deleted", deleted=True, value=10),
        PodSample(name="pending", phase="Pending"),
        pod("missing"),
    ]
    g = group_pods(pods, {"ok": 10, "failed": 10, "deleted": 10}, "", 300, 30)
    assert g.ready_count == 1
    assert g.ignored == {"failed", "deleted"}
    assert g.unready == {"pending"}
    assert g.missing == {"missing"}


def test_group_pods_cpu_initialization_window():
    # within the CPU initialisation period a READY pod's sample only counts
    # once a full metric window has passed since the ready transition
    fresh_sample = PodSample(
        name="warm", start_age=100, transition_age=90, sample_age=10,
        window=60, value=10,
    )
    stale_sample = PodSample(
        name="cold", start_age=100, transition_age=30, sample_age=10,
        window=60, value=10,
    )
    g = group_pods(
        [fresh_sample, stale_sample], {"warm": 10, "cold": 10}, "cpu",
        300, 30,
    )
    assert g.ready_count == 1
    assert g.unready == {"cold"}


def test_group_pods_cpu_never_ready():
    # past initialisation, unready counts only when the pod has never been
    # ready (transition within the initial-readiness delay of start)
    never_ready = PodSample(
        name="never", ready=False, start_age=1000, transition_age=990,
        value=10,
    )
    was_ready = PodSample(
        name="flap", ready=False, start_age=1000, transition_age=100,
        value=10,
    )
    g = group_pods(
        [never_ready, was_ready], {"never": 10, "flap": 10}, "cpu", 300, 30
    )
    assert g.unready == {"never"}
    assert g.ready_count == 1


# -- Object metrics (:829-1010) --------------------------------------------


def test_get_object_metric_replicas_scale_up():
    pods = [pod("pod1"), pod("pod2")]
    n, usage = CALC.get_object_metric_replicas(2, 10, 30, pods)
    assert (n, usage) == (6, 30)


def test_get_object_metric_replicas_tolerance_holds():
    pods = [pod("pod1"), pod("pod2")]
    n, usage = CALC.get_object_metric_replicas(2, 10, 10, pods)
    assert (n, usage) == (2, 10)


def test_get_object_metric_replicas_scale_to_zero():
    # currentReplicas == 0 bypasses tolerance and ready counts
    n, usage = CALC.get_object_metric_replicas(0, 10, 30, [])
    assert n == 3


def test_get_object_per_pod_metric_replicas():
    # usage 30 across 2 status replicas vs average target 10 -> 3 replicas,
    # per-pod usage ceil(30/2) = 15
    n, usage = CALC.get_object_per_pod_metric_replicas(2, 10, 30)
    assert (n, usage) == (3, 15)


def test_get_object_per_pod_metric_replicas_calibration():
    n, usage = CALC.get_object_per_pod_metric_replicas(2, 10, 30, 0.5)
    assert (n, usage) == (12, 15)  # ceil(ceil(30/10/0.5) / 0.5)


def test_get_object_per_pod_metric_replicas_tolerance():
    n, usage = CALC.get_object_per_pod_metric_replicas(3, 10, 30)
    assert (n, usage) == (3, 10)


# -- direction-change guards (replica_calculator.go:130-140) ----------------


def test_direction_change_guard_holds_current():
    # ratio < 1 (scale-down) but the missing-pod backfill flips the new
    # ratio above 1 -> keep current
    n, _ = CALC.get_metric_replicas(
        4, 10, {"pod1": 9, "pod2": 9},
        [pod("pod1"), pod("pod2"), pod("pod3"), pod("pod4")],
    )
    assert n == 4


# -- utilization helpers (metrics/utilization_test.go:67-140) ---------------


def test_resource_utilization_ratio_base():
    ratio, util, raw = resource_utilization_ratio(
        {"pod1": 300, "pod2": 500}, {"pod1": 500, "pod2": 500}, 50
    )
    assert (util, raw) == (80, 400)
    assert ratio == pytest.approx(1.6)


def test_resource_utilization_ratio_ignores_extraneous_metrics():
    # metrics without a matching request are skipped (extraneous)
    ratio, util, _ = resource_utilization_ratio(
        {"pod1": 250, "ghost": 9999}, {"pod1": 500}, 50
    )
    assert util == 50
    assert ratio == pytest.approx(1.0)


def test_resource_utilization_ratio_extra_request_ok():
    # requests for pods without metrics don't count toward the total
    _, util, _ = resource_utilization_ratio(
        {"pod1": 250}, {"pod1": 500, "unsampled": 500}, 50
    )
    assert util == 50


def test_resource_utilization_ratio_no_requests_errors():
    with pytest.raises(MetricsError):
        resource_utilization_ratio({"pod1": 100}, {}, 50)


def test_metric_usage_ratio():
    ratio, usage = metric_usage_ratio({"pod1": 15, "pod2": 15}, 10)
    assert usage == 15
    assert ratio == pytest.approx(1.5)
