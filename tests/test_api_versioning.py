"""Multi-version API + conversion seam (VERDICT r3 missing #2).

Ref: pkg/apis/work/v1alpha1/binding_types_conversion.go — v1alpha1
bindings nest replicas/per-replica requirements inside spec.resource;
the hub (v1alpha2) hoists them. Tests cover the pure conversions, the
bus upgrade path (a legacy client applies v1alpha1 and the store holds
hub objects), the CLI manifest path, and the ConversionReview wire
contract through the real TLS webhook process.
"""

from __future__ import annotations

import json

from karmada_tpu.api.versioning import (
    HUB_VERSION,
    LEGACY_VERSION,
    convert,
    handle_conversion_review,
    maybe_upgrade,
    served_versions,
)
from karmada_tpu.bus.service import decode_object, encode_object


def _legacy_binding(name="web-deployment"):
    return {
        "apiVersion": LEGACY_VERSION,
        "kind": "ResourceBinding",
        "meta": {"name": name, "namespace": "default"},
        "spec": {
            "resource": {
                "api_version": "apps/v1", "kind": "Deployment",
                "namespace": "default", "name": "web",
                "replicas": 7,
                "replicaResourceRequirements": {"cpu": 250, "memory": 512},
            },
            "clusters": [
                {"name": "member1", "replicas": 4},
                {"name": "member2", "replicas": 3},
            ],
        },
        "status": {
            "conditions": [{"type": "Scheduled", "status": True}],
            "aggregated_status": [
                {"cluster_name": "member1", "applied": True},
            ],
        },
    }


class TestConversions:
    def test_legacy_to_hub_hoists_replica_fields(self):
        hub = convert(_legacy_binding(), "ResourceBinding", HUB_VERSION)
        assert hub["spec"]["replicas"] == 7
        assert hub["spec"]["replica_requirements"]["resource_request"] == {
            "cpu": 250, "memory": 512,
        }
        assert "replicas" not in hub["spec"]["resource"]
        assert [c["name"] for c in hub["spec"]["clusters"]] == [
            "member1", "member2",
        ]

    def test_round_trip_preserves_legacy_representable_fields(self):
        legacy = _legacy_binding()
        hub = convert(legacy, "ResourceBinding", HUB_VERSION)
        back = convert(hub, "ResourceBinding", LEGACY_VERSION)
        assert back["spec"]["resource"]["replicas"] == 7
        assert back["spec"]["resource"]["replicaResourceRequirements"] == {
            "cpu": 250, "memory": 512,
        }
        assert back["spec"]["clusters"] == legacy["spec"]["clusters"]
        assert back["status"]["aggregated_status"] == [
            {"cluster_name": "member1", "applied": True}
        ]

    def test_down_conversion_drops_hub_only_fields(self):
        hub = convert(_legacy_binding(), "ResourceBinding", HUB_VERSION)
        hub["spec"]["conflict_resolution"] = "Overwrite"
        hub["spec"]["propagate_deps"] = True
        down = convert(hub, "ResourceBinding", LEGACY_VERSION)
        assert "conflict_resolution" not in down["spec"]
        assert "propagate_deps" not in down["spec"]

    def test_served_versions(self):
        assert served_versions("ResourceBinding") == [
            HUB_VERSION, LEGACY_VERSION,
        ]
        assert served_versions("ClusterResourceBinding") == [
            HUB_VERSION, LEGACY_VERSION,
        ]

    def test_unknown_version_fails_review(self):
        review = {
            "request": {
                "uid": "u1",
                "desiredAPIVersion": "work.karmada.io/v9",
                "objects": [_legacy_binding()],
            }
        }
        resp = handle_conversion_review(review)["response"]
        assert resp["result"]["status"] == "Failure"
        assert "not served" in resp["result"]["message"]


class TestBusUpgrade:
    def test_legacy_payload_decodes_to_hub_object(self):
        obj = decode_object(
            "ResourceBinding", json.dumps(_legacy_binding())
        )
        assert obj.spec.replicas == 7
        assert obj.spec.replica_requirements.resource_request == {
            "cpu": 250, "memory": 512,
        }
        assert {tc.name: tc.replicas for tc in obj.spec.clusters} == {
            "member1": 4, "member2": 3,
        }
        # the hub encode round-trips without any legacy residue
        doc = json.loads(encode_object(obj))
        assert "replicas" not in doc["spec"]["resource"]

    def test_hub_payload_is_untouched(self):
        hub_doc = convert(_legacy_binding(), "ResourceBinding", HUB_VERSION)
        assert maybe_upgrade("ResourceBinding", hub_doc) is hub_doc


class TestCliManifest:
    def test_apply_of_legacy_manifest_lands_hub_typed(self):
        from karmada_tpu.cli import _manifest_to_obj

        manifest = _legacy_binding()
        manifest["metadata"] = manifest.pop("meta")
        obj = _manifest_to_obj(manifest)
        assert type(obj).KIND == "ResourceBinding"
        assert obj.spec.replicas == 7
        assert obj.meta.namespace == "default"


class TestConvertWebhook:
    def test_convert_endpoint_over_tls_process(self, tmp_path):
        """ConversionReview through the real HTTPS webhook process (the
        CRD conversion strategy: Webhook deployment shape)."""
        import ssl
        import subprocess
        import sys
        import urllib.request

        from karmada_tpu.localup import scrape_line, spawn_child

        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(tmp_path / "w.key"),
             "-out", str(tmp_path / "w.crt"),
             "-days", "2", "-subj", "/CN=localhost",
             "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
            check=True, capture_output=True,
        )
        proc = spawn_child(
            [sys.executable, "-m", "karmada_tpu.webhook.server",
             "--address", "127.0.0.1:0",
             "--certfile", str(tmp_path / "w.crt"),
             "--keyfile", str(tmp_path / "w.key")]
        )
        try:
            port = scrape_line(proc, r"listening on port (\d+)")
            ctx = ssl.create_default_context(cafile=str(tmp_path / "w.crt"))
            review = {
                "apiVersion": "apiextensions.k8s.io/v1",
                "kind": "ConversionReview",
                "request": {
                    "uid": "abc",
                    "desiredAPIVersion": HUB_VERSION,
                    "objects": [_legacy_binding()],
                },
            }
            req = urllib.request.Request(
                f"https://127.0.0.1:{port}/convert",
                data=json.dumps(review).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30, context=ctx) as r:
                out = json.loads(r.read())
            resp = out["response"]
            assert resp["uid"] == "abc"
            assert resp["result"]["status"] == "Success"
            [converted] = resp["convertedObjects"]
            assert converted["apiVersion"] == HUB_VERSION
            assert converted["spec"]["replicas"] == 7
        finally:
            proc.terminate()
            proc.wait(timeout=10)
