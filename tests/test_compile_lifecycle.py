"""Compile-lifecycle subsystem: persistent trace manifest + AOT prewarm.

The cold-start contract (ISSUE 1): the fleet engine persists every fresh
solve-family trace signature (kernel + input shapes + statics) to a
TraceManifest; ``prewarm.warmup`` replays the manifest through AOT
compilation in a process that has never scheduled anything; an engine
restored from a REPLAYED manifest reports ``new_trace=False`` on its
first pass over a covered fleet shape — including across a real process
restart (subprocess test below).

Everything runs at toy shapes on the conftest CPU platform, so tier-1
exercises the whole subsystem without TPU access.
"""

import json
import os
import subprocess
import sys

import numpy as np

from karmada_tpu.scheduler import (
    BindingProblem,
    ClusterSnapshot,
    TensorScheduler,
)
from karmada_tpu.scheduler import prewarm
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    synthetic_fleet,
)
from karmada_tpu.utils.quantity import parse_resource_list

C, B = 50, 300


def toy_problems(n=B, seed=11):
    rng = np.random.default_rng(seed)
    pl = dynamic_weight_placement()
    profiles = [
        parse_resource_list(
            {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
        )
        for p in range(3)
    ]
    return [
        BindingProblem(
            key=f"t{i}",
            placement=pl,
            replicas=int(rng.integers(1, 40)),
            requests=profiles[i % 3],
            gvk="apps/v1/Deployment",
        )
        for i in range(n)
    ]


def seed_manifest(path, *, passes=3):
    """Schedule a toy fleet with manifest recording on; returns the
    settled engine (its trace set is what the manifest must replay)."""
    snap = ClusterSnapshot(synthetic_fleet(C, seed=7))
    problems = toy_problems()
    eng = TensorScheduler(snap, trace_manifest=str(path))
    assert eng.trace_manifest is not None
    for _ in range(passes):
        eng.schedule(problems)
    assert eng._fleet is not None, "fleet path did not engage"
    return eng


class TestTraceManifest:
    def test_records_written_and_deduped(self, tmp_path):
        path = tmp_path / "manifest.json"
        seed_manifest(path)
        data = json.loads(path.read_text())
        kernels = [r["kernel"] for r in data["records"]]
        assert kernels, "no trace records persisted"
        assert set(kernels) <= set(prewarm._KERNELS)
        # re-loading dedups to the same record set, and every observed
        # record round-trips its ledger key back to a tuple
        m = prewarm.TraceManifest(str(path))
        assert len(m.records) == len(data["records"])
        for key in m.keys():
            assert isinstance(key, tuple) and isinstance(key[0], str)

    def test_same_workload_records_once(self, tmp_path):
        path = tmp_path / "manifest.json"
        eng = seed_manifest(path)
        n = len(eng.trace_manifest.records)
        # more passes over the settled shape add nothing
        eng.schedule(toy_problems())
        assert len(eng.trace_manifest.records) == n

    def test_corrupt_manifest_tolerated(self, tmp_path):
        path = tmp_path / "manifest.json"
        path.write_text("{not json")
        m = prewarm.TraceManifest(str(path))
        assert m.records == []
        # and replay of an empty manifest is a clean no-op
        stats = prewarm.replay(m)
        assert stats["specs"] == 0 and stats["failed"] == 0

    def test_ir_retrace_round_trip(self, tmp_path):
        """A recorded manifest entry re-traced by the graftlint IR tier
        yields a byte-identical shape/static signature across a
        save/load cycle — the IR004 fidelity contract: replay dedup and
        ledger seeding key on this canon, so any serialization loss
        would make prewarm cover less than the serving path."""
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools.graftlint import ir as graft_ir

        path = tmp_path / "manifest.json"
        seed_manifest(path)
        m1 = prewarm.TraceManifest(str(path))
        assert m1.records
        canons = []
        for i, rec in enumerate(m1.records):
            spec = graft_ir.spec_from_record(rec, f"manifest[{i}]")
            original, rebuilt = graft_ir.record_canon(rec, spec)
            assert original == rebuilt, (original, rebuilt)
            canons.append(rebuilt)
        # the full cycle: re-save, re-load, re-derive — still identical
        m1._save()
        m2 = prewarm.TraceManifest(str(path))
        assert [
            graft_ir.record_canon(r, graft_ir.spec_from_record(r, "x"))[1]
            for r in m2.records
        ] == canons

    def test_expand_records_next_bucket(self):
        from karmada_tpu.scheduler.fleet import M_ROUND, _cap_round

        solve = {
            "kernel": "fleet_solve",
            "key": ["L", 1024],
            "in_shapes": [[[64, 4], "int64"]],
            "statics": {"e_cap": 1024, "chunk": 256},
        }
        grown = prewarm.expand_records([solve])
        assert len(grown) == 1
        # expanded specs are honest: no ledger key (never dispatched),
        # and the e_cap landed on the NEXT quantized bucket
        assert grown[0]["key"] is None
        assert grown[0]["statics"]["e_cap"] == _cap_round(1025) > 1024

        # fleet_pass grows the changed-meta cap, but only within the
        # padded row count (an over-bound m_cap is a trace nothing ever
        # dispatches): with n_pad == m_cap, no meta expansion happens
        def pass_rec(n_pad):
            return {
                "kernel": "fleet_pass",
                "key": ["A", 7],
                "in_shapes": [[[4], "int32"]] * 5
                + [[[n_pad, 8], "int64"]],
                "statics": {"m_cap": M_ROUND, "d_cap": 0},
            }

        grown = prewarm.expand_records([pass_rec(4 * M_ROUND)])
        # grow to the next quantum AND shrink to the 4096 floor (the
        # settle-train bucket); the toy key is too short for derivation,
        # so the shrink spec stays compile-only (key=None)
        assert [g["statics"]["m_cap"] for g in grown] == [2 * M_ROUND, 4096]
        assert all(g["key"] is None for g in grown)
        shrunk_only = prewarm.expand_records([pass_rec(M_ROUND)])
        assert [g["statics"]["m_cap"] for g in shrunk_only] == [4096]

        # floor caps expand to the engine's REAL next bucket, not
        # floor+quantum: m_round's first step is 4096 -> M_ROUND, and
        # d_round's is D_FLOOR -> D_ROUND (phantom buckets like 36864
        # would be compiles nothing ever dispatches)
        from karmada_tpu.scheduler.fleet import D_FLOOR, D_ROUND

        floor = {
            "kernel": "fleet_pass",
            "key": ["A", 9],
            "in_shapes": [[[4], "int32"]] * 5
            + [[[4 * M_ROUND, 8], "int64"]],
            "statics": {"m_cap": 4096, "d_cap": D_FLOOR},
        }
        caps = {
            k: g["statics"][k]
            for g in prewarm.expand_records([floor])
            for k in ("m_cap", "d_cap")
            if g["statics"][k] != floor["statics"][k]
        }
        assert caps == {"m_cap": M_ROUND, "d_cap": D_ROUND}


class TestRestoreContract:
    def test_round_trip_restored_engine_first_pass_warm(self, tmp_path):
        path = tmp_path / "manifest.json"
        seed_manifest(path)
        # replay in-process (the warmup boot stage), then a FRESH engine
        # restored from the same manifest must report new_trace=False on
        # its very first pass — zero compiles on the serving path
        stats = prewarm.warmup(str(path))
        assert stats["compiled"] >= stats["records"] > 0
        assert stats["failed"] == 0
        snap = ClusterSnapshot(synthetic_fleet(C, seed=7))
        eng = TensorScheduler(snap, trace_manifest=str(path))
        eng.schedule(toy_problems())
        assert eng.last_pass_new_trace is False

    def test_partial_warm_seeds_only_compiled_keys(self, tmp_path):
        # a record whose compile FAILS during replay (stale manifest vs
        # new build) must not seed the ledger: its trace would still
        # compile at first dispatch, so claiming new_trace=False for it
        # would put a cold compile inside the "warm" window
        path = tmp_path / "manifest.json"
        seed_manifest(path)
        m = prewarm.TraceManifest(str(path))
        good_keys = m.keys()
        bogus = {
            "kernel": "fleet_solve",
            "key": ["L", "bogus", 999],
            "in_shapes": [[[3, 3], "int64"]],
            "statics": {"e_cap": -1, "chunk": 0},
        }
        m.records.append(bogus)
        m._seen.add(prewarm._canon(bogus))
        stats = prewarm.replay(m, expand=False)
        assert stats["failed"] >= 1 and stats["compiled"] >= 1
        warmed = m.warmed_keys()
        assert ("L", "bogus", 999) not in warmed
        assert warmed == good_keys

    def test_explicit_opt_out_beats_env(self, tmp_path, monkeypatch):
        # trace_manifest="" is the documented opt-out; an inherited
        # KARMADA_TPU_TRACE_MANIFEST must not resurrect recording at the
        # fleet layer (the engine resolved the opt-out once)
        env_manifest = tmp_path / "env.json"
        monkeypatch.setenv("KARMADA_TPU_TRACE_MANIFEST", str(env_manifest))
        snap = ClusterSnapshot(synthetic_fleet(C, seed=7))
        eng = TensorScheduler(snap, trace_manifest="")
        assert eng.trace_manifest is None
        eng.schedule(toy_problems())
        assert eng._fleet is not None and eng._fleet._manifest is None
        assert not env_manifest.exists()

    def test_seeding_gated_on_replay(self, tmp_path):
        # an engine handed a manifest that was NOT replayed in this
        # process must not claim a warm first pass: seeding without the
        # compile would report new_trace=False while the compile still
        # runs at first dispatch
        path = tmp_path / "unreplayed.json"
        seed_manifest(path)
        snap = ClusterSnapshot(synthetic_fleet(C, seed=7))
        eng = TensorScheduler(snap, trace_manifest=str(path))
        eng.schedule(toy_problems())
        assert eng.last_pass_new_trace is True

    def test_restore_across_mesh_change(self, tmp_path):
        """A manifest recorded at mesh=1 must NOT seed ``new_trace=False``
        on a multi-device boot (the partitioned executables are distinct
        compiles — their ledger keys carry the mesh shape), while a
        meshed engine's own records DO warm the next meshed boot and the
        single-device records keep warming single-device engines."""
        from karmada_tpu.parallel.mesh import scheduling_mesh

        path = tmp_path / "manifest.json"
        seed_manifest(path)  # single-device records
        prewarm.warmup(str(path))
        snap = ClusterSnapshot(synthetic_fleet(C, seed=7))
        meshed = TensorScheduler(
            snap, mesh=scheduling_mesh(2), trace_manifest=str(path)
        )
        meshed.schedule(toy_problems())
        assert meshed.last_pass_new_trace is True, (
            "a mesh=1 manifest fake-warmed a mesh=2 boot"
        )
        # the meshed pass recorded its partitioned traces (mesh shape in
        # the statics); a fresh warmup replays them over this process's
        # devices and a meshed restart is then genuinely warm
        for _ in range(2):
            meshed.schedule(toy_problems())
        stats = prewarm.warmup(str(path))
        assert stats["failed"] == 0 and stats["compiled"] > 0
        recorded_meshes = {
            json.dumps(r["statics"].get("mesh"))
            for r in prewarm.TraceManifest(str(path)).records
        }
        assert '[["b", 2], ["c", 1]]' in recorded_meshes
        meshed2 = TensorScheduler(
            snap, mesh=scheduling_mesh(2), trace_manifest=str(path)
        )
        meshed2.schedule(toy_problems())
        assert meshed2.last_pass_new_trace is False
        # and the original single-device records still warm 1-chip boots
        single = TensorScheduler(snap, trace_manifest=str(path))
        single.schedule(toy_problems())
        assert single.last_pass_new_trace is False

    def test_restored_engine_settle_train_stays_warm(
        self, tmp_path, monkeypatch
    ):
        """The BENCH_r05 mid-settle compile, at toy scale: a manifest that
        only observed CHURN passes misses the shrink-bucket solve family
        (a settle train's entry demand collapses to the cap floor, and
        the sustained-shrink retune mints a fresh trace mid-settle). The
        shrink expansion must cover it: an engine restored from the
        churn-only manifest reports new_trace=False across a FULL settle
        train. Legacy path (the tier that regressed), full passes only
        (the delta path freezes cap tuning, so shrink dynamics live on
        the full-pass side)."""
        import karmada_tpu.scheduler.fleet as fleet_mod

        monkeypatch.setattr(fleet_mod, "DENSE_RESIDENT_MAX_BYTES", 0)
        monkeypatch.setenv("KARMADA_TPU_DELTA_SOLVE", "0")

        def churned(problems, seed):
            rng = np.random.default_rng(seed)
            out = list(problems)
            for i in rng.choice(len(out), len(out) // 2, replace=False):
                p = out[i]
                out[i] = BindingProblem(
                    key=p.key, placement=p.placement,
                    replicas=int(rng.integers(1, 40)),
                    requests=p.requests, gvk=p.gvk,
                )
            return out

        def settled(problems, seed):
            # exactly 3 rows, replicas GUARANTEED changed and bounded by
            # the churn range (a new max would legitimately re-key the
            # solve) — the settle dispatch shapes stay deterministic
            rng = np.random.default_rng(seed)
            out = list(problems)
            for i in rng.choice(len(out), 3, replace=False):
                p = out[i]
                out[i] = BindingProblem(
                    key=p.key, placement=p.placement,
                    replicas=(p.replicas % 39) + 1,
                    requests=p.requests, gvk=p.gvk,
                )
            return out

        path = tmp_path / "churn.json"
        snap = ClusterSnapshot(synthetic_fleet(C, seed=7))
        eng = TensorScheduler(snap, trace_manifest=str(path))
        problems = toy_problems()
        eng.schedule(problems)
        for s in range(1, 4):  # the churn storm: caps grow and stay up
            problems = churned(problems, s)
            eng.schedule(problems)
        # one light pass: the small-scatter upload shapes are part of any
        # real churn history; what the manifest must NOT have observed is
        # the settle train's shrink retune
        problems = settled(problems, 5)
        eng.schedule(problems)
        churn_records = path.read_bytes()
        settle_start = problems
        # the manifest-persisted solve families (fleet.py ledger-key
        # prefixes): the multi-second compiles the warmup contract
        # covers. Tiny ledger-only utility kernels (the "S" row scatter)
        # stay out of the manifest by design — their first-dispatch
        # compiles are sub-millisecond and allowed.
        solve_fams = ("L", "A", "E", "B")

        def fresh_solve_keys(fleet, before):
            return [
                k for k in fleet._seen_traces - before
                if k[0] in solve_fams
            ]

        # the repro: keep settling THIS engine (light churn, demand near
        # zero) — the cap shrink retunes mid-train and mints a fresh
        # SOLVE trace the churn records never covered
        saw_fresh = []
        for s in range(10, 20):
            problems = settled(problems, s)
            before = set(eng._fleet._seen_traces)
            eng.schedule(problems)
            saw_fresh += fresh_solve_keys(eng._fleet, before)
        assert saw_fresh, (
            "settle train minted no fresh solve trace — shrink dynamics "
            "moved; re-point this regression at the new retune path"
        )
        # restore from the CHURN-ONLY record set: shrink expansion must
        # prepay (and honestly seed) the settle train's buckets
        path2 = tmp_path / "restored.json"
        path2.write_bytes(churn_records)
        stats = prewarm.warmup(str(path2))
        assert stats["failed"] == 0 and stats["compiled"] > 0
        eng2 = TensorScheduler(snap, trace_manifest=str(path2))
        problems = settle_start
        eng2.schedule(problems)
        assert eng2.last_pass_new_trace is False
        for s in range(10, 20):
            problems = settled(problems, s)
            before = set(eng2._fleet._seen_traces)
            eng2.schedule(problems)
            assert not fresh_solve_keys(eng2._fleet, before), (
                f"settle pass {s - 9} compiled a solve trace on the "
                "restored engine"
            )

    def test_restart_smoke_subprocess(self, tmp_path):
        """The real restart: process 1 schedules and exits; process 2
        prewarms from the manifest + persistent cache and must run its
        first pass with new_trace=False. CPU toy shapes — the tier-1
        smoke for the whole cold-start path."""
        manifest = tmp_path / "manifest.json"
        cache = tmp_path / "cache"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COMPILATION_CACHE_DIR"] = str(cache)
        env["KARMADA_TPU_TRACE_MANIFEST"] = str(manifest)
        body = (
            "import json, sys\n"
            f"sys.path.insert(0, "
            f"{os.path.dirname(os.path.abspath(__file__))!r})\n"
            "from test_compile_lifecycle import "
            "seed_manifest, toy_problems, C\n"
            "from karmada_tpu.scheduler import "
            "ClusterSnapshot, TensorScheduler\n"
            "from karmada_tpu.scheduler.prewarm import warmup\n"
            "from karmada_tpu.utils.builders import synthetic_fleet\n"
            "phase = sys.argv[1]\n"
            "manifest = sys.argv[2]\n"
            "if phase == 'seed':\n"
            "    eng = seed_manifest(manifest)\n"
            "    out = {'records': len(eng.trace_manifest.records)}\n"
            "else:\n"
            "    stats = warmup(manifest)\n"
            "    snap = ClusterSnapshot(synthetic_fleet(C, seed=7))\n"
            "    eng = TensorScheduler(snap, trace_manifest=manifest)\n"
            "    eng.schedule(toy_problems())\n"
            "    out = {'prewarm': stats,\n"
            "           'new_trace': eng.last_pass_new_trace}\n"
            "print(json.dumps(out))\n"
        )

        def run(phase):
            proc = subprocess.run(
                [sys.executable, "-c", body, phase, str(manifest)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, timeout=300,
                cwd=os.path.dirname(os.path.dirname(__file__)),
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            return json.loads(proc.stdout.strip().splitlines()[-1])

        seeded = run("seed")
        assert seeded["records"] > 0
        restored = run("restore")
        assert restored["prewarm"]["compiled"] > 0
        assert restored["prewarm"]["failed"] == 0
        assert restored["new_trace"] is False


class TestWarmupCLI:
    def test_warmup_verb(self, tmp_path, capsys):
        from karmada_tpu import cli

        path = tmp_path / "manifest.json"
        seed_manifest(path)
        rc = cli.main(["warmup", "--manifest", str(path)])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0
        assert out["compiled"] >= out["records"] > 0
        assert out["failed"] == 0
        assert out["manifest"] == str(path)

    def test_warmup_missing_manifest_is_noop(self, tmp_path, capsys):
        from karmada_tpu import cli

        rc = cli.main(
            ["warmup", "--manifest", str(tmp_path / "absent.json")]
        )
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0
        assert out["specs"] == 0 and out["compiled"] == 0
