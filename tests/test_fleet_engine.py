"""Device-resident fleet path: differential equivalence with the host path.

The fleet table (scheduler/fleet.py) re-implements Filter+Assign as one
fused resident-state program; these tests pin it to the general host path
(_schedule_host) — same placements, same errors, same feasible sets — over
randomized mixed-strategy fleets, plus the no-idx dispense mode, snapshot
swap-in-place, and the entry-buffer overflow fallback."""

import numpy as np
import jax.numpy as jnp
import pytest

import karmada_tpu.scheduler.fleet as fleet_mod
from karmada_tpu.ops.dispense import take_by_weight, take_by_weight_fast
from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.utils.builders import (
    aggregated_placement,
    duplicated_placement,
    dynamic_weight_placement,
    static_weight_placement,
    synthetic_fleet,
)
from karmada_tpu.utils.quantity import parse_resource_list


REQ = parse_resource_list({"cpu": "250m", "memory": "512Mi"})


def _mixed_problems(clusters, n, seed):
    rng = np.random.default_rng(seed)
    pls = [
        dynamic_weight_placement(),
        duplicated_placement(),
        static_weight_placement(
            {c.name: (i % 3) + 1 for i, c in enumerate(clusters[:10])}
        ),
        aggregated_placement(),
    ]
    out = []
    for i in range(n):
        prev_n = int(rng.integers(0, 5))
        prev_idx = rng.choice(len(clusters), prev_n, replace=False)
        out.append(
            BindingProblem(
                key=f"b{i}",
                placement=pls[i % 4],
                replicas=int(rng.integers(0, 40)),
                requests=REQ,
                gvk="apps/v1/Deployment",
                prev={
                    clusters[j].name: int(rng.integers(1, 9)) for j in prev_idx
                },
                fresh=bool(rng.random() < 0.2),
            )
        )
    return out


def _assert_same(slow, fast):
    for s, f in zip(slow, fast):
        assert s.success == f.success, (s.key, s.error, f.error)
        assert s.error == f.error, s.key
        assert s.clusters == f.clusters, (s.key, s.clusters, f.clusters)
        assert sorted(s.feasible) == sorted(f.feasible), s.key
        assert s.affinity_name == f.affinity_name, s.key


@pytest.mark.parametrize("seed", [1, 2])
def test_fleet_matches_host_path_mixed_strategies(seed):
    clusters = synthetic_fleet(50, seed=7)
    snap = ClusterSnapshot(clusters)
    problems = _mixed_problems(clusters, 300, seed)
    host = TensorScheduler(snap)
    slow = host._schedule_host(
        problems, [host._compiled(p.placement) for p in problems]
    )
    eng = TensorScheduler(snap)
    eng.fleet_threshold = 1
    fast = eng.schedule(problems)
    assert eng._fleet is not None, "fleet path did not engage"
    _assert_same(slow, fast)
    # repeat pass: identity fast path must return identical placements
    again = eng.schedule(problems)
    _assert_same(fast, again)
    # rebuilt problem objects (the controller case): fingerprint dedupe
    rebuilt = [
        BindingProblem(
            key=p.key, placement=p.placement, replicas=p.replicas,
            requests=p.requests, gvk=p.gvk, prev=p.prev, fresh=p.fresh,
        )
        for p in problems
    ]
    _assert_same(fast, eng.schedule(rebuilt))


def test_fleet_incremental_update_changes_only_touched_rows():
    clusters = synthetic_fleet(50, seed=7)
    snap = ClusterSnapshot(clusters)
    problems = _mixed_problems(clusters, 200, 3)
    eng = TensorScheduler(snap)
    eng.fleet_threshold = 1
    first = eng.schedule(problems)
    # mutate a handful of bindings (replicas change)
    changed = []
    for i in (5, 17, 101):
        p = problems[i]
        changed.append(
            BindingProblem(
                key=p.key, placement=p.placement,
                replicas=max(1, p.replicas + 3), requests=p.requests,
                gvk=p.gvk, prev=p.prev, fresh=p.fresh,
            )
        )
    problems2 = list(problems)
    for p in changed:
        problems2[int(p.key[1:])] = p
    second = eng.schedule(problems2)
    host = TensorScheduler(snap)
    want = host._schedule_host(
        problems2, [host._compiled(p.placement) for p in problems2]
    )
    _assert_same(want, second)


def test_update_snapshot_keeps_fleet_valid():
    clusters = synthetic_fleet(40, seed=9)
    snap = ClusterSnapshot(clusters)
    problems = _mixed_problems(clusters, 150, 4)
    eng = TensorScheduler(snap)
    eng.fleet_threshold = 1
    eng.schedule(problems)
    fleet_before = eng._fleet
    # capacity drift on the same cluster set
    for cl in clusters:
        rs = cl.status.resource_summary
        for d in list(rs.allocated):
            rs.allocated[d] = int(rs.allocated[d] * 1.5) + 1
    snap2 = ClusterSnapshot(clusters)
    assert eng.update_snapshot(snap2)
    got = eng.schedule(problems)
    assert eng._fleet is fleet_before  # table survived the swap
    fresh_engine = TensorScheduler(snap2)
    want = fresh_engine._schedule_host(
        problems, [fresh_engine._compiled(p.placement) for p in problems]
    )
    _assert_same(want, got)
    # cluster-set change must refuse the in-place swap
    snap3 = ClusterSnapshot(clusters[:-1])
    assert not eng.update_snapshot(snap3)


def test_entry_buffer_overflow_falls_back_to_safe_bound(monkeypatch):
    clusters = synthetic_fleet(30, seed=5)
    snap = ClusterSnapshot(clusters)
    problems = _mixed_problems(clusters, 120, 6)
    monkeypatch.setattr(fleet_mod, "E_ROUND", 16)
    eng = TensorScheduler(snap)
    eng.fleet_threshold = 1
    first = eng.schedule(problems)
    # result views are valid only until the next pass (generation-guarded):
    # snapshot pass 1 eagerly before re-scheduling
    first = [
        (r.success, r.error, dict(r.clusters), tuple(r.feasible), r.key)
        for r in first
    ]
    # lie about the last total so the tuned cap must overflow and retry
    eng._fleet._last_total = 1
    second = eng.schedule(problems)
    for (succ, err, clus, feas, key), f in zip(first, second):
        assert succ == f.success and err == f.error, key
        assert clus == f.clusters, (key, clus, f.clusters)
        assert sorted(feas) == sorted(f.feasible), key


def test_slot_eviction_survives_generational_placement_churn(monkeypatch):
    """Crossing the unique-placement cap with RETIRED placements must not
    rebuild the table per call: idle rows are reclaimed, their slots
    swept, and the SAME FleetTable keeps scheduling (delta base intact).
    Placements exceeding the cap while all still live do rebuild — that
    is the genuine capacity limit, not the cliff."""
    from karmada_tpu.utils.builders import static_weight_placement

    monkeypatch.setattr(fleet_mod, "MAX_SLOTS", 16)
    monkeypatch.setattr(fleet_mod, "MAX_SLOTS_HARD", 16)
    monkeypatch.setattr(fleet_mod, "CP_TABLE_MAX_BYTES", 0)
    clusters = synthetic_fleet(20, seed=3)
    snap = ClusterSnapshot(clusters)
    names = [c.name for c in clusters]
    eng = TensorScheduler(snap)
    eng.fleet_threshold = 1

    def gen_problems(gen: int):
        pls = [
            static_weight_placement({names[j]: j + k + 1 for j in range(5)})
            for k in range(10)  # 10 unique placements per generation
        ]
        return [
            BindingProblem(
                key=f"g{gen}_{i}", placement=pls[i % 10], replicas=4 + i % 7,
                requests={}, gvk="apps/v1/Deployment",
            )
            for i in range(40)
        ]

    tables = set()
    for gen in range(4):  # 40 uniques over the table's life vs cap 16
        probs = gen_problems(gen)
        for _ in range(6):  # age the previous generation past the window
            res = eng.schedule(probs)
        tables.add(id(eng._fleet))
        host = TensorScheduler(snap)
        want = host._schedule_host(
            probs, [host._compiled(p.placement) for p in probs]
        )
        _assert_same(want, res)
    # generations retire cleanly: one table (first gen fills 10/16; later
    # gens evict the retired ones instead of tripping the rebuild path).
    # At most the live generation + its not-yet-swept predecessor remain
    # (the sweep runs at the NEXT cap-pressure check).
    assert len(tables) == 1, "table rebuilt despite retirable slots"
    assert len(eng._fleet._cp_pl) <= 20, len(eng._fleet._cp_pl)


def test_batch_reuse_survives_compaction():
    """The batch-identity fast path skips upsert (and its last-used bump);
    a compaction sweep must still see those rows as live, not idle."""
    clusters = synthetic_fleet(30, seed=8)
    snap = ClusterSnapshot(clusters)
    problems = _mixed_problems(clusters, 600, 3)
    eng = TensorScheduler(snap)
    eng.fleet_threshold = 1
    for _ in range(8):  # advance _pass well past COMPACT_IDLE_PASSES
        eng.schedule(problems)
    ft = eng._fleet
    assert ft._reuse is not None  # the fast path engaged
    keys_before = set(ft._key_row)
    assert not ft._compact()  # live batch: nothing to reclaim
    assert set(ft._key_row) == keys_before


def test_dispense_no_idx_mode_matches_sort_dispense():
    """Tie-heavy fuzz of with_idx=False (two-stage top_k) vs the exact
    3-key sort, including placed-site coverage of the returned top-k."""
    rng = np.random.default_rng(0)
    for trial in range(60):
        c = int(rng.integers(3, 120))
        num = int(rng.integers(0, 60))
        w = rng.choice(
            [0, 1, 2, 5, 7], size=c, p=[0.2, 0.3, 0.2, 0.2, 0.1]
        ).astype(np.int32)
        last = rng.integers(0, 4, c).astype(np.int32)
        init = np.zeros(c, np.int32)
        ref = np.asarray(
            take_by_weight(
                jnp.int32(num), jnp.asarray(w), jnp.asarray(last),
                jnp.asarray(init), True,
            )
        )
        k_top = min(c, 1 << max(1, max(1, num) - 1).bit_length())
        got, sites = take_by_weight_fast(
            jnp.int32(num), jnp.asarray(w), jnp.asarray(last),
            jnp.asarray(init), 23, 8, k_top, True,
            with_idx=False, return_sites=True,
        )
        got, sites = np.asarray(got), np.asarray(sites)
        assert np.array_equal(ref, got), (trial, c, num)
        placed = set(np.flatnonzero(got).tolist())
        assert placed <= set(sites.tolist()), (trial, placed)


def test_fleet_compacts_rows_of_deleted_bindings():
    """Create/delete churn must not grow the table without bound: rows idle
    past the compaction window are reclaimed before the table grows."""
    clusters = synthetic_fleet(10, seed=1)
    snap = ClusterSnapshot(clusters)
    eng = TensorScheduler(snap, chunk_size=64)
    eng.fleet_threshold = 1
    pl = dynamic_weight_placement()

    def gen(tag, n):
        return [
            BindingProblem(
                key=f"{tag}-{i}", placement=pl, replicas=3, requests=REQ,
                gvk="apps/v1/Deployment",
            )
            for i in range(n)
        ]

    caps = []
    for gen_i in range(12):  # each generation uses entirely fresh keys
        res = eng.schedule(gen(f"g{gen_i}", 48))
        assert all(r.success for r in res)
        caps.append(eng._fleet.cap)
    # without eviction cap would reach >= 12*48 rounded up; with the
    # 4-pass idle window it stays bounded by a few live generations
    assert eng._fleet.cap <= 512, caps
    assert eng._fleet.n_rows <= 48 * (eng._fleet.COMPACT_IDLE_PASSES + 2)


def test_fleet_lazy_results_expose_schedule_result_surface():
    clusters = synthetic_fleet(20, seed=2)
    snap = ClusterSnapshot(clusters)
    problems = [
        BindingProblem(
            key="w", placement=dynamic_weight_placement(), replicas=6,
            requests=REQ, gvk="apps/v1/Deployment",
        ),
        # zero-replica (non-workload): all feasible clusters, no counts
        BindingProblem(key="cfg", placement=duplicated_placement(),
                       replicas=0, requests={}, gvk="apps/v1/Deployment"),
    ]
    eng = TensorScheduler(snap)
    eng.fleet_threshold = 1
    res = eng.schedule(problems)
    assert res[0].success and sum(res[0].clusters.values()) == 6
    assert res[1].success and res[1].clusters == {}
    assert len(res[1].feasible) > 0


@pytest.mark.parametrize("path", ["dense", "legacy"])
def test_delta_fetch_sequence_fuzz(path, monkeypatch):
    """Multi-pass mutation fuzz for the delta-fetch machinery: random
    per-pass mutations (replica bumps, prev rewrites, fresh flips, NEW
    bindings, availability-only snapshot swaps, partial batches) must keep
    the fleet path identical to a fresh host-path run on EVERY pass — the
    resident entry base / host mirror / changed-bit protocol can never
    serve a stale placement. Runs against BOTH solve paths (the legacy
    entry-resident path serves tables past the dense HBM budget)."""
    if path == "legacy":
        monkeypatch.setattr(fleet_mod, "DENSE_RESIDENT_MAX_BYTES", 0)
    rng = np.random.default_rng(123)
    clusters = synthetic_fleet(40, seed=21)
    snap = ClusterSnapshot(clusters)
    problems = _mixed_problems(clusters, 240, 11)
    eng = TensorScheduler(snap, chunk_size=64)
    eng.fleet_threshold = 1
    next_key = len(problems)
    for pass_no in range(8):
        op = pass_no % 4
        if op == 1:  # mutate ~10% of rows
            for i in rng.choice(len(problems), 24, replace=False):
                p = problems[i]
                problems[i] = BindingProblem(
                    key=p.key, placement=p.placement,
                    replicas=int(rng.integers(0, 40)), requests=p.requests,
                    gvk=p.gvk,
                    prev={
                        clusters[int(j)].name: int(rng.integers(1, 9))
                        for j in rng.choice(len(clusters), 2, replace=False)
                    } if rng.random() < 0.5 else {},
                    fresh=bool(rng.random() < 0.3),
                )
        elif op == 2:  # availability-only snapshot swap (token unchanged)
            for cl in clusters:
                rs = cl.status.resource_summary
                for dim, q in list(rs.allocated.items()):
                    cap = rs.allocatable.get(dim, 0)
                    rs.allocated[dim] = int(
                        min(max(0, q + int(rng.integers(-2, 3)) * max(1, cap // 100)), cap)
                    )
            snap = ClusterSnapshot(clusters)
            assert eng.update_snapshot(snap)
        elif op == 3:  # grow the fleet with new bindings
            for _ in range(16):
                problems.append(
                    BindingProblem(
                        key=f"b{next_key}",
                        placement=problems[int(rng.integers(0, 4))].placement,
                        replicas=int(rng.integers(0, 40)), requests=REQ,
                        gvk="apps/v1/Deployment",
                    )
                )
                next_key += 1
        # alternate full batches with partial ones (delta rows subset)
        if pass_no % 2 == 0:
            batch = problems
        else:
            idx = sorted(
                int(j) for j in rng.choice(len(problems), 96, replace=False)
            )
            batch = [problems[j] for j in idx]
        got = eng.schedule(batch)
        assert eng._fleet is not None, "fleet path did not engage"
        host = TensorScheduler(snap)
        want = host._schedule_host(
            batch, [host._compiled(p.placement) for p in batch]
        )
        try:
            _assert_same(want, got)
        except AssertionError as e:
            raise AssertionError(f"pass {pass_no}: {e}") from e


def test_spread_rows_ride_the_fleet_and_match_host_path():
    """Spread-constraint selections intern as DERIVED placements so those
    rows ride the device-resident path; placements must equal the host
    path exactly, and capacity drift that changes the selection must
    re-pack the affected rows (derived identity = selection content)."""
    from karmada_tpu.api.policy import (
        ClusterAffinity, LabelSelector, SpreadConstraint,
    )

    rng = np.random.default_rng(77)
    clusters = synthetic_fleet(60, seed=13)
    snap = ClusterSnapshot(clusters)
    pls = []
    for _ in range(4):
        pls.append(
            dynamic_weight_placement(
                cluster_affinity=ClusterAffinity(
                    label_selector=LabelSelector(
                        match_labels={"env": str(rng.choice(["prod", "staging", "dev"]))}
                    )
                ),
                spread_constraints=[
                    SpreadConstraint(
                        spread_by_field="region",
                        min_groups=int(rng.integers(1, 3)),
                        max_groups=int(rng.integers(3, 6)),
                    ),
                    SpreadConstraint(
                        spread_by_field="cluster",
                        min_groups=2,
                        max_groups=int(rng.integers(4, 12)),
                    ),
                ],
            )
        )
    problems = [
        BindingProblem(
            key=f"s{i}", placement=pls[i % 4],
            replicas=int(rng.integers(1, 30)), requests=REQ,
            gvk="apps/v1/Deployment",
            prev={
                clusters[int(j)].name: int(rng.integers(1, 6))
                for j in rng.choice(len(clusters), 2, replace=False)
            } if rng.random() < 0.4 else {},
        )
        for i in range(300)
    ]
    eng = TensorScheduler(snap, chunk_size=128)
    eng.fleet_threshold = 1
    got = eng.schedule(problems)
    assert eng._fleet is not None, "spread rows must engage the fleet"
    # the fleet table actually carries them (derived placements interned)
    assert eng._fleet.n_rows >= 250
    host = TensorScheduler(snap)
    want = host._schedule_host(
        problems, [host._compiled(p.placement) for p in problems]
    )
    _assert_same(want, got)

    # capacity drift changes selections: the derived identities change and
    # the fleet re-packs — still identical to a fresh host run
    for cl in clusters:
        rs = cl.status.resource_summary
        rs.allocated["cpu"] = int(rs.allocatable.get("cpu", 0) * float(rng.uniform(0.1, 0.9)))
    snap2 = ClusterSnapshot(clusters)
    assert eng.update_snapshot(snap2)
    got2 = eng.schedule(problems)
    host2 = TensorScheduler(snap2)
    want2 = host2._schedule_host(
        problems, [host2._compiled(p.placement) for p in problems]
    )
    _assert_same(want2, got2)


def test_zero_replica_spread_rows_match_host_path():
    """Zero-replica (non-workload) spread rows must expose the same
    feasible/selected set on the fleet path as on the host path — the
    selection availability mirrors merge_estimates' zero-replica
    short-circuit exactly."""
    from karmada_tpu.api.policy import (
        ClusterAffinity, LabelSelector, SpreadConstraint,
    )

    clusters = synthetic_fleet(30, seed=4)
    snap = ClusterSnapshot(clusters)
    pl = dynamic_weight_placement(
        cluster_affinity=ClusterAffinity(
            label_selector=LabelSelector(match_labels={"env": "prod"})
        ),
        spread_constraints=[
            SpreadConstraint(spread_by_field="region", min_groups=1, max_groups=3),
            SpreadConstraint(spread_by_field="cluster", min_groups=1, max_groups=5),
        ],
    )
    problems = [
        BindingProblem(key=f"z{i}", placement=pl, replicas=(0 if i % 3 == 0 else 5),
                       requests=REQ, gvk="apps/v1/Deployment")
        for i in range(120)
    ]
    eng = TensorScheduler(snap, chunk_size=64)
    eng.fleet_threshold = 1
    got = eng.schedule(problems)
    assert eng._fleet is not None
    host = TensorScheduler(snap)
    want = host._schedule_host(
        problems, [host._compiled(p.placement) for p in problems]
    )
    _assert_same(want, got)


def test_cell_delta_overflow_rows_fall_back_to_full_fetch():
    """A churn pass whose rows moved MORE than 62 cells must fetch those
    rows' full entry runs (the 6-bit delta field saturates) while normal
    rows still ride the delta wire — and both stay host-identical."""
    clusters = synthetic_fleet(200, seed=31)
    snap = ClusterSnapshot(clusters)
    pl = dynamic_weight_placement()
    problems = [
        BindingProblem(
            key=f"b{i}", placement=pl, replicas=100, requests=REQ,
            gvk="apps/v1/Deployment",
        )
        for i in range(128)
    ]
    eng = TensorScheduler(snap, chunk_size=64)
    eng.fleet_threshold = 1
    eng.schedule(problems)
    eng.schedule(problems)
    assert eng._fleet is not None and eng._fleet._delta_live is False
    # shrink replicas 100 -> 3: ~all of each row's ~100 placed cells
    # change, saturating the per-row delta field
    problems = [
        BindingProblem(
            key=p.key, placement=p.placement, replicas=3, requests=p.requests,
            gvk=p.gvk,
        )
        for p in problems
    ]
    res = eng.schedule(problems)
    bd = eng.last_breakdown
    assert bd.get("changed_rows") == 128.0
    # every row overflowed: delta path engaged but served them via the
    # exact full-row fetch
    assert bd.get("delta_rows") == 0.0, bd
    host = TensorScheduler(snap)
    want = host._schedule_host(
        problems, [host._compiled(p.placement) for p in problems]
    )
    _assert_same(want, res)
    # ...and a subsequent small mutation (a few cells per row) rides the
    # delta wire again
    problems = [
        BindingProblem(
            key=p.key, placement=p.placement,
            replicas=5 if i < 30 else p.replicas, requests=p.requests,
            gvk=p.gvk,
        )
        for i, p in enumerate(problems)
    ]
    res2 = eng.schedule(problems)
    bd2 = eng.last_breakdown
    assert bd2.get("changed_rows", 0) >= 30, bd2
    assert bd2.get("delta_rows", 0) >= 30, bd2
    host2 = TensorScheduler(snap)
    want2 = host2._schedule_host(
        problems, [host2._compiled(p.placement) for p in problems]
    )
    _assert_same(want2, res2)


def test_post_compaction_delta_pass_is_host_identical():
    """After _compact() remaps rows, a DELTA-carried pass (small table:
    total and dtotal under the floor caps, so use_delta engages on the
    very first post-compact pass) must not merge insert-only deltas into
    another binding's stale host-mirror run — the reset must drop the
    entry mirror with the residents."""
    clusters = synthetic_fleet(50, seed=13)
    snap = ClusterSnapshot(clusters)
    pl = dynamic_weight_placement()

    def mk(key, reps):
        return BindingProblem(key=key, placement=pl, replicas=reps,
                              requests=REQ, gvk="apps/v1/Deployment")

    doomed = [mk(f"d{i}", 5 + i % 7) for i in range(80)]
    kept = [mk(f"k{i}", 3 + i % 9) for i in range(80)]
    eng = TensorScheduler(snap, chunk_size=64)
    eng.fleet_threshold = 1
    eng.schedule(doomed + kept)
    # age the doomed rows out, then compact: rows remap (kept rows shift
    # down into the doomed rows' slots)
    for _ in range(10):
        eng.schedule(kept)
    table = eng._fleet
    assert table._compact(), "compaction must trigger for this layout"
    res = eng.schedule(kept)
    bd = eng.last_breakdown
    # the point of the test: this pass must be delta-carried
    assert bd.get("delta_rows", 0) > 0, bd
    host = TensorScheduler(snap)
    want = host._schedule_host(kept, [host._compiled(p.placement) for p in kept])
    _assert_same(want, res)


def test_caps_compile_stable_after_warm_window():
    """Cap tuning must never dispatch an unseen XLA trace once the warm
    window (SHRINK_SUSTAIN + a couple of passes) has run: growth lands at
    churn onset, sustained shrinks land inside the window, and wobbles
    ride already-compiled traces. A vote-delayed shrink used to fire MID
    storm — a 94s dispatch stall on the TPU bench."""
    import copy

    clusters = synthetic_fleet(48, seed=21)
    snap = ClusterSnapshot(clusters)
    pl = dynamic_weight_placement()
    problems = [
        BindingProblem(key=f"b{i}", placement=pl, replicas=(i % 25) + 1,
                       requests=REQ, gvk="apps/v1/Deployment")
        for i in range(1500)
    ]
    eng = TensorScheduler(snap, chunk_size=256)
    eng.schedule(problems)  # warm/compile

    rng = np.random.default_rng(3)

    def drift():
        for cl in clusters:
            rs = cl.status.resource_summary
            for dim, q in list(rs.allocated.items()):
                alloc = rs.allocatable.get(dim, 0)
                rs.allocated[dim] = int(min(max(
                    0, q + int(rng.integers(-2, 3)) * max(1, alloc // 100)
                ), alloc))
        assert eng.update_snapshot(ClusterSnapshot(clusters))

    # warm window: steady settle + churn onset + the sustained-shrink span
    window = fleet_mod.SHRINK_SUSTAIN + 4
    for _ in range(3):
        eng.schedule(problems)
    for _ in range(window):
        drift()
        eng.schedule(problems)
    # beyond the window: alternate steady and churn passes — no pass may
    # compile anything new, whatever the cap tuner wants
    for i in range(8):
        if i % 3:
            drift()
        eng.schedule(problems)
        assert not eng.last_pass_new_trace, (
            f"pass {i} dispatched an unseen trace after the warm window"
        )
