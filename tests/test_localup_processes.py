"""Multi-process deployment e2e: the hack/local-up-karmada.sh +
hack/run-e2e.sh tier (VERDICT r3 items 4/5/7).

``LocalUp`` spawns solver sidecar, estimator server, the plane (store bus +
cluster proxy + /metrics) and a pull-mode agent as REAL OS processes; every
assertion here drives the system through network surfaces only — the bus
(gRPC), the proxy (HTTP), /metrics (HTTP), and the remote CLI as its own
subprocess. Nothing in this file touches a ControlPlane object directly.
"""

import json
import subprocess
import sys
import time
import urllib.request

import pytest

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.bus.service import StoreReplica
from karmada_tpu.localup import LocalUp
from karmada_tpu.utils.builders import duplicated_placement, new_deployment


def wait_for(predicate, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def run_cli(*args: str) -> str:
    out = subprocess.run(
        [sys.executable, "-m", "karmada_tpu.cli", *args],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, f"cli {args} failed: {out.stdout}\n{out.stderr}"
    return out.stdout


@pytest.fixture(scope="module")
def deployment():
    with LocalUp(members=2, pull=("pull1",), lease_grace=3.0) as lu:
        replica = StoreReplica(f"127.0.0.1:{lu.endpoints['bus']}")
        replica.start()
        assert replica.wait_synced(10)
        yield lu, replica
        replica.close()


class TestMultiProcessQuickstart:
    def test_quickstart_through_network_surfaces(self, deployment):
        lu, r = deployment
        # platform policy: control-plane components run CPU jax; the
        # scraped backend confirms the solver honored it (the TPU-owning
        # variant is tests/test_tpu_solver_localup.py, opt-in)
        assert lu.solver_backend == "cpu"
        # all three clusters visible over the bus
        assert wait_for(
            lambda: {c.name for c in r.store.list("Cluster")}
            >= {"member1", "member2", "pull1"}
        )
        # quickstart: apply template + policy THROUGH the bus
        r.apply(new_deployment("nginx", replicas=2))
        r.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="nginx-policy", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    placement=duplicated_placement(),
                ),
            )
        )

        def scheduled_everywhere():
            rb = r.store.get("ResourceBinding", "default/nginx-deployment")
            if rb is None:
                return False
            placed = {tc.name for tc in rb.spec.clusters}
            return placed >= {"member1", "member2", "pull1"}

        assert wait_for(scheduled_everywhere), "binding never spanned all clusters"

        # the out-of-process agent applied the Work and reflected status
        def pull_work_applied():
            w = r.store.get("Work", "karmada-es-pull1/default.nginx-deployment")
            return w is not None and any(
                c.type == "Applied" and c.status for c in w.status.conditions
            )

        assert wait_for(pull_work_applied), "pull agent never applied the Work"

        # aggregated status reaches the binding for the pull member
        def aggregated():
            rb = r.store.get("ResourceBinding", "default/nginx-deployment")
            return any(
                i.cluster_name == "pull1" and i.applied
                for i in rb.status.aggregated_status
            )

        assert wait_for(aggregated), "no aggregated status from the pull member"

    def test_remote_cli_reads_and_writes(self, deployment):
        lu, r = deployment
        bus = f"127.0.0.1:{lu.endpoints['bus']}"
        proxy = f"127.0.0.1:{lu.endpoints['proxy']}"

        # get (fleet scope, from the karmada tier)
        out = run_cli(
            "--bus", bus, "--proxy", proxy,
            "get", "apps/v1/Deployment", "--namespace", "default",
            "--name", "nginx",
        )
        obj = json.loads(out)
        assert obj["meta"]["name"] == "nginx"

        # cluster-scoped get rides the HTTP proxy passthrough (the member
        # object as applied by the plane's execution controller)
        def member_get():
            try:
                out = run_cli(
                    "--bus", bus, "--proxy", proxy,
                    "get", "apps/v1/Deployment", "--namespace", "default",
                    "--name", "nginx", "--cluster", "member1",
                )
                return json.loads(out)["meta"]["name"] == "nginx"
            except AssertionError:
                return False

        assert wait_for(member_get), "cluster-scoped remote get never served"

        # describe aggregates binding placements
        out = run_cli(
            "--bus", bus, "describe", "apps/v1/Deployment", "default", "nginx"
        )
        assert "placements:" in out and "pull1" in out

        # cordon/uncordon round-trip THROUGH the bus (write path + admission)
        run_cli("--bus", bus, "cordon", "member2")
        assert wait_for(
            lambda: any(
                t.key == "node.karmada.io/unschedulable"
                for t in r.store.get("Cluster", "member2").spec.taints
            )
        )
        run_cli("--bus", bus, "uncordon", "member2")
        assert wait_for(
            lambda: not any(
                t.key == "node.karmada.io/unschedulable"
                for t in r.store.get("Cluster", "member2").spec.taints
            )
        )

    def test_remote_cli_generic_verbs(self, deployment, tmp_path):
        """VERDICT r3 item 8: the kubectl-style write surface over the bus
        (pkg/karmadactl/karmadactl.go:98-178 — apply/patch/label/annotate/
        delete/api-resources), with admission enforced SERVER-SIDE in the
        plane process."""
        lu, r = deployment
        bus = f"127.0.0.1:{lu.endpoints['bus']}"

        # apply: a Deployment template + a policy, one manifest file
        manifest = tmp_path / "app.json"
        manifest.write_text(json.dumps([
            {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": "verbs-app", "namespace": "default"},
                "spec": {"replicas": 4},
            },
            {
                "kind": "PropagationPolicy",
                "metadata": {"name": "verbs-pp", "namespace": "default"},
                "spec": {
                    "resource_selectors": [
                        {"api_version": "apps/v1", "kind": "Deployment",
                         "name": "verbs-app"}
                    ],
                    "placement": {
                        "replica_scheduling": {
                            "replica_scheduling_type": "Divided",
                            "replica_division_preference": "Weighted",
                        }
                    },
                },
            },
        ]))
        out = run_cli("--bus", bus, "apply", "-f", str(manifest))
        assert "Resource/default/verbs-app" in out
        assert "PropagationPolicy/default/verbs-pp" in out

        def divided(total):
            def check():
                rb = r.store.get(
                    "ResourceBinding", "default/verbs-app-deployment"
                )
                return rb is not None and sum(
                    tc.replicas for tc in rb.spec.clusters
                ) == total
            return check

        assert wait_for(divided(4)), "applied workload never scheduled"

        # patch: bump replicas through the bus; the binding re-divides
        out = run_cli(
            "--bus", bus, "patch", "apps/v1/Deployment", "default",
            "verbs-app", "-p", json.dumps({"spec": {"replicas": 9}}),
        )
        assert json.loads(out)["spec"]["replicas"] == 9
        assert wait_for(divided(9)), "patched replica count never re-divided"

        # label + annotate round-trip
        out = run_cli(
            "--bus", bus, "label", "apps/v1/Deployment", "default",
            "verbs-app", "tier=web", "junk-",
        )
        assert json.loads(out)["meta"]["labels"]["tier"] == "web"
        out = run_cli(
            "--bus", bus, "annotate", "apps/v1/Deployment", "default",
            "verbs-app", "owner=cli-e2e",
        )
        assert json.loads(out)["meta"]["annotations"]["owner"] == "cli-e2e"

        # admission observed: an invalid policy is REJECTED by the plane's
        # chain, server-side, through the same wire path
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({
            "kind": "PropagationPolicy",
            "metadata": {"name": "bad-pp", "namespace": "default"},
            "spec": {"resource_selectors": []},
        }))
        proc = subprocess.run(
            [sys.executable, "-m", "karmada_tpu.cli", "--bus", bus,
             "apply", "-f", str(bad)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "resourceSelectors" in proc.stdout
        assert r.store.get("PropagationPolicy", "default/bad-pp") is None

        # api-resources discovery
        out = run_cli("--bus", bus, "api-resources")
        kinds = {e["kind"] for e in json.loads(out)}
        assert {"PropagationPolicy", "Cluster", "apps/v1/Deployment"} <= kinds

        # delete: template gone; binding cleaned up by the detector
        out = run_cli(
            "--bus", bus, "delete", "apps/v1/Deployment", "default",
            "verbs-app",
        )
        assert "deleted" in out
        assert wait_for(
            lambda: r.store.get("Resource", "default/verbs-app") is None
        )

    def test_cluster_proxy_passthrough_serves_member_state(self, deployment):
        lu, r = deployment
        # the deployment propagated to member1 inside the plane process; the
        # HTTP proxy passthrough reads it back out (impersonation + REST)
        req = urllib.request.Request(
            f"http://127.0.0.1:{lu.endpoints['proxy']}"
            "/apis/cluster.karmada.io/v1alpha1/clusters/member1/proxy"
            "/apis/apps/v1/namespaces/default/deployments/nginx",
            headers={"Authorization": "Bearer admin-token"},
        )

        def proxied():
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    body = json.loads(resp.read())
                return body["metadata"]["name"] == "nginx"
            except Exception:
                return False

        assert wait_for(proxied), "proxy passthrough never served the object"

    def test_metrics_endpoint_serves_scheduler_metrics(self, deployment):
        lu, _r = deployment
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{lu.endpoints['metrics']}/metrics", timeout=5
        ).read().decode()
        assert "karmada_scheduler_schedule_attempts_total" in body
        # scheduling happened in the quickstart: at least one sample line
        assert any(
            line and not line.startswith("#") for line in body.splitlines()
        ), body
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{lu.endpoints['metrics']}/healthz", timeout=5
        ).read()
        assert health == b"ok\n"

    def test_agent_process_death_fails_workload_over(self, deployment):
        """Runs LAST in the module: kills the pull agent process and expects
        the lease to go stale (grace shortened to 3s), the cluster to
        degrade, and the binding to rehome onto surviving members."""
        lu, r = deployment
        lu.kill("agent-pull1")

        def failed_over():
            rb = r.store.get("ResourceBinding", "default/nginx-deployment")
            placed = {tc.name for tc in rb.spec.clusters}
            return "pull1" not in placed and placed >= {"member1", "member2"}

        assert wait_for(failed_over, timeout=45.0), (
            "binding never left the dead pull cluster"
        )
        cluster = r.store.get("Cluster", "pull1")
        ready = next(c for c in cluster.status.conditions if c.type == "Ready")
        assert not ready.status
