"""Registration authority, custom filter plugins, store concurrency."""

import threading

import numpy as np

from karmada_tpu.api import Cluster, ObjectMeta
from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.utils import Store
from karmada_tpu.utils.builders import duplicated_placement, new_cluster
from karmada_tpu.utils.register import RegistrationAuthority


class TestRegistrationAuthority:
    def test_token_csr_flow(self):
        clock = [0.0]
        ra = RegistrationAuthority(clock=lambda: clock[0])
        tok = ra.create_token()
        assert ra.validate_token(tok.token)
        assert not ra.validate_token("bogus.token")
        cert = ra.submit_csr("member9", tok.token)
        assert cert is not None and ra.approved_csrs == ["member9"]
        # expired token rejected
        clock[0] += ra.TOKEN_TTL + 1
        assert ra.submit_csr("memberX", tok.token) is None

    def test_rotation(self):
        clock = [0.0]
        ra = RegistrationAuthority(clock=lambda: clock[0])
        tok = ra.create_token()
        first = ra.submit_csr("m", tok.token)
        assert ra.rotate_if_needed("m") is None  # fresh
        clock[0] = first.expires_at - 1000  # nearly expired
        renewed = ra.rotate_if_needed("m")
        assert renewed is not None and renewed.serial != first.serial


class TestCustomFilterPlugin:
    def test_custom_mask_composes(self):
        clusters = [new_cluster("a"), new_cluster("b"), new_cluster("c")]
        snap = ClusterSnapshot(clusters)

        def only_even(snapshot, problems):
            mask = np.zeros((len(problems), snapshot.num_clusters), bool)
            mask[:, ::2] = True  # a, c
            return mask

        sched = TensorScheduler(snap, custom_filters=[only_even])
        [res] = sched.schedule(
            [BindingProblem(key="b", placement=duplicated_placement(), replicas=1,
                            gvk="apps/v1/Deployment")]
        )
        assert set(res.clusters) == {"a", "c"}


class TestStoreConcurrency:
    def test_concurrent_writers(self):
        """The Go suite runs under -race; the analogue here is hammering the
        store from threads and asserting invariants hold."""
        store = Store()
        errors = []

        def writer(start):
            try:
                for i in range(200):
                    store.apply(Cluster(meta=ObjectMeta(name=f"c-{start}-{i % 20}")))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        clusters = store.list("Cluster")
        assert len(clusters) == 8 * 20
        versions = [c.meta.resource_version for c in clusters]
        assert len(set(versions)) == len(versions)  # rv uniqueness held
