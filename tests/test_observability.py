"""Plane-wide observability (ISSUE 6): exposition format golden file,
read/write race hammer, wave-scoped span tracing, MetricsServer endpoints
(in-proc and over real HTTP from the standalone processes)."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from karmada_tpu.utils.metrics import (
    E2E_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsServer,
    Registry,
    e2e_scheduling_duration,
    registry as global_registry,
    serve_process_metrics,
)
from karmada_tpu.utils.tracing import EventRecorder, WaveTracer


def _get(port: int, path: str, timeout: float = 10.0) -> tuple[int, str]:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return resp.status, resp.read().decode()


# --------------------------------------------------------------------------
# exposition format
# --------------------------------------------------------------------------


class TestExpositionGolden:
    def test_render_golden(self):
        """The full text exposition, byte for byte: HELP before TYPE,
        label sets sorted, cumulative buckets, +Inf, sum/count tails."""
        reg = Registry()
        c = reg.counter("karmada_tpu_req_total", "requests served")
        g = reg.gauge("karmada_tpu_depth", "queue depth")
        h = reg.histogram(
            "karmada_tpu_lat_seconds", "latency", buckets=(0.1, 1.0)
        )
        c.inc(result="ok")
        c.inc(result="ok")
        c.inc(result="err")
        g.set(7, worker="detector")
        h.observe(0.05)
        h.observe(0.5)
        h.observe(9.0)
        want = "\n".join(
            [
                "# HELP karmada_tpu_req_total requests served",
                "# TYPE karmada_tpu_req_total counter",
                'karmada_tpu_req_total{result="err"} 1.0',
                'karmada_tpu_req_total{result="ok"} 2.0',
                "# HELP karmada_tpu_depth queue depth",
                "# TYPE karmada_tpu_depth gauge",
                'karmada_tpu_depth{worker="detector"} 7.0',
                "# HELP karmada_tpu_lat_seconds latency",
                "# TYPE karmada_tpu_lat_seconds histogram",
                'karmada_tpu_lat_seconds_bucket{le="0.1"} 1',
                'karmada_tpu_lat_seconds_bucket{le="1.0"} 2',
                'karmada_tpu_lat_seconds_bucket{le="+Inf"} 3',
                "karmada_tpu_lat_seconds_sum 9.55",
                "karmada_tpu_lat_seconds_count 3",
                "",
            ]
        )
        assert reg.render() == want

    def test_label_value_escaping(self):
        c = Counter("karmada_tpu_esc_total", "")
        c.inc(path='a"b\\c\nd')
        [line] = [
            ln for ln in c.render() if not ln.startswith("#")
        ]
        assert line == 'karmada_tpu_esc_total{path="a\\"b\\\\c\\nd"} 1.0'

    def test_help_omitted_when_empty(self):
        c = Counter("karmada_tpu_nohelp_total")
        c.inc()
        lines = list(c.render())
        assert lines[0].startswith("# TYPE")

    def test_e2e_buckets_cover_settle_passes(self):
        """A 14-15s settle pass must land in a finite bucket (the old
        default buckets topped out at 10s — everything fell in +Inf)."""
        assert any(b >= 15.0 for b in E2E_BUCKETS)
        assert e2e_scheduling_duration.buckets == E2E_BUCKETS
        h = Histogram("karmada_tpu_x_seconds", buckets=E2E_BUCKETS)
        h.observe(14.7)
        finite = [
            ln for ln in h.render()
            if '_bucket' in ln and '+Inf' not in ln and ln.endswith(" 1")
        ]
        assert finite, "14.7s observation landed only in +Inf"

    def test_gauge_value_and_add(self):
        g = Gauge("karmada_tpu_g", "")
        g.set(3, k="a")
        g.add(2, k="a")
        assert g.value(k="a") == 5.0


class TestConcurrencyHammer:
    def test_concurrent_inc_observe_render(self):
        """Writers storm counters/histograms while readers render: no
        exceptions (dict-changed-mid-iteration, bucket rows mid-update)
        and the final totals are exact."""
        reg = Registry()
        c = reg.counter("karmada_tpu_h_total", "hammer")
        h = reg.histogram("karmada_tpu_h_seconds", "hammer")
        n, writers = 2000, 4
        stop = threading.Event()
        errors: list = []

        def write(i):
            try:
                for k in range(n):
                    c.inc(worker=f"w{i}")
                    h.observe(0.001 * (k % 50), worker=f"w{i}")
            except Exception as exc:  # noqa: BLE001 — the assertion target
                errors.append(exc)

        def read():
            try:
                while not stop.is_set():
                    text = reg.render()
                    assert "# TYPE karmada_tpu_h_total counter" in text
                    c.value(worker="w0")
                    h.summary(worker="w1")
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        readers = [threading.Thread(target=read) for _ in range(2)]
        ws = [threading.Thread(target=write, args=(i,)) for i in range(writers)]
        for t in readers + ws:
            t.start()
        for t in ws:
            t.join()
        stop.set()
        for t in readers:
            t.join()
        assert not errors, errors
        for i in range(writers):
            assert c.value(worker=f"w{i}") == n
            assert h.summary(worker=f"w{i}")["count"] == n

    def test_event_recorder_threaded_ring(self):
        rec = EventRecorder(capacity=256)
        errors: list = []

        def spam(i):
            try:
                for k in range(500):
                    rec.event(f"Kind/obj{i}", "Normal", "R", str(k))
                    rec.for_object(f"Kind/obj{i}")
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=spam, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert len(rec.events) == 256  # deque(maxlen) bound


# --------------------------------------------------------------------------
# wave tracing
# --------------------------------------------------------------------------


class TestWaveTracer:
    def test_nesting_and_parent_ids(self):
        tr = WaveTracer()
        wave = tr.begin_wave("test")
        with tr.span("settle") as root:
            with tr.span("controller.scheduler") as mid:
                with tr.span("scheduler.pass") as leaf:
                    pass
        spans = tr.dump(wave)
        by_name = {s["name"]: s for s in spans}
        assert by_name["scheduler.pass"]["parent_id"] == mid.span_id
        assert by_name["controller.scheduler"]["parent_id"] == root.span_id
        assert by_name["settle"]["parent_id"] is None
        assert {s["wave"] for s in spans} == {wave}

    def test_ensure_wave_reuses_open_wave(self):
        tr = WaveTracer()
        w1 = tr.ensure_wave("a")
        assert tr.ensure_wave("b") == w1
        tr.end_wave()
        assert tr.ensure_wave("c") == w1 + 1

    def test_ring_bound(self):
        tr = WaveTracer(capacity=16)
        tr.begin_wave()
        for _ in range(64):
            with tr.span("x"):
                pass
        assert len(tr.dump()) == 16

    def test_record_retroactive_span(self):
        tr = WaveTracer()
        tr.begin_wave()
        with tr.span("parent") as p:
            tr.record("kernel.device", 0.25, kind="device", compile=True)
        [dev] = [s for s in tr.dump() if s["name"] == "kernel.device"]
        assert dev["parent_id"] == p.span_id
        assert abs(dev["duration_s"] - 0.25) < 1e-6

    def test_wave_summary_attribution(self):
        tr = WaveTracer()
        wave = tr.begin_wave()
        with tr.span("settle"):
            time.sleep(0.01)
            with tr.span("controller.scheduler"):
                time.sleep(0.02)
                tr.record("kernel.device", 0.015, kind="device",
                          compile=True)
        s = tr.wave_summary(wave)
        assert s["wave"] == wave
        assert s["coverage"] == pytest.approx(1.0)
        assert s["total_s"] >= 0.03
        assert s["device_s"] == pytest.approx(0.015, abs=1e-6)
        assert s["compile_s"] == pytest.approx(0.015, abs=1e-6)
        # self-times sum to the root total (summary values are rounded
        # to 6 decimals, so compare at rounding precision)
        assert sum(s["phases"].values()) == pytest.approx(
            s["total_s"], abs=1e-4
        )

    def test_threaded_spans_do_not_cross_parent(self):
        tr = WaveTracer()
        tr.begin_wave()
        done = threading.Event()

        def other():
            with tr.span("other-thread"):
                done.wait(2)

        t = threading.Thread(target=other)
        with tr.span("main-thread"):
            t.start()
            time.sleep(0.02)
        done.set()
        t.join()
        [other_span] = [
            s for s in tr.dump() if s["name"] == "other-thread"
        ]
        # the other thread's span must NOT parent under main's open span
        assert other_span["parent_id"] is None


class TestPlaneWaveTrace:
    def test_settle_produces_single_wave_tree(self):
        """An in-proc storm renders as ONE wave whose tree attributes
        detector / scheduler (pack+pass) / binding / status time."""
        from karmada_tpu import cli
        from karmada_tpu.api import (
            PropagationPolicy,
            PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.api.core import ObjectMeta
        from karmada_tpu.utils.builders import (
            dynamic_weight_placement,
            new_cluster,
            new_deployment,
        )
        from karmada_tpu.utils.tracing import tracer

        cp = cli.cmd_init()
        for i in range(3):
            cp.join_cluster(new_cluster(f"m{i}", cpu="100", memory="200Gi"))
        cp.settle()
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment")],
                placement=dynamic_weight_placement(),
            ),
        ))
        for i in range(20):
            cp.store.apply(new_deployment(f"d{i}", replicas=(i % 4) + 1))
        t0 = time.perf_counter()
        cp.settle()
        wall = time.perf_counter() - t0
        s = tracer.wave_summary()
        assert s["spans"] > 0
        # the storm's spans share one wave id, and the root settle spans
        # cover >=95% of the externally measured wall time (the bench
        # acceptance criterion, asserted here at test scale)
        assert s["total_s"] >= 0.95 * wall or wall < 0.05
        assert s["coverage"] == pytest.approx(1.0)
        for phase in ("controller.detector", "controller.scheduler",
                      "controller.binding", "scheduler.pass"):
            assert phase in s["phases"], sorted(s["phases"])


# --------------------------------------------------------------------------
# endpoints
# --------------------------------------------------------------------------


class TestMetricsServerEndpoints:
    def test_metrics_healthz_traces(self):
        from karmada_tpu.utils.tracing import tracer

        tracer.ensure_wave("test")
        with tracer.span("settle"):
            pass
        srv = MetricsServer()
        port = srv.start()
        try:
            status, body = _get(port, "/metrics")
            assert status == 200
            # the full family catalogue is served from every process
            for family in (
                "karmada_tpu_kernel_compiles_total",
                "karmada_tpu_estimator_rpcs_total",
                "karmada_tpu_bus_events_total",
                "karmada_tpu_controller_works_rendered_total",
                "karmada_tpu_settle_seconds",
                "karmada_scheduler_schedule_attempts_total",
            ):
                assert f"# TYPE {family}" in body, family
            status, body = _get(port, "/healthz")
            assert (status, body) == (200, "ok\n")
            status, body = _get(port, "/debug/traces")
            assert status == 200
            doc = json.loads(body)
            assert "waves" in doc and "spans" in doc
            assert any(s["name"] == "settle" for s in doc["spans"])
            with pytest.raises(urllib.error.HTTPError):
                _get(port, "/nope")
        finally:
            srv.stop()

    def test_serve_process_metrics_flag_semantics(self, monkeypatch):
        monkeypatch.delenv("KARMADA_TPU_METRICS_PORT", raising=False)
        assert serve_process_metrics(None) is None  # env empty = disabled
        assert serve_process_metrics("") is None  # explicit empty = disabled
        srv = serve_process_metrics("0")  # 0 = ephemeral
        try:
            assert srv is not None and srv.port > 0
        finally:
            srv.stop()
        monkeypatch.setenv("KARMADA_TPU_METRICS_PORT", "0")
        srv = serve_process_metrics(None)  # flag absent -> env
        try:
            assert srv is not None and srv.port > 0
        finally:
            srv.stop()


class TestProcessExposition:
    """The acceptance half of ISSUE 6 (c): solver, estimator and bus
    PROCESSES all answer /metrics with the new families, over real HTTP
    from real spawned processes."""

    def _spawn_cases(self):
        import sys

        py = sys.executable
        return [
            (
                "bus",
                [py, "-m", "karmada_tpu.bus", "--address", "127.0.0.1:0",
                 "--metrics-port", "0"],
                r'"metrics": (\d+)',
                "karmada_tpu_bus_events_total",
            ),
            (
                "estimator",
                [py, "-m", "karmada_tpu.estimator", "--cluster", "m1",
                 "--address", "127.0.0.1:0", "--metrics-port", "0"],
                r"metrics listening on port (\d+)",
                "karmada_tpu_estimator_server_requests_total",
            ),
            (
                "solver",
                [py, "-m", "karmada_tpu.solver", "--address", "127.0.0.1:0",
                 "--metrics-port", "0", "--warmup-manifest", ""],
                r"metrics listening on port (\d+)",
                "karmada_tpu_solver_requests_total",
            ),
        ]

    def test_all_processes_serve_metrics(self):
        from karmada_tpu.localup import scrape_line, spawn_child

        # SEQUENTIAL spawn/assert/teardown: three concurrent jax children
        # thrash a small CI rig into multi-minute import stalls; one at a
        # time each comes up in seconds
        for name, cmd, pattern, family in self._spawn_cases():
            proc = spawn_child(cmd)
            try:
                port = int(scrape_line(proc, pattern, timeout=240))
                status, body = _get(port, "/metrics", timeout=30)
                assert status == 200, name
                assert f"# TYPE {family}" in body, (name, family)
                # the catalogue is shared: every process serves the full
                # family set regardless of which subsystem runs in it
                assert "# TYPE karmada_tpu_settle_seconds" in body, name
                status, body = _get(port, "/healthz", timeout=30)
                assert (status, body) == (200, "ok\n"), name
                status, body = _get(port, "/debug/traces", timeout=30)
                assert status == 200 and "waves" in json.loads(body), name
            finally:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    proc.kill()
