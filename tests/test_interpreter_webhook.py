"""Interpreter webhook transport: HTTPS extension point for resource semantics.

Ref: config/v1alpha1 ResourceInterpreterWebhookConfiguration +
interpretercontext_types.go request/response contract;
pkg/resourceinterpreter/customized/webhook client/configmanager.
"""

import subprocess

import pytest

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.interpreter.webhook import (
    InterpreterWebhook,
    InterpreterWebhookServer,
    ResourceInterpreterWebhookConfiguration,
    RuleWithOperations,
    WebhookClientConfig,
    WebhookInterpreterClient,
    apply_json_patch,
)
from karmada_tpu.utils.builders import new_cluster, static_weight_placement
from karmada_tpu.utils.member import MemberCluster

GVK = "example.io/v1/Canary"


def canary(replicas=6):
    return Resource(
        api_version="example.io/v1",
        kind="Canary",
        meta=ObjectMeta(name="demo", namespace="default"),
        spec={"workers": replicas, "configRef": "canary-conf"},
        status={},
    )


def canary_handlers():
    """The extension author's webhook logic."""

    def interpret_replica(req):
        obj = req["object"]
        return {
            "replicas": obj["spec"].get("workers", 0),
            "replicaRequirements": {"resourceRequest": {"cpu": "100m"}},
        }

    def revise_replica(req):
        return {
            "patch": [
                {"op": "replace", "path": "/spec/workers", "value": req["replicas"]}
            ],
            "patchType": "JSONPatch",
        }

    def interpret_health(req):
        return {"healthy": (req["object"].get("status") or {}).get("phase") == "Ready"}

    def interpret_dependency(req):
        name = req["object"]["spec"].get("configRef")
        return {
            "dependencies": (
                [{"apiVersion": "v1", "kind": "ConfigMap", "name": name}] if name else []
            )
        }

    def aggregate_status(req):
        total = sum(
            (i.get("status") or {}).get("readyWorkers", 0)
            for i in req.get("aggregatedStatus") or []
        )
        return {
            "patch": [
                {"op": "add", "path": "/status/readyWorkers", "value": total}
            ],
            "patchType": "JSONPatch",
        }

    def retain(req):
        observed = req.get("observedObject") or {}
        paused = (observed.get("spec") or {}).get("paused")
        if paused is None:
            return {}
        return {
            "patch": [{"op": "add", "path": "/spec/paused", "value": paused}],
            "patchType": "JSONPatch",
        }

    return {
        "InterpretReplica": interpret_replica,
        "ReviseReplica": revise_replica,
        "InterpretHealth": interpret_health,
        "InterpretDependency": interpret_dependency,
        "AggregateStatus": aggregate_status,
        "Retain": retain,
    }


@pytest.fixture()
def server():
    s = InterpreterWebhookServer(canary_handlers())
    s.start()
    yield s
    s.stop()


def make_webhook(url, operations=("*",)):
    return InterpreterWebhook(
        name="canary.example.io",
        client_config=WebhookClientConfig(url=url),
        rules=[
            RuleWithOperations(
                operations=list(operations),
                api_versions=["example.io/v1"],
                kinds=["Canary"],
            )
        ],
        timeout_seconds=5.0,
    )


class TestClientRoundTrip:
    def test_get_replicas_and_requirements(self, server):
        client = WebhookInterpreterClient(make_webhook(server.url))
        replicas, reqs = client.get_replicas(canary(9))
        assert replicas == 9
        assert reqs.resource_request == {"cpu": 100}

    def test_revise_replica_via_json_patch(self, server):
        client = WebhookInterpreterClient(make_webhook(server.url))
        out = client.revise_replica(canary(6), 2)
        assert out.spec["workers"] == 2

    def test_health_and_dependencies(self, server):
        client = WebhookInterpreterClient(make_webhook(server.url))
        obj = canary()
        assert not client.interpret_health(obj)
        obj.status = {"phase": "Ready"}
        assert client.interpret_health(obj)
        deps = client.get_dependencies(obj)
        assert [(d.kind, d.name) for d in deps] == [("ConfigMap", "canary-conf")]

    def test_retain_pulls_member_written_field(self, server):
        client = WebhookInterpreterClient(make_webhook(server.url))
        desired, observed = canary(), canary()
        observed.spec["paused"] = True
        out = client.retain(desired, observed)
        assert out.spec["paused"] is True

    def test_unsupported_operation_raises(self, server):
        client = WebhookInterpreterClient(make_webhook(server.url))
        server.handlers.pop("InterpretHealth")
        with pytest.raises(RuntimeError, match="not supported"):
            client.interpret_health(canary())


class TestJsonPatch:
    def test_add_replace_remove_nested_and_lists(self):
        doc = {"spec": {"a": 1, "items": [1, 2, 3]}}
        out = apply_json_patch(
            doc,
            [
                {"op": "replace", "path": "/spec/a", "value": 5},
                {"op": "add", "path": "/spec/b", "value": {"x": 1}},
                {"op": "add", "path": "/spec/items/-", "value": 9},
                {"op": "remove", "path": "/spec/items/0"},
            ],
        )
        assert out == {"spec": {"a": 5, "b": {"x": 1}, "items": [2, 3, 9]}}
        assert doc["spec"]["a"] == 1  # original untouched

    def test_escaped_path_tokens(self):
        doc = {"metadata": {"labels": {}}}
        out = apply_json_patch(
            doc,
            [{"op": "add", "path": "/metadata/labels/app~1name", "value": "x"}],
        )
        assert out["metadata"]["labels"]["app/name"] == "x"


class TestControlPlaneIntegration:
    def test_webhook_drives_propagation(self, server):
        """The full pipeline uses the webhook for an unknown CRD: replica
        extraction, division revise, health — via the CR config manager."""
        cp = ControlPlane()
        for i in (1, 2):
            member = MemberCluster(f"member{i}")
            member.api_enablements.append(GVK)
            cp.join_cluster(
                new_cluster(f"member{i}", cpu="100", memory="200Gi"), member=member
            )
        cp.settle()
        cp.store.apply(
            ResourceInterpreterWebhookConfiguration(
                meta=ObjectMeta(name="canary-hooks"),
                webhooks=[make_webhook(server.url)],
            )
        )
        cp.store.apply(canary(8))
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="canary-policy", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="example.io/v1", kind="Canary")
                    ],
                    placement=static_weight_placement({"member1": 3, "member2": 1}),
                ),
            )
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/demo-canary")
        assert rb.spec.replicas == 8  # webhook GetReplicas
        placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
        assert placed == {"member1": 6, "member2": 2}
        # webhook ReviseReplica divided the member manifest via JSONPatch
        obj = cp.members.get("member1").get(GVK, "default", "demo")
        assert obj.spec["workers"] == 6

    def test_config_deletion_deregisters(self, server):
        cp = ControlPlane()
        config = ResourceInterpreterWebhookConfiguration(
            meta=ObjectMeta(name="canary-hooks"),
            webhooks=[make_webhook(server.url)],
        )
        cp.store.apply(config)
        cp.settle()
        assert cp.interpreter.hook_enabled(GVK, "GetReplicas")
        cp.store.delete(ResourceInterpreterWebhookConfiguration.KIND, "canary-hooks")
        cp.settle()
        assert not cp.interpreter.hook_enabled(GVK, "GetReplicas")


@pytest.fixture(scope="module")
def tls_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("webhook-pki")
    ext = d / "san.ext"
    ext.write_text("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(d / "srv.key"), "-out", str(d / "srv.crt"),
         "-days", "1", "-subj", "/CN=localhost",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True,
    )
    return d


class TestHttps:
    def test_https_round_trip_with_ca_bundle(self, tls_files):
        server = InterpreterWebhookServer(
            canary_handlers(),
            certfile=str(tls_files / "srv.crt"),
            keyfile=str(tls_files / "srv.key"),
        )
        server.start()
        try:
            webhook = make_webhook(server.url)
            webhook.client_config.ca_bundle = (tls_files / "srv.crt").read_bytes()
            client = WebhookInterpreterClient(webhook)
            replicas, _ = client.get_replicas(canary(4))
            assert replicas == 4
        finally:
            server.stop()


class TestWildcardRules:
    def test_wildcard_binds_gvk_appearing_later(self, server):
        cp = ControlPlane()
        cp.store.apply(
            ResourceInterpreterWebhookConfiguration(
                meta=ObjectMeta(name="wildcard-hooks"),
                webhooks=[
                    InterpreterWebhook(
                        name="all.example.io",
                        client_config=WebhookClientConfig(url=server.url),
                        rules=[
                            RuleWithOperations(
                                operations=["InterpretReplica"],
                                api_versions=["example.io/v1"],
                                kinds=["*"],
                            )
                        ],
                    )
                ],
            )
        )
        cp.settle()
        assert not cp.interpreter.hook_enabled(GVK, "GetReplicas")
        cp.store.apply(canary(3))  # the kind appears after the config
        cp.settle()
        assert cp.interpreter.hook_enabled(GVK, "GetReplicas")
        replicas, _ = cp.interpreter.get_replicas(canary(3))
        assert replicas == 3


class TestOverlappingConfigs:
    def test_deleting_one_config_keeps_the_overlapping_owner(self, server):
        cp = ControlPlane()
        cp.store.apply(canary(1))
        for name in ("hooks-a", "hooks-b"):
            cp.store.apply(
                ResourceInterpreterWebhookConfiguration(
                    meta=ObjectMeta(name=name),
                    webhooks=[make_webhook(server.url)],
                )
            )
        cp.settle()
        assert cp.interpreter.hook_enabled(GVK, "GetReplicas")
        # deleting A must not clobber B's live registration
        cp.store.delete(ResourceInterpreterWebhookConfiguration.KIND, "hooks-a")
        cp.settle()
        assert cp.interpreter.hook_enabled(GVK, "GetReplicas")
        replicas, _ = cp.interpreter.get_replicas(canary(5))
        assert replicas == 5

    def test_quantity_strings_in_replica_requirements(self, server):
        server.handlers["InterpretReplica"] = lambda req: {
            "replicas": 2,
            "replicaRequirements": {
                "resourceRequest": {"cpu": "500m", "memory": "1Gi"}
            },
        }
        client = WebhookInterpreterClient(make_webhook(server.url))
        replicas, reqs = client.get_replicas(canary())
        assert replicas == 2
        assert reqs.resource_request["cpu"] == 500
        assert reqs.resource_request["memory"] == 1 << 30
