"""Networked store watch bus: replica convergence + write-through over a
real gRPC socket (the control-plane <-> agent DCN channel)."""

import time

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.policy import PropagationPolicy, PropagationSpec
from karmada_tpu.api.work import ResourceBinding, ResourceBindingSpec
from karmada_tpu.bus import StoreBusServer, StoreReplica, kind_registry
from karmada_tpu.utils import Store


def _cm(name, payload):
    return Resource(
        api_version="v1", kind="ConfigMap",
        meta=ObjectMeta(name=name, namespace="ns"),
        spec={"payload": payload},
    )


@pytest.fixture()
def bus():
    store = Store()
    server = StoreBusServer(store, "127.0.0.1:0")
    port = server.start()
    yield store, port
    server.stop()


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


class TestStoreBus:
    def test_replica_replays_and_follows_live_events(self, bus):
        store, port = bus
        store.apply(_cm("pre", 1))
        store.apply(
            ResourceBinding(meta=ObjectMeta(name="rb1", namespace="ns"),
                            spec=ResourceBindingSpec())
        )
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert replica.wait_synced()
        assert _wait(lambda: replica.store.get("Resource", "ns/pre") is not None)
        # typed decode: the binding comes back as a ResourceBinding
        assert _wait(
            lambda: replica.store.get("ResourceBinding", "ns/rb1") is not None
        )
        rb = replica.store.get("ResourceBinding", "ns/rb1")
        assert isinstance(rb, ResourceBinding)
        # live event
        store.apply(_cm("live", 2))
        assert _wait(
            lambda: (o := replica.store.get("Resource", "ns/live")) is not None
            and o.spec["payload"] == 2
        )
        # deletion propagates
        store.delete("Resource", "ns/pre", force=True)
        assert _wait(lambda: replica.store.get("Resource", "ns/pre") is None)
        replica.close()

    def test_kind_filter_and_write_through(self, bus):
        store, port = bus
        replica = StoreReplica(f"127.0.0.1:{port}", kinds=("Resource",))
        replica.start()
        assert replica.wait_synced()
        # write-through: the replica's apply lands on the PRIMARY and echoes
        rv = replica.apply(_cm("via-bus", 7))
        assert rv > 0
        assert store.get("Resource", "ns/via-bus").spec["payload"] == 7
        assert _wait(
            lambda: replica.store.get("Resource", "ns/via-bus") is not None
        )
        # filtered kinds never reach this replica
        store.apply(
            ResourceBinding(meta=ObjectMeta(name="rb2", namespace="ns"),
                            spec=ResourceBindingSpec())
        )
        store.apply(_cm("marker", 1))
        assert _wait(
            lambda: replica.store.get("Resource", "ns/marker") is not None
        )
        assert replica.store.get("ResourceBinding", "ns/rb2") is None
        # delete write-through
        assert replica.delete("Resource", "ns/via-bus", force=True)
        assert store.get("Resource", "ns/via-bus") is None
        replica.close()

    def test_registry_covers_core_kinds(self):
        reg = kind_registry()
        for kind in ("ResourceBinding", "Work", "Cluster",
                     "PropagationPolicy", "FederatedHPA", "Resource"):
            assert kind in reg, kind

    def test_replica_reconnects_after_server_restart(self):
        store = Store()
        server = StoreBusServer(store, "127.0.0.1:0")
        port = server.start()
        store.apply(_cm("a", 1))
        replica = StoreReplica(f"127.0.0.1:{port}")
        replica.start()
        assert _wait(lambda: replica.store.get("Resource", "ns/a") is not None)
        server.stop(grace=0)
        # writes while the replica is disconnected
        store.apply(_cm("b", 2))
        server2 = StoreBusServer(store, f"127.0.0.1:{port}")
        server2.start()
        try:
            assert _wait(
                lambda: replica.store.get("Resource", "ns/b") is not None,
                timeout=10.0,
            )
        finally:
            replica.close()
            server2.stop()
