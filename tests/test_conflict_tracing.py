"""Conflict resolution, tracing, events, plugin toggles."""

import logging

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.utils.builders import (
    duplicated_placement,
    new_cluster,
    new_deployment,
)
from karmada_tpu.utils.tracing import EventRecorder, Trace


def make_plane(n=1, **kw):
    cp = ControlPlane(**kw)
    for i in range(1, n + 1):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.settle()
    return cp


def nginx_policy(conflict_resolution="Abort"):
    return PropagationPolicy(
        meta=ObjectMeta(name="p", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=duplicated_placement(),
            conflict_resolution=conflict_resolution,
        ),
    )


class TestConflictResolution:
    def test_abort_on_unmanaged_existing_object(self):
        cp = make_plane(1)
        # a pre-existing unmanaged deployment in the member
        cp.members.get("member1").apply(new_deployment("app", replicas=9))
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(nginx_policy("Abort"))
        cp.settle()
        # member object untouched; work carries the conflict condition
        obj = cp.members.get("member1").get("apps/v1/Deployment", "default", "app")
        assert obj.spec["replicas"] == 9
        work = cp.store.get("Work", "karmada-es-member1/default.app-deployment")
        cond = next(c for c in work.status.conditions if c.type == "Applied")
        assert not cond.status and cond.reason == "ResourceConflict"

    def test_overwrite_takes_over(self):
        cp = make_plane(1)
        cp.members.get("member1").apply(new_deployment("app", replicas=9))
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(nginx_policy("Overwrite"))
        cp.settle()
        obj = cp.members.get("member1").get("apps/v1/Deployment", "default", "app")
        assert obj.spec["replicas"] == 2
        assert obj.meta.annotations["karmada.io/managed"] == "true"


class TestTracing:
    def test_trace_logs_only_slow_ops(self, caplog):
        t = Trace("fast-op")
        t.step("a")
        assert t.log_if_long(10.0) is None
        t2 = Trace("slow-op", binding="default/x")
        t2.step("estimate")
        msg = t2.log_if_long(0.0)
        assert "slow-op" in msg and "estimate=" in msg and "binding=default/x" in msg

    def test_event_recorder_ring(self):
        rec = EventRecorder(capacity=2)
        for i in range(4):
            rec.event("ResourceBinding/default/x", "Normal", "Scheduled", str(i))
        assert len(rec.events) == 2
        assert [e.message for e in rec.for_object("ResourceBinding/default/x")] == [
            "2", "3",
        ]


class TestPluginToggles:
    def test_disabled_taint_plugin_admits_tainted_cluster(self):
        from karmada_tpu.api.cluster import Taint

        clusters = [
            new_cluster("ok"),
            new_cluster("tainted", taints=[Taint(key="k", value="v",
                                                 effect="NoSchedule")]),
        ]
        snap = ClusterSnapshot(clusters)
        strict = TensorScheduler(snap)
        lenient = TensorScheduler(snap, disabled_plugins=["TaintToleration"])
        problem = BindingProblem(
            key="b", placement=duplicated_placement(), replicas=1,
            gvk="apps/v1/Deployment",
        )
        [r1] = strict.schedule([problem])
        [r2] = lenient.schedule([problem])
        assert set(r1.clusters) == {"ok"}
        assert set(r2.clusters) == {"ok", "tainted"}


class TestPluginFlagsPlumbing:
    """--plugins enable/disable + out-of-tree filters reach the engine from
    the control-plane constructor (options.go:130-165 analogue)."""

    def _plane(self, **kw):
        from karmada_tpu.api import (
            PropagationPolicy, PropagationSpec, ResourceSelector)
        from karmada_tpu.api.core import ObjectMeta
        from karmada_tpu.api.cluster import Taint
        from karmada_tpu.controlplane import ControlPlane
        from karmada_tpu.utils.builders import (
            dynamic_weight_placement, new_cluster, new_deployment)

        cp = ControlPlane(**kw)
        cp.join_cluster(new_cluster("plain"))
        cp.join_cluster(new_cluster(
            "salty", taints=[Taint(key="dedicated", effect="NoSchedule")]))
        cp.settle()
        cp.store.apply(new_deployment("app", replicas=4, cpu="100m"))
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment")],
                placement=dynamic_weight_placement(),
            )))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        return {tc.name for tc in rb.spec.clusters}

    def test_default_filters_tainted_cluster(self):
        assert self._plane() == {"plain"}

    def test_disable_taint_toleration_flag(self):
        names = self._plane(disabled_scheduler_plugins=["TaintToleration"])
        assert names == {"plain", "salty"}

    def test_out_of_tree_filter_plugin(self):
        import numpy as np

        def no_salty(snap, problems):
            mask = np.ones((len(problems), snap.num_clusters), bool)
            for j, name in enumerate(snap.names):
                if name == "plain":
                    mask[:, j] = False
            return mask

        names = self._plane(
            disabled_scheduler_plugins=["TaintToleration"],
            scheduler_filter_plugins=[no_salty],
        )
        assert names == {"salty"}
