"""FederatedHPA / CronFederatedHPA tests (ref: federatedhpa e2e + unit
tables)."""

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.autoscaling import (
    CronFederatedHPA,
    CronFederatedHPARule,
    CronFederatedHPASpec,
    FederatedHPA,
    FederatedHPASpec,
    MetricSpec,
    ScaleTargetRef,
)
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)
from karmada_tpu.utils.cron import cron_matches


def make_plane(clock):
    cp = ControlPlane(clock=clock)
    for i in (1, 2):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.store.apply(new_deployment("web", replicas=4))
    cp.store.apply(
        PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        )
    )
    cp.settle()
    return cp


def make_hpa(min_r=1, max_r=10, target_util=50, window=0):
    return FederatedHPA(
        meta=ObjectMeta(name="web-hpa", namespace="default"),
        spec=FederatedHPASpec(
            scale_target_ref=ScaleTargetRef(kind="Deployment", name="web"),
            min_replicas=min_r,
            max_replicas=max_r,
            metrics=[MetricSpec(resource_name="cpu", target_average_utilization=target_util)],
            stabilization_window_seconds=window,
        ),
    )


class TestFederatedHPA:
    def test_scale_up_on_high_utilization(self):
        clock = [0.0]
        cp = make_plane(lambda: clock[0])
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        for tc in rb.spec.clusters:
            cp.members.get(tc.name).pod_metrics["default/web"] = {
                "pods": tc.replicas, "ready_pods": tc.replicas,
                "cpu_utilization": 100.0,
            }
        cp.store.apply(make_hpa(target_util=50))
        cp.settle()
        template = cp.store.get("Resource", "default/web")
        assert template.spec["replicas"] == 8  # 4 * 100/50
        # binding followed the scale (detector -> scheduler scale-up)
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        assert sum(tc.replicas for tc in rb.spec.clusters) == 8

    def test_scale_down_respects_stabilization_window(self):
        clock = [0.0]
        cp = make_plane(lambda: clock[0])
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        for tc in rb.spec.clusters:
            cp.members.get(tc.name).pod_metrics["default/web"] = {
                "pods": tc.replicas, "ready_pods": tc.replicas,
                "cpu_utilization": 10.0,
            }
        cp.store.apply(make_hpa(target_util=50, window=300))
        cp.settle()
        # low utilization recommends scale-down to 1, but the window holds
        # the recent high recommendation (initial = current 4)
        template = cp.store.get("Resource", "default/web")
        assert template.spec["replicas"] == 4
        # past the window, scale-down proceeds
        clock[0] += 400
        cp.settle()
        template = cp.store.get("Resource", "default/web")
        assert template.spec["replicas"] == 1

    def test_per_pod_resource_metrics_scale_up(self):
        # per-pod sets (workload_pods) route through the full replica
        # calculator: 4 pods at 150m vs 100m request, 50% target -> 12
        clock = [0.0]
        cp = make_plane(lambda: clock[0])
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        for tc in rb.spec.clusters:
            cp.members.get(tc.name).workload_pods["default/web"] = [
                {"name": f"{tc.name}-p{i}", "request": 100, "value": 150}
                for i in range(tc.replicas)
            ]
        cp.store.apply(make_hpa(target_util=50, max_r=20))
        cp.settle()
        # calibration = assigned/current = 1; ratio 3.0 over 4 ready pods
        assert cp.store.get("Resource", "default/web").spec["replicas"] == 12

    def test_per_pod_unready_holds_scale_up(self):
        # an unready pod backfills 0 on scale-up; ratio falls back inside
        # the tolerance band -> current size holds (calculator semantics
        # the aggregate path cannot express)
        clock = [0.0]
        cp = make_plane(lambda: clock[0])
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        pods_left = 4
        for tc in rb.spec.clusters:
            samples = []
            for i in range(tc.replicas):
                if pods_left == 1:
                    samples.append({
                        "name": f"{tc.name}-p{i}", "request": 100,
                        "ready": False,
                    })
                else:
                    samples.append({
                        "name": f"{tc.name}-p{i}", "request": 100,
                        "value": 150,
                    })
                pods_left -= 1
            cp.members.get(tc.name).workload_pods["default/web"] = samples
        cp.store.apply(make_hpa(target_util=100, max_r=20))
        cp.settle()
        # 3 ready at 150% of a 100% target with one unready backfilled to
        # 0 -> new ratio (450/400)=1.125 -> ceil(1.125*4)=5
        assert cp.store.get("Resource", "default/web").spec["replicas"] == 5

    def test_object_metric_scale(self):
        clock = [0.0]
        cp = make_plane(lambda: clock[0])
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        first = rb.spec.clusters[0].name
        cp.members.get(first).custom_metric_series.append({
            "resource": "services", "namespaced": True,
            "namespace": "default", "object": "web-svc",
            "metric": "queue_length", "value": 30.0,
        })
        hpa = make_hpa(max_r=20)
        hpa.spec.metrics = [
            MetricSpec(
                type="Object", metric_name="queue_length",
                target_value=10.0,
                described_object=ScaleTargetRef(
                    kind="Service", name="web-svc"
                ),
            )
        ]
        cp.store.apply(hpa)
        cp.settle()
        # usage 30 / target 10 = ratio 3 over current 4 (no per-pod sets:
        # the synthesized ready list has len=current) -> 12
        assert cp.store.get("Resource", "default/web").spec["replicas"] == 12

    def test_max_replicas_clamp(self):
        clock = [0.0]
        cp = make_plane(lambda: clock[0])
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        for tc in rb.spec.clusters:
            cp.members.get(tc.name).pod_metrics["default/web"] = {
                "pods": tc.replicas, "ready_pods": tc.replicas,
                "cpu_utilization": 500.0,
            }
        cp.store.apply(make_hpa(max_r=6))
        cp.settle()
        assert cp.store.get("Resource", "default/web").spec["replicas"] == 6


class TestCron:
    def test_cron_matcher(self):
        # 2026-01-01 00:00 UTC is a Thursday
        import calendar

        ts = calendar.timegm((2026, 1, 1, 0, 0, 0, 0, 0, 0))
        assert cron_matches("* * * * *", ts)
        assert cron_matches("0 0 * * *", ts)
        assert not cron_matches("30 * * * *", ts)
        assert cron_matches("*/15 * * * *", ts)
        assert cron_matches("0 0 1 1 *", ts)
        assert not cron_matches("0 0 2 1 *", ts)
        assert cron_matches("0 0 * * 4", ts)  # Thursday

    def test_cron_scales_workload(self):
        import calendar

        base = calendar.timegm((2026, 1, 1, 8, 59, 30, 0, 0, 0))
        clock = [float(base)]
        cp = make_plane(lambda: clock[0])
        cp.store.apply(
            CronFederatedHPA(
                meta=ObjectMeta(name="nightly", namespace="default"),
                spec=CronFederatedHPASpec(
                    scale_target_ref=ScaleTargetRef(kind="Deployment", name="web"),
                    rules=[
                        CronFederatedHPARule(
                            name="morning-scale",
                            schedule="0 9 * * *",
                            target_replicas=12,
                        )
                    ],
                ),
            )
        )
        cp.settle()
        assert cp.store.get("Resource", "default/web").spec["replicas"] == 4
        clock[0] += 40  # crosses 09:00
        cp.settle()
        assert cp.store.get("Resource", "default/web").spec["replicas"] == 12
        cron = cp.store.get("CronFederatedHPA", "default/nightly")
        assert cron.status.execution_histories[0].applied_replicas == 12
