"""Aggregated proxy passthrough over real HTTP: unified auth via
impersonation headers + streamed log follow + multi-cluster list paging.

Ref: pkg/registry/cluster/storage/proxy.go:41-102 and
pkg/search/proxy/store/multi_cluster_cache.go:187-265."""

import http.client
import threading
import time

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.search.proxyserver import ClusterProxyServer
from karmada_tpu.search.registry import MultiClusterCache, decode_token
from karmada_tpu.utils.member import MemberCluster, MemberClientRegistry


def _pod(name, ns="default"):
    return Resource(
        api_version="v1", kind="Pod",
        meta=ObjectMeta(name=name, namespace=ns),
        spec={"containers": []},
    )


@pytest.fixture()
def proxy():
    members = MemberClientRegistry()
    m1 = MemberCluster("member1")
    m1.apply(_pod("web-0"))
    m1.append_pod_log("default", "web-0", "hello")
    m1.append_pod_log("default", "web-0", "world")
    members.register(m1)
    server = ClusterProxyServer(
        members,
        tokens={"tok-alice": ("alice", ["dev", "oncall"])},
    )
    port = server.start()
    yield members, port, m1
    server.stop()


def _get(port, path, token="tok-alice"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    headers = {}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


BASE = "/apis/cluster.karmada.io/v1alpha1/clusters/member1/proxy"


class TestProxyPassthrough:
    def test_rejects_missing_or_bad_token(self, proxy):
        _, port, _ = proxy
        status, _ = _get(port, f"{BASE}/api/v1/namespaces/default/pods", token="")
        assert status == 401
        status, _ = _get(port, f"{BASE}/api/v1/namespaces/default/pods",
                         token="tok-wrong")
        assert status == 401

    def test_resource_get_carries_impersonated_identity(self, proxy):
        _, port, m1 = proxy
        status, body = _get(
            port, f"{BASE}/api/v1/namespaces/default/pods/web-0"
        )
        assert status == 200
        assert b"web-0" in body
        audit = m1.proxy_audit[-1]
        assert audit["user"] == "alice"
        assert audit["groups"] == ["dev", "oncall"]

    def test_list_and_unknown_cluster(self, proxy):
        _, port, _ = proxy
        status, body = _get(port, f"{BASE}/api/v1/namespaces/default/pods")
        assert status == 200 and b'"List"' in body
        status, _ = _get(
            port,
            "/apis/cluster.karmada.io/v1alpha1/clusters/ghost/proxy/api/v1"
            "/namespaces/default/pods",
        )
        assert status == 404

    def test_log_follow_streams_lines_appended_mid_request(self, proxy):
        _, port, m1 = proxy

        def late_writer():
            time.sleep(0.15)
            m1.append_pod_log("default", "web-0", "late-line")

        t = threading.Thread(target=late_writer)
        t.start()
        status, body = _get(
            port,
            f"{BASE}/api/v1/namespaces/default/pods/web-0/log?follow=true",
        )
        t.join()
        assert status == 200
        text = body.decode()
        assert "hello" in text and "world" in text
        # the late line arrived AFTER the request began and still streamed
        assert "late-line" in text


class TestMultiClusterListPaging:
    def _cache(self):
        cache = MultiClusterCache()
        for c in ("alpha", "beta"):
            for i in range(5):
                obj = _pod(f"p{i}")
                obj.meta.resource_version = 100 + i
                cache.put(c, obj)
        return cache

    def test_pages_span_clusters_with_continue(self):
        cache = self._cache()
        seen = []
        token = ""
        pages = 0
        while True:
            items, token, rv = cache.list_paged(
                "v1/Pod", limit=3, continue_token=token
            )
            seen.extend((c, o.meta.name) for c, o in items)
            pages += 1
            if not token:
                break
        assert pages == 4  # 10 items / 3 per page
        assert seen == sorted(seen)  # cluster-major, name order
        assert len(seen) == 10 and len(set(seen)) == 10
        # the multi-cluster resource version carries per-cluster maxima
        assert decode_token(rv) == {"alpha": 104, "beta": 104}

    def test_continue_resumes_mid_cluster(self):
        cache = self._cache()
        items, token, _ = cache.list_paged("v1/Pod", limit=2)
        assert [(c, o.meta.name) for c, o in items] == [
            ("alpha", "p0"), ("alpha", "p1"),
        ]
        tok = decode_token(token)
        assert tok["cluster"] == "alpha" and tok["after"].endswith("p1")
        items2, _, _ = cache.list_paged(
            "v1/Pod", limit=4, continue_token=token
        )
        assert [(c, o.meta.name) for c, o in items2] == [
            ("alpha", "p2"), ("alpha", "p3"), ("alpha", "p4"),
            ("beta", "p0"),
        ]


class TestExecStreaming:
    def test_exec_streams_a_real_subprocess_end_to_end(self, proxy):
        """VERDICT r3 missing #5: the exec subresource pipes a REAL OS
        process through the proxy — output chunks arrive while the
        process is still running (the SPDY-session analogue), not as one
        buffered body after it exits."""
        from karmada_tpu.utils.member import SubprocessExecRuntime

        members, port, m1 = proxy
        m1.exec_stream_handler = SubprocessExecRuntime()
        script = (
            "echo first; sleep 0.4; echo second; sleep 0.4; echo third"
        )
        qs = "&".join(
            f"command={c}" for c in ("sh", "-c", script.replace(" ", "%20"))
        )
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request(
            "POST",
            f"{BASE}/api/v1/namespaces/default/pods/web-0/exec?{qs}",
            headers={"Authorization": "Bearer tok-alice"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        t0 = time.monotonic()
        arrivals = []
        line = b""
        while True:
            ch = resp.read(1)
            if not ch:
                break
            line += ch
            if ch == b"\n":
                arrivals.append((line.decode().strip(), time.monotonic() - t0))
                line = b""
        conn.close()
        texts = [t for t, _ in arrivals if t]
        assert texts == ["first", "second", "third"], texts
        # LIVE streaming: "first" arrived well before the process could
        # have finished (>=0.8s of sleeps follow it)
        first_at = next(at for t, at in arrivals if t == "first")
        third_at = next(at for t, at in arrivals if t == "third")
        assert third_at - first_at > 0.5, (first_at, third_at)
        assert first_at < 0.4, first_at

    def test_exec_failure_reports_exit_code_trailer(self, proxy):
        from karmada_tpu.utils.member import SubprocessExecRuntime

        members, port, m1 = proxy
        m1.exec_stream_handler = SubprocessExecRuntime()
        qs = "&".join(f"command={c}" for c in ("sh", "-c", "exit%207"))
        status, body = _get(
            port, f"{BASE}/api/v1/namespaces/default/pods/web-0/exec?{qs}"
        )
        assert status == 200
        assert b"command terminated with exit code 7" in body

    def test_exec_missing_pod_is_a_clean_404(self, proxy):
        members, port, m1 = proxy
        status, body = _get(
            port,
            f"{BASE}/api/v1/namespaces/default/pods/ghost/exec?command=true",
        )
        assert status == 404

    def test_attach_follows_the_log_stream(self, proxy):
        members, port, m1 = proxy
        status, body = _get(
            port, f"{BASE}/api/v1/namespaces/default/pods/web-0/attach"
        )
        assert status == 200
        assert b"hello" in body and b"world" in body

    def test_remote_cli_exec_rides_the_proxy(self, proxy):
        """cmd_exec against a RemotePlane-shaped chain: argv survives the
        query round-trip and the rc trailer parses."""
        from karmada_tpu.cli import _RemoteProxyChain
        from karmada_tpu.search import ProxyRequest
        from karmada_tpu.utils.member import SubprocessExecRuntime

        members, port, m1 = proxy
        m1.exec_stream_handler = SubprocessExecRuntime()

        class _FakeStore:
            def get(self, *a):
                return None

            def list(self, *a):
                return []

        chain = _RemoteProxyChain(_FakeStore(), f"127.0.0.1:{port}", "tok-alice")
        resp = chain.connect(ProxyRequest(
            verb="exec", gvk="v1/Pod", namespace="default", name="web-0",
            cluster="member1",
            options={"command": ["sh", "-c", "echo streamed via proxy; exit 3"]},
        ))
        assert resp.error is None or resp.error == ""
        assert "streamed via proxy" in resp.data["stdout"]
        assert resp.data["rc"] == 3
