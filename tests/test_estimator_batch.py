"""Batched estimator wire protocol + generation-gated delta refresh.

The reference fans out one MaxAvailableReplicas RPC per (cluster, query)
under a shared deadline (client/accurate.go:139-162); the batched protocol
collapses a scheduling pass to one MaxAvailableReplicasBatch per SERVER and
gates refreshes on each cluster's snapshot generation (GetGenerations), so
a no-movement refresh never re-pays the profile fan-out. Old servers
(UNIMPLEMENTED) negotiate the per-profile unary fallback per connection —
pipelined, with placements byte-identical to the batch path.

Servers here are real gRPC servers (EstimatorGrpcServer) hosted in-process
so the tests can mutate the member NodeCaches directly and watch the
generation gate react.
"""

import numpy as np
import pytest

from karmada_tpu.estimator.accurate import (
    AccurateEstimator,
    EstimatorRegistry,
    NodeCache,
    NodeState,
)
from karmada_tpu.estimator.grpc_transport import (
    EstimatorGrpcServer,
    GrpcEstimatorConnection,
    RemoteAccurateEstimator,
)
from karmada_tpu.estimator.service import (
    EstimatorService,
    GetGenerationsRequest,
    MaxAvailableReplicasBatchRequest,
    MaxAvailableReplicasRequest,
    MultiClusterEstimatorService,
    UnsupportedMethodError,
)

DIMS = ["cpu", "memory", "pods"]


def make_member_caches(names, cpu_step=4000):
    return {
        name: NodeCache(
            DIMS,
            [
                NodeState(
                    name=f"{name}-n0",
                    allocatable={
                        "cpu": cpu_step * (i + 1),
                        "memory": 1 << 32,
                        "pods": 110,
                    },
                )
            ],
        )
        for i, name in enumerate(names)
    }


@pytest.fixture()
def wired_fleet():
    """Two real gRPC server processes' worth of clusters, hosted in-proc:
    server 1 hosts a+b, server 2 hosts c+d. Yields (caches, conns,
    registry, names)."""
    names = ["a", "b", "c", "d"]
    caches = make_member_caches(names)
    services = {
        n: EstimatorService(AccurateEstimator(n, caches[n])) for n in names
    }
    servers, conns = [], []
    registry = EstimatorRegistry()
    try:
        for hosted in (names[:2], names[2:]):
            srv = EstimatorGrpcServer(
                MultiClusterEstimatorService(
                    {n: services[n] for n in hosted}
                )
            )
            port = srv.start()
            servers.append(srv)
            conn = GrpcEstimatorConnection(
                "multi", f"127.0.0.1:{port}", timeout_seconds=5.0
            )
            conns.append(conn)
            for n in hosted:
                registry.register(
                    RemoteAccurateEstimator(n, conn, lambda: list(DIMS))
                )
        yield caches, conns, registry, names
    finally:
        for conn in conns:
            conn.close()
        for srv in servers:
            srv.stop()


def reqs_matrix(cpus):
    out = np.zeros((len(cpus), len(DIMS)), np.int64)
    out[:, 0] = cpus
    return out


class TestBatchWire:
    def test_batch_rpc_matches_unary(self, wired_fleet):
        """One batch RPC answers every hosted cluster; values equal the
        per-profile unary protocol's bit for bit."""
        caches, conns, registry, names = wired_fleet
        conn = conns[0]
        rows = [[1000, 0, 0], [2500, 0, 0], [500, 1 << 30, 0]]
        resp = conn.call(
            "MaxAvailableReplicasBatch",
            MaxAvailableReplicasBatchRequest(
                clusters=[], dims=DIMS, rows=rows
            ),
        )
        got = {r.cluster: list(r.max_replicas) for r in resp.results}
        assert sorted(got) == ["a", "b"]
        for cluster, vec in got.items():
            for row, expect in zip(rows, vec):
                unary = conn.call(
                    "MaxAvailableReplicas",
                    MaxAvailableReplicasRequest(
                        cluster=cluster,
                        resource_request={
                            d: int(v) for d, v in zip(DIMS, row) if v
                        },
                    ),
                )
                assert unary.max_replicas == expect
        assert conn.supports_batch is True

    def test_generations_ping(self, wired_fleet):
        caches, conns, registry, names = wired_fleet
        resp = conns[1].call("GetGenerations", GetGenerationsRequest())
        assert sorted(resp.generations) == ["c", "d"]
        g0 = resp.generations["c"]
        caches["c"].add_pod("c-n0", {"cpu": 100})
        resp = conns[1].call(
            "GetGenerations", GetGenerationsRequest(clusters=["c"])
        )
        assert resp.generations == {"c": g0 + 1}

    def test_registry_one_rpc_per_server_and_delta_refresh(
        self, wired_fleet
    ):
        """The steady-pass RPC shape the bench asserts: first pass = one
        batch per server; a no-movement refresh = one ping per server and
        NO profile fan-out; movement re-queries exactly the changed
        clusters."""
        caches, conns, registry, names = wired_fleet
        est = registry.make_batch_estimator(names, timeout_seconds=5.0)
        reqs = reqs_matrix([1000, 2000, 500])
        reps = np.asarray([5, 5, 5])

        out = est(reqs, reps)
        assert dict(registry.rpc_counts) == {"batch": 2, "unary": 0, "ping": 0}
        assert (out >= 0).all()

        # steady repeat: pure memo, zero wire traffic
        out2 = est(reqs, reps)
        assert dict(registry.rpc_counts) == {"batch": 2, "unary": 0, "ping": 0}
        assert (out2 == out).all()

        # no-movement refresh: one ping per server, memo survives
        registry.invalidate()
        out3 = est(reqs, reps)
        assert dict(registry.rpc_counts) == {"batch": 2, "unary": 0, "ping": 2}
        assert (out3 == out).all()

        # one member moves: its server re-queried (ping + batch), the
        # other server answers from its pinged-valid memo
        caches["b"].add_pod("b-n0", {"cpu": 1000})
        registry.invalidate()
        out4 = est(reqs, reps)
        assert dict(registry.rpc_counts) == {"batch": 3, "unary": 0, "ping": 4}
        b_col = names.index("b")
        assert out4[0, b_col] == out[0, b_col] - 1  # 1000m less free cpu
        others = [i for i in range(len(names)) if i != b_col]
        assert (out4[:, others] == out[:, others]).all()

    def test_hard_invalidate_refans_everything(self, wired_fleet):
        caches, conns, registry, names = wired_fleet
        est = registry.make_batch_estimator(names, timeout_seconds=5.0)
        reqs = reqs_matrix([1000])
        est(reqs, np.asarray([5]))
        registry.invalidate(drop=True)
        est(reqs, np.asarray([5]))
        assert registry.rpc_counts["batch"] == 4  # 2 servers x 2 full passes
        assert registry.rpc_counts["ping"] == 0


class TestMixedVersionFallback:
    @pytest.fixture()
    def old_and_new(self):
        """The same member state behind a batch-capable server AND an old
        server with the batch handler deliberately unregistered."""
        names = ["a", "b", "c"]
        caches = make_member_caches(names)
        services = {
            n: EstimatorService(AccurateEstimator(n, caches[n]))
            for n in names
        }
        new_srv = EstimatorGrpcServer(MultiClusterEstimatorService(services))
        old_srv = EstimatorGrpcServer(
            MultiClusterEstimatorService(services), enable_batch=False
        )
        try:
            yield names, new_srv.start(), old_srv.start()
        finally:
            new_srv.stop()
            old_srv.stop()

    def _registry(self, names, port):
        registry = EstimatorRegistry()
        conn = GrpcEstimatorConnection(
            "multi", f"127.0.0.1:{port}", timeout_seconds=5.0
        )
        for n in names:
            registry.register(
                RemoteAccurateEstimator(n, conn, lambda: list(DIMS))
            )
        return registry, conn

    def test_fallback_negotiation_and_parity(self, old_and_new):
        names, new_port, old_port = old_and_new
        reqs = reqs_matrix([1000, 2500, 700])
        reps = np.asarray([9, 9, 9])

        reg_new, conn_new = self._registry(names, new_port)
        reg_old, conn_old = self._registry(names, old_port)
        try:
            batch_out = reg_new.make_batch_estimator(
                names, timeout_seconds=5.0
            )(reqs, reps)
            fallback_out = reg_old.make_batch_estimator(
                names, timeout_seconds=5.0
            )(reqs, reps)
            # byte-identical placably: the min-merge sees the same matrix
            assert (batch_out == fallback_out).all()
            assert batch_out.dtype == fallback_out.dtype
            assert conn_old.supports_batch is False
            assert conn_new.supports_batch is True
            # the fallback actually fanned out per profile
            assert reg_old.rpc_counts["unary"] == 3 * len(names)
            # old servers cannot delta-gate: an invalidated pass re-pays
            # the unary fan-out (no ping protocol to ask)
            reg_old.invalidate()
            fallback_out2 = reg_old.make_batch_estimator(
                names, timeout_seconds=5.0
            )(reqs, reps)
            assert (fallback_out2 == fallback_out).all()
            assert reg_old.rpc_counts["unary"] == 2 * 3 * len(names)
            assert reg_old.rpc_counts["ping"] == 0
        finally:
            conn_new.close()
            conn_old.close()

    def test_unsupported_method_error_over_wire(self, old_and_new):
        names, _new_port, old_port = old_and_new
        conn = GrpcEstimatorConnection(
            "multi", f"127.0.0.1:{old_port}", timeout_seconds=5.0
        )
        try:
            with pytest.raises(UnsupportedMethodError):
                conn.call(
                    "MaxAvailableReplicasBatch",
                    MaxAvailableReplicasBatchRequest(
                        clusters=[], dims=DIMS, rows=[[1000, 0, 0]]
                    ),
                )
            assert conn.supports_batch is False
        finally:
            conn.close()

    def test_reprobe_after_reconnect(self, old_and_new):
        """Negotiation is per CONNECTION: after an evict/reconnect lands on
        an upgraded server, the fresh connection probes batch again."""
        names, new_port, old_port = old_and_new
        reqs = reqs_matrix([1000])
        reps = np.asarray([5])

        registry, conn_old = self._registry(names, old_port)
        try:
            est = registry.make_batch_estimator(names, timeout_seconds=5.0)
            est(reqs, reps)
            assert conn_old.supports_batch is False
            assert registry.rpc_counts["batch"] == 1  # the probe
            # reconnect: the server was upgraded (same members, batch on)
            conn_new = GrpcEstimatorConnection(
                "multi", f"127.0.0.1:{new_port}", timeout_seconds=5.0
            )
            for n in names:
                registry.register(
                    RemoteAccurateEstimator(n, conn_new, lambda: list(DIMS))
                )
            try:
                est(reqs, reps)
                assert conn_new.supports_batch is True
                assert registry.rpc_counts["batch"] == 2
                # and the batch path serves refreshes from generations now
                registry.invalidate()
                est(reqs, reps)
                assert registry.rpc_counts["ping"] == 1
                assert registry.rpc_counts["batch"] == 2
            finally:
                conn_new.close()
        finally:
            conn_old.close()

    def test_env_kill_switch_forces_unary(self, old_and_new, monkeypatch):
        names, new_port, _old_port = old_and_new
        monkeypatch.setenv("KARMADA_TPU_ESTIMATOR_BATCH", "0")
        registry, conn = self._registry(names, new_port)
        try:
            est = registry.make_batch_estimator(names, timeout_seconds=5.0)
            out = est(reqs_matrix([1000, 2000]), np.asarray([5, 5]))
            assert (out >= 0).all()
            assert registry.rpc_counts["batch"] == 0
            assert registry.rpc_counts["unary"] == 2 * len(names)
        finally:
            conn.close()


class TestPerColumnCompleteness:
    def test_straggler_does_not_block_healthy_memoization(self):
        """One dead server must not force the healthy clusters to re-pay
        the fan-out next pass (the old whole-matrix `complete` gate did)."""
        names = ["live1", "live2", "dead"]
        caches = make_member_caches(names[:2])
        services = {
            n: EstimatorService(AccurateEstimator(n, caches[n]))
            for n in names[:2]
        }
        srv = EstimatorGrpcServer(MultiClusterEstimatorService(services))
        port = srv.start()
        conn = GrpcEstimatorConnection(
            "multi", f"127.0.0.1:{port}", timeout_seconds=5.0
        )
        dead_conn = GrpcEstimatorConnection(
            "dead", "127.0.0.1:1", timeout_seconds=0.5
        )
        registry = EstimatorRegistry()
        try:
            for n in names[:2]:
                registry.register(
                    RemoteAccurateEstimator(n, conn, lambda: list(DIMS))
                )
            registry.register(
                RemoteAccurateEstimator("dead", dead_conn, lambda: list(DIMS))
            )
            est = registry.make_batch_estimator(names, timeout_seconds=5.0)
            reqs = reqs_matrix([1000, 2000])
            out = est(reqs, np.asarray([5, 5]))
            assert (out[:, :2] >= 0).all()
            assert (out[:, 2] == -1).all()
            batches_first = registry.rpc_counts["batch"]

            # healthy columns answered from memo; only the straggler is
            # re-attempted
            out2 = est(reqs, np.asarray([5, 5]))
            assert (out2 == out).all()
            assert (
                registry.rpc_counts["batch"] == batches_first + 1
            ), "only the dead server's group should re-fan"
        finally:
            conn.close()
            dead_conn.close()
            srv.stop()


class TestDegradedPassNeverReplayed:
    class FlakyConn:
        """In-proc transport seam with a kill switch: while ``down``, every
        call fails like an unreachable server."""

        def __init__(self, service):
            from karmada_tpu.estimator.service import EstimatorConnection

            self._inner = EstimatorConnection("multi", service)
            self.down = False

        def call(self, method, request):
            if self.down:
                raise ConnectionError("server unreachable")
            return self._inner.call(method, request)

    def test_recovered_cluster_invalidates_replay_token(self):
        """The arming race: a pass degraded by a transiently-down server
        must never become replayable just because the server recovers in
        time for the post-pass confirmation ping — refresh_token has to
        answer None until a full pass re-answers the cluster."""
        caches = make_member_caches(["a"])
        svc = MultiClusterEstimatorService(
            {"a": EstimatorService(AccurateEstimator("a", caches["a"]))}
        )
        conn = self.FlakyConn(svc)
        registry = EstimatorRegistry()
        registry.register(RemoteAccurateEstimator("a", conn, lambda: DIMS))
        est = registry.make_batch_estimator(["a"], timeout_seconds=2.0)
        reqs = reqs_matrix([1000])
        reps = np.asarray([5])

        # healthy pass: memoized, confirmed, replayable
        out1 = est(reqs, reps)
        assert (out1 >= 0).all()
        token1 = est.refresh_token()
        assert token1 is not None

        # server drops; the invalidated pass cannot confirm -> -1
        registry.invalidate()
        conn.down = True
        out2 = est(reqs, reps)
        assert (out2 == -1).all()
        # server recovers JUST in time for the confirmation probe: the
        # generation still matches, so confirm_token could confirm — but
        # the degraded pass must not be replayable
        conn.down = False
        assert est.refresh_token() is None

        # the next full pass answers from the still-valid memo and
        # becomes replayable again
        out3 = est(reqs, reps)
        assert (out3 == out1).all()
        assert est.refresh_token() is not None


class TestSchedulerParity:
    def test_batch_and_fallback_placements_identical(self):
        """End to end through TensorScheduler: estimator-backed placements
        are identical between the batched protocol and the unary fallback,
        and identical to the snapshot-fed engine (min-merge degeneracy:
        each cluster's single node holds exactly the snapshot's free
        capacity)."""
        from karmada_tpu.scheduler import (
            BindingProblem,
            ClusterSnapshot,
            TensorScheduler,
        )
        from karmada_tpu.utils.builders import (
            dynamic_weight_placement,
            synthetic_fleet,
        )
        from karmada_tpu.utils.quantity import parse_resource_list

        snap = ClusterSnapshot(synthetic_fleet(8, seed=77))
        dims = list(snap.dims)
        free = np.maximum(np.asarray(snap.available_cap), 0)
        services = {}
        for i, name in enumerate(snap.names):
            node = NodeState(
                name=f"{name}-n0",
                allocatable={d: int(free[i][r]) for r, d in enumerate(dims)},
            )
            services[name] = EstimatorService(
                AccurateEstimator(name, NodeCache(dims, [node]))
            )
        srv = EstimatorGrpcServer(MultiClusterEstimatorService(services))
        old_srv = EstimatorGrpcServer(
            MultiClusterEstimatorService(services), enable_batch=False
        )
        port, old_port = srv.start(), old_srv.start()

        rng = np.random.default_rng(3)
        pl = dynamic_weight_placement()
        profiles = [
            parse_resource_list(
                {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
            )
            for p in range(4)
        ]
        problems = [
            BindingProblem(
                key=f"e{i}", placement=pl,
                replicas=int(rng.integers(1, 40)),
                requests=profiles[int(rng.integers(0, 4))],
                gvk="apps/v1/Deployment",
            )
            for i in range(96)
        ]

        def run(target_port):
            registry = EstimatorRegistry()
            conn = GrpcEstimatorConnection(
                "multi", f"127.0.0.1:{target_port}", timeout_seconds=5.0
            )
            try:
                for name in snap.names:
                    registry.register(
                        RemoteAccurateEstimator(
                            name, conn, lambda: list(dims)
                        )
                    )
                batch = registry.make_batch_estimator(
                    snap.names, timeout_seconds=5.0
                )
                eng = TensorScheduler(snap, extra_estimators=[batch])
                return eng.schedule(problems), registry
            finally:
                conn.close()

        try:
            res_batch, reg_batch = run(port)
            res_fallback, reg_fallback = run(old_port)
            assert reg_batch.rpc_counts["batch"] >= 1
            assert reg_batch.rpc_counts["unary"] == 0
            assert reg_fallback.rpc_counts["unary"] > 0
            plain = TensorScheduler(snap).schedule(problems)
            for a, b, c in zip(res_batch, res_fallback, plain):
                assert a.success == b.success == c.success
                assert dict(a.clusters) == dict(b.clusters) == dict(c.clusters)
        finally:
            srv.stop()
            old_srv.stop()


class TestChannelResilience:
    """ISSUE 7: the estimator channel under the unified resilience policy —
    wire failures reset the batch negotiation (re-probe before reuse), a
    breaker-open server answers -1 with zero executor/wire cost, and the
    breaker recovers half-open -> closed without operator action."""

    def _one_server_registry(self, name="a", reset="0.3"):
        import os

        os.environ["KARMADA_TPU_BREAKER_RESET_SECONDS"] = reset
        try:
            caches = make_member_caches([name])
            svc = MultiClusterEstimatorService(
                {name: EstimatorService(AccurateEstimator(name, caches[name]))}
            )
            srv = EstimatorGrpcServer(svc, "127.0.0.1:0")
            port = srv.start()
            conn = GrpcEstimatorConnection(
                name, f"127.0.0.1:{port}", timeout_seconds=2.0
            )
            registry = EstimatorRegistry()
            registry.register(
                RemoteAccurateEstimator(name, conn, lambda: list(DIMS))
            )
        finally:
            del os.environ["KARMADA_TPU_BREAKER_RESET_SECONDS"]
        return caches, svc, srv, port, conn, registry

    def test_wire_failure_resets_batch_negotiation(self):
        """A server that dies and returns mid-pass must re-probe the batch
        protocol before reuse: the returning build may be OLDER (no batch
        handler), and a pinned supports_batch=True would ship it batch
        RPCs forever."""
        caches, svc, srv, port, conn, registry = self._one_server_registry()
        try:
            est = registry.make_batch_estimator(["a"], timeout_seconds=2.0)
            out = est(reqs_matrix([1000]), np.asarray([5]))
            assert (out >= 0).all()
            assert conn.supports_batch is True

            srv.stop(0)
            registry.invalidate(drop=True)
            out = est(reqs_matrix([1000]), np.asarray([5]))
            assert (out == -1).all()
            # the wire failure reset the pin: next use re-negotiates
            assert conn.supports_batch is None

            # the server returns AS AN OLD BUILD on the same port
            old_srv = EstimatorGrpcServer(
                svc, f"127.0.0.1:{port}", enable_batch=False
            )
            old_srv.start()
            try:
                import grpc as _grpc

                _grpc.channel_ready_future(conn._channel).result(timeout=10)
                conn.breaker.record_success()  # heal: recovery is below
                registry.invalidate(drop=True)
                out = est(reqs_matrix([1000]), np.asarray([5]))
                assert (out >= 0).all()
                assert conn.supports_batch is False  # unary negotiated
                assert registry.rpc_counts["unary"] > 0
            finally:
                old_srv.stop(0)
        finally:
            try:
                srv.stop(0)
            except Exception:
                pass
            conn.close()

    def test_breaker_open_answers_unauthentic_with_zero_wire_cost(self):
        from karmada_tpu.utils import backoff
        from karmada_tpu.utils.metrics import circuit_state

        caches, svc, srv, port, conn, registry = self._one_server_registry(
            reset="30"
        )
        try:
            est = registry.make_batch_estimator(["a"], timeout_seconds=2.0)
            out = est(reqs_matrix([1000]), np.asarray([5]))
            assert (out >= 0).all()

            srv.stop(0)
            # burn passes until the breaker opens (each degraded pass
            # costs a ping and/or fetch attempt)
            for _ in range(4):
                registry.invalidate(drop=True)
                est(reqs_matrix([1000]), np.asarray([5]))
                if conn.breaker.state == backoff.OPEN:
                    break
            assert conn.breaker.state == backoff.OPEN
            assert (
                circuit_state.value(channel=f"estimator@127.0.0.1:{port}")
                == backoff.OPEN
            )
            # breaker-open pass: -1 immediately, ZERO new wire traffic
            before = dict(registry.rpc_counts)
            registry.invalidate(drop=True)
            out = est(reqs_matrix([1000]), np.asarray([5]))
            assert (out == -1).all()
            assert dict(registry.rpc_counts) == before
            # degraded and never replayable
            assert est.refresh_token() is None
        finally:
            conn.close()

    def test_breaker_recovers_half_open_to_closed_without_operator(self):
        import time as _time

        from karmada_tpu.utils import backoff
        from karmada_tpu.utils.metrics import circuit_state

        caches, svc, srv, port, conn, registry = self._one_server_registry(
            reset="0.3"
        )
        try:
            est = registry.make_batch_estimator(["a"], timeout_seconds=2.0)
            out1 = est(reqs_matrix([1000]), np.asarray([5]))
            assert (out1 >= 0).all()

            srv.stop(0)
            for _ in range(4):
                registry.invalidate(drop=True)
                est(reqs_matrix([1000]), np.asarray([5]))
                if conn.breaker.state == backoff.OPEN:
                    break
            assert conn.breaker.state == backoff.OPEN

            # server returns on the same port; after the reset window the
            # next pass IS the half-open probe and closes the breaker —
            # no operator action, no registry surgery
            srv2 = EstimatorGrpcServer(svc, f"127.0.0.1:{port}")
            srv2.start()
            try:
                import grpc as _grpc

                _grpc.channel_ready_future(conn._channel).result(timeout=10)
                _time.sleep(0.35)  # past the breaker reset window
                registry.invalidate(drop=True)
                out2 = est(reqs_matrix([1000]), np.asarray([5]))
                assert (out2 == out1).all()
                assert conn.breaker.state == backoff.CLOSED
                assert (
                    circuit_state.value(
                        channel=f"estimator@127.0.0.1:{port}"
                    )
                    == backoff.CLOSED
                )
                assert est.refresh_token() is not None
            finally:
                srv2.stop(0)
        finally:
            conn.close()


class TestQuotaPluginWireParity:
    """ISSUE 8 satellite: the batch matrix path must apply the
    ResourceQuota plugin's namespace cap identically to the per-profile
    unary path — for every (namespace, profile) the batch row's answer
    over the wire equals the unary answer with the same namespace."""

    def _quota_service(self):
        from karmada_tpu.estimator.accurate import ResourceQuotaPlugin

        caches = make_member_caches(["q"], cpu_step=64_000)
        plugin = ResourceQuotaPlugin({
            "teamA": {"cpu": 3_000},  # caps cpu-requesting profiles at 3/req
            "teamB": {"cpu": 10_000},
        })
        return EstimatorService(
            AccurateEstimator("q", caches["q"], quota_plugin=plugin)
        )

    def _parity(self, conn):
        cpus = [1000, 500, 250]
        rows = reqs_matrix(cpus).tolist()
        for ns in ("teamA", "teamB", "unquotad", ""):
            batch = conn.call(
                "MaxAvailableReplicasBatch",
                MaxAvailableReplicasBatchRequest(
                    clusters=["q"], dims=list(DIMS), rows=rows,
                    namespaces=[ns] * len(rows),
                ),
            )
            got = list(batch.results[0].max_replicas)
            want = [
                conn.call(
                    "MaxAvailableReplicas",
                    MaxAvailableReplicasRequest(
                        cluster="q",
                        resource_request={
                            d: int(v) for d, v in zip(DIMS, row) if v > 0
                        },
                        namespace=ns,
                    ),
                ).max_replicas
                for row in rows
            ]
            assert got == want, (ns, got, want)
        return True

    def test_inproc_parity_and_cap_applied(self):
        from karmada_tpu.estimator.service import EstimatorConnection
        from karmada_tpu.utils.features import (
            RESOURCE_QUOTA_ESTIMATE,
            feature_gate,
        )

        svc = self._quota_service()
        conn = EstimatorConnection("q", svc)
        feature_gate.set(RESOURCE_QUOTA_ESTIMATE, True)
        try:
            assert self._parity(conn)
            # and the cap actually bites: 1000m profile in teamA fits 3
            resp = conn.call(
                "MaxAvailableReplicasBatch",
                MaxAvailableReplicasBatchRequest(
                    clusters=["q"], dims=list(DIMS),
                    rows=reqs_matrix([1000]).tolist(),
                    namespaces=["teamA"],
                ),
            )
            assert list(resp.results[0].max_replicas) == [3]
        finally:
            feature_gate.set(RESOURCE_QUOTA_ESTIMATE, False)

    def test_grpc_wire_parity_and_namespace_roundtrip(self):
        from karmada_tpu.utils.features import (
            RESOURCE_QUOTA_ESTIMATE,
            feature_gate,
        )

        svc = self._quota_service()
        srv = EstimatorGrpcServer(
            MultiClusterEstimatorService({"q": svc})
        )
        port = srv.start()
        conn = GrpcEstimatorConnection(
            "q", f"127.0.0.1:{port}", timeout_seconds=5.0
        )
        feature_gate.set(RESOURCE_QUOTA_ESTIMATE, True)
        try:
            assert self._parity(conn)
        finally:
            feature_gate.set(RESOURCE_QUOTA_ESTIMATE, False)
            conn.close()
            srv.stop()

    def test_namespace_free_batch_unchanged(self):
        """Old clients (no namespaces field) keep the pre-quota answers
        even with a plugin registered and the feature on."""
        from karmada_tpu.estimator.service import EstimatorConnection
        from karmada_tpu.utils.features import (
            RESOURCE_QUOTA_ESTIMATE,
            feature_gate,
        )

        svc = self._quota_service()
        conn = EstimatorConnection("q", svc)
        feature_gate.set(RESOURCE_QUOTA_ESTIMATE, True)
        try:
            resp = conn.call(
                "MaxAvailableReplicasBatch",
                MaxAvailableReplicasBatchRequest(
                    clusters=["q"], dims=list(DIMS),
                    rows=reqs_matrix([1000]).tolist(),
                ),
            )
            assert list(resp.results[0].max_replicas) == [64]  # node fit
        finally:
            feature_gate.set(RESOURCE_QUOTA_ESTIMATE, False)
