"""Expression-tier declarative interpreter: sandboxed scripts carried by
ResourceInterpreterCustomization, mirroring the reference's Lua VM contract
(luavm/lua.go:46-316). Ports of the reference's gnarlier Lua
customizations (kruise CloneSet status aggregation, FlinkDeployment
replica/health math) prove expression-completeness beyond the path DSL."""

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.work import AggregatedStatusItem
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.interpreter.declarative import (
    CustomizationConfigManager,
    CustomizationRules,
    ResourceInterpreterCustomization,
)
from karmada_tpu.interpreter.exprlang import ExprVM, ScriptError
from karmada_tpu.utils import Runtime, Store


# --------------------------------------------------------------------------
# VM sandbox semantics
# --------------------------------------------------------------------------


class TestSandbox:
    def test_forbidden_constructs_rejected_at_registration(self):
        for src in (
            "import os",
            "def f():\n    return open('/etc/passwd')",  # unknown name
            "def f():\n    return ().__class__",
            "x = lambda: 1",
            "def f():\n    exec('1')",
            "def f(*a):\n    return a",
        ):
            with pytest.raises(ScriptError):
                vm = ExprVM(src)
                if vm.has("f"):
                    vm.call("f")

    def test_runaway_loop_hits_fuel_budget(self):
        vm = ExprVM("def f():\n    x = 0\n    while True:\n        x = x + 1\n    return x")
        with pytest.raises(ScriptError, match="budget|bound"):
            vm.call("f")

    def test_exponential_growth_via_add_is_bounded(self):
        # `s = s + s` doubles per fuel unit at C speed — the fuel meter alone
        # cannot stop it before the allocation outruns memory
        for src in (
            "def f():\n    s = 'x' * 1000\n    for i in range(40):\n        s = s + s\n",
            "def f():\n    l = [1] * 1000\n    for i in range(40):\n        l = l + l\n",
            "def f():\n    s = 'x' * 1000\n    for i in range(40):\n        s += s\n",
        ):
            with pytest.raises(ScriptError, match="too large"):
                ExprVM(src).call("f")

    def test_growth_methods_are_bounded(self):
        for src in (
            # list.extend(l) doubles per call
            "def f():\n    l = [1] * 1000\n    for i in range(40):\n        l.extend(l)\n",
            # str.replace(a, s) squares in one call
            "def f():\n    s = 'a' * 100000\n    return s.replace('a', s)\n",
            # str.join multiplies in one call
            "def f():\n    s = 'a' * 100000\n    return s.join([s] * 1000)\n",
        ):
            with pytest.raises(ScriptError, match="too large"):
                ExprVM(src).call("f")

    def test_builtin_growth_bypasses_are_bounded(self):
        # sum() with a sequence start concatenates at C speed in one step
        with pytest.raises(ScriptError, match="too large"):
            ExprVM(
                "def f():\n    l = [1] * 100000\n    return sum([l] * 200, [])\n"
            ).call("f")
        # printf width allocates the result in one step
        with pytest.raises(ScriptError, match="width too large"):
            ExprVM("def f():\n    return '%999999999d' % 1\n").call("f")
        with pytest.raises(ScriptError, match="width too large"):
            ExprVM("def f():\n    return '%*d' % (1000000000, 1)\n").call("f")
        # normal uses unaffected
        assert ExprVM("def f():\n    return sum([1, 2, 3])\n").call("f") == 6
        assert (
            ExprVM("def f():\n    return 'id-%05d of 100000' % 7\n").call("f")
            == "id-00007 of 100000"
        )

    def test_bounded_methods_still_work_for_normal_sizes(self):
        vm = ExprVM(
            "def f():\n"
            "    l = [1, 2]\n"
            "    l.extend([3, 4])\n"
            "    s = 'a-b-c'.replace('-', '.')\n"
            "    return ','.join(['x', 'y']) + s + str(l[3])\n"
        )
        assert vm.call("f") == "x,ya.b.c4"

    def test_nil_semantics_match_lua_field_access(self):
        vm = ExprVM(
            "def f(obj):\n"
            "    if obj.spec.missing.deeply.nested == None:\n"
            "        return 1\n"
            "    return 2\n"
        )
        assert vm.call("f", {"spec": {}}) == 1

    def test_attribute_and_subscript_access_are_equivalent(self):
        vm = ExprVM(
            "def f(obj):\n"
            "    return obj.spec.replicas + obj['spec']['replicas']\n"
        )
        assert vm.call("f", {"spec": {"replicas": 4}}) == 8


# --------------------------------------------------------------------------
# ported reference scripts
# --------------------------------------------------------------------------

# kruise CloneSet AggregateStatus — the generation-counting aggregation
# (resourcecustomizations/apps.kruise.io/v1alpha1/CloneSet/customizations.yaml)
CLONESET_AGGREGATE = """
def AggregateStatus(desiredObj, statusItems):
    if desiredObj.status == None:
        desiredObj["status"] = {}
    if desiredObj.metadata.generation == None:
        desiredObj["metadata"]["generation"] = 0
    if desiredObj.status.observedGeneration == None:
        desiredObj["status"]["observedGeneration"] = 0

    fields = ["replicas", "readyReplicas", "updatedReplicas",
              "availableReplicas", "updatedReadyReplicas",
              "expectedUpdatedReplicas"]
    if statusItems == None or len(statusItems) == 0:
        desiredObj["status"]["observedGeneration"] = desiredObj.metadata.generation
        for f in fields:
            desiredObj["status"][f] = 0
        return desiredObj

    generation = desiredObj.metadata.generation
    observedGeneration = desiredObj.status.observedGeneration
    totals = {}
    for f in fields:
        totals[f] = 0
    updateRevision = ''
    currentRevision = ''
    labelSelector = ''
    observedCount = 0
    for item in statusItems:
        st = item.status
        if st == None:
            continue
        for f in fields:
            if st[f] != None:
                totals[f] = totals[f] + st[f]
        if st.updateRevision != None and st.updateRevision != '':
            updateRevision = st.updateRevision
        if st.currentRevision != None and st.currentRevision != '':
            currentRevision = st.currentRevision
        if st.labelSelector != None and st.labelSelector != '':
            labelSelector = st.labelSelector
        rtg = st.resourceTemplateGeneration if st.resourceTemplateGeneration != None else 0
        mg = st.generation if st.generation != None else 0
        mog = st.observedGeneration if st.observedGeneration != None else 0
        if rtg == generation and mg == mog:
            observedCount = observedCount + 1
    if observedCount == len(statusItems):
        desiredObj["status"]["observedGeneration"] = generation
    else:
        desiredObj["status"]["observedGeneration"] = observedGeneration
    for f in fields:
        desiredObj["status"][f] = totals[f]
    desiredObj["status"]["updateRevision"] = updateRevision
    desiredObj["status"]["currentRevision"] = currentRevision
    desiredObj["status"]["labelSelector"] = labelSelector
    return desiredObj
"""

# FlinkDeployment health + replica math
# (resourcecustomizations/flink.apache.org/v1beta1/FlinkDeployment)
FLINK_HEALTH = """
def InterpretHealth(observedObj):
    if observedObj.status != None and observedObj.status.jobStatus != None:
        if observedObj.status.jobStatus.state != 'CREATED' and observedObj.status.jobStatus.state != 'RECONCILING':
            return True
        return observedObj.status.jobManagerDeploymentStatus == 'ERROR'
    return False
"""

FLINK_REPLICAS = """
def isempty(s):
    return s == None or s == ''

def GetReplicas(observedObj):
    requires = {"resourceRequest": {}, "nodeClaim": {}}
    jm_replicas = observedObj.spec.jobManager.replicas
    if isempty(jm_replicas):
        jm_replicas = 1
    tm_replicas = observedObj.spec.taskManager.replicas
    if isempty(tm_replicas):
        parallelism = observedObj.spec.job.parallelism
        task_slots = observedObj.spec.flinkConfiguration['taskmanager.numberOfTaskSlots']
        if isempty(parallelism) or isempty(task_slots):
            tm_replicas = 1
        else:
            tm_replicas = math.ceil(parallelism / task_slots)
    replica = jm_replicas + tm_replicas
    requires["resourceRequest"]["cpu"] = max(
        observedObj.spec.taskManager.resource.cpu,
        observedObj.spec.jobManager.resource.cpu)
    jm_mem = kube.getResourceQuantity(observedObj.spec.jobManager.resource.memory)
    tm_mem = kube.getResourceQuantity(observedObj.spec.taskManager.resource.memory)
    if jm_mem > tm_mem:
        requires["resourceRequest"]["memory"] = observedObj.spec.jobManager.resource.memory
    else:
        requires["resourceRequest"]["memory"] = observedObj.spec.taskManager.resource.memory
    if not isempty(observedObj.metadata.namespace):
        requires["namespace"] = observedObj.metadata.namespace
    return replica, requires
"""


def _cloneset(gen=3, status=None):
    return Resource(
        api_version="apps.kruise.io/v1alpha1",
        kind="CloneSet",
        meta=ObjectMeta(name="web", namespace="default", generation=gen),
        spec={"replicas": 5},
        status=status or {},
    )


class TestPortedScripts:
    def test_cloneset_aggregate_counts_generations(self):
        vm = ExprVM(CLONESET_AGGREGATE)
        desired = {
            "metadata": {"generation": 3},
            "spec": {},
            "status": {"observedGeneration": 2},
        }
        items = [
            {"clusterName": "m1", "status": {
                "replicas": 2, "readyReplicas": 2, "updatedReplicas": 2,
                "availableReplicas": 2, "resourceTemplateGeneration": 3,
                "generation": 7, "observedGeneration": 7,
                "updateRevision": "rev-b", "labelSelector": "app=web"}},
            {"clusterName": "m2", "status": {
                "replicas": 3, "readyReplicas": 1,
                "resourceTemplateGeneration": 3,
                "generation": 4, "observedGeneration": 4,
                "currentRevision": "rev-a"}},
        ]
        out = vm.call("AggregateStatus", desired, items)
        st = out["status"]
        assert st["replicas"] == 5 and st["readyReplicas"] == 3
        assert st["updatedReplicas"] == 2 and st["availableReplicas"] == 2
        # every member caught up to template generation 3 -> observed moves
        assert st["observedGeneration"] == 3
        assert st["updateRevision"] == "rev-b"
        assert st["currentRevision"] == "rev-a"
        assert st["labelSelector"] == "app=web"

    def test_cloneset_aggregate_holds_generation_back(self):
        vm = ExprVM(CLONESET_AGGREGATE)
        desired = {"metadata": {"generation": 3}, "spec": {},
                   "status": {"observedGeneration": 2}}
        items = [{"clusterName": "m1", "status": {
            "replicas": 1, "resourceTemplateGeneration": 2,  # stale member
            "generation": 4, "observedGeneration": 4}}]
        out = vm.call("AggregateStatus", desired, items)
        assert out["status"]["observedGeneration"] == 2

    def test_flink_health(self):
        vm = ExprVM(FLINK_HEALTH)
        assert vm.call("InterpretHealth", {
            "status": {"jobStatus": {"state": "RUNNING"}}}) is True
        assert vm.call("InterpretHealth", {
            "status": {"jobStatus": {"state": "CREATED"},
                       "jobManagerDeploymentStatus": "ERROR"}}) is True
        assert vm.call("InterpretHealth", {
            "status": {"jobStatus": {"state": "RECONCILING"},
                       "jobManagerDeploymentStatus": "READY"}}) is False
        assert vm.call("InterpretHealth", {"status": {}}) is False

    def test_flink_replica_math(self):
        vm = ExprVM(FLINK_REPLICAS)
        obj = {
            "metadata": {"namespace": "flink"},
            "spec": {
                "jobManager": {"resource": {"cpu": 1, "memory": "2048m"}},
                "taskManager": {"resource": {"cpu": 2, "memory": "1Gi"}},
                "job": {"parallelism": 7},
                "flinkConfiguration": {"taskmanager.numberOfTaskSlots": 2},
            },
        }
        replica, requires = vm.call("GetReplicas", obj)
        # jm 1 (default) + ceil(7/2) = 4 task managers
        assert replica == 5
        assert requires["resourceRequest"]["cpu"] == 2
        # 2048m (2048*10^-3 = ~2.05 units...) vs 1Gi bytes: Gi is larger
        assert requires["resourceRequest"]["memory"] == "1Gi"
        assert requires["namespace"] == "flink"


# --------------------------------------------------------------------------
# CR-carried registration through the configmanager
# --------------------------------------------------------------------------


class TestCustomizationCR:
    def test_scripts_registered_via_cr_drive_interpreter(self):
        store = Store()
        runtime = Runtime()
        interp = ResourceInterpreter()
        mgr = CustomizationConfigManager(store, runtime, interp)
        store.apply(
            ResourceInterpreterCustomization(
                meta=ObjectMeta(name="cloneset-custom"),
                target_api_version="apps.kruise.io/v1alpha1",
                target_kind="CloneSet",
                rules=CustomizationRules(
                    status_aggregation_script=CLONESET_AGGREGATE,
                    health_script=FLINK_HEALTH.replace(
                        "jobStatus", "flags"
                    ),  # any script shape works; proves override
                    replica_revision_script=(
                        "def ReviseReplica(obj, n):\n"
                        "    obj['spec']['replicas'] = n\n"
                        "    return obj\n"
                    ),
                ),
            )
        )
        runtime.run_until_settled(100)
        obj = _cloneset()
        revised = interp.revise_replica(obj, 9)
        assert revised.spec["replicas"] == 9
        out = interp.aggregate_status(
            _cloneset(gen=1, status={"observedGeneration": 0}),
            [AggregatedStatusItem(cluster_name="m1", status={
                "replicas": 4, "resourceTemplateGeneration": 1,
                "generation": 2, "observedGeneration": 2})],
        )
        assert out.status["replicas"] == 4
        assert out.status["observedGeneration"] == 1
        # deleting the CR deregisters the tier
        store.delete("ResourceInterpreterCustomization", "cloneset-custom")
        runtime.run_until_settled(100)
        assert interp.revise_replica(obj, 2) is obj  # no hook again

    def test_invalid_script_does_not_poison_the_interpreter(self):
        store = Store()
        runtime = Runtime()
        interp = ResourceInterpreter()
        CustomizationConfigManager(store, runtime, interp)
        store.apply(
            ResourceInterpreterCustomization(
                meta=ObjectMeta(name="bad"),
                target_api_version="v1",
                target_kind="Thing",
                rules=CustomizationRules(
                    health_script="import os\n",
                ),
            )
        )
        runtime.run_until_settled(100)
        # registration failed loudly but the interpreter still works
        obj = Resource(api_version="v1", kind="Thing")
        assert interp.interpret_health(obj) is True
