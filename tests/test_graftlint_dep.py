"""graftlint dep tier: row-dependence certification (delta-safety) gate.

Mirror of test_graftlint_ir.py one tier up: the full dep grid over the
committed registry must certify clean (every kernel's ``row_coupled``
declaration present, agreeing across its surfaces, and never
contradicted by the analyzer's proof), inside the runtime budget, with
ZERO baselined entries. The seeded mutants (tests/ir_mutant_kernels.py)
then pin that IR006 fires in BOTH contradiction directions and IR007
fires on the PR 9 sharded-scan regression shape — a certifier that
stops firing fails here, never silently.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import ir as graft_ir  # noqa: E402
from tools.graftlint.ir import (  # noqa: E402
    ENTRY_POINTS,
    KernelEntry,
    KernelSpec,
    entries_for_changed,
)
from tools.graftlint.dep import (  # noqa: E402
    declared_row_coupled,
    delta_safe_registry,
    render_delta_safe_table,
    run_dep,
)

MUTANT_MODULE = "ir_mutant_kernels"
MUTANT_PATH = "tests/ir_mutant_kernels.py"

VEC = (((8,), "int32"),)
MESH_B2 = (("b", 2), ("c", 1))


def dep_entry(attr: str, in_shapes, *, statics=None, row_coupled=None,
              row_args=(), plane_args=()) -> KernelEntry:
    spec = KernelSpec("mutant", tuple(in_shapes), dict(statics or {}))
    return KernelEntry(
        name=attr, family="ops", module=MUTANT_MODULE, attr=attr,
        path=MUTANT_PATH, make_specs=lambda: [spec],
        row_coupled=row_coupled, row_args=tuple(row_args),
        plane_args=tuple(plane_args),
    )


# -- the tier-1 gate + runtime budget ---------------------------------------


@pytest.fixture(scope="module")
def full_run():
    t0 = time.perf_counter()
    result = run_dep(root=REPO, baseline="auto")
    return result, time.perf_counter() - t0


def test_full_grid_certifies_clean(full_run):
    result, _ = full_run
    assert result.checked_files >= 30, "dep trace grid shrank"
    assert not result.findings, (
        "dep findings on the committed kernels:\n"
        + "\n".join(f.render() for f in result.findings)
    )
    assert not result.baseline_errors
    assert not result.unused_baseline
    # the delta-safety gate ships with a CLEAN tree, not a grandfathered
    # one: no dep finding is ever baselined
    assert not result.baselined


def test_full_grid_runtime_budget(full_run):
    _, seconds = full_run
    # the abstract interpretation must stay cheap enough for tier-1 and
    # the pre-commit --all path: the whole grid (trace + analysis) in
    # seconds, not minutes
    assert seconds < 5.0, f"dep grid took {seconds:.2f}s (budget 5s)"


def test_every_registered_kernel_declares_row_coupled():
    # the coverage half of the contract: every entry point states the
    # delta-safety bit on EVERY surface, and the surfaces agree
    for name, entry in ENTRY_POINTS.items():
        decl = declared_row_coupled(entry)
        assert decl["registry"] is not None, (
            f"{name}: ENTRY_POINTS entry missing row_coupled"
        )
        assert decl["kernel"] is not None, (
            f"{name}: kernel function missing the row_coupled attribute"
        )
        assert bool(decl["kernel"]) == bool(decl["registry"]), name
        if entry.manifest_kernel:
            assert decl.get("prewarm") is not None, (
                f"{name}: prewarm._KERNELS missing its row_coupled value"
            )
            assert bool(decl["prewarm"]) == bool(decl["registry"]), name


# -- seeded mutants: IR006 must fire in BOTH directions ---------------------


def test_ir006_declared_independent_but_coupled():
    entry = dep_entry("ir006_hidden_cumsum", VEC,
                      row_coupled=False, row_args=(0,))
    result = run_dep(entries={entry.name: entry}, root=REPO, baseline=None)
    assert not result.ok
    assert {f.rule for f in result.findings} == {"IR006"}
    (f,) = result.findings
    assert f.path == MUTANT_PATH
    assert f.detail.startswith("declared-independent-but-coupled:"), f.detail
    assert "cum" in f.detail, f.detail


def test_ir006_declared_coupled_but_independent():
    entry = dep_entry(
        "ir006_decoupled", (((8,), "int32"), ((8,), "int32")),
        row_coupled=True, row_args=(0,),
    )
    result = run_dep(entries={entry.name: entry}, root=REPO, baseline=None)
    assert not result.ok
    assert {f.rule for f in result.findings} == {"IR006"}
    (f,) = result.findings
    assert f.detail == "declared-coupled-but-independent"


def test_ir006_missing_declaration_on_full_scope(monkeypatch):
    # full-scope-only negative (the GL003 precedent): an entry with NO
    # declaration at all only convicts on the unscoped run
    entry = dep_entry("ir006_hidden_cumsum", VEC, row_args=(0,))
    monkeypatch.setattr(graft_ir, "ENTRY_POINTS", {entry.name: entry})
    result = run_dep(root=REPO, baseline=None)
    details = {f.detail for f in result.findings}
    assert "missing-declaration" in details, details
    # ...and stays OFF the scoped (entries=) runs
    scoped = run_dep(entries={entry.name: entry}, root=REPO, baseline=None)
    assert "missing-declaration" not in {f.detail for f in scoped.findings}


def test_ir007_fires_on_unreplicated_sharded_scan():
    entry = dep_entry(
        "ir007_sharded_scan", VEC, statics={"mesh": MESH_B2},
        row_coupled=True, row_args=(0,),
    )
    result = run_dep(entries={entry.name: entry}, root=REPO, baseline=None)
    assert not result.ok
    rules = {f.rule for f in result.findings}
    assert rules == {"IR007"}, [f.render() for f in result.findings]
    (f,) = result.findings
    assert f.path == MUTANT_PATH
    assert f.detail.startswith("unreplicated-coupler:cum"), f.detail


def test_ir007_silent_on_single_device_variant():
    # the same coupler without a mesh static is an honest single-device
    # coupled kernel — IR007 is a SHARDED-variant discipline only
    entry = dep_entry("ir007_sharded_scan", VEC,
                      row_coupled=True, row_args=(0,))
    result = run_dep(entries={entry.name: entry}, root=REPO, baseline=None)
    assert result.ok, [f.render() for f in result.findings]


# -- changed-only scoping over the spec_deps import graph -------------------


def test_entries_for_changed_follows_spec_deps():
    scoped = entries_for_changed(["karmada_tpu/ops/quota.py"])
    # quota.py is the source of the quota kernels AND a declared spec
    # dep of preempt_select and the fleet solve family (the cap grid
    # feeds both); the dispense/divide/masks kernels never read it
    assert {"quota_admit", "quota_cluster_caps"} <= set(scoped)
    assert "preempt_select" in scoped
    assert "fleet_solve" in scoped
    assert "divide_replicas" not in scoped
    assert "masks.contains_all" not in scoped

    scoped = entries_for_changed(["karmada_tpu/ops/dispense.py"])
    assert "take_by_weight" in scoped  # own source file
    assert "divide_replicas" in scoped  # via spec_deps
    assert "masks.intersects" not in scoped

    assert entries_for_changed(["karmada_tpu/utils/store.py"]) == {}


# -- the delta-safe registry surface ----------------------------------------


@pytest.fixture(scope="module")
def safe_rows():
    return delta_safe_registry(REPO)


def test_delta_safe_registry_matches_contract(safe_rows):
    by_name = {r["name"]: r for r in safe_rows}
    assert set(by_name) == set(ENTRY_POINTS)
    for r in safe_rows:
        # delta_safe is EARNED: declared independent AND proven so
        assert r["delta_safe"] == (
            r["row_coupled"] is False and r["verdict"] == "independent"
        )
    # the anchor kernels of each class (pinned so a lattice regression
    # that degrades proofs to 'unproven' cannot pass silently)
    assert by_name["divide_replicas"]["delta_safe"] is True
    assert by_name["explain_pass"]["delta_safe"] is True
    assert by_name["quota_admit"]["verdict"] == "coupled"
    assert by_name["masks.first_fit_group"]["plane_coupled"] is True
    assert not by_name["quota_admit"]["delta_safe"]


def test_delta_safe_table_renders_every_kernel(safe_rows):
    table = render_delta_safe_table(REPO)
    assert table.splitlines()[0].startswith("| kernel ")
    for r in safe_rows:
        assert f"`{r['name']}`" in table


def test_docs_delta_safe_table_not_drifted():
    # the generated DEVELOPMENT.md table is drift-guarded the same way
    # as the env-flag/metric/span tables: regenerate, don't hand-edit
    sys.path.insert(0, str(REPO / "tools"))
    import docs_from_bench

    docs_from_bench.check_delta_safe_table()


# -- the CLI surface --------------------------------------------------------


def _lint(*argv):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *argv],
        cwd=REPO, capture_output=True, text=True,
    )


def test_cli_all_merges_three_tiers():
    proc = _lint("--all", "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    assert set(doc["tiers"]) == {"ast", "ir", "dep"}
    for name, tier in doc["tiers"].items():
        assert tier["tier"] == name
        assert tier["seconds"] >= 0.0
        assert tier["ok"] is True


def test_cli_dep_tier_json_tags_findings():
    proc = _lint("--dep", "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["tier"] == "dep"
    assert doc["ok"] is True


def test_cli_tier_flags_mutually_exclusive():
    for combo in (("--ir", "--dep"), ("--ir", "--all"),
                  ("--dep", "--all")):
        proc = _lint(*combo)
        assert proc.returncode == 2, combo
        assert "mutually exclusive" in proc.stderr


def test_cli_all_refuses_path_scope():
    proc = _lint("--all", "karmada_tpu/ops/quota.py")
    assert proc.returncode == 2
    assert "--changed-only" in proc.stderr
