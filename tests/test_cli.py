"""karmadactl-analogue tests (ref: pkg/karmadactl command behaviors)."""

from karmada_tpu import cli
from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.utils.builders import (
    duplicated_placement,
    new_deployment,
)


def policy(placement):
    return PropagationPolicy(
        meta=ObjectMeta(name="p", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=placement,
        ),
    )


class TestLifecycle:
    def test_local_up_with_pull_member(self):
        cp = cli.cmd_local_up(3)
        clusters = {c.name: c.spec.sync_mode for c in cp.store.list("Cluster")}
        assert set(clusters) == {"member1", "member2", "member3"}
        assert clusters["member3"] == "Pull"
        assert "member3" in cp.agents

    def test_join_unjoin(self):
        cp = cli.cmd_init()
        cli.cmd_join(cp, "m1")
        cp.settle()
        assert cp.store.get("Cluster", "m1") is not None
        cli.cmd_unjoin(cp, "m1")
        assert cp.store.get("Cluster", "m1") is None


class TestMaintenance:
    def test_cordon_excludes_from_scheduling(self):
        cp = cli.cmd_local_up(2)
        cli.cmd_cordon(cp, "member2")
        cp.settle()
        cp.store.apply(new_deployment("app", replicas=1))
        cp.store.apply(policy(duplicated_placement()))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert {tc.name for tc in rb.spec.clusters} == {"member1"}
        cli.cmd_uncordon(cp, "member2")
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/app-deployment")
        assert {tc.name for tc in rb.spec.clusters} == {"member1", "member2"}

    def test_taint_add_remove(self):
        cp = cli.cmd_local_up(1)
        cli.cmd_taint(cp, "member1", key="dedicated", value="infra")
        cluster = cp.store.get("Cluster", "member1")
        assert any(t.key == "dedicated" for t in cluster.spec.taints)
        cli.cmd_taint(cp, "member1", key="dedicated", remove=True)
        cluster = cp.store.get("Cluster", "member1")
        assert not any(t.key == "dedicated" for t in cluster.spec.taints)


class TestOps:
    def test_promote_imports_member_resource(self):
        cp = cli.cmd_local_up(2)
        member = cp.members.get("member1")
        member.apply(
            Resource(
                api_version="v1",
                kind="ConfigMap",
                meta=ObjectMeta(name="legacy", namespace="default"),
                spec={"data": {"k": "v"}},
            )
        )
        cli.cmd_promote(cp, "member1", "v1/ConfigMap", "default", "legacy")
        cp.settle()
        assert cp.store.get("Resource", "default/legacy") is not None
        rb = cp.store.get("ResourceBinding", "default/legacy-configmap")
        assert rb is not None
        assert {tc.name for tc in rb.spec.clusters} == {"member1"}

    def test_describe_and_top(self):
        cp = cli.cmd_local_up(2)
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(policy(duplicated_placement()))
        cp.settle()
        out = cli.cmd_describe(cp, "apps/v1/Deployment", "default", "app")
        assert "member1: 2 replicas" in out
        cp.members.get("member1").pod_metrics["default/app"] = {
            "pods": 2, "cpu_utilization": 42.0,
        }
        top = cli.cmd_top(cp, "default/app")
        assert top["clusters"] == {"member1": 42.0}

    def test_interpret_dry_run(self):
        cp = cli.cmd_init()
        template = new_deployment("app", replicas=7)
        replicas, reqs = cli.cmd_interpret(cp, template, "GetReplicas")
        assert replicas == 7 and reqs.resource_request["cpu"] == 250
        revised = cli.cmd_interpret(cp, template, "ReviseReplica", replicas=3)
        assert revised.spec["replicas"] == 3

    def test_main_local_up(self, capsys):
        assert cli.main(["local-up", "--members", "2"]) == 0
        out = capsys.readouterr().out
        assert "member1" in out


class TestAddons:
    def test_toggle_descheduler(self):
        cp = cli.cmd_local_up(1)
        assert cp.descheduler is None
        state = cli.cmd_addons(cp, enable=["karmada-descheduler"])
        assert state["karmada-descheduler"] == "enabled"
        assert cp.descheduler is not None
        first = cp.descheduler
        cli.cmd_addons(cp, disable=["karmada-descheduler"])
        # the ticker registration is permanent, so disable deactivates in
        # place (a None'd-out instance would keep ticking forever)
        assert cp.descheduler is first and not cp.descheduler.active
        cli.cmd_addons(cp, enable=["karmada-descheduler"])
        # re-enable must reuse the registered instance, not double-register
        assert cp.descheduler is first and cp.descheduler.active


class TestMigrationAndRollback:
    """Seamless migration + rollback (migration_and_rollback_test.go):
    promote adopts the live member object (Overwrite), and rolling the
    migration back with PreserveResourcesOnDeletion leaves it running."""

    def _migrated_plane(self):
        cp = cli.cmd_local_up(2)
        member = cp.members.get("member1")
        member.apply(new_deployment("legacy-app", replicas=3))
        cli.cmd_promote(cp, "member1", "apps/v1/Deployment", "default",
                        "legacy-app")
        cp.settle()
        return cp, member

    def test_promote_adopts_with_overwrite(self):
        cp, member = self._migrated_plane()
        pp = cp.store.get("PropagationPolicy", "default/promote-legacy-app")
        assert pp is not None and pp.spec.conflict_resolution == "Overwrite"
        rb = cp.store.get("ResourceBinding", "default/legacy-app-deployment")
        assert rb is not None
        assert {tc.name for tc in rb.spec.clusters} == {"member1"}
        # the live object is managed now, not deleted/recreated
        assert member.get("apps/v1/Deployment", "default", "legacy-app") is not None

    def test_rollback_preserves_member_resource(self):
        cp, member = self._migrated_plane()
        # flip the policy to preserve-on-deletion, then tear the
        # migration down control-plane-side
        pp = cp.store.get("PropagationPolicy", "default/promote-legacy-app")
        pp.spec.preserve_resources_on_deletion = True
        cp.store.apply(pp)
        cp.settle()
        cp.store.delete("Resource", "default/legacy-app")
        cp.store.delete("PropagationPolicy", "default/promote-legacy-app")
        cp.settle()
        assert cp.store.get("ResourceBinding", "default/legacy-app-deployment") is None
        # the member keeps serving the workload (rollback is seamless)
        assert member.get("apps/v1/Deployment", "default", "legacy-app") is not None

    def test_teardown_without_preserve_removes_member_resource(self):
        cp, member = self._migrated_plane()
        cp.store.delete("Resource", "default/legacy-app")
        cp.store.delete("PropagationPolicy", "default/promote-legacy-app")
        cp.settle()
        assert member.get("apps/v1/Deployment", "default", "legacy-app") is None


class TestDeinit:
    def test_deinit_drains_members_and_clears_state(self):
        cp = cli.cmd_local_up(2)
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(policy(duplicated_placement()))
        cp.settle()
        assert cp.members.get("member1").get(
            "apps/v1/Deployment", "default", "app") is not None
        cli.cmd_deinit(cp)
        assert list(cp.members.names()) == []
        for kind in cp.store.kinds():
            assert cp.store.list(kind) == [], kind


class TestGetAcrossClusters:
    def test_get_resolves_from_members_and_karmada(self):
        cp = cli.cmd_local_up(2)
        cp.store.apply(new_deployment("app", replicas=2))
        cp.store.apply(policy(duplicated_placement()))
        cp.settle()
        # proxy chain answers from the cache first
        resp = cli.cmd_get(cp, "apps/v1/Deployment", "default", "app")
        assert resp.error == "" and resp.obj is not None
        assert resp.obj.spec["replicas"] == 2
        # single-cluster scope goes to that member
        one = cli.cmd_get(cp, "apps/v1/Deployment", "default", "app",
                          cluster="member2")
        assert one.error == "" and one.obj is not None
        assert one.served_by in ("cluster", "cache")


class TestGenericVerbs:
    """create / edit / explain / completion (ref: pkg/karmadactl/{create,
    edit,explain,completion})."""

    def test_create_is_create_only(self):
        def manifest(name):
            return {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"replicas": 1},
            }

        cp = cli.cmd_local_up(1)
        created = cli.cmd_create(cp, [manifest("app")])
        assert created == ["Resource/default/app"]
        # second create of the same object is AlreadyExists, and a batch
        # with one conflicting manifest writes NOTHING
        import pytest

        with pytest.raises(ValueError, match="already exists"):
            cli.cmd_create(cp, [manifest("other"), manifest("app")])
        assert cp.store.get("Resource", "default/other") is None

    def test_edit_applies_editor_changes(self):
        cp = cli.cmd_local_up(1)
        cp.store.apply(new_deployment("app", replicas=1))
        # "editor" = a python one-liner rewriting replicas in place
        editor = (
            f"{__import__('sys').executable} -c \""
            "import json,sys; p=sys.argv[1]; d=json.load(open(p)); "
            "d['spec']['replicas']=7; json.dump(d, open(p,'w'))\""
        )
        obj = cli.cmd_edit(cp, "Deployment", "default", "app", editor=editor)
        assert obj is not None and obj.spec["replicas"] == 7
        stored = cp.store.get("Resource", "default/app")
        assert stored.spec["replicas"] == 7
        # spec change bumps generation (apiserver contract)
        assert stored.meta.generation >= 1

    def test_edit_unchanged_is_noop(self):
        cp = cli.cmd_local_up(1)
        cp.store.apply(new_deployment("app", replicas=1))
        rv_before = cp.store.get("Resource", "default/app").meta.resource_version
        assert cli.cmd_edit(cp, "Deployment", "default", "app", editor="true") is None
        assert (
            cp.store.get("Resource", "default/app").meta.resource_version
            == rv_before
        )

    def test_explain_walks_fields(self):
        out = cli.cmd_explain("PropagationPolicy.spec.placement")
        assert "cluster_affinity" in out and "spread_constraints" in out
        out = cli.cmd_explain("Cluster")
        assert "KIND:     Cluster" in out
        import pytest

        with pytest.raises(KeyError, match="does not exist"):
            cli.cmd_explain("PropagationPolicy.spec.bogus")
        with pytest.raises(KeyError, match="unknown kind"):
            cli.cmd_explain("Bogus")

    def test_completion_lists_all_verbs(self):
        script = cli.cmd_completion("bash")
        for verb in ("apply", "create", "edit", "explain", "promote",
                     "api-resources", "completion"):
            assert verb in script
        # every emitted flag really exists on its subparser
        assert "--editor" in script and "--force" in script

    def test_edit_preserves_buffer_on_bad_edit(self, capsys, tmp_path):
        import os
        import re
        import sys as _sys

        cp = cli.cmd_local_up(1)
        cp.store.apply(new_deployment("app", replicas=1))
        # editor renames the object: identity changes are rejected and the
        # buffer survives for the user to recover
        editor = (
            f"{_sys.executable} -c \""
            "import json,sys; p=sys.argv[1]; d=json.load(open(p)); "
            "d['meta']['name']='app2'; json.dump(d, open(p,'w'))\""
        )
        import pytest

        with pytest.raises(ValueError, match="may not change meta.name"):
            cli.cmd_edit(cp, "Deployment", "default", "app", editor=editor)
        err = capsys.readouterr().err
        m = re.search(r"edit buffer preserved at (\S+)", err)
        assert m, err
        assert os.path.exists(m.group(1))
        os.unlink(m.group(1))
        assert cp.store.get("Resource", "default/app2") is None

    def test_completion_handles_global_flag_values(self):
        import subprocess

        script = cli.cmd_completion("bash")
        # simulate: karmadactl-tpu --bus host:1234 <TAB> on 'ap'
        probe = script + """
COMP_WORDS=(karmadactl-tpu --bus host:1234 apply --f)
COMP_CWORD=4
_karmadactl_tpu
echo "${COMPREPLY[@]}"
"""
        out = subprocess.run(
            ["bash", "-c", probe], capture_output=True, text=True
        )
        assert out.returncode == 0, out.stderr
        assert "--filename" in out.stdout
        # zsh variant bootstraps bashcompinit
        assert "bashcompinit" in cli.cmd_completion("zsh")


class TestGetSelectorsAndOutput:
    def test_get_with_label_selector(self):
        cp = cli.cmd_local_up(1)
        d1 = new_deployment("blue", replicas=1)
        d1.meta.labels["tier"] = "web"
        d2 = new_deployment("green", replicas=1)
        d2.meta.labels["tier"] = "db"
        cp.store.apply(d1)
        cp.store.apply(d2)
        resp = cli.cmd_get(cp, "apps/v1/Deployment", "default",
                           labels={"tier": "web"})
        names = [o.meta.name for _, o in resp.items]
        assert names == ["blue"], names

    def test_output_formats(self):
        doc = [{
            "cluster": "m1",
            "object": {
                "api_version": "apps/v1", "kind": "Deployment",
                "meta": {"name": "app", "namespace": "default",
                         "generation": 3},
                "spec": {"replicas": 4},
                "status": {"readyReplicas": 4},
            },
        }]
        assert cli._format_get(doc, "name", "apps/v1/Deployment") == (
            "deployment/app"
        )
        wide = cli._format_get(doc, "wide", "apps/v1/Deployment")
        assert "CLUSTER" in wide and "4/4" in wide and "m1" in wide
        yml = cli._format_get(doc, "yaml", "apps/v1/Deployment")
        assert "name: app" in yml
        import json as _json

        assert _json.loads(cli._format_get(doc, "json", "x")) == doc

    def test_remote_cluster_list_filters_labels(self, monkeypatch):
        """The cluster-routed list branch must honor -l even when the
        member API behind the passthrough ignores labelSelector."""
        import json as _json

        from karmada_tpu.cli import _RemoteProxyChain
        from karmada_tpu.search.proxy import ProxyRequest

        chain = _RemoteProxyChain(store=None, proxy_target="x:1", token="t")
        body = _json.dumps({"items": [
            {"apiVersion": "apps/v1", "kind": "Deployment",
             "metadata": {"name": "blue", "namespace": "default",
                          "labels": {"tier": "web"}}},
            {"apiVersion": "apps/v1", "kind": "Deployment",
             "metadata": {"name": "green", "namespace": "default",
                          "labels": {"tier": "db"}}},
        ]})
        monkeypatch.setattr(chain, "_http", lambda path, timeout=10.0: (200, body))
        resp = chain.connect(ProxyRequest(
            verb="list", gvk="apps/v1/Deployment", namespace="default",
            cluster="m1", labels={"tier": "web"},
        ))
        assert [o.meta.name for _, o in resp.items] == ["blue"]
        # no selector: both come back
        resp = chain.connect(ProxyRequest(
            verb="list", gvk="apps/v1/Deployment", namespace="default",
            cluster="m1",
        ))
        assert len(resp.items) == 2


class TestColdStartImportHygiene:
    def test_cli_import_never_pulls_jax(self):
        """The GL005 cold-start contract, checked TRANSITIVELY: importing
        the CLI entry module must not reach jax through any chain
        (controlplane -> controllers -> member -> estimator was one). The
        lint verb additionally depends on this — the IR/dep tiers must
        set XLA_FLAGS before the process's first jax import or the
        sharded spec variants cannot materialize their >=2-device mesh
        (karmadactl-tpu lint --all would fail with IR004 trace errors)."""
        import subprocess
        import sys
        from pathlib import Path

        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; import karmada_tpu.cli; "
             "sys.exit(1 if 'jax' in sys.modules else 0)"],
            cwd=Path(__file__).resolve().parent.parent,
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
