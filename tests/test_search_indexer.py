"""Networked search backend: documents over a wire protocol (VERDICT r3
missing #6; ref pkg/search/backendstore/opensearch.go).

The IndexerServer runs as a REAL subprocess (the external-OpenSearch
stand-in); HttpIndexerBackend ships the SearchController's documents to it
as bulk batches and answers searches from it. Also covers the BulkIndexer
retry semantics when the indexer is down."""

import re
import subprocess
import sys
import time

import pytest

from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.search.indexer import HttpIndexerBackend, IndexerServer
from karmada_tpu.search.registry import ResourceRegistry, ResourceRegistrySpec
from karmada_tpu.utils.builders import new_deployment


@pytest.fixture()
def indexer_proc():
    proc = subprocess.Popen(
        [sys.executable, "-m", "karmada_tpu.search.indexer"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    port = None
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline()
        m = re.search(r"listening on port (\d+)", line)
        if m:
            port = m.group(1)
            break
    assert port, "indexer never printed its port"
    yield f"127.0.0.1:{port}"
    proc.kill()
    proc.wait(timeout=5)


class TestHttpIndexerBackend:
    def test_bulk_round_trip_against_subprocess(self, indexer_proc):
        be = HttpIndexerBackend(indexer_proc, batch_size=8)
        for i in range(20):
            be.upsert("member1", new_deployment(f"web-{i}", replicas=1))
        be.upsert("member2", new_deployment("api", replicas=2))
        assert be.count() == 21
        hits = be.search("kind:deployment name:web name:3")
        names = {h["name"] for h in hits}
        assert names == {"web-3"}
        assert hits[0]["object"].spec["replicas"] == 1
        # prefix form over the wire
        assert len(be.search("name:web*")) >= 20
        # cluster scoping + delete + drop
        assert len(be.search("", clusters=["member2"])) == 1
        be.delete("member1", "apps/v1/Deployment", "default", "web-0")
        assert be.count() == 20
        be.drop_cluster("member1")
        assert be.count() == 1

    def test_unreachable_indexer_buffers_and_retries(self):
        be = HttpIndexerBackend("127.0.0.1:1", batch_size=2, timeout_seconds=0.3)
        be.upsert("m1", new_deployment("a", replicas=1))
        be.upsert("m1", new_deployment("b", replicas=1))  # flush fails
        assert len(be._buffer) == 2  # batch queued for retry, in order
        server = IndexerServer()
        port = server.start()
        try:
            be.target = f"127.0.0.1:{port}"
            assert be.flush()
            assert be.count() == 2
        finally:
            server.stop()

    def test_poison_batch_is_dropped_not_requeued(self, indexer_proc):
        """A rejected op must not head-of-line block its batchmates: the
        server rejects atomically with the failing index, the client drops
        ONLY that op (counted) and delivers the rest of the batch."""
        be = HttpIndexerBackend(indexer_proc, batch_size=100)
        be._enqueue({"op": "bogus-op"})
        be.upsert("m1", new_deployment("batchmate", replicas=1))
        assert be.flush()  # poison dropped, batchmate delivered
        assert be.dropped == 1 and not be._buffer
        assert be.count() == 1  # batchmate survived, nothing else applied
        be.upsert("m1", new_deployment("after-poison", replicas=1))
        assert be.flush()
        assert be.count() == 2

    def test_search_controller_ships_documents_over_the_wire(self, indexer_proc):
        """The controller's opensearch-backend registries land documents in
        the EXTERNAL indexer process."""
        from karmada_tpu.controlplane import ControlPlane
        from karmada_tpu.utils.builders import new_cluster

        cp = ControlPlane()
        # swap the search controller's indexer for the networked one
        cp.search.indexer = HttpIndexerBackend(indexer_proc, batch_size=4)
        cp.join_cluster(new_cluster("member1", cpu="100", memory="200Gi"))
        cp.settle()
        cp.members.get("member1").apply(new_deployment("shipped", replicas=1))
        cp.store.apply(
            ResourceRegistry(
                meta=ObjectMeta(name="rr"),
                spec=ResourceRegistrySpec(
                    resource_selectors=[
                        {"apiVersion": "apps/v1", "kind": "Deployment"}
                    ],
                    backend="opensearch",
                ),
            )
        )
        cp.settle()
        hits = cp.search.indexer.search("name:shipped")
        assert len(hits) == 1 and hits[0]["cluster"] == "member1"
