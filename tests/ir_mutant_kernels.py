"""Intentionally-defective kernels: seeded mutants for the graftlint IR
tier (tests/test_graftlint_ir.py registers each as a temporary entry
point and asserts its rule fires — a rule that stops firing fails the
gate's fixture tests, never silently).

This module lives under tests/ (outside the linted tree) and is only
imported by the IR tracer at test time.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp


def ir001_weak_promotion(x):  # int32 input promoted to float64
    return (x.astype(jnp.float64) * 2.0).astype(jnp.int32)


def ir002_host_callback(x):  # host round-trip on every dispatch
    return jax.pure_callback(
        lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x
    )


_CAPTURED = np.arange(8192, dtype=np.int32)  # 32 KB baked into the trace


def ir003_const_capture(x):
    return x + jnp.asarray(_CAPTURED)[: x.shape[0]]


@partial(jax.jit, donate_argnames=("buf",))
def ir005_dropped_donation(x, buf):  # buf donated, no aliasable output
    return x + buf.sum()


@partial(jax.jit, donate_argnames=("buf",))
def ir005_reshaped_donation(x, buf):  # donation silently dropped: a
    # reshape at the kernel boundary leaves no output of the donated
    # buffer's shape for XLA to alias into
    return (buf + x).reshape(2, -1)


@partial(jax.jit, donate_argnames=("buf",))
def ir005_astype_donation(x, buf):  # donation silently dropped: a dtype
    # widen at the boundary breaks the identical-shape+dtype alias rule
    return (buf + x).astype(jnp.int64)


# -- dep-tier mutants (IR006/IR007) -----------------------------------------
#
# Each declares row_coupled on BOTH checked surfaces (registry entry and
# function attribute) so only the declaration-vs-proof contradiction —
# the thing the mutant seeds — can fire.


def ir006_hidden_cumsum(x):  # declared independent, but the "running
    # normalizer" is a row-axis prefix scan: row k's output reads every
    # row <= k — exactly the coupling a delta replay would miss
    return x * 2 - jnp.cumsum(x, axis=0)


ir006_hidden_cumsum.row_coupled = False


def ir006_decoupled(x, caps):  # declared coupled, but a refactor left a
    # purely elementwise body: the documented coupling no longer exists
    return jnp.clip(x * 3 + 1, 0, caps)


ir006_decoupled.row_coupled = True


def ir007_sharded_scan(x, mesh=None):  # honestly declared coupled, but
    # the sharded variant feeds the row-sharded operand straight into a
    # global prefix scan with no re-replication — the PR 9 CPU-SPMD
    # miscompile shape IR007 exists to catch
    del mesh
    return jnp.cumsum(x, axis=0)


ir007_sharded_scan.row_coupled = True
