"""Golden tests for the region-DFS group selection quirks
(select_groups.go:102-224, select_clusters_by_region.go:28-70,
group_clusters.go:138-330)."""

import numpy as np

from karmada_tpu.api.policy import SpreadConstraint
from karmada_tpu.scheduler.groups import (
    _Group,
    calc_group_score,
    select_by_topology_groups,
    select_groups,
)
from karmada_tpu.scheduler.snapshot import ClusterSnapshot
from karmada_tpu.utils.builders import new_cluster


def g(name, value, weight):
    return _Group(name=name, value=value, weight=weight)


class TestSelectGroups:
    def test_min_groups_infeasible_returns_empty(self):
        assert select_groups([g("r1", 2, 10)], min_c=2, max_c=3, target=0) == []

    def test_shortest_sufficient_path_wins(self):
        # subpath preference: with minGroups=1 satisfied by r1 alone, the
        # heavier two-group superpath loses to its own prefix
        got = select_groups(
            [g("r1", 1, 30), g("r2", 1, 20), g("r3", 1, 10)],
            min_c=1, max_c=2, target=0,
        )
        assert [x.name for x in got] == ["r1"]

    def test_min_groups_forces_path_length(self):
        got = select_groups(
            [g("r1", 1, 30), g("r2", 1, 20), g("r3", 1, 10)],
            min_c=2, max_c=2, target=0,
        )
        assert [x.name for x in got] == ["r1", "r2"]

    def test_weight_dominates_value(self):
        got = select_groups(
            [g("small-heavy", 1, 100), g("big-light", 5, 10)],
            min_c=1, max_c=1, target=0,
        )
        assert [x.name for x in got] == ["small-heavy"]

    def test_subpath_preferred_over_superpath(self):
        # both [r1] and [r1, r2] are feasible with equal weight when r2
        # contributes nothing; the shorter matching prefix must win
        got = select_groups(
            [g("r1", 3, 50), g("r2", 1, 0)],
            min_c=1, max_c=2, target=2,
        )
        assert [x.name for x in got] == ["r1"]

    def test_target_cluster_count_forces_combination(self):
        # one region alone cannot reach the cluster min-groups target
        got = select_groups(
            [g("r1", 1, 50), g("r2", 1, 40)],
            min_c=1, max_c=2, target=2,
        )
        assert sorted(x.name for x in got) == ["r1", "r2"]


class TestCalcGroupScore:
    def test_duplicated_counts_covering_clusters(self):
        score = np.asarray([100, 0, 0])
        credited = np.asarray([10, 3, 10])
        # replicas=5: clusters 0 and 2 cover it; avg score of valid = 50
        assert calc_group_score(
            [0, 1, 2], score, credited, duplicated=True, replicas=5,
            group_min_groups=1, cluster_min_groups=1,
        ) == 2 * 1000 + 50

    def test_divided_walks_until_target_covered(self):
        score = np.asarray([100, 100, 0])
        credited = np.asarray([4, 4, 4])
        # replicas=6, minGroups=2 -> per-group target ceil(6/2)=3: first
        # cluster covers it, one valid member, score avg 100
        assert calc_group_score(
            [0, 1, 2], score, credited, duplicated=False, replicas=6,
            group_min_groups=2, cluster_min_groups=1,
        ) == 3 * 1000 + 100

    def test_divided_insufficient_capacity_scores_by_sum(self):
        score = np.asarray([10, 10])
        credited = np.asarray([1, 1])
        got = calc_group_score(
            [0, 1], score, credited, duplicated=False, replicas=100,
            group_min_groups=1, cluster_min_groups=1,
        )
        assert got == 2 * 1000 + 10  # sum_avail x unit + avg score


class TestRegionAssembly:
    def _snap(self):
        clusters = [
            new_cluster("a1", region="east"),
            new_cluster("a2", region="east"),
            new_cluster("b1", region="west"),
            new_cluster("b2", region="west"),
            new_cluster("nr"),  # no region -> excluded
        ]
        return ClusterSnapshot(clusters), clusters

    def test_region_only_selects_one_cluster_per_region(self):
        snap, clusters = self._snap()
        order = np.asarray([0, 1, 2, 3, 4])
        score = np.zeros(5)
        credited = np.full(5, 10)
        sel = select_by_topology_groups(
            snap, {"region": SpreadConstraint(spread_by_field="region",
                                              min_groups=2, max_groups=2)},
            order, score, credited, need=4, duplicated=False, replicas=4,
        )
        # the reference's 0-max-groups quirk: exactly one (best) cluster
        # per chosen region
        names = sorted(clusters[j].name for j in sel)
        assert names == ["a1", "b1"]

    def test_cluster_constraint_fills_from_remainder(self):
        snap, clusters = self._snap()
        order = np.asarray([0, 1, 2, 3])
        score = np.asarray([0, 100, 0, 0])
        credited = np.full(5, 10)
        sel = select_by_topology_groups(
            snap,
            {"region": SpreadConstraint(spread_by_field="region",
                                        min_groups=2, max_groups=2),
             "cluster": SpreadConstraint(spread_by_field="cluster",
                                         min_groups=2, max_groups=3)},
            order, score, credited, need=4, duplicated=False, replicas=4,
        )
        names = sorted(clusters[j].name for j in sel)
        # one best per region + highest-score leftover (a2, score 100)
        assert names == ["a1", "a2", "b1"]

    def test_zone_without_region_is_fit_error(self):
        snap, _ = self._snap()
        sel = select_by_topology_groups(
            snap, {"zone": SpreadConstraint(spread_by_field="zone",
                                            min_groups=1)},
            np.asarray([0, 1]), np.zeros(5), np.full(5, 10),
            need=1, duplicated=False, replicas=1,
        )
        assert sel is None

    def test_too_few_regions_is_fit_error(self):
        snap, _ = self._snap()
        sel = select_by_topology_groups(
            snap, {"region": SpreadConstraint(spread_by_field="region",
                                              min_groups=3)},
            np.asarray([0, 1, 2, 3]), np.zeros(5), np.full(5, 10),
            need=1, duplicated=False, replicas=1,
        )
        assert sel is None


class TestSpreadOracleDifferential:
    """The engine's spread selection (scheduler/spread + groups, array-based
    with memoization) vs the pure-Python verification oracle
    (refimpl/spread, per-binding dicts): randomized fleets must select
    IDENTICAL cluster sets for region+cluster constraint mixes — the
    config-4 identity claim rests on these two paths being independent yet
    equal."""

    def test_randomized_selection_identity(self):
        from karmada_tpu.refimpl.spread import select_spread_clusters
        from karmada_tpu.scheduler.spread import cluster_order
        from karmada_tpu.scheduler.groups import select_by_topology_groups
        from karmada_tpu.scheduler.spread import select_by_cluster_constraint

        rng = np.random.default_rng(11)
        for trial in range(200):
            c = int(rng.integers(4, 40))
            regions = [f"r{k}" for k in range(int(rng.integers(1, 6)))]
            clusters = []
            for j in range(c):
                cl = new_cluster(f"m{j:02d}", cpu="50", memory="100Gi")
                cl.spec.region = (
                    str(rng.choice(regions)) if rng.random() < 0.9 else ""
                )
                clusters.append(cl)
            snap = ClusterSnapshot(clusters)
            feasible = rng.random(c) < 0.85
            if not feasible.any():
                continue
            score = np.where(rng.random(c) < 0.3, 100, 0)
            credited = rng.integers(0, 30, c).astype(np.int64)
            replicas = int(rng.integers(1, 60))
            duplicated = bool(rng.random() < 0.3)
            need = -1 if duplicated else replicas
            r_min = int(rng.integers(1, 4))
            r_max = int(rng.integers(r_min, 6))
            c_min = int(rng.integers(1, 5))
            c_max = int(rng.integers(c_min, 12))
            use_region = bool(rng.random() < 0.7)

            order = cluster_order(score, credited, feasible)
            if use_region:
                sc = {
                    "region": SpreadConstraint(
                        spread_by_field="region",
                        min_groups=r_min, max_groups=r_max,
                    ),
                    "cluster": SpreadConstraint(
                        spread_by_field="cluster",
                        min_groups=c_min, max_groups=c_max,
                    ),
                }
                got = select_by_topology_groups(
                    snap, sc, order, score, credited, need,
                    duplicated=duplicated, replicas=replicas,
                )
                constraints = {
                    "region": (r_min, r_max), "cluster": (c_min, c_max)
                }
            else:
                sc_c = SpreadConstraint(
                    spread_by_field="cluster",
                    min_groups=c_min, max_groups=c_max,
                )
                got = select_by_cluster_constraint(
                    sc_c, order, credited, need
                )
                constraints = {"cluster": (c_min, c_max)}

            cand = [int(j) for j in np.flatnonzero(feasible)]
            want = select_spread_clusters(
                cand,
                {j: clusters[j].spec.region for j in range(c)},
                {j: int(score[j]) for j in cand},
                {j: int(credited[j]) for j in cand},
                constraints,
                replicas,
                duplicated=duplicated,
            )
            got_set = sorted(int(j) for j in got) if got is not None else None
            want_set = sorted(want) if want is not None else None
            assert got_set == want_set, (
                f"trial {trial}: engine={got_set} oracle={want_set} "
                f"(region={use_region}, dup={duplicated}, reps={replicas}, "
                f"rmin/max={r_min}/{r_max}, cmin/max={c_min}/{c_max})"
            )
