"""Live gRPC estimator fan-out in the scheduling hot path (VERDICT r4 #5).

Real server subprocesses (python -m karmada_tpu.estimator --spec-file) host
many clusters' estimators behind MultiClusterEstimatorService; the
scheduler side fans out concurrently under a shared deadline with per-
profile memoization (EstimatorRegistry.make_batch_estimator). Placements
must be identical to the snapshot-fed engine when the estimators' node
capacities equal the snapshot's free capacities (min-merge degeneracy:
accurate == general), and the memo must answer repeat passes without
touching the wire until invalidated.
Ref: client/accurate.go:139-162 (fan-out), core/util.go:54-104 (min-merge).
"""

import numpy as np
import pytest

from karmada_tpu.estimator.fleet import spawn_estimator_fleet
from karmada_tpu.estimator.grpc_transport import (
    GrpcEstimatorConnection,
    RemoteAccurateEstimator,
)
from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.utils.builders import dynamic_weight_placement, synthetic_fleet
from karmada_tpu.utils.quantity import parse_resource_list

C, B, SERVERS = 16, 500, 2


@pytest.fixture()
def estimator_fleet():
    clusters = synthetic_fleet(C, seed=77)
    snap = ClusterSnapshot(clusters)
    dims = list(snap.dims)
    free = np.maximum(np.asarray(snap.available_cap), 0)
    with spawn_estimator_fleet(
        snap.names, free, dims, n_servers=SERVERS, index=snap.index,
        timeout_seconds=5.0,
    ) as fleet:
        yield snap, fleet.registry


def make_problems(snap):
    rng = np.random.default_rng(17)
    pl = dynamic_weight_placement()
    profiles = [
        parse_resource_list(
            {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
        )
        for p in range(4)
    ]
    return [
        BindingProblem(
            key=f"e{i}", placement=pl,
            replicas=int(rng.integers(1, 40)),
            requests=profiles[int(rng.integers(0, 4))],
            gvk="apps/v1/Deployment",
        )
        for i in range(B)
    ]


class TestEstimatorFanout:
    def test_live_fanout_identity_and_memo(self, estimator_fleet):
        snap, registry = estimator_fleet
        batch = registry.make_batch_estimator(
            snap.names, timeout_seconds=5.0
        )
        problems = make_problems(snap)
        eng = TensorScheduler(snap, extra_estimators=[batch])
        res = eng.schedule(problems)
        assert registry.fanout_seconds_total > 0, "no live fan-out happened"

        # memo: a repeat pass answers from the profile memo, not the wire
        f0 = registry.fanout_seconds_total
        res2 = eng.schedule(problems)
        assert registry.fanout_seconds_total == f0
        # invalidation (the cluster-event staleness hook) re-queries live
        registry.invalidate()
        eng.schedule(problems)
        assert registry.fanout_seconds_total > f0

        # identity vs the snapshot-fed engine (min-merge degeneracy)
        plain = TensorScheduler(snap).schedule(problems)
        for a, b in zip(res, plain):
            assert a.success == b.success
            assert dict(a.clusters) == dict(b.clusters)
        for a, b in zip(res2, plain):
            assert dict(a.clusters) == dict(b.clusters)

    def test_dead_server_answers_unauthentic(self, estimator_fleet):
        snap, registry = estimator_fleet
        # point one cluster at a dead target: it must answer -1 (ignored by
        # the min-merge) without failing the batch
        dead = GrpcEstimatorConnection(
            "dead", "127.0.0.1:1", timeout_seconds=0.5
        )
        dims = list(snap.dims)
        registry.register(
            RemoteAccurateEstimator(snap.names[0], dead, lambda: dims)
        )
        batch = registry.make_batch_estimator(
            snap.names, timeout_seconds=5.0
        )
        reqs = np.zeros((3, len(dims)), np.int64)
        reqs[:, 0] = 250
        out = batch(reqs, np.asarray([5, 5, 5]))
        assert (out[:, 0] == -1).all()
        assert (out[:, 1:] >= 0).all()
        dead.close()
