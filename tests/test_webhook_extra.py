"""Extended webhook handler coverage (HPA/cron/MCS/customization/work)."""

import pytest

from karmada_tpu.api.autoscaling import (
    CronFederatedHPA,
    CronFederatedHPARule,
    CronFederatedHPASpec,
    FederatedHPA,
    FederatedHPASpec,
    MetricSpec,
    ScaleTargetRef,
)
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.networking import MultiClusterService, MultiClusterServiceSpec
from karmada_tpu.api.work import Work, WorkSpec
from karmada_tpu.interpreter.declarative import (
    CustomizationRules,
    ResourceInterpreterCustomization,
)
from karmada_tpu.webhook import ValidationError, default_admission_chain


@pytest.fixture
def chain():
    return default_admission_chain()


def test_hpa_bounds(chain):
    hpa = FederatedHPA(
        meta=ObjectMeta(name="h", namespace="default"),
        spec=FederatedHPASpec(
            scale_target_ref=ScaleTargetRef(name="web"),
            min_replicas=5, max_replicas=2,
        ),
    )
    with pytest.raises(ValidationError, match="maxReplicas"):
        chain.admit("FederatedHPA", hpa)


def test_hpa_utilization_range(chain):
    hpa = FederatedHPA(
        meta=ObjectMeta(name="h", namespace="default"),
        spec=FederatedHPASpec(
            scale_target_ref=ScaleTargetRef(name="web"),
            metrics=[MetricSpec(target_average_utilization=250)],
        ),
    )
    with pytest.raises(ValidationError, match="targetAverageUtilization"):
        chain.admit("FederatedHPA", hpa)


def test_cron_schedule_validated(chain):
    cron = CronFederatedHPA(
        meta=ObjectMeta(name="c", namespace="default"),
        spec=CronFederatedHPASpec(
            rules=[CronFederatedHPARule(name="r", schedule="not a cron",
                                        target_replicas=1)]
        ),
    )
    with pytest.raises(ValidationError, match="cron schedule"):
        chain.admit("CronFederatedHPA", cron)


def test_cron_rule_needs_target(chain):
    cron = CronFederatedHPA(
        meta=ObjectMeta(name="c", namespace="default"),
        spec=CronFederatedHPASpec(
            rules=[CronFederatedHPARule(name="r", schedule="0 9 * * *")]
        ),
    )
    with pytest.raises(ValidationError, match="targetReplicas"):
        chain.admit("CronFederatedHPA", cron)


def test_mcs_types(chain):
    mcs = MultiClusterService(
        meta=ObjectMeta(name="m", namespace="default"),
        spec=MultiClusterServiceSpec(types=["Teleport"]),
    )
    with pytest.raises(ValidationError, match="exposure type"):
        chain.admit("MultiClusterService", mcs)


def test_customization_health_op(chain):
    cr = ResourceInterpreterCustomization(
        meta=ObjectMeta(name="c"),
        target_api_version="example.io/v1",
        target_kind="Thing",
        # "!=" became a supported op with the DSL extensions; "~=" stays invalid
        rules=CustomizationRules(health=[{"path": "x", "op": "~=", "value": 1}]),
    )
    with pytest.raises(ValidationError, match="health op"):
        chain.admit("ResourceInterpreterCustomization", cr)


def test_empty_work_rejected(chain):
    with pytest.raises(ValidationError, match="manifest"):
        chain.admit("Work", Work(meta=ObjectMeta(name="w", namespace="karmada-es-x")))


class TestFieldSelectorValidation:
    def test_bad_key_rejected(self):
        import pytest
        from karmada_tpu.api.policy import (
            ClusterAffinity, FieldSelector, LabelSelectorRequirement, Placement)
        from karmada_tpu.webhook import ValidationError
        from karmada_tpu.webhook.chain import validate_placement

        pl = Placement(cluster_affinity=ClusterAffinity(
            field_selector=FieldSelector(match_expressions=[
                LabelSelectorRequirement(key="name", operator="In",
                                         values=["x"])])))
        with pytest.raises(ValidationError):
            validate_placement(pl)

    def test_bad_operator_rejected(self):
        import pytest
        from karmada_tpu.api.policy import (
            ClusterAffinity, FieldSelector, LabelSelectorRequirement, Placement)
        from karmada_tpu.webhook import ValidationError
        from karmada_tpu.webhook.chain import validate_placement

        pl = Placement(cluster_affinity=ClusterAffinity(
            field_selector=FieldSelector(match_expressions=[
                LabelSelectorRequirement(key="region", operator="Exists")])))
        with pytest.raises(ValidationError):
            validate_placement(pl)

    def test_valid_selector_passes(self):
        from karmada_tpu.api.policy import (
            ClusterAffinity, FieldSelector, LabelSelectorRequirement, Placement)
        from karmada_tpu.webhook.chain import validate_placement

        validate_placement(Placement(cluster_affinity=ClusterAffinity(
            field_selector=FieldSelector(match_expressions=[
                LabelSelectorRequirement(key="region", operator="NotIn",
                                         values=["us-east1"])]))))


class TestClusterResourceModelDefaulting:
    def test_empty_models_get_nine_default_grades(self):
        from karmada_tpu.utils.builders import new_cluster
        from karmada_tpu.webhook.chain import mutate_cluster

        cl = new_cluster("m1")
        assert cl.spec.resource_models == []
        mutate_cluster(cl)
        grades = [m.grade for m in cl.spec.resource_models]
        assert grades == list(range(9))
        first, last = cl.spec.resource_models[0], cl.spec.resource_models[-1]
        assert all(r.min == 0 for r in first.ranges)
        assert all(r.max == 2**63 - 1 for r in last.ranges)
        cpu1 = next(r for r in cl.spec.resource_models[1].ranges
                    if r.name == "cpu")
        assert (cpu1.min, cpu1.max) == (1000, 2000)  # canonical milli units

    def test_declared_models_standardize(self):
        from karmada_tpu.api.cluster import ResourceModel, ResourceModelRange
        from karmada_tpu.utils.builders import new_cluster
        from karmada_tpu.webhook.chain import mutate_cluster

        cl = new_cluster("m1")
        cl.spec.resource_models = [
            ResourceModel(grade=1, ranges=[
                ResourceModelRange(name="cpu", min=2000, max=4000)]),
            ResourceModel(grade=0, ranges=[
                ResourceModelRange(name="cpu", min=500, max=2000)]),
        ]
        mutate_cluster(cl)
        assert [m.grade for m in cl.spec.resource_models] == [0, 1]
        assert cl.spec.resource_models[0].ranges[0].min == 0  # first min -> 0
        assert cl.spec.resource_models[-1].ranges[0].max == 2**63 - 1

    def test_gate_off_leaves_models_alone(self):
        from karmada_tpu.utils.builders import new_cluster
        from karmada_tpu.utils.features import (
            CUSTOMIZED_CLUSTER_RESOURCE_MODELING, feature_gate)
        from karmada_tpu.webhook.chain import mutate_cluster

        feature_gate.set(CUSTOMIZED_CLUSTER_RESOURCE_MODELING, False)
        try:
            cl = new_cluster("m1")
            mutate_cluster(cl)
            assert cl.spec.resource_models == []
        finally:
            feature_gate.set(CUSTOMIZED_CLUSTER_RESOURCE_MODELING, True)


class TestClusterValidation:
    def _cluster(self):
        from karmada_tpu.utils.builders import new_cluster

        return new_cluster("ok-name")

    def test_bad_name_rejected(self):
        import pytest
        from karmada_tpu.webhook import ValidationError
        from karmada_tpu.webhook.chain import validate_cluster

        cl = self._cluster()
        cl.meta.name = "Bad_Name!"
        with pytest.raises(ValidationError):
            validate_cluster(cl)
        cl.meta.name = "x" * 49
        with pytest.raises(ValidationError):
            validate_cluster(cl)

    def test_bad_sync_mode_rejected(self):
        import pytest
        from karmada_tpu.webhook import ValidationError
        from karmada_tpu.webhook.chain import validate_cluster

        cl = self._cluster()
        cl.spec.sync_mode = "Sideways"
        with pytest.raises(ValidationError):
            validate_cluster(cl)

    def test_non_contiguous_models_rejected(self):
        import pytest
        from karmada_tpu.api.cluster import (
            MAX_INT64, ResourceModel, ResourceModelRange)
        from karmada_tpu.webhook import ValidationError
        from karmada_tpu.webhook.chain import validate_cluster

        cl = self._cluster()
        cl.spec.resource_models = [
            ResourceModel(grade=0, ranges=[
                ResourceModelRange(name="cpu", min=0, max=1000)]),
            ResourceModel(grade=1, ranges=[
                ResourceModelRange(name="cpu", min=1500, max=MAX_INT64)]),
        ]
        with pytest.raises(ValidationError):
            validate_cluster(cl)  # gap 1000..1500

    def test_defaulted_models_pass(self):
        from karmada_tpu.webhook.chain import mutate_cluster, validate_cluster

        cl = self._cluster()
        mutate_cluster(cl)
        validate_cluster(cl)  # the nine default grades are self-consistent
