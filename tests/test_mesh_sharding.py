"""Multichip sharding of the scheduling grid (ISSUE 9).

Placement identity between a single-device engine and a mesh-sharded one
(the conftest 8-virtual-CPU-device mesh stands in for a TPU slice), the
donated persistent residents, the env-resolved mesh construction, and
the mesh-divisible padding semantics. Fast shapes only — the heavier
multi-stage lifecycle (churn/growth/compaction at 4k rows) lives in
``__graft_entry__.dryrun_multichip`` and ``bench.py --multichip``.
"""

import numpy as np
import pytest

import karmada_tpu.scheduler.fleet as fleet_mod
from karmada_tpu.api.policy import Placement, ReplicaSchedulingStrategy
from karmada_tpu.parallel import mesh as mesh_mod
from karmada_tpu.parallel.mesh import (
    divisible,
    mesh_from_shape,
    mesh_shape,
    pad_to_mesh,
    resolve_mesh,
    scheduling_mesh,
)
from karmada_tpu.scheduler import (
    BindingProblem,
    ClusterSnapshot,
    TensorScheduler,
)
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    synthetic_fleet,
)
from karmada_tpu.utils.quantity import parse_resource_list

C = 48


@pytest.fixture(scope="module")
def snap():
    return ClusterSnapshot(synthetic_fleet(C, seed=7, taint_fraction=0.08))


def build_problems(snap, n, *, seed=3, with_dup=True, prefix="b"):
    """A mixed batch: Divided rows with prev placements, plus (opt-in)
    Duplicated and zero-replica rows so the feasibility-bitset path runs
    under the mesh too."""
    pl = dynamic_weight_placement()
    pl_dup = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Duplicated"
        )
    )
    profiles = [
        parse_resource_list(
            {"cpu": f"{250 * (p + 1)}m", "memory": f"{512 * (p + 1)}Mi"}
        )
        for p in range(4)
    ]
    rng = np.random.default_rng(seed)
    names = snap.names
    out = []
    for i in range(n):
        if with_dup and i % 19 == 0:
            out.append(
                BindingProblem(
                    key=f"{prefix}{i}", placement=pl_dup,
                    replicas=int(rng.integers(0, 5)),
                    requests=profiles[i % 4], gvk="apps/v1/Deployment",
                )
            )
            continue
        prev = (
            {
                names[int(j)]: int(rng.integers(1, 20))
                for j in rng.choice(C, 3, replace=False)
            }
            if rng.random() < 0.7
            else {}
        )
        out.append(
            BindingProblem(
                key=f"{prefix}{i}", placement=pl,
                replicas=int(rng.integers(1, 100)),
                requests=profiles[i % 4], gvk="apps/v1/Deployment",
                prev=prev, fresh=bool(rng.random() < 0.05),
            )
        )
    return out


def decoded(results):
    return [
        (dict(r.clusters), r.success, tuple(sorted(r.feasible)))
        for r in results
    ]


class TestMeshConstruction:
    def test_resolve_mesh_env_off_by_default(self, monkeypatch):
        monkeypatch.delenv(mesh_mod.MESH_ENV, raising=False)
        assert resolve_mesh(None) is None
        for off in ("", "0", "1"):
            monkeypatch.setenv(mesh_mod.MESH_ENV, off)
            assert resolve_mesh(None) is None

    def test_resolve_mesh_env_builds_and_false_opts_out(self, monkeypatch):
        monkeypatch.setenv(mesh_mod.MESH_ENV, "2")
        m = resolve_mesh(None)
        assert mesh_shape(m) == (("b", 2), ("c", 1))
        # the explicit opt-out beats the env (the trace-manifest pattern)
        assert resolve_mesh(False) is None
        # an explicit Mesh passes through untouched
        assert resolve_mesh(m) is m

    def test_resolve_mesh_cluster_axis_env(self, monkeypatch):
        monkeypatch.setenv(mesh_mod.MESH_ENV, "4")
        monkeypatch.setenv(mesh_mod.CLUSTER_AXIS_ENV, "2")
        assert mesh_shape(resolve_mesh(None)) == (("b", 2), ("c", 2))

    def test_resolve_mesh_bad_values_fail_loudly(self, monkeypatch):
        monkeypatch.setenv(mesh_mod.MESH_ENV, "banana")
        with pytest.raises(ValueError):
            resolve_mesh(None)
        # more devices than the backend hosts: loud, never silent 1-chip
        monkeypatch.setenv(mesh_mod.MESH_ENV, "4096")
        with pytest.raises(ValueError):
            resolve_mesh(None)

    def test_mesh_shape_round_trips(self):
        m = scheduling_mesh(4, cluster_axis=2)
        shape = mesh_shape(m)
        assert shape == (("b", 2), ("c", 2))
        m2 = mesh_from_shape(shape)
        assert mesh_shape(m2) == shape
        assert mesh_shape(None) is None and mesh_from_shape(None) is None

    def test_pad_and_divisible(self):
        m = scheduling_mesh(4)
        assert pad_to_mesh(10, m) == 12 and pad_to_mesh(12, m) == 12
        assert divisible(12, m) and not divisible(10, m)
        assert pad_to_mesh(10, None) == 10 and divisible(10, None)

    def test_materialize_mesh_statics(self):
        st = mesh_mod.materialize_mesh_statics(
            {"mesh": (("b", 2), ("c", 1)), "e_cap": 4}
        )
        assert mesh_shape(st["mesh"]) == (("b", 2), ("c", 1))
        assert st["e_cap"] == 4
        passthrough = {"mesh": None, "e_cap": 4}
        assert mesh_mod.materialize_mesh_statics(passthrough) == passthrough

    def test_family_shardings_cover_families(self):
        m = scheduling_mesh(2)
        for family, spec in mesh_mod.FAMILY_SPECS.items():
            ins = mesh_mod.family_shardings(m, family)
            assert len(ins) == len(spec["in"]), family
            outs = mesh_mod.family_shardings(m, family, "out")
            assert len(outs) == len(spec["out"]), family


class TestShardedPlacementIdentity:
    """Sharded-vs-single identity across the bucket grid (both resident
    paths), including B not divisible by the device count and batches
    small enough that padding dominates whole chunks."""

    # (rows, note) — 512 aligns with the 256-chunk; 300/31 leave padding
    # rows in the tail chunk (31 pads a whole sub-chunk at eff_chunk 256)
    BATCHES = ((512, "aligned"), (300, "padded-tail"), (31, "tiny"))

    @pytest.mark.parametrize("legacy", (False, True), ids=("dense", "legacy"))
    def test_mesh2_identity_across_batch_shapes(
        self, snap, legacy, monkeypatch
    ):
        if legacy:
            monkeypatch.setattr(fleet_mod, "DENSE_RESIDENT_MAX_BYTES", 0)
        mesh = scheduling_mesh(2)
        for n, note in self.BATCHES:
            problems = build_problems(snap, n, prefix=f"s{n}_")
            single = TensorScheduler(snap, trace_manifest="")
            shard = TensorScheduler(snap, mesh=mesh, trace_manifest="")
            for p in range(2):  # steady pass re-uses the delta base
                ref = decoded(single.schedule(problems))
                got = decoded(shard.schedule(problems))
                assert ref == got, (note, n, "pass", p)
            # the fleet path must actually have engaged under the mesh
            # for batches past the threshold — identity over the host
            # fallback would prove nothing about the sharded kernels
            if n >= TensorScheduler.fleet_threshold:
                assert shard._fleet is not None
                assert shard._fleet._mesh is mesh

    def test_mesh4_churn_identity(self, snap, monkeypatch):
        clusters = synthetic_fleet(C, seed=7, taint_fraction=0.08)
        base = ClusterSnapshot(clusters)
        problems = build_problems(base, 512)
        single = TensorScheduler(base, trace_manifest="")
        shard = TensorScheduler(
            base, mesh=scheduling_mesh(4), trace_manifest=""
        )
        assert decoded(single.schedule(problems)) == decoded(
            shard.schedule(problems)
        )
        rng = np.random.default_rng(17)
        for r in range(2):  # availability drift: the churn fold paths
            for cl in clusters:
                rs = cl.status.resource_summary
                for dim, q in list(rs.allocated.items()):
                    alloc = rs.allocatable.get(dim, 0)
                    step = int(rng.integers(-3, 4)) * max(1, alloc // 100)
                    rs.allocated[dim] = int(min(max(0, q + step), alloc))
            drifted = ClusterSnapshot(clusters)
            assert single.update_snapshot(drifted)
            assert shard.update_snapshot(drifted)
            assert decoded(single.schedule(problems)) == decoded(
                shard.schedule(problems)
            ), f"churn-{r}"

    def test_non_pow2_mesh_falls_back_single_device(self, snap):
        # 3 devices cannot divide the pow2 chunk buckets: the table must
        # disable the mesh (loudly logged) and still place identically
        mesh3 = scheduling_mesh(3)
        problems = build_problems(snap, 300)
        single = TensorScheduler(snap, trace_manifest="")
        shard = TensorScheduler(snap, mesh=mesh3, trace_manifest="")
        ref = decoded(single.schedule(problems))
        got = decoded(shard.schedule(problems))
        assert ref == got
        assert shard._fleet is not None and shard._fleet._mesh is None


class TestMeshedQuotaAdmission:
    def test_quota_admission_identity_under_mesh(self, snap):
        """The quota family shards B-wise too (FAMILY_SPECS "quota"):
        admission decisions and the surviving placements must match the
        single-device engine exactly, with the meshed dispatch minting
        its own ledger key."""
        from karmada_tpu.scheduler.quota import QuotaSnapshot

        problems = build_problems(snap, 512, with_dup=False)
        for i, p in enumerate(problems):
            p.namespace = f"ns{i % 3}"
            p.prev = {}  # fresh demand so admission actually gates
        dims = ["cpu", "memory", "pods"]
        # ns0 tight (some denials), ns1 roomy, ns2 unquota'd
        remaining = np.array(
            [[200_000, 2 << 33, 500], [2**50, 2**50, 2**50]], np.int64
        )

        def quota():
            return QuotaSnapshot(
                dims=dims, ns_index={"ns0": 0, "ns1": 1},
                remaining=remaining.copy(), cap_index={},
                cluster_caps=np.zeros((0, C, 3), np.int64),
                generation=1, cap_token=0,
            )

        single = TensorScheduler(snap, trace_manifest="")
        shard = TensorScheduler(
            snap, mesh=scheduling_mesh(2), trace_manifest=""
        )
        single.set_quota(quota())
        shard.set_quota(quota())
        ref = [(dict(r.clusters), r.success, r.error)
               for r in single.schedule(problems)]
        got = [(dict(r.clusters), r.success, r.error)
               for r in shard.schedule(problems)]
        assert ref == got
        assert any(not s for _, s, _ in ref), "quota never denied anything"
        q_keys = lambda eng: {  # noqa: E731
            k for k in eng._engine_traces if k[0] == "Q"
        }
        assert q_keys(single).isdisjoint(q_keys(shard))


class TestDonatedResidents:
    """The persistent packed state is donated into the next solve: the
    pre-pass buffers are CONSUMED (aliased in place), not copied."""

    def test_dense_residents_donated(self, snap):
        problems = build_problems(snap, 512, with_dup=False)
        eng = TensorScheduler(
            snap, mesh=scheduling_mesh(2), trace_manifest=""
        )
        eng.schedule(problems)
        old_dense = eng._fleet._res_dense
        old_meta = eng._fleet._res_meta
        eng.schedule(problems)
        assert old_dense.is_deleted() and old_meta.is_deleted()
        # and the new residents keep the row-sharded layout (the alias
        # only holds when in/out shardings agree)
        spec = eng._fleet._res_dense.sharding.spec
        assert tuple(spec)[:1] == ("b",)

    @pytest.mark.parametrize("meshed", (False, True), ids=("single", "mesh2"))
    def test_legacy_resident_donated(self, snap, monkeypatch, meshed):
        monkeypatch.setattr(fleet_mod, "DENSE_RESIDENT_MAX_BYTES", 0)
        eng = TensorScheduler(
            snap,
            mesh=scheduling_mesh(2) if meshed else False,
            trace_manifest="",
        )
        problems = build_problems(snap, 512, with_dup=False)
        eng.schedule(problems)
        old = eng._fleet._resident_entries
        eng.schedule(problems)
        assert old.is_deleted()

    def test_steady_upload_bounded(self, snap):
        # a steady storm must not re-upload the packed grid: after the
        # first pass the only host->device traffic is the (cached) row
        # index buffer — asserted well below the full state upload
        problems = build_problems(snap, 512, with_dup=False)
        eng = TensorScheduler(snap, trace_manifest="")
        eng.schedule(problems)
        first = eng._fleet.last_breakdown["upload_mb"]
        eng.schedule(problems)
        steady = eng._fleet.last_breakdown["upload_mb"]
        assert first > 0.1  # the initial packed-state upload
        assert steady == 0.0  # all-rows index cached on device


class TestMeshTraceIdentity:
    def test_trace_keys_distinguish_mesh_shapes(self, snap):
        """The same workload on mesh=1 vs mesh=2 engines mints DISTINCT
        ledger keys — the restart-across-mesh-change hazard: equal keys
        would let a single-device manifest fake-warm a meshed boot."""
        problems = build_problems(snap, 512, with_dup=False)
        single = TensorScheduler(snap, trace_manifest="")
        shard = TensorScheduler(
            snap, mesh=scheduling_mesh(2), trace_manifest=""
        )
        single.schedule(problems)
        shard.schedule(problems)
        solve_keys = lambda eng: {  # noqa: E731
            k for k in eng._fleet._seen_traces if k[0] in ("A", "L")
        }
        assert solve_keys(single).isdisjoint(solve_keys(shard))

    def test_bits_key_carries_mesh_shape_and_skips_manifest(
        self, snap, tmp_path
    ):
        """The feasibility-bitset ("B") trace key carries the canonical
        mesh shape — not a bool — and its meshed dispatches stay
        manifest-UNRECORDED (the kernel has no mesh static: a replay
        could only compile the single-device form, so recording would
        fake-warm a later boot's ledger). Regression for the review
        finding: a bool element let a mesh=2 manifest seed a mesh=8
        boot's "B" key as already-warmed."""
        from karmada_tpu.scheduler import prewarm

        # Duplicated rows drive the bits path; decoding (feasible access)
        # triggers the lazy dispatch
        problems = build_problems(snap, 256, with_dup=True)
        path = tmp_path / "mesh_bits.json"
        eng = TensorScheduler(
            snap, mesh=scheduling_mesh(2), trace_manifest=str(path)
        )
        decoded(eng.schedule(problems))
        b_keys = {k for k in eng._fleet._seen_traces if k[0] == "B"}
        assert b_keys, "bits path did not dispatch"
        assert all(k[-1] == (("b", 2), ("c", 1)) for k in b_keys)
        assert not any(
            r["kernel"] == "fleet_bits"
            for r in prewarm.TraceManifest(str(path)).records
        )
        # positive control: the single-device engine records it
        path1 = tmp_path / "single_bits.json"
        eng1 = TensorScheduler(
            snap, mesh=False, trace_manifest=str(path1)
        )
        decoded(eng1.schedule(problems))
        assert any(
            r["kernel"] == "fleet_bits"
            for r in prewarm.TraceManifest(str(path1)).records
        )
        assert {
            k for k in eng1._fleet._seen_traces if k[0] == "B"
        } .isdisjoint(b_keys)

    def test_trace_dump_and_debug_endpoint_report_mesh(self, snap):
        """`trace dump` and /debug/traces carry the process's scheduling-
        mesh shape — how an operator tells a single-chip from an 8-chip
        plane without poking jax."""
        import json as _json
        import urllib.request

        from karmada_tpu.cli import cmd_trace_dump
        from karmada_tpu.parallel.mesh import record_active_mesh
        from karmada_tpu.utils.metrics import MetricsServer

        record_active_mesh(scheduling_mesh(2))
        doc = cmd_trace_dump()
        assert doc["mesh"] == [["b", 2], ["c", 1]]
        srv = MetricsServer()
        srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/traces", timeout=10
            ) as resp:
                remote = _json.loads(resp.read().decode())
            assert remote["mesh"] == [["b", 2], ["c", 1]]
        finally:
            srv.stop()

    def test_engine_mesh_info(self, snap):
        assert TensorScheduler(snap, trace_manifest="").mesh_info is None
        eng = TensorScheduler(
            snap, mesh=scheduling_mesh(4, cluster_axis=2),
            trace_manifest="",
        )
        assert eng.mesh_info == (("b", 2), ("c", 2))
        # a >1 cluster axis opts the engine into cluster sharding
        assert eng.shard_clusters is True
