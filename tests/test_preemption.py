"""ISSUE 14: the scarcity plane — priority classes, the batched
plane-wide preemption kernel, and the continuous descheduler tier.

Coverage map:
- kernel vs the sequential numpy oracle (randomized, multi-class,
  multi-dim, equal-or-higher-priority immunity, fewest-displacements
  order), single-device and sharded;
- engine integration: same-pass re-solve, quota composition (a denied
  row never preempts; caps still bound the boosted re-solve), the
  disarmed `is None` check;
- controller e2e: victim evictions through the graceful-eviction
  machinery, the Preempted condition, the TransitionDedup-gated
  preemptions counter, priority-descending FIFO wave ordering, the
  KARMADA_TPU_PREEMPTION kill switch, detector priority plumbing with
  default-0 back-compat;
- the continuous descheduler: drift triggers bounded by the disruption
  budget exactly, RescheduleTriggeredAt honored (no re-stamp while
  unconsumed), oracle-identical trigger sets;
- the explain stage bit and the history/top scarcity columns;
- the spawn-family hardening: RemoteAdmission's env-tunable deadline
  with one bounded retry.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from karmada_tpu import cli as _cli
from karmada_tpu.api import (
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
    LabelSelector,
)
from karmada_tpu.api.work import PREEMPTED, SCHEDULED
from karmada_tpu.estimator.accurate import NodeState
from karmada_tpu.ops.preempt import preempt_select
from karmada_tpu.refimpl.preempt_np import (
    rebalance_np,
    select_victims_np,
)
from karmada_tpu.scheduler import (
    BindingProblem,
    ClusterSnapshot,
    TensorScheduler,
)
from karmada_tpu.scheduler.core import INSUFFICIENT_ERROR
from karmada_tpu.scheduler.quota import (
    QUOTA_EXCEEDED_ERROR,
    build_quota_snapshot,
)
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)
from karmada_tpu.utils.member import MemberCluster
from karmada_tpu.utils.metrics import preemptions_total
from karmada_tpu.utils.quantity import parse_resource_list

CPU_REQ = {"cpu": 1000}


def reset_counter(counter) -> None:
    """Zero a process-global counter between tests (no public reset —
    counters are monotone by design)."""
    with counter._lock:
        counter._values.clear()


# --------------------------------------------------------------------------
# kernel vs oracle
# --------------------------------------------------------------------------


def random_rows(rng, b, r, c, classes=5):
    prio = rng.integers(0, classes, b).astype(np.int32)
    demand = np.zeros((b, r), np.int64)
    freed = np.zeros((b, r), np.int64)
    victim_ok = np.zeros(b, bool)
    weight = np.zeros(b, np.int32)
    assigned = np.zeros((b, c), np.int32)
    requests = rng.integers(0, 8, (b, r)).astype(np.int64)
    for i in range(b):
        role = rng.integers(0, 3)
        if role == 0 and prio[i] > 0:
            demand[i] = rng.integers(0, 24, r)
        elif role == 1:
            assigned[i] = rng.integers(0, 4, c)
            weight[i] = assigned[i].sum()
            victim_ok[i] = weight[i] > 0
            freed[i] = int(weight[i]) * requests[i]
    return prio, demand, freed, victim_ok, weight, assigned, requests


class TestKernelOracleIdentity:
    def test_randomized_victims_identical(self):
        rng = np.random.default_rng(7)
        checked = 0
        for _ in range(120):
            b = int(rng.integers(1, 64))
            r = int(rng.integers(1, 4))
            c = int(rng.integers(1, 8))
            rows = random_rows(rng, b, r, c)
            prio, demand, freed, victim_ok, weight, assigned, requests = rows
            v_dev, caps_dev = preempt_select(*rows)
            want = select_victims_np(prio, demand, freed, victim_ok, weight)
            assert np.asarray(v_dev).tolist() == want
            want_caps = np.zeros((c, r), np.int64)
            for i in range(b):
                if want[i]:
                    want_caps += assigned[i][:, None].astype(np.int64) * requests[i]
            assert np.array_equal(np.asarray(caps_dev), want_caps)
            checked += int(sum(want))
        assert checked > 50  # the fuzz actually exercised selections

    def test_never_victimizes_equal_or_higher_priority(self):
        # one demander at prio 5; victims at prio 5 and 7 are immune,
        # prio 4 is taken
        prio = np.array([5, 5, 7, 4], np.int32)
        demand = np.array([[10], [0], [0], [0]], np.int64)
        freed = np.array([[0], [50], [50], [50]], np.int64)
        victim_ok = np.array([False, True, True, True])
        weight = np.array([0, 5, 5, 5], np.int32)
        assigned = np.array([[0], [5], [5], [5]], np.int32)
        requests = np.array([[10], [10], [10], [10]], np.int64)
        v, _ = preempt_select(
            prio, demand, freed, victim_ok, weight, assigned, requests
        )
        assert np.asarray(v).tolist() == [False, False, False, True]
        assert select_victims_np(prio, demand, freed, victim_ok, weight) == [
            False, False, False, True,
        ]

    def test_fewest_displacements_order(self):
        # demand 6; victims free 6 (weight 6) and 3+3 (weight 3 each):
        # the largest-weight victim alone covers it
        prio = np.array([3, 0, 0, 0], np.int32)
        demand = np.array([[6], [0], [0], [0]], np.int64)
        freed = np.array([[0], [3], [6], [3]], np.int64)
        victim_ok = np.array([False, True, True, True])
        weight = np.array([0, 3, 6, 3], np.int32)
        assigned = np.array([[0], [3], [6], [3]], np.int32)
        requests = np.ones((4, 1), np.int64)
        v, caps = preempt_select(
            prio, demand, freed, victim_ok, weight, assigned, requests
        )
        assert np.asarray(v).tolist() == [False, False, True, False]
        assert int(np.asarray(caps)[0, 0]) == 6

    def test_lower_class_demand_cannot_take_higher_victims(self):
        # demanders at 10 (needs 5) and 5 (needs 5); victims prio 1
        # (frees 5) and prio 6 (frees 5): the prio-6 victim may only
        # serve the prio-10 demand, which the prio-1 victim already
        # covered — so it survives and the prio-5 demand stays unmet
        prio = np.array([10, 5, 1, 6], np.int32)
        demand = np.array([[5], [5], [0], [0]], np.int64)
        freed = np.array([[0], [0], [5], [5]], np.int64)
        victim_ok = np.array([False, False, True, True])
        weight = np.array([0, 0, 5, 5], np.int32)
        assigned = np.array([[0], [0], [5], [5]], np.int32)
        requests = np.ones((4, 1), np.int64)
        v, _ = preempt_select(
            prio, demand, freed, victim_ok, weight, assigned, requests
        )
        assert np.asarray(v).tolist() == [False, False, True, False]
        assert select_victims_np(prio, demand, freed, victim_ok, weight) == [
            False, False, True, False,
        ]

    @pytest.mark.parametrize("devices", (2, 4))
    def test_sharded_identity(self, devices):
        from karmada_tpu.parallel.mesh import scheduling_mesh

        rng = np.random.default_rng(devices)
        mesh = scheduling_mesh(devices)
        for b in (16, 32):
            rows = random_rows(rng, b, 3, 6)
            v1, c1 = preempt_select(*rows)
            v2, c2 = preempt_select(*rows, mesh=mesh)
            assert np.array_equal(np.asarray(v1), np.asarray(v2))
            assert np.array_equal(np.asarray(c1), np.asarray(c2))

    def test_registries_in_lockstep(self):
        from karmada_tpu.scheduler import fleet, prewarm

        assert "preempt_select" in fleet.FLEET_KERNELS
        assert "preempt_select" in prewarm._KERNELS
        import tools.graftlint.ir as ir

        assert "preempt_select" in ir.ENTRY_POINTS


# --------------------------------------------------------------------------
# engine integration
# --------------------------------------------------------------------------


def saturated_snapshot(c=2, cap_cpu=4):
    """Clusters with all CPU allocated: any dynamic-weight demand is
    insufficient until something frees capacity."""
    return ClusterSnapshot([
        new_cluster(
            f"m{i}", cpu=str(cap_cpu), memory="100Gi",
            allocated={"cpu": str(cap_cpu)},
        )
        for i in range(c)
    ])


def demander(key, replicas=4, prio=100, ns=""):
    return BindingProblem(
        key=key,
        placement=dynamic_weight_placement(),
        replicas=replicas,
        requests=dict(CPU_REQ),
        gvk="apps/v1/Deployment",
        namespace=ns,
        priority=prio,
    )


def resident(key, prev, prio=0):
    return BindingProblem(
        key=key,
        placement=dynamic_weight_placement(),
        replicas=sum(prev.values()),
        requests=dict(CPU_REQ),
        gvk="apps/v1/Deployment",
        prev=dict(prev),
        priority=prio,
    )


class TestEnginePreemption:
    def test_same_pass_resolve(self):
        eng = TensorScheduler(saturated_snapshot(), trace_manifest="")
        pool = [
            resident("v0", {"m0": 1, "m1": 1}),
            resident("v1", {"m0": 1, "m1": 1}),
            resident("v2", {"m0": 1, "m1": 1}),
            resident("v3", {"m0": 1, "m1": 1}),
        ]
        eng.set_preemption(lambda exclude: pool)
        res = eng.schedule([demander("hi", replicas=4)])
        assert res[0].success, res[0].error
        assert sum(res[0].clusters.values()) == 4
        out = eng.last_preemption
        assert out is not None and len(out.victims) == 2
        assert out.placed == ["hi"]
        # freed capacity landed on the victims' clusters
        assert out.freed_caps is not None and out.freed_caps.sum() > 0

    def test_disarmed_is_none_check(self):
        eng = TensorScheduler(saturated_snapshot(), trace_manifest="")
        res = eng.schedule([demander("hi")])
        assert res[0].error == INSUFFICIENT_ERROR
        assert eng.last_preemption is None

    def test_priority_zero_never_demands(self):
        eng = TensorScheduler(saturated_snapshot(), trace_manifest="")
        called = []
        eng.set_preemption(lambda exclude: called.append(1) or [])
        res = eng.schedule([demander("lo", prio=0)])
        assert res[0].error == INSUFFICIENT_ERROR
        assert not called  # no priority>0 demander: no victim-pool call

    def test_no_eligible_victims_stays_unschedulable(self):
        eng = TensorScheduler(saturated_snapshot(), trace_manifest="")
        # residents at the SAME priority: immune
        pool = [resident("v0", {"m0": 2, "m1": 2}, prio=100)]
        eng.set_preemption(lambda exclude: pool)
        res = eng.schedule([demander("hi", prio=100)])
        assert res[0].error == INSUFFICIENT_ERROR
        out = eng.last_preemption
        assert out is not None and not out.victims
        assert out.still_unschedulable == ["hi"]

    def test_quota_denied_row_never_preempts(self):
        snap = saturated_snapshot()
        eng = TensorScheduler(snap, trace_manifest="")
        q = FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="a"),
            spec=FederatedResourceQuotaSpec(overall={"cpu": 0}),
        )
        eng.set_quota(build_quota_snapshot([q], snap, generation=1))
        pool = [resident("v0", {"m0": 2, "m1": 2})]
        calls = []

        def source(exclude):
            calls.append(1)
            return pool

        eng.set_preemption(source)
        res = eng.schedule([demander("a/hi", ns="a")])
        assert res[0].error == QUOTA_EXCEEDED_ERROR
        assert not calls  # denied by quota: never reached victim selection

    def test_boosted_resolve_still_respects_static_caps(self):
        from karmada_tpu.api.policy import StaticClusterAssignment

        snap = saturated_snapshot()
        eng = TensorScheduler(snap, trace_manifest="")
        q = FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="a"),
            spec=FederatedResourceQuotaSpec(
                overall={"cpu": 1 << 40},
                static_assignments=[StaticClusterAssignment(
                    cluster_name="m0", hard={"cpu": 0}
                )],
            ),
        )
        eng.set_quota(build_quota_snapshot([q], snap, generation=1))
        pool = [
            resident("v0", {"m0": 2, "m1": 2}),
            resident("v1", {"m0": 2, "m1": 2}),
        ]
        eng.set_preemption(lambda exclude: pool)
        res = eng.schedule([demander("a/hi", replicas=2, ns="a")])
        assert res[0].success, res[0].error
        # the cap-zeroed cluster stays excluded even though victims
        # freed capacity there
        assert "m0" not in res[0].clusters

    def test_trace_ledgered_and_manifest_kernel_registered(self):
        eng = TensorScheduler(saturated_snapshot(), trace_manifest="")
        pool = [resident("v0", {"m0": 2, "m1": 2})]
        eng.set_preemption(lambda exclude: pool)
        eng.schedule([demander("hi", replicas=2)])
        assert any(k[0] == "P" for k in eng._engine_traces)


# --------------------------------------------------------------------------
# controller e2e (the scarcity storm in miniature)
# --------------------------------------------------------------------------


def scarcity_plane(n_clusters=2, cap_cpu=4):
    cp = _cli.cmd_init()
    members = {}
    for i in range(n_clusters):
        name = f"c{i}"
        caps = {"cpu": str(cap_cpu), "memory": "100Gi", "pods": 1000}
        m = MemberCluster(name)
        m.nodes = [NodeState(
            name=f"{name}-n0", allocatable=parse_resource_list(caps)
        )]
        members[name] = m
        cp.join_cluster(new_cluster(name, **caps), m)
    cp.settle()
    pl = dynamic_weight_placement()

    def policy(name, tier, priority=0):
        return PropagationPolicy(
            meta=ObjectMeta(name=name, namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(
                    api_version="apps/v1", kind="Deployment",
                    label_selector=LabelSelector(
                        match_labels={"tier": tier}
                    ),
                )],
                placement=pl,
                priority=priority,
            ),
        )

    cp.store.apply(policy("low", "low"))
    cp.store.apply(policy("high", "high", priority=100))

    def sync_member_usage():
        """The kubelet's role in this harness: node.requested mirrors
        bound replicas so summaries are genuine capacity math."""
        usage = {name: {} for name in members}
        for rb in cp.store.list("ResourceBinding"):
            req = (
                rb.spec.replica_requirements.resource_request
                if rb.spec.replica_requirements
                else {}
            )
            for tc in rb.spec.clusters:
                acc = usage.get(tc.name)
                if acc is None:
                    continue
                for res, qty in req.items():
                    acc[res] = acc.get(res, 0) + qty * tc.replicas
                acc["pods"] = acc.get("pods", 0) + tc.replicas
        for name, m in members.items():
            m.nodes[0].requested = dict(usage[name])
        cp.settle()

    return cp, members, sync_member_usage


def fill_low(cp, sync, n=4, replicas=2):
    for i in range(n):
        cp.store.apply(new_deployment(
            f"low{i}", replicas=replicas, cpu="1", memory="1Gi",
            labels={"tier": "low"},
        ))
    cp.settle()
    sync()


class TestControllerE2E:
    def setup_method(self):
        reset_counter(preemptions_total)

    def test_surge_evicts_victims_and_places(self):
        cp, members, sync = scarcity_plane()
        fill_low(cp, sync)
        cp.store.apply(new_deployment(
            "hi", replicas=4, cpu="1", memory="1Gi",
            labels={"tier": "high"},
        ))
        cp.settle()
        hi = cp.store.get("ResourceBinding", "default/hi-deployment")
        assert sum(tc.replicas for tc in hi.spec.clusters) == 4
        assert hi.spec.priority == 100
        victims = [
            rb
            for rb in cp.store.list("ResourceBinding")
            if any(
                t.reason == "PreemptedByHigherPriority"
                for t in rb.spec.graceful_eviction_tasks
            )
        ]
        assert len(victims) == 2
        for rb in victims:
            assert not rb.spec.clusters  # fully displaced
            cond = next(
                c for c in rb.status.conditions if c.type == PREEMPTED
            )
            assert cond.status and "hi-deployment" in cond.message
            for t in rb.spec.graceful_eviction_tasks:
                assert t.producer == "PreemptionKernel"
        samples = preemptions_total.samples()
        assert samples == {
            (("reason", "PreemptedByHigherPriority"),): 2.0
        }

    def test_transition_dedup_never_double_counts(self):
        """A displaced binding re-enqueued across settle waves within
        one displacement episode counts exactly once; a NEW displacement
        after a successful re-placement counts anew."""
        cp, members, sync = scarcity_plane()
        fill_low(cp, sync)
        cp.store.apply(new_deployment(
            "hi", replicas=4, cpu="1", memory="1Gi",
            labels={"tier": "high"},
        ))
        cp.settle()
        count0 = sum(preemptions_total.samples().values())
        assert count0 == 2
        # re-settle storms within the same episode: the parked victims
        # re-enqueue but the counter must not move
        for _ in range(3):
            for kind in ("ResourceBinding",):
                for rb in cp.store.list(kind):
                    cp.scheduler.worker.enqueue(
                        (kind, rb.meta.namespaced_name)
                    )
            cp.settle()
        assert sum(preemptions_total.samples().values()) == count0
        # free the fleet: drop the high-priority workload, let evictions
        # time out, and re-place the victims — the episode closes
        cp.store.delete("Resource", "default/hi")
        for rb in cp.store.list("ResourceBinding"):
            rb.spec.graceful_eviction_tasks = []
            cp.store.apply(rb)
        # sync-settle until the usage mirror is stable: freed capacity
        # lets the parked victims re-place, and the NEXT sync must see
        # those placements before the second storm arrives
        for _ in range(3):
            sync()
            cp.settle()
        placed = [
            rb for rb in cp.store.list("ResourceBinding")
            if rb.spec.clusters
        ]
        assert len(placed) == 4  # every low binding re-placed
        for rb in placed:
            cond = next(
                (c for c in rb.status.conditions if c.type == PREEMPTED),
                None,
            )
            assert cond is None or not cond.status  # episode resolved
        # a second storm displaces fresh victims: counts again
        cp.store.apply(new_deployment(
            "hi2", replicas=4, cpu="1", memory="1Gi",
            labels={"tier": "high"},
        ))
        cp.settle()
        assert sum(preemptions_total.samples().values()) == count0 + 2

    def test_kill_switch_disarms(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_PREEMPTION", "0")
        cp, members, sync = scarcity_plane()
        fill_low(cp, sync)
        cp.store.apply(new_deployment(
            "hi", replicas=4, cpu="1", memory="1Gi",
            labels={"tier": "high"},
        ))
        cp.settle()
        hi = cp.store.get("ResourceBinding", "default/hi-deployment")
        cond = next(
            c for c in hi.status.conditions if c.type == SCHEDULED
        )
        assert not cond.status
        assert cond.reason == "InsufficientReplicas"
        assert not any(
            rb.spec.graceful_eviction_tasks
            for rb in cp.store.list("ResourceBinding")
        )
        assert preemptions_total.samples() == {}

    def test_equal_priority_is_immune(self):
        cp, members, sync = scarcity_plane()
        # fill with HIGH-priority workloads, surge with the same class
        for i in range(4):
            cp.store.apply(new_deployment(
                f"hi{i}", replicas=2, cpu="1", memory="1Gi",
                labels={"tier": "high"},
            ))
        cp.settle()
        sync()
        cp.store.apply(new_deployment(
            "hi-late", replicas=4, cpu="1", memory="1Gi",
            labels={"tier": "high"},
        ))
        cp.settle()
        late = cp.store.get("ResourceBinding", "default/hi-late-deployment")
        cond = next(
            c for c in late.status.conditions if c.type == SCHEDULED
        )
        assert not cond.status  # nothing below it to displace
        assert not any(
            rb.spec.graceful_eviction_tasks
            for rb in cp.store.list("ResourceBinding")
        )

    def test_wave_orders_priority_desc_fifo_within_class(self):
        cp, members, sync = scarcity_plane(n_clusters=2, cap_cpu=1000)
        seen = []
        orig = TensorScheduler.schedule

        def spy(self, problems):
            seen.append([
                (p.key, getattr(p, "priority", 0)) for p in problems
            ])
            return orig(self, problems)

        TensorScheduler.schedule = spy
        try:
            # interleave low/high arrivals in one wave
            for i in range(3):
                cp.store.apply(new_deployment(
                    f"low{i}", replicas=1, cpu="1", memory="1Gi",
                    labels={"tier": "low"},
                ))
                cp.store.apply(new_deployment(
                    f"hi{i}", replicas=1, cpu="1", memory="1Gi",
                    labels={"tier": "high"},
                ))
            cp.settle()
        finally:
            TensorScheduler.schedule = orig
        wave = next(w for w in seen if len(w) == 6)
        prios = [p for _, p in wave]
        assert prios == sorted(prios, reverse=True)
        his = [k for k, p in wave if p == 100]
        lows = [k for k, p in wave if p == 0]
        # FIFO within each class: arrival order preserved
        assert his == sorted(his, key=lambda k: int(k[10]))
        assert lows == sorted(lows, key=lambda k: int(k[11]))

    def test_detector_priority_plumb_and_default(self):
        cp, members, sync = scarcity_plane(cap_cpu=1000)
        cp.store.apply(new_deployment(
            "hi0", replicas=1, cpu="1", memory="1Gi",
            labels={"tier": "high"},
        ))
        cp.store.apply(new_deployment(
            "low0", replicas=1, cpu="1", memory="1Gi",
            labels={"tier": "low"},
        ))
        cp.settle()
        hi = cp.store.get("ResourceBinding", "default/hi0-deployment")
        low = cp.store.get("ResourceBinding", "default/low0-deployment")
        assert hi.spec.priority == 100
        assert low.spec.priority == 0
        # back-compat: a checkpoint written by a pre-priority build
        # unpickles without the field — reads as 0, not a spec change
        del low.spec.__dict__["priority"]
        assert cp.scheduler._problem_for(
            "default/low0-deployment", low, False
        ).priority == 0
        gen = low.meta.generation
        cp.detector.worker.enqueue("default/low0")
        cp.settle()
        low2 = cp.store.get("ResourceBinding", "default/low0-deployment")
        assert low2.meta.generation == gen  # no spurious generation bump


# --------------------------------------------------------------------------
# the continuous descheduler tier
# --------------------------------------------------------------------------


def drift_plane(budget=None, monkeypatch=None):
    cp = _cli.cmd_init(enable_drift_rebalancer=True)
    # manual rounds only: the ticker would re-run per settle pass
    cp.drift_rebalancer.active = False
    members = {}

    def add_cluster(name, cpu):
        caps = {"cpu": str(cpu), "memory": "100Gi", "pods": 1000}
        m = MemberCluster(name)
        m.nodes = [NodeState(
            name=f"{name}-n0", allocatable=parse_resource_list(caps)
        )]
        members[name] = m
        cp.join_cluster(new_cluster(name, **caps), m)

    add_cluster("c0", 8)
    add_cluster("c1", 8)
    cp.settle()
    cp.store.apply(PropagationPolicy(
        meta=ObjectMeta(name="pol", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment"
            )],
            placement=dynamic_weight_placement(),
        ),
    ))
    return cp, members, add_cluster


class TestContinuousDescheduler:
    def setup_method(self):
        reset_counter(preemptions_total)

    def test_steady_plane_triggers_nothing(self):
        cp, members, _add = drift_plane()
        for i in range(3):
            cp.store.apply(new_deployment(
                f"w{i}", replicas=4, cpu="1", memory="1Gi"
            ))
        cp.settle()
        stats = cp.drift_rebalancer.rebalance_once()
        assert stats["drifted"] == 0 and not stats["triggered"]

    def test_drift_triggers_bounded_by_budget(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION", "2")
        cp, members, add_cluster = drift_plane()
        for i in range(4):
            cp.store.apply(new_deployment(
                f"w{i}", replicas=4, cpu="1", memory="1Gi"
            ))
        cp.settle()
        before = {
            rb.meta.namespaced_name: {
                tc.name: tc.replicas for tc in rb.spec.clusters
            }
            for rb in cp.store.list("ResourceBinding")
        }
        # a new, much larger cluster joins: the fresh solve would spread
        # replicas onto it — every resident placement drifts
        add_cluster("c2", 64)
        cp.settle()
        from karmada_tpu.utils.metrics import (
            desched_disruption_budget,
            desched_disruption_used,
        )

        stats = cp.drift_rebalancer.rebalance_once()
        assert stats["budget"] == 2
        assert stats["drifted"] >= 3
        assert len(stats["triggered"]) == 2  # the budget, exactly
        assert sum(desched_disruption_budget.samples().values()) == 2
        assert sum(desched_disruption_used.samples().values()) == 2
        samples = preemptions_total.samples()
        assert samples == {(("reason", "RebalanceTriggered"),): 2.0}
        # the triggered bindings re-place as Fresh waves
        cp.settle()
        for key in stats["triggered"]:
            now = {
                tc.name: tc.replicas
                for tc in cp.store.get("ResourceBinding", key).spec.clusters
            }
            assert now != before[key]
            assert "c2" in now
        # a second round while nothing else drifted: the re-placed rows
        # score 0; remaining drifted rows (beyond the old budget) trigger
        stats2 = cp.drift_rebalancer.rebalance_once()
        assert all(
            k not in stats["triggered"] for k in stats2["triggered"]
        )

    def test_unconsumed_trigger_never_restamped(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION", "8")
        cp, members, add_cluster = drift_plane()
        cp.store.apply(new_deployment("w0", replicas=4, cpu="1", memory="1Gi"))
        cp.settle()
        add_cluster("c2", 64)
        cp.settle()
        stats = cp.drift_rebalancer.rebalance_once()
        assert stats["triggered"] == ["default/w0-deployment"]
        rb = cp.store.get("ResourceBinding", "default/w0-deployment")
        stamp = rb.spec.reschedule_triggered_at
        # the trigger is pending (we have not settled): a second round
        # must skip the binding entirely
        stats2 = cp.drift_rebalancer.rebalance_once()
        assert stats2 is None or not stats2["triggered"]
        assert rb.spec.reschedule_triggered_at == stamp
        assert sum(preemptions_total.samples().values()) == 1

    def test_dry_solve_leaves_no_trace(self):
        """A scoring pass must not touch the live plane: the quota
        working remaining is restored (a dry admit never debits budget
        real bindings need) and the provenance store captures nothing
        (a hypothetical fresh solve must not overwrite a binding's real
        decision chain)."""
        from karmada_tpu.utils.explainstore import ExplainStore

        cp, members, _add = drift_plane()
        cp.store.apply(FederatedResourceQuota(
            meta=ObjectMeta(name="q", namespace="default"),
            spec=FederatedResourceQuotaSpec(overall={"cpu": 100000}),
        ))
        cp.store.apply(new_deployment(
            "w0", replicas=4, cpu="1", memory="1Gi"
        ))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/w0-deployment")
        # a pending scale-up: delta demand > 0, so a leaky dry solve
        # WOULD debit remaining
        rb.spec.replicas += 2
        problem = cp.scheduler._problem_for(
            "default/w0-deployment", rb, True
        )
        engine = cp.scheduler._inproc_engine()
        store = ExplainStore(cap=4)
        engine.set_explain(store)
        cp.scheduler._ensure_engine_quota(engine)
        before = engine.quota.remaining.copy()
        res = cp.scheduler.dry_solve([problem])
        assert res[0].success
        assert np.array_equal(engine.quota.remaining, before)
        assert store.debug_doc(proc="t")["waves"] == []  # no captures
        assert engine.explain is store  # re-armed after the dry pass

    def test_budget_zero_disables(self, monkeypatch):
        monkeypatch.setenv("KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION", "0")
        cp, members, add_cluster = drift_plane()
        cp.store.apply(new_deployment("w0", replicas=4, cpu="1", memory="1Gi"))
        cp.settle()
        add_cluster("c2", 64)
        cp.settle()
        assert cp.drift_rebalancer.rebalance_once() is None
        rb = cp.store.get("ResourceBinding", "default/w0-deployment")
        assert rb.spec.reschedule_triggered_at is None

    def test_oracle_identical_trigger_set(self, monkeypatch):
        """The controller's trigger set matches the sequential numpy
        rebalance oracle exactly (drift desc, arrival asc, budget cap)."""
        monkeypatch.setenv("KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION", "2")
        cp, members, add_cluster = drift_plane()
        for i in range(4):
            cp.store.apply(new_deployment(
                f"w{i}", replicas=2 + i, cpu="1", memory="1Gi"
            ))
        cp.settle()
        add_cluster("c2", 64)
        cp.settle()
        engine = cp.scheduler._inproc_engine()
        snap = engine.snapshot
        from karmada_tpu.scheduler.snapshot import compile_placement

        keys, current, candidates, strategies, replicas, avail = (
            [], {}, {}, {}, {}, {}
        )
        for rb in cp.store.list("ResourceBinding"):
            key = rb.meta.namespaced_name
            keys.append(key)
            current[key] = {
                tc.name: tc.replicas for tc in rb.spec.clusters
            }
            cpl = compile_placement(rb.spec.placement, snap)
            candidates[key] = (
                cpl.terms[0][1] & cpl.taint_ok & cpl.spread_field_ok
            )
            strategies[key] = int(cpl.strategy)
            replicas[key] = rb.spec.replicas
            req = np.zeros((1, len(snap.dims)), np.int64)
            for d, q in (
                rb.spec.replica_requirements.resource_request or {}
            ).items():
                j = snap.dim_index(d)
                if j is not None:
                    req[0, j] = q
            pods = snap.dim_index("pods")
            if pods is not None:
                req[0, pods] = max(req[0, pods], 1)
            avail[key] = engine._availability_np(
                req, np.asarray([rb.spec.replicas], np.int32)
            )[0]
        _drifts, want = rebalance_np(
            keys,
            names=snap.names,
            current=current,
            candidates=candidates,
            strategies=strategies,
            replicas=replicas,
            avail=avail,
            budget=2,
        )
        stats = cp.drift_rebalancer.rebalance_once()
        assert stats["triggered"] == want


# --------------------------------------------------------------------------
# explain stage bit + history columns
# --------------------------------------------------------------------------


class TestSurfaces:
    def test_explain_preempted_stage_bit(self):
        from karmada_tpu.utils.explainstore import ExplainStore

        snap = ClusterSnapshot([
            new_cluster("m0", cpu="1000", memory="100Gi"),
            new_cluster("m1", cpu="1000", memory="100Gi"),
        ])
        eng = TensorScheduler(snap, trace_manifest="")
        store = ExplainStore(cap=4)
        eng.set_explain(store)
        victim = BindingProblem(
            key="d/victim",
            placement=dynamic_weight_placement(),
            replicas=2,
            requests=dict(CPU_REQ),
            gvk="apps/v1/Deployment",
            evict_clusters=("m0",),
            preempt_clusters=("m0",),
        )
        res = eng.schedule([victim])
        assert res[0].success
        doc = store.explain_binding("d/victim")
        assert "PreemptedByHigherPriority" in doc["stages"]
        assert doc["stages"]["PreemptedByHigherPriority"]["clusters"] == [
            "m0"
        ]
        # the eviction ALSO explains as the folded taint stage — both
        # bits name the same cluster, the preemption one says WHY
        assert doc["stages"]["TaintUntolerated"]["clusters"] == ["m0"]

    def test_history_row_carries_scarcity_columns(self):
        from karmada_tpu.utils.history import (
            HISTORY_SERIES,
            WaveHistory,
            render_history_table,
        )
        from karmada_tpu.utils.tracing import WaveTracer

        for name in (
            "preemptions", "disruption_budget", "disruption_used",
        ):
            assert name in HISTORY_SERIES
        tr = WaveTracer()
        hist = WaveHistory(cap=8)
        wave = tr.ensure_wave("test")
        with tr.span("settle"):
            preemptions_total.inc(reason="PreemptedByHigherPriority")
        hist.sample(tr, wave)
        hist.sample(tr, wave)  # baseline seeded: second row deltas 0
        preemptions_total.inc(reason="RebalanceTriggered")
        row = hist.sample(tr, wave)
        assert row["preemptions"] == 1
        assert "disruption_budget" in row and "disruption_used" in row
        table = render_history_table([row])
        assert "pre" in table.splitlines()[0]

    def test_top_parses_preemption_levels(self):
        from karmada_tpu.cli import cmd_plane_top

        reset_counter(preemptions_total)
        preemptions_total.inc(reason="PreemptedByHigherPriority")
        preemptions_total.inc(reason="RebalanceTriggered")
        doc = cmd_plane_top()
        entry = next(iter(doc["procs"].values()))
        assert entry["preemptions_total"] == 2
        assert entry["preemptions_by_reason"] == {
            "PreemptedByHigherPriority": 1,
            "RebalanceTriggered": 1,
        }

    def test_reasons_registered(self):
        from karmada_tpu.utils.reasons import REASONS, STAGE_REASONS

        assert STAGE_REASONS[7] == "PreemptedByHigherPriority"
        assert REASONS["PreemptedByHigherPriority"].stage_bit == 7
        assert REASONS["Preempted"].kind == "condition"
        assert REASONS["RebalanceTriggered"].kind == "event"


# --------------------------------------------------------------------------
# spawn-family hardening: the admission channel's boot window
# --------------------------------------------------------------------------


class TestRemoteAdmissionRetry:
    def test_env_tunable_deadline(self, monkeypatch):
        from karmada_tpu.webhook.server import RemoteAdmission

        monkeypatch.setenv("KARMADA_TPU_ADMISSION_TIMEOUT", "7.5")
        assert RemoteAdmission("http://x/admit").timeout == 7.5
        monkeypatch.setenv("KARMADA_TPU_ADMISSION_TIMEOUT", "bogus")
        assert RemoteAdmission("http://x/admit").timeout == 5.0
        monkeypatch.delenv("KARMADA_TPU_ADMISSION_TIMEOUT")
        assert RemoteAdmission(
            "http://x/admit", timeout_seconds=1.25
        ).timeout == 1.25

    def test_one_bounded_retry_absorbs_slow_first_request(self):
        """The regression: a webhook process slow to answer its FIRST
        request (machine under full-suite load) used to fail admission
        outright; one bounded retry absorbs exactly that window."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from karmada_tpu.webhook.server import RemoteAdmission

        hits = []

        class SlowFirst(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                hits.append(time.time())
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if len(hits) == 1:
                    time.sleep(1.0)  # past the 0.3s deadline
                data = json.dumps(
                    {"allowed": True, "object": body.get("object")}
                ).encode()
                try:
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionError):
                    pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), SlowFirst)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        try:
            remote = RemoteAdmission(
                f"http://127.0.0.1:{httpd.server_address[1]}/admit",
                timeout_seconds=0.3,
            )
            obj = new_deployment("w0")
            remote.admit("Resource", obj)  # would raise without retry
            assert len(hits) == 2
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_retry_is_bounded(self):
        from karmada_tpu.webhook.server import (
            AdmissionDenied,
            RemoteAdmission,
        )

        remote = RemoteAdmission(
            "http://127.0.0.1:9/admit", timeout_seconds=0.2
        )
        t0 = time.time()
        with pytest.raises(AdmissionDenied):
            remote.admit("Resource", new_deployment("w0"))
        assert time.time() - t0 < 5.0  # two fast refusals, not a spin
