"""End-to-end control-plane tests: template + policy -> scheduled binding ->
Works -> member clusters -> status return -> failover.

The in-process analogue of the reference's kind-based e2e suites
(test/e2e/scheduling_test.go, failover_test.go, rescheduling_test.go):
member clusters are fabricated, the whole reconciler fleet runs to a fixed
point, and assertions check member-side applied objects and status-return.
"""

import pytest

from karmada_tpu.api import (
    Cluster,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
    Toleration,
)
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    ApplicationFailoverBehavior,
    FailoverBehavior,
    ImageOverrider,
    OverridePolicy,
    OverrideSpec,
    Overriders,
    RuleWithCluster,
    ClusterAffinity,
    SpreadConstraint,
)
from karmada_tpu.controllers import execution_namespace
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    duplicated_placement,
    new_cluster,
    new_deployment,
    static_weight_placement,
)
from karmada_tpu.utils.features import FAILOVER, feature_gate


def nginx_policy(placement, name="nginx-policy", ns="default"):
    return PropagationPolicy(
        meta=ObjectMeta(name=name, namespace=ns),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=placement,
        ),
    )


def make_plane(n_clusters=3, **kw):
    cp = ControlPlane(**kw)
    for i in range(1, n_clusters + 1):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.settle()
    return cp


class TestQuickstart:
    """BASELINE config 1: the samples/nginx Duplicated scenario."""

    def test_duplicated_propagation(self):
        cp = make_plane(3)
        cp.store.apply(new_deployment("nginx", replicas=2))
        cp.store.apply(nginx_policy(duplicated_placement()))
        cp.settle()

        rb = cp.store.get("ResourceBinding", "default/nginx-deployment")
        assert rb is not None
        assert {tc.name: tc.replicas for tc in rb.spec.clusters} == {
            "member1": 2, "member2": 2, "member3": 2,
        }
        # member clusters actually hold the deployment with full replicas
        for name in ("member1", "member2", "member3"):
            member = cp.members.get(name)
            obj = member.get("apps/v1/Deployment", "default", "nginx")
            assert obj is not None and obj.spec["replicas"] == 2

    def test_static_weight_division(self):
        """BASELINE config 2: Divided + StaticWeightList, 10 replicas 2:1:1."""
        cp = make_plane(3)
        cp.store.apply(new_deployment("web", replicas=10))
        cp.store.apply(
            nginx_policy(
                static_weight_placement({"member1": 2, "member2": 1, "member3": 1})
            )
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        # largest-remainder: floors 5/2/2, the leftover goes to the heaviest
        assert {tc.name: tc.replicas for tc in rb.spec.clusters} == {
            "member1": 6, "member2": 2, "member3": 2,
        }
        # ReviseReplica hook divided the member manifests
        assert (
            cp.members.get("member1")
            .get("apps/v1/Deployment", "default", "web")
            .spec["replicas"]
            == 6
        )

    def test_status_aggregation_back_to_template(self):
        cp = make_plane(2)
        template = new_deployment("api", replicas=4)
        cp.store.apply(template)
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/api-deployment")
        placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
        assert sum(placed.values()) == 4
        # members report ready replicas
        for name, reps in placed.items():
            cp.members.get(name).set_workload_status(
                "apps/v1/Deployment", "default", "api",
                {"replicas": reps, "readyReplicas": reps, "updatedReplicas": reps},
            )
        cp.settle()
        template = cp.store.get("Resource", "default/api")
        assert template.status.get("readyReplicas") == 4
        rb = cp.store.get("ResourceBinding", "default/api-deployment")
        assert all(i.health == "Healthy" for i in rb.status.aggregated_status)


class TestOverrides:
    def test_image_override_per_cluster(self):
        cp = make_plane(2)
        cp.store.apply(new_deployment("app", replicas=1, image="docker.io/nginx:1.25"))
        cp.store.apply(nginx_policy(duplicated_placement()))
        cp.store.apply(
            OverridePolicy(
                meta=ObjectMeta(name="registry-override", namespace="default"),
                spec=OverrideSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    override_rules=[
                        RuleWithCluster(
                            target_cluster=ClusterAffinity(cluster_names=["member2"]),
                            overriders=Overriders(
                                image_overrider=[
                                    ImageOverrider(
                                        component="Registry",
                                        operator="replace",
                                        value="registry.eu.example.com",
                                    )
                                ]
                            ),
                        )
                    ],
                ),
            )
        )
        cp.settle()
        img1 = (
            cp.members.get("member1")
            .get("apps/v1/Deployment", "default", "app")
            .spec["template"]["spec"]["containers"][0]["image"]
        )
        img2 = (
            cp.members.get("member2")
            .get("apps/v1/Deployment", "default", "app")
            .spec["template"]["spec"]["containers"][0]["image"]
        )
        assert img1 == "docker.io/nginx:1.25"
        assert img2 == "registry.eu.example.com/nginx:1.25"

    def test_cluster_label_edit_rebuilds_overridden_work(self):
        """Override rules match LIVE cluster labels: editing a cluster's
        labels after propagation must rebuild that cluster's Works (the
        build cache carries a cluster-state token; round-2 advisor
        finding)."""
        from karmada_tpu.api.policy import LabelSelector

        cp = make_plane(2)
        cp.store.apply(new_deployment("app", replicas=1, image="docker.io/nginx:1.25"))
        cp.store.apply(nginx_policy(duplicated_placement()))
        cp.store.apply(
            OverridePolicy(
                meta=ObjectMeta(name="edge-override", namespace="default"),
                spec=OverrideSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    override_rules=[
                        RuleWithCluster(
                            target_cluster=ClusterAffinity(
                                label_selector=LabelSelector(
                                    match_labels={"tier": "edge"}
                                )
                            ),
                            overriders=Overriders(
                                image_overrider=[
                                    ImageOverrider(
                                        component="Registry",
                                        operator="replace",
                                        value="edge.example.com",
                                    )
                                ]
                            ),
                        )
                    ],
                ),
            )
        )
        cp.settle()
        img = (
            cp.members.get("member1")
            .get("apps/v1/Deployment", "default", "app")
            .spec["template"]["spec"]["containers"][0]["image"]
        )
        assert img == "docker.io/nginx:1.25"  # no label yet: rule inert
        # flip the cluster label so the override rule starts matching
        cluster = cp.store.get("Cluster", "member1")
        cluster.meta.labels["tier"] = "edge"
        cp.store.apply(cluster)
        cp.settle()
        img = (
            cp.members.get("member1")
            .get("apps/v1/Deployment", "default", "app")
            .spec["template"]["spec"]["containers"][0]["image"]
        )
        assert img == "edge.example.com/nginx:1.25"
        # and member2 (unlabelled) is untouched
        img2 = (
            cp.members.get("member2")
            .get("apps/v1/Deployment", "default", "app")
            .spec["template"]["spec"]["containers"][0]["image"]
        )
        assert img2 == "docker.io/nginx:1.25"


class TestFailover:
    def test_cluster_failover_evicts_and_reschedules(self):
        feature_gate.set(FAILOVER, True)
        try:
            cp = make_plane(3)
            cp.store.apply(new_deployment("ha-app", replicas=6))
            cp.store.apply(nginx_policy(dynamic_weight_placement()))
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/ha-app-deployment")
            before = {tc.name: tc.replicas for tc in rb.spec.clusters}
            assert sum(before.values()) == 6

            # member2 dies
            cp.members.get("member2").reachable = False
            cp.settle()

            cluster2 = cp.store.get("Cluster", "member2")
            assert any(t.effect == "NoExecute" for t in cluster2.spec.taints)
            rb = cp.store.get("ResourceBinding", "default/ha-app-deployment")
            after = {tc.name: tc.replicas for tc in rb.spec.clusters}
            assert "member2" not in after
            assert sum(after.values()) == 6  # replicas rehomed
            # eviction task holds the old work until replacement healthy
            if before.get("member2"):
                assert rb.spec.graceful_eviction_tasks or True
        finally:
            feature_gate.set(FAILOVER, False)

    def test_graceful_eviction_completes_when_replacement_healthy(self):
        feature_gate.set(FAILOVER, True)
        try:
            cp = make_plane(2)
            cp.store.apply(new_deployment("svc", replicas=2))
            cp.store.apply(nginx_policy(dynamic_weight_placement()))
            cp.settle()
            cp.members.get("member1").reachable = False
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/svc-deployment")
            if rb.spec.graceful_eviction_tasks:
                # replacement becomes healthy
                placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
                for name, reps in placed.items():
                    cp.members.get(name).set_workload_status(
                        "apps/v1/Deployment", "default", "svc",
                        {"replicas": reps, "readyReplicas": reps,
                         "updatedReplicas": reps},
                    )
                cp.settle()
                rb = cp.store.get("ResourceBinding", "default/svc-deployment")
                assert not rb.spec.graceful_eviction_tasks
                # the evicted cluster's work is garbage-collected
                work = cp.store.get(
                    "Work", f"{execution_namespace('member1')}/default.svc-deployment"
                )
                assert work is None
        finally:
            feature_gate.set(FAILOVER, False)

    def test_application_failover(self):
        clock = [1000.0]
        cp = ControlPlane(clock=lambda: clock[0])
        for i in (1, 2):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        policy = nginx_policy(dynamic_weight_placement())
        policy.spec.failover = FailoverBehavior(
            application=ApplicationFailoverBehavior(
                decision_conditions_toleration_seconds=30
            )
        )
        cp.store.apply(new_deployment("flaky", replicas=2))
        cp.store.apply(policy)
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/flaky-deployment")
        placed = {tc.name for tc in rb.spec.clusters}
        victim = sorted(placed)[0]
        # report unhealthy on the victim cluster
        cp.members.get(victim).set_workload_status(
            "apps/v1/Deployment", "default", "flaky",
            {"replicas": 1, "readyReplicas": 0, "updatedReplicas": 0},
        )
        cp.settle()
        # not yet past toleration
        rb = cp.store.get("ResourceBinding", "default/flaky-deployment")
        assert any(tc.name == victim for tc in rb.spec.clusters)
        clock[0] += 60
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/flaky-deployment")
        assert not any(tc.name == victim for tc in rb.spec.clusters)
        assert sum(tc.replicas for tc in rb.spec.clusters) == 2


class TestDescheduler:
    def test_unschedulable_replicas_reclaimed(self):
        cp = ControlPlane(enable_descheduler=True)
        for i in (1, 2):
            cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        cp.store.apply(new_deployment("batchy", replicas=8))
        cp.store.apply(nginx_policy(dynamic_weight_placement()))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/batchy-deployment")
        placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
        victim = max(placed, key=lambda n: placed[n])
        # victim cluster can't actually run 2 of its replicas
        cp.members.get(victim).unschedulable_replicas["default/batchy"] = 2
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/batchy-deployment")
        after = {tc.name: tc.replicas for tc in rb.spec.clusters}
        assert sum(after.values()) == 8  # scale-up rehomed the reclaimed 2


class TestLazyActivationPolicy:
    """ActivationPreference=Lazy: policy changes defer until the user next
    updates the template (lazy_activation_policy_test.go analogue;
    detector.go:444-450)."""

    def _lazy_policy(self, placement):
        p = nginx_policy(placement, name="lazy-policy")
        p.spec.activation_preference = "Lazy"
        return p

    def test_policy_change_defers_until_template_update(self):
        cp = make_plane(3)
        cp.store.apply(new_deployment("web", replicas=6))
        cp.store.apply(self._lazy_policy(static_weight_placement(
            {"member1": 1, "member2": 1})))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member1", "member2"}

        # policy update alone must NOT re-sync the binding
        cp.store.apply(self._lazy_policy(static_weight_placement(
            {"member3": 1})))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member1", "member2"}

        # ... but the next USER template change activates the new placement
        cp.store.apply(new_deployment("web", replicas=6, image="nginx:2"))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member3"}

    def test_immediate_policy_still_syncs_on_policy_change(self):
        cp = make_plane(3)
        cp.store.apply(new_deployment("web", replicas=6))
        cp.store.apply(nginx_policy(static_weight_placement({"member1": 1})))
        cp.settle()
        cp.store.apply(nginx_policy(static_weight_placement({"member2": 1})))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member2"}

    def test_webhook_rejects_bad_activation_preference(self):
        import pytest
        from karmada_tpu.webhook import ValidationError

        cp = make_plane(1)
        bad = nginx_policy(duplicated_placement())
        bad.spec.activation_preference = "Eventually"
        with pytest.raises(ValidationError):
            cp.store.apply(bad)


class TestPolicyPreemption:
    """preemption_test.go analogue: a higher-priority policy takes a claimed
    template only when the gate is on AND it declares preemption Always."""

    def _plane_with_claim(self):
        cp = make_plane(2)
        cp.store.apply(new_deployment("web", replicas=4))
        low = nginx_policy(static_weight_placement({"member1": 1}), name="low")
        low.spec.priority = 1
        cp.store.apply(low)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member1"}
        return cp

    def _high(self, preemption):
        high = nginx_policy(static_weight_placement({"member2": 1}), name="high")
        high.spec.priority = 10
        high.spec.preemption = preemption
        return high

    def test_preempts_with_always_and_gate(self):
        from karmada_tpu.utils.features import POLICY_PREEMPTION, feature_gate

        cp = self._plane_with_claim()
        feature_gate.set(POLICY_PREEMPTION, True)
        try:
            cp.store.apply(self._high("Always"))
            cp.settle()
            rb = next(iter(cp.store.list("ResourceBinding")))
            assert {tc.name for tc in rb.spec.clusters} == {"member2"}
            template = cp.store.get("Resource", "default/web")
            assert template.meta.labels.get(
                "propagationpolicy.karmada.io/name") == "high"
        finally:
            feature_gate.set(POLICY_PREEMPTION, False)

    def test_no_preemption_without_always(self):
        from karmada_tpu.utils.features import POLICY_PREEMPTION, feature_gate

        cp = self._plane_with_claim()
        feature_gate.set(POLICY_PREEMPTION, True)
        try:
            cp.store.apply(self._high("Never"))
            cp.settle()
            rb = next(iter(cp.store.list("ResourceBinding")))
            assert {tc.name for tc in rb.spec.clusters} == {"member1"}
        finally:
            feature_gate.set(POLICY_PREEMPTION, False)

    def test_no_preemption_with_gate_off(self):
        cp = self._plane_with_claim()
        cp.store.apply(self._high("Always"))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member1"}


class TestOrderedClusterAffinities:
    """clusteraffinities_test.go: ordered failover groups — the scheduler
    tries each ClusterAffinityTerm in order and records which one served
    (scheduler.go:533-596)."""

    def test_falls_through_to_second_group(self):
        from karmada_tpu.api.policy import ClusterAffinityTerm, Placement

        cp = make_plane(3)
        placement = Placement(
            cluster_affinities=[
                ClusterAffinityTerm(affinity_name="primary",
                                    cluster_names=["absent-cluster"]),
                ClusterAffinityTerm(affinity_name="backup",
                                    cluster_names=["member2"]),
            ]
        )
        cp.store.apply(new_deployment("web", replicas=2))
        cp.store.apply(nginx_policy(placement))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member2"}
        assert rb.status.scheduler_observed_affinity_name == "backup"


class TestFieldSelectorAffinity:
    """fieldselector_test.go: ClusterAffinity.fieldSelector matches cluster
    provider/region/zone fields."""

    def test_region_field_selector(self):
        from karmada_tpu.api.policy import (
            ClusterAffinity, FieldSelector, LabelSelectorRequirement, Placement)

        cp = ControlPlane()
        cp.join_cluster(new_cluster("m-east", region="us-east1"))
        cp.join_cluster(new_cluster("m-west", region="us-west1"))
        cp.settle()
        placement = Placement(
            cluster_affinity=ClusterAffinity(
                field_selector=FieldSelector(match_expressions=[
                    LabelSelectorRequirement(
                        key="region", operator="In", values=["us-east1"])
                ])
            )
        )
        cp.store.apply(new_deployment("web", replicas=2))
        cp.store.apply(nginx_policy(placement))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"m-east"}

    def test_notin_field_selector(self):
        from karmada_tpu.api.policy import (
            ClusterAffinity, FieldSelector, LabelSelectorRequirement, Placement)

        cp = ControlPlane()
        cp.join_cluster(new_cluster("m-east", region="us-east1"))
        cp.join_cluster(new_cluster("m-west", region="us-west1"))
        cp.settle()
        placement = Placement(
            cluster_affinity=ClusterAffinity(
                field_selector=FieldSelector(match_expressions=[
                    LabelSelectorRequirement(
                        key="region", operator="NotIn", values=["us-east1"])
                ])
            )
        )
        cp.store.apply(new_deployment("web", replicas=2))
        cp.store.apply(nginx_policy(placement))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"m-west"}


class TestClusterOverridePolicy:
    """clusteroverridepolicy_test.go: cluster-scoped override policies apply
    before namespaced ones, and a namespaced OverridePolicy wins on the
    fields it also touches (applied second)."""

    def _cop(self, name, registry):
        from karmada_tpu.api.policy import ClusterOverridePolicy

        return ClusterOverridePolicy(
            meta=ObjectMeta(name=name),
            spec=OverrideSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                override_rules=[
                    RuleWithCluster(
                        overriders=Overriders(
                            image_overrider=[
                                ImageOverrider(
                                    component="Registry",
                                    operator="replace",
                                    value=registry,
                                )
                            ]
                        ),
                    )
                ],
            ),
        )

    def test_cluster_override_applies_to_all_clusters(self):
        cp = make_plane(2)
        cp.store.apply(new_deployment("app", replicas=1,
                                      image="docker.io/nginx:1.25"))
        cp.store.apply(nginx_policy(duplicated_placement()))
        cp.store.apply(self._cop("global-registry", "mirror.example.com"))
        cp.settle()
        for m in ("member1", "member2"):
            img = (
                cp.members.get(m)
                .get("apps/v1/Deployment", "default", "app")
                .spec["template"]["spec"]["containers"][0]["image"]
            )
            assert img == "mirror.example.com/nginx:1.25", (m, img)

    def test_namespaced_override_wins_over_cluster_override(self):
        cp = make_plane(1)
        cp.store.apply(new_deployment("app", replicas=1,
                                      image="docker.io/nginx:1.25"))
        cp.store.apply(nginx_policy(duplicated_placement()))
        cp.store.apply(self._cop("global-registry", "mirror.example.com"))
        cp.store.apply(
            OverridePolicy(
                meta=ObjectMeta(name="ns-registry", namespace="default"),
                spec=OverrideSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    override_rules=[
                        RuleWithCluster(
                            overriders=Overriders(
                                image_overrider=[
                                    ImageOverrider(
                                        component="Registry",
                                        operator="replace",
                                        value="team.example.com",
                                    )
                                ]
                            ),
                        )
                    ],
                ),
            )
        )
        cp.settle()
        img = (
            cp.members.get("member1")
            .get("apps/v1/Deployment", "default", "app")
            .spec["template"]["spec"]["containers"][0]["image"]
        )
        # OverridePolicy is applied after ClusterOverridePolicy
        # (overridemanager.go ordering), so it wins the same field
        assert img == "team.example.com/nginx:1.25"


class TestPerClusterSuspension:
    """Suspension.dispatchingOnClusters: only the listed member is held
    back; the rest dispatch normally (binding/common.go:305-318)."""

    def test_suspends_only_listed_cluster(self):
        cp = make_plane(2)
        cp.store.apply(new_deployment("app", replicas=2))
        pol = nginx_policy(duplicated_placement())
        pol.spec.suspend_dispatching_on_clusters = ["member2"]
        cp.store.apply(pol)
        cp.settle()
        assert cp.members.get("member1").get(
            "apps/v1/Deployment", "default", "app") is not None
        assert cp.members.get("member2").get(
            "apps/v1/Deployment", "default", "app") is None
        # lifting the suspension dispatches the held Work
        pol.spec.suspend_dispatching_on_clusters = None
        cp.store.apply(pol)
        cp.settle()
        assert cp.members.get("member2").get(
            "apps/v1/Deployment", "default", "app") is not None


class TestFieldOverrider:
    """FieldOverrider: patch embedded JSON/YAML documents inside string
    fields (the ConfigMap data-key case, override_types.go:266-310)."""

    def _plane_with_configmap(self, data):
        from karmada_tpu.api.core import Resource

        cp = make_plane(1)
        cp.store.apply(Resource(
            api_version="v1", kind="ConfigMap",
            meta=ObjectMeta(name="db-config", namespace="default"),
            spec={"data": data},
        ))
        cp.store.apply(PropagationPolicy(
            meta=ObjectMeta(name="cm-policy", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(api_version="v1",
                                                     kind="ConfigMap")],
                placement=duplicated_placement(),
            ),
        ))
        return cp

    def test_yaml_document_patch(self):
        from karmada_tpu.api.policy import FieldOverrider, FieldPatchOperation

        cp = self._plane_with_configmap(
            {"db.yaml": "host: db.local\nport: 5432\n"})
        cp.store.apply(OverridePolicy(
            meta=ObjectMeta(name="db-override", namespace="default"),
            spec=OverrideSpec(
                resource_selectors=[ResourceSelector(api_version="v1",
                                                     kind="ConfigMap")],
                override_rules=[RuleWithCluster(overriders=Overriders(
                    field_overrider=[FieldOverrider(
                        field_path="/spec/data/db.yaml",
                        yaml=[FieldPatchOperation(
                            sub_path="/host", operator="replace",
                            value="db.member1.local")],
                    )]
                ))],
            ),
        ))
        cp.settle()
        import yaml as _yaml

        got = cp.members.get("member1").get("v1/ConfigMap", "default",
                                            "db-config")
        doc = _yaml.safe_load(got.spec["data"]["db.yaml"])
        assert doc == {"host": "db.member1.local", "port": 5432}

    def test_json_document_patch_add(self):
        from karmada_tpu.api.policy import FieldOverrider, FieldPatchOperation

        cp = self._plane_with_configmap({"cfg.json": '{"replicas": 1}'})
        cp.store.apply(OverridePolicy(
            meta=ObjectMeta(name="cfg-override", namespace="default"),
            spec=OverrideSpec(
                resource_selectors=[ResourceSelector(api_version="v1",
                                                     kind="ConfigMap")],
                override_rules=[RuleWithCluster(overriders=Overriders(
                    field_overrider=[FieldOverrider(
                        field_path="/spec/data/cfg.json",
                        json=[FieldPatchOperation(
                            sub_path="/debug", operator="add", value=True)],
                    )]
                ))],
            ),
        ))
        cp.settle()
        import json as _json

        got = cp.members.get("member1").get("v1/ConfigMap", "default",
                                            "db-config")
        assert _json.loads(got.spec["data"]["cfg.json"]) == {
            "replicas": 1, "debug": True}

    def test_webhook_rejects_json_and_yaml_together(self):
        import pytest
        from karmada_tpu.api.policy import FieldOverrider, FieldPatchOperation
        from karmada_tpu.webhook import ValidationError

        cp = make_plane(1)
        bad = OverridePolicy(
            meta=ObjectMeta(name="bad", namespace="default"),
            spec=OverrideSpec(
                resource_selectors=[ResourceSelector(api_version="v1",
                                                     kind="ConfigMap")],
                override_rules=[RuleWithCluster(overriders=Overriders(
                    field_overrider=[FieldOverrider(
                        field_path="/spec/data/x",
                        json=[FieldPatchOperation(sub_path="/a")],
                        yaml=[FieldPatchOperation(sub_path="/b")],
                    )]
                ))],
            ),
        )
        with pytest.raises(ValidationError):
            cp.store.apply(bad)


class TestSchedulerNameFilter:
    """event_handler.go:93-113: a binding addressed to a different scheduler
    is left untouched by the default scheduler instance."""

    def test_foreign_scheduler_name_is_ignored(self):
        cp = make_plane(2)
        pol = nginx_policy(dynamic_weight_placement())
        pol.spec.scheduler_name = "my-custom-scheduler"
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(pol)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert rb.spec.scheduler_name == "my-custom-scheduler"
        assert rb.spec.clusters == []  # nobody scheduled it

    def test_second_scheduler_instance_picks_it_up(self):
        from karmada_tpu.controllers.scheduler_controller import (
            SchedulerController,
        )

        cp = make_plane(2)
        SchedulerController(cp.store, cp.runtime,
                            scheduler_name="my-custom-scheduler")
        pol = nginx_policy(dynamic_weight_placement())
        pol.spec.scheduler_name = "my-custom-scheduler"
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(pol)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert sum(tc.replicas for tc in rb.spec.clusters) == 4


class TestPortingWorkloads:
    """porting_workloads_test.go: a member already holds an unmanaged
    same-named object. Default Abort conflict resolution refuses that
    cluster (others proceed); Overwrite adopts it."""

    def _plane(self):
        cp = make_plane(2)
        from karmada_tpu.api.core import Resource

        legacy = new_deployment("web", replicas=9)  # diverged legacy content
        cp.members.get("member1").apply(legacy)
        return cp

    def test_abort_refuses_conflicting_cluster_only(self):
        cp = self._plane()
        cp.store.apply(new_deployment("web", replicas=2))
        cp.store.apply(nginx_policy(duplicated_placement()))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        applied = {i.cluster_name: i.applied for i in rb.status.aggregated_status}
        assert applied.get("member2") is True
        assert applied.get("member1") is False  # conflict: unmanaged object
        # the legacy object was not stomped
        got = cp.members.get("member1").get("apps/v1/Deployment", "default", "web")
        assert got.spec["replicas"] == 9

    def test_overwrite_adopts_conflicting_object(self):
        cp = self._plane()
        cp.store.apply(new_deployment("web", replicas=2))
        pol = nginx_policy(duplicated_placement())
        pol.spec.conflict_resolution = "Overwrite"
        cp.store.apply(pol)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        applied = {i.cluster_name: i.applied for i in rb.status.aggregated_status}
        assert applied.get("member1") is True
        got = cp.members.get("member1").get("apps/v1/Deployment", "default", "web")
        assert got.spec["replicas"] == 2  # adopted and converged


class TestClusterPropagationPolicy:
    """clusterpropagationpolicy_test.go: a CPP serves namespaced templates
    when no namespaced policy matches, and a namespaced PP outranks it."""

    def _cpp(self, placement, name="cpp"):
        from karmada_tpu.api import ClusterPropagationPolicy

        return ClusterPropagationPolicy(
            meta=ObjectMeta(name=name),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=placement,
            ),
        )

    def test_cpp_binds_namespaced_template(self):
        cp = make_plane(2)
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(self._cpp(static_weight_placement({"member1": 1})))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member1"}
        template = cp.store.get("Resource", "default/web")
        assert template.meta.labels.get(
            "clusterpropagationpolicy.karmada.io/name") == "cpp"

    def test_namespaced_pp_outranks_cpp(self):
        cp = make_plane(2)
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(self._cpp(static_weight_placement({"member1": 1})))
        cp.store.apply(nginx_policy(static_weight_placement({"member2": 1})))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member2"}
        template = cp.store.get("Resource", "default/web")
        assert template.meta.labels.get(
            "propagationpolicy.karmada.io/name") == "nginx-policy"


class TestLazyGateRaces:
    def test_user_update_survives_concurrent_lazy_policy_event(self):
        """A user template update queued BEFORE a lazy-policy event in the
        same settle batch must still sync (the coalesced reconcile may not
        be marked Karmada-triggered)."""
        cp = make_plane(3)
        lazy = nginx_policy(static_weight_placement({"member1": 1}),
                            name="lazy")
        lazy.spec.activation_preference = "Lazy"
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(lazy)
        cp.settle()
        # same batch: user bumps replicas, THEN the policy changes
        cp.store.apply(new_deployment("web", replicas=8))
        lazy2 = nginx_policy(static_weight_placement({"member2": 1}),
                             name="lazy")
        lazy2.spec.activation_preference = "Lazy"
        cp.store.apply(lazy2)
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        # the user's replica change applied (and with it the new placement,
        # since the template edit activates the pending policy content)
        assert rb.spec.replicas == 8


class TestCppPreemptionGate:
    def test_cpp_claim_protected_like_pp_claim(self):
        from karmada_tpu.api import ClusterPropagationPolicy

        def cpp(name, placement, priority=0, preemption="Never"):
            p = ClusterPropagationPolicy(
                meta=ObjectMeta(name=name),
                spec=PropagationSpec(
                    resource_selectors=[ResourceSelector(
                        api_version="apps/v1", kind="Deployment")],
                    placement=placement,
                ),
            )
            p.spec.priority = priority
            p.spec.preemption = preemption
            return p

        cp = make_plane(2)
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(cpp("a", static_weight_placement({"member1": 1})))
        cp.settle()
        # higher-priority CPP without preemption=Always (gate off anyway)
        # must NOT steal the claim
        cp.store.apply(cpp("b", static_weight_placement({"member2": 1}),
                           priority=10))
        cp.settle()
        rb = next(iter(cp.store.list("ResourceBinding")))
        assert {tc.name for tc in rb.spec.clusters} == {"member1"}
        template = cp.store.get("Resource", "default/web")
        assert template.meta.labels.get(
            "clusterpropagationpolicy.karmada.io/name") == "a"


class TestFieldOverriderNoOps:
    def test_empty_operation_lists_preserve_document_format(self):
        from karmada_tpu.api.core import Resource
        from karmada_tpu.api.policy import FieldOverrider, Overriders
        from karmada_tpu.controllers.overridemanager import apply_overriders

        obj = Resource(api_version="v1", kind="ConfigMap",
                       meta=ObjectMeta(name="c", namespace="default"),
                       spec={"data": {"cfg.json": '{"a": 1}'}})
        apply_overriders(obj, Overriders(field_overrider=[
            FieldOverrider(field_path="/spec/data/cfg.json")]))
        # no ops -> the embedded JSON must NOT be re-serialized as YAML
        assert obj.spec["data"]["cfg.json"] == '{"a": 1}'


class TestSpreadConstraintPolicy:
    """Plane-level spread constraints: a PropagationPolicy carrying
    region+cluster SpreadConstraints schedules through the engine's
    derived-selection fleet path and honors the constraint bounds."""

    def test_spread_policy_bounds_regions_and_clusters(self):
        cp = ControlPlane()
        for i in range(1, 9):
            cp.join_cluster(
                new_cluster(f"m{i}", cpu="100", memory="200Gi",
                            region=f"r{(i - 1) // 2}")  # 4 regions x 2
            )
        cp.settle()
        placement = dynamic_weight_placement(
            spread_constraints=[
                SpreadConstraint(spread_by_field="region",
                                 min_groups=2, max_groups=3),
                SpreadConstraint(spread_by_field="cluster",
                                 min_groups=2, max_groups=4),
            ]
        )
        cp.store.apply(new_deployment("spread-app", replicas=8))
        cp.store.apply(nginx_policy(placement))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/spread-app-deployment")
        assert rb is not None and rb.spec.clusters, "not scheduled"
        placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
        assert sum(placed.values()) == 8
        regions = {
            cp.store.get("Cluster", n).spec.region for n in placed
        }
        assert 2 <= len(regions) <= 3, regions
        assert 2 <= len(placed) <= 4, placed
        # members actually hold the divided workload
        for name, reps in placed.items():
            obj = cp.members.get(name).get(
                "apps/v1/Deployment", "default", "spread-app"
            )
            assert obj is not None and obj.spec["replicas"] == reps
