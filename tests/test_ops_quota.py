"""Quota kernels (ops.quota) vs the sequential numpy oracle
(refimpl.quota_np): admission decisions and cap tensors must be identical
for any inputs — the kernel is one sort + segment cumsum, the oracle a
per-binding Python loop sharing no code with it."""

import numpy as np
import pytest

from karmada_tpu.ops.quota import (
    DEMAND_CLAMP,
    MAX_INT32,
    UNLIMITED,
    cluster_caps_np,
    quota_admit,
    quota_cluster_caps,
)
from karmada_tpu.refimpl.quota_np import (
    admit_wave_np,
    cluster_caps_seq,
)


class TestQuotaAdmit:
    def test_fifo_head_of_line(self):
        """First-come wins inside a wave: a denied binding's demand holds
        its place in line, so a later smaller request cannot leapfrog."""
        ns = np.zeros(3, np.int32)
        demand = np.array([[6], [6], [3]], np.int64)
        remaining = np.array([[10]], np.int64)
        admitted, used = quota_admit(ns, demand, remaining)
        assert np.asarray(admitted).tolist() == [True, False, False]
        assert np.asarray(used).tolist() == [[6]]

    def test_unquotad_rows_always_admit(self):
        ns = np.array([-1, 0, -1], np.int32)
        demand = np.array([[100], [100], [100]], np.int64)
        remaining = np.array([[0]], np.int64)
        admitted, used = quota_admit(ns, demand, remaining)
        assert np.asarray(admitted).tolist() == [True, False, True]
        assert np.asarray(used).tolist() == [[0]]

    def test_unlimited_dim_never_constrains(self):
        ns = np.zeros(2, np.int32)
        demand = np.array([[5, 10**9], [5, 10**9]], np.int64)
        remaining = np.array([[10, UNLIMITED]], np.int64)
        admitted, _ = quota_admit(ns, demand, remaining)
        assert np.asarray(admitted).tolist() == [True, True]

    def test_multi_dim_all_must_fit(self):
        ns = np.zeros(2, np.int32)
        demand = np.array([[5, 5], [5, 5]], np.int64)
        remaining = np.array([[100, 7]], np.int64)  # dim 1 blocks row 2
        admitted, _ = quota_admit(ns, demand, remaining)
        assert np.asarray(admitted).tolist() == [True, False]

    def test_interleaved_namespaces_keep_arrival_order(self):
        """Namespace grouping is a STABLE sort: within each namespace the
        cumsum runs in arrival order even when rows interleave."""
        ns = np.array([0, 1, 0, 1, 0], np.int32)
        demand = np.array([[4], [9], [4], [9], [4]], np.int64)
        remaining = np.array([[9], [18]], np.int64)
        admitted, used = quota_admit(ns, demand, remaining)
        # ns0: 4, 8 ok; 12 > 9 denied. ns1: 9, 18 both ok.
        assert np.asarray(admitted).tolist() == [True, True, True, True, False]
        assert np.asarray(used).tolist() == [[8], [18]]

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_randomized_oracle_identity(self, seed):
        rng = np.random.default_rng(seed)
        for _ in range(10):
            b = int(rng.integers(1, 130))
            n = int(rng.integers(1, 9))
            r = int(rng.integers(1, 5))
            ns = rng.integers(-1, n, b).astype(np.int32)
            demand = rng.integers(0, 25, (b, r)).astype(np.int64)
            demand[ns < 0] = 0
            remaining = rng.integers(0, 80, (n, r)).astype(np.int64)
            remaining[rng.random((n, r)) < 0.25] = UNLIMITED
            a_dev, u_dev = quota_admit(ns, demand, remaining)
            a_np, u_np = admit_wave_np(ns.tolist(), demand, remaining)
            assert np.asarray(a_dev).tolist() == a_np
            assert np.array_equal(np.asarray(u_dev), u_np)

    def test_demand_clamp_headroom(self):
        """A wave of clamp-sized demands must not overflow the cumsum."""
        b = 64
        ns = np.zeros(b, np.int32)
        demand = np.full((b, 1), DEMAND_CLAMP, np.int64)
        remaining = np.array([[UNLIMITED]], np.int64)
        admitted, used = quota_admit(ns, demand, remaining)
        assert np.asarray(admitted).all()
        assert int(np.asarray(used)[0, 0]) == b * DEMAND_CLAMP


class TestClusterCaps:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_device_numpy_sequential_identity(self, seed):
        rng = np.random.default_rng(seed)
        for _ in range(10):
            ncap = int(rng.integers(1, 5))
            c = int(rng.integers(1, 12))
            r = int(rng.integers(1, 5))
            b = int(rng.integers(1, 24))
            caps = rng.integers(0, 200, (ncap, c, r)).astype(np.int64)
            caps[rng.random((ncap, c, r)) < 0.3] = UNLIMITED
            rows = rng.integers(-1, ncap, b).astype(np.int32)
            reqs = rng.integers(0, 12, (b, r)).astype(np.int64)
            dev = np.asarray(quota_cluster_caps(caps, rows, reqs))
            mirror = cluster_caps_np(caps, rows, reqs)
            assert np.array_equal(dev, mirror)
            for i in range(b):
                assert np.array_equal(
                    dev[i], cluster_caps_seq(caps, int(rows[i]), reqs[i])
                )

    def test_uncapped_rows_answer_no_constraint(self):
        caps = np.full((1, 3, 2), 10, np.int64)
        out = np.asarray(quota_cluster_caps(
            caps, np.array([-1], np.int32), np.array([[5, 5]], np.int64)
        ))
        assert (out == MAX_INT32).all()

    def test_unlimited_cell_with_huge_request(self):
        """An UNLIMITED cap must never constrain, even when the request is
        large enough that UNLIMITED // request would fall below
        MAX_INT32."""
        caps = np.full((1, 1, 1), UNLIMITED, np.int64)
        req = np.array([[2**40]], np.int64)
        out = np.asarray(quota_cluster_caps(
            caps, np.array([0], np.int32), req
        ))
        assert out[0, 0] == MAX_INT32

    def test_min_over_requested_dims(self):
        caps = np.array([[[12, 9]]], np.int64)  # one cluster, dims 12 / 9
        req = np.array([[4, 3]], np.int64)  # fits 3 by either dim
        out = np.asarray(quota_cluster_caps(
            caps, np.array([0], np.int32), req
        ))
        assert out[0, 0] == 3
        # zero-request dim is ignored
        req2 = np.array([[4, 0]], np.int64)
        out2 = np.asarray(quota_cluster_caps(
            caps, np.array([0], np.int32), req2
        ))
        assert out2[0, 0] == 3  # 12 // 4


class TestOverflowHardening:
    def test_demand_row_scale_cannot_wrap(self):
        """An absurd-but-legal request x a huge replica delta must clamp,
        never wrap int64 to zero/negative demand (which would bypass
        admission and INCREASE remaining on debit)."""
        from karmada_tpu.scheduler.quota import QuotaSnapshot

        q = QuotaSnapshot(
            dims=["cpu", "memory"], ns_index={"a": 0},
            remaining=np.zeros((1, 2), np.int64),
            cap_index={}, cluster_caps=np.zeros((0, 1, 2), np.int64),
            generation=1, cap_token=0,
        )
        row = q.demand_row({"memory": 2**43}, 2**21)  # would wrap to 0
        assert row.tolist() == [0, DEMAND_CLAMP]
        row2 = q.demand_row({"memory": 2**43}, 2**21 - 1)  # would wrap < 0
        assert (row2 >= 0).all() and row2[1] == DEMAND_CLAMP

    def test_admit_rejects_over_bound_waves(self):
        from karmada_tpu.ops.quota import MAX_ADMIT_ROWS

        b = MAX_ADMIT_ROWS * 2
        with pytest.raises(AssertionError):
            quota_admit(
                np.zeros(b, np.int32),
                np.zeros((b, 1), np.int64),
                np.zeros((1, 1), np.int64),
            )
