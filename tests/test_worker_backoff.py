"""Wall-clock requeue discipline (ref: pkg/util/worker.go over a
rate-limiting workqueue — DefaultControllerRateLimiter's per-item
exponential backoff). Cooperative mode keeps the deterministic
immediate-requeue contract the e2e drivers depend on."""

from karmada_tpu.utils.worker import DONE, REQUEUE, Runtime


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_cooperative_mode_drops_after_max_retries():
    rt = Runtime()
    calls = []
    w = rt.new_worker("fail", lambda k: calls.append(k) or REQUEUE)
    w.enqueue("x")
    rt.run_until_settled()
    assert len(calls) == w.MAX_RETRIES + 1
    assert len(w) == 0 and w.delayed == 0


def test_realtime_mode_backs_off_exponentially():
    rt = Runtime()
    rt.realtime = True
    clock = FakeClock()
    calls = []
    w = rt.new_worker(
        "fail", lambda k: calls.append(clock.t) or REQUEUE,
        backoff_base=0.01, backoff_max=1.0, clock=clock,
    )
    w.enqueue("x")
    assert w.process_one() and not w.process_one()  # parked, not requeued
    assert w.delayed == 1
    assert abs(w.next_due() - 0.01) < 1e-9
    # not due yet: half the window passes, still parked
    clock.t += 0.005
    assert not w.process_one()
    clock.t += 0.006
    assert w.process_one()  # due: retried, parked again at 2x
    assert abs(w.next_due() - 0.02) < 1e-9
    # backoff caps at backoff_max
    for _ in range(12):
        clock.t += 2.0
        assert w.process_one()
    assert w.next_due() <= 1.0 + 1e-9
    # success resets the per-key backoff
    ok = rt.new_worker("ok", lambda k: DONE, clock=clock)
    ok.enqueue("x")
    assert ok.process_one()
    assert ok._retries.get("x") is None


def test_realtime_never_drops_and_runtime_reports_due():
    rt = Runtime()
    rt.realtime = True
    clock = FakeClock()
    n = [0]

    def reconcile(k):
        n[0] += 1
        return REQUEUE if n[0] < 25 else DONE  # beyond MAX_RETRIES

    w = rt.new_worker("flaky", reconcile, backoff_base=0.001,
                      backoff_max=0.01, clock=clock)
    w.enqueue("k")
    while n[0] < 25:
        due = rt.next_due()
        if due is not None and due > 0:
            clock.t += due
        rt.run_until_settled(tick=False)
    assert n[0] == 25  # survived past the cooperative drop threshold
    assert w.delayed == 0 and len(w) == 0
    assert rt.next_due() is None
