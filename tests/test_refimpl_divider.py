"""Oracle tests: hand-computed tables mirroring the reference's unit-test
strategy for the divider (ref: pkg/scheduler/core/division_algorithm_test.go,
assignment_test.go — table-driven exact-assignment checks)."""

import pytest

from karmada_tpu.refimpl import (
    AGGREGATED,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    STATIC_WEIGHT,
    DivisionProblem,
    UnschedulableError,
    assign_replicas,
    merge_estimates,
    take_by_weight,
)


class TestTakeByWeight:
    def test_exact_division(self):
        # N=6 over weights 1:2:3 with init 1/2/3 -> 2/4/6
        out = take_by_weight(6, [(0, 1, 0), (1, 2, 0), (2, 3, 0)], {0: 1, 1: 2, 2: 3})
        assert out == {0: 2, 1: 4, 2: 6}

    def test_remainder_to_heaviest(self):
        # N=2 over 1:2:3: floors 0/0/0 after w*2//6 = 0,0,1; remainder goes to
        # heaviest first (C), merged with init
        out = take_by_weight(2, [(0, 1, 0), (1, 2, 0), (2, 3, 0)], {0: 1, 1: 2, 2: 3})
        assert out == {0: 1, 1: 2, 2: 5}

    def test_remainder_tiebreak_last_replicas(self):
        # equal weights; lastReplicas desc decides who gets the remainder
        out = take_by_weight(4, [(0, 1, 0), (1, 1, 5), (2, 1, 0)])
        assert out == {1: 2, 0: 1, 2: 1}

    def test_remainder_tiebreak_index(self):
        # full tie -> ascending index order gets the remainder
        out = take_by_weight(4, [(2, 1, 0), (0, 1, 0), (1, 1, 0)])
        assert out == {0: 2, 1: 1, 2: 1}

    def test_zero_weight_sum_no_op(self):
        assert take_by_weight(5, [(0, 0, 0)], {0: 3}) == {0: 3}

    def test_done_short_circuit(self):
        assert take_by_weight(0, [(0, 1, 0)], {0: 3}) == {0: 3}


class TestStaticWeight:
    def _solve(self, replicas, weights, prev=None):
        p = DivisionProblem(
            replicas=replicas,
            strategy=STATIC_WEIGHT,
            candidates=list(range(len(weights))),
            static_weights=weights,
            prev=prev,
        )
        return assign_replicas(p)

    def test_replica_12_weight_3_2_1(self):
        assert self._solve(12, [3, 2, 1]) == {0: 6, 1: 4, 2: 2}

    def test_replica_14_weight_3_2_1(self):
        # floors: 7, 4, 2 (sum 13), remainder 1 -> heaviest
        assert self._solve(14, [3, 2, 1]) == {0: 8, 1: 4, 2: 2}

    def test_insufficient_gets_zero(self):
        # N=2 over weight 1:1:1 -> two clusters get 1, the third 0 (dropped)
        assert self._solve(2, [1, 1, 1]) == {0: 1, 1: 1}

    def test_unweighted_cluster_ignored(self):
        assert self._solve(12, [3, 0, 1]) == {0: 9, 2: 3}

    def test_all_zero_weights_default_to_one(self):
        assert self._solve(3, [0, 0, 0]) == {0: 1, 1: 1, 2: 1}


class TestDynamicWeight:
    def _solve(self, replicas, avail, prev=None, fresh=False, strategy=DYNAMIC_WEIGHT):
        p = DivisionProblem(
            replicas=replicas,
            strategy=strategy,
            candidates=list(range(len(avail))),
            available=avail,
            prev=prev,
            fresh=fresh,
        )
        return assign_replicas(p)

    def test_first_assignment_6_8_10(self):
        # ref table "replica 12, dynamic weight 6:8:10": 3/4/5
        assert self._solve(12, [6, 8, 10]) == {0: 3, 1: 4, 2: 5}

    def test_first_assignment_8_8_10(self):
        # floors: 12*8//26=3, 3, 12*10//26=4 -> remainder 2 -> avail desc
        # (cluster2 w10 first, then tie 8:8 -> index asc)
        assert self._solve(12, [8, 8, 10]) == {0: 4, 1: 3, 2: 5}

    def test_scale_up_keeps_previous(self):
        # ref "replica 12 -> 24, dynamic weighted 10:10:10": delta 12 over
        # availability with init = previous
        prev = {0: 4, 1: 4, 2: 4}
        assert self._solve(24, [10, 10, 10], prev) == {0: 8, 1: 8, 2: 8}

    def test_scale_down_proportional(self):
        # ref "replica 12 -> 6, dynamic weighted 1:1:1": shrink by prev weights
        prev = {0: 4, 1: 4, 2: 4}
        assert self._solve(6, [1, 1, 1], prev) == {0: 2, 1: 2, 2: 2}

    def test_scale_down_ignores_availability(self):
        prev = {0: 9, 1: 3}
        assert self._solve(4, [0, 0], prev) == {0: 3, 1: 1}

    def test_unschedulable(self):
        with pytest.raises(UnschedulableError):
            self._solve(12, [1, 1, 1])

    def test_steady_noop_when_equal(self):
        prev = {0: 5, 1: 7}
        assert self._solve(12, [100, 100], prev) == {0: 5, 1: 7}

    def test_fresh_credits_previous(self):
        # fresh: avail credited with prev, full recompute, no init
        prev = {0: 6, 1: 6}
        out = self._solve(12, [0, 0, 12], prev, fresh=True)
        # credited: 6, 6, 12 -> weights 6:6:12 over 12 -> 3/3/6
        assert out == {0: 3, 1: 3, 2: 6}


class TestAggregated:
    def _solve(self, replicas, avail, prev=None, fresh=False):
        p = DivisionProblem(
            replicas=replicas,
            strategy=AGGREGATED,
            candidates=list(range(len(avail))),
            available=avail,
            prev=prev,
            fresh=fresh,
        )
        return assign_replicas(p)

    def test_first_assignment_packs_fewest(self):
        # ref "replica 12, aggregated 6:8:10": prefix by avail desc =
        # [c2(10), c1(8)] cum 18 >= 12 -> dispense 12 by 10:8
        assert self._solve(12, [6, 8, 10]) == {2: 7, 1: 5}

    def test_single_cluster_fits_all(self):
        # ref "replica 12, aggregated 12:8:10": cluster0 alone suffices
        assert self._solve(12, [12, 8, 10]) == {0: 12}

    def test_all_needed(self):
        # ref "replica 12, aggregated 3:3:3" -> unschedulable (9 < 12)
        with pytest.raises(UnschedulableError):
            self._solve(12, [3, 3, 3])

    def test_scale_up_sticky(self):
        # ref "replica 12 -> 24, aggregated 4:6:8": prev on all three; delta 12
        prev = {0: 2, 1: 4, 2: 6}
        out = self._solve(24, [4, 6, 8], prev)
        assert sum(out.values()) == 24
        # previously-used clusters keep at least their replicas
        assert all(out[i] >= prev[i] for i in prev)

    def test_scale_up_prefers_prev_prefix(self):
        # prev only on cluster0; delta fits in prev cluster -> stays there
        prev = {0: 6}
        out = self._solve(8, [10, 50], prev)
        assert out == {0: 8}


class TestDuplicated:
    def test_broadcast(self):
        p = DivisionProblem(replicas=5, strategy=DUPLICATED, candidates=[0, 3, 7])
        assert assign_replicas(p) == {0: 5, 3: 5, 7: 5}


class TestMergeEstimates:
    def test_min_merge_with_sentinel(self):
        out = merge_estimates(10, [[5, -1, 30], [7, -1, 20]], 3)
        assert out == [5, 10, 20]  # -1 ignored everywhere -> clamp to replicas

    def test_non_workload_skips(self):
        assert merge_estimates(0, [[5, 5]], 2) == [0, 0]
