"""Embedded third-party customization corpus (kruise/argo/flux/kyverno/flink).

Ref: pkg/resourceinterpreter/default/thirdparty/resourcecustomizations/**
+ loader thirdparty.go; chain order interpreter.go:120-143 (user customized
> thirdparty > native). Fixtures mirror the reference's testdata
desired/observed pairs.
"""

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.work import AggregatedStatusItem
from karmada_tpu.interpreter import default_interpreter
from karmada_tpu.interpreter.thirdparty import THIRDPARTY_CUSTOMIZATIONS


def item(cluster, status):
    return AggregatedStatusItem(cluster_name=cluster, status=status, applied=True)


def cloneset(replicas=5, generation=3):
    return Resource(
        api_version="apps.kruise.io/v1alpha1",
        kind="CloneSet",
        meta=ObjectMeta(name="cs", namespace="default", generation=generation),
        spec={
            "replicas": replicas,
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": "app",
                            "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}},
                            "env": [
                                {
                                    "name": "CFG",
                                    "valueFrom": {"configMapKeyRef": {"name": "cs-config"}},
                                }
                            ],
                        }
                    ]
                }
            },
        },
        status={},
    )


class TestKruise:
    def test_cloneset_replicas_and_pod_requests(self):
        interp = default_interpreter()
        replicas, reqs = interp.get_replicas(cloneset(replicas=7))
        assert replicas == 7
        assert reqs.resource_request["cpu"] == 500
        assert reqs.resource_request["memory"] == 1 << 30

    def test_cloneset_revise_replica(self):
        interp = default_interpreter()
        out = interp.revise_replica(cloneset(replicas=7), 3)
        assert out.spec["replicas"] == 3

    def test_cloneset_aggregation_sums_and_revision_last(self):
        interp = default_interpreter()
        obj = cloneset(generation=4)
        out = interp.aggregate_status(
            obj,
            [
                item("m1", {"replicas": 3, "readyReplicas": 3, "updateRevision": "rev-a",
                            "generation": 2, "observedGeneration": 2}),
                item("m2", {"replicas": 2, "readyReplicas": 1, "updateRevision": "rev-b",
                            "generation": 2, "observedGeneration": 2}),
            ],
        )
        assert out.status["replicas"] == 5
        assert out.status["readyReplicas"] == 4
        assert out.status["updateRevision"] == "rev-b"
        # every member observed its generation -> template observedGeneration
        assert out.status["observedGeneration"] == 4

    def test_cloneset_observed_generation_held_back(self):
        interp = default_interpreter()
        obj = cloneset(generation=4)
        out = interp.aggregate_status(
            obj,
            [
                item("m1", {"replicas": 3, "generation": 5, "observedGeneration": 4}),
            ],
        )
        assert "observedGeneration" not in out.status or out.status[
            "observedGeneration"
        ] != 4

    def test_cloneset_empty_zero_fill(self):
        interp = default_interpreter()
        out = interp.aggregate_status(cloneset(generation=2), [])
        assert out.status["replicas"] == 0
        assert out.status["availableReplicas"] == 0
        assert out.status["observedGeneration"] == 2

    def test_cloneset_health(self):
        interp = default_interpreter()
        obj = cloneset(replicas=2, generation=1)
        obj.status = {"observedGeneration": 1, "updatedReplicas": 2,
                      "replicas": 2, "readyReplicas": 2}
        assert interp.interpret_health(obj)
        obj.status["readyReplicas"] = 1
        assert not interp.interpret_health(obj)

    def test_cloneset_reflect_projects_member_generation(self):
        """meta.generation is projected into the reflected status so the
        aggregation hold-back sees real member generations."""
        interp = default_interpreter()
        obj = cloneset(generation=6)
        obj.status = {"replicas": 3, "observedGeneration": 5}
        reflected = interp.reflect_status(obj)
        assert reflected["generation"] == 6
        assert reflected["observedGeneration"] == 5

    def test_broadcastjob_int_or_string_parallelism(self):
        """IntOrString parallelism ('50%') must not wedge the reconciler."""
        interp = default_interpreter()
        bj = Resource(
            api_version="apps.kruise.io/v1alpha1", kind="BroadcastJob",
            meta=ObjectMeta(name="bj"),
            spec={"parallelism": "50%", "template": {"spec": {}}},
        )
        replicas, _ = interp.get_replicas(bj)
        assert replicas == 1  # falls back to the default

    def test_cloneset_pod_dependencies(self):
        interp = default_interpreter()
        deps = interp.get_dependencies(cloneset())
        assert {(d.kind, d.name) for d in deps} == {("ConfigMap", "cs-config")}

    def test_broadcastjob_parallelism_default_and_health(self):
        interp = default_interpreter()
        bj = Resource(
            api_version="apps.kruise.io/v1alpha1",
            kind="BroadcastJob",
            meta=ObjectMeta(name="bj", namespace="default"),
            spec={"template": {"spec": {"containers": []}}},
            status={"desired": 3, "failed": 0, "succeeded": 0, "active": 2},
        )
        replicas, _ = interp.get_replicas(bj)
        assert replicas == 1  # no parallelism -> 1
        assert interp.interpret_health(bj)
        bj.status["failed"] = 1
        assert not interp.interpret_health(bj)
        bj.status = {"desired": 3, "failed": 0, "succeeded": 0, "active": 0}
        assert not interp.interpret_health(bj)  # nothing running nor done

    def test_broadcastjob_retains_member_template_labels(self):
        interp = default_interpreter()
        desired = Resource(
            api_version="apps.kruise.io/v1alpha1", kind="BroadcastJob",
            meta=ObjectMeta(name="bj"),
            spec={"template": {"metadata": {}, "spec": {}}},
        )
        observed = Resource(
            api_version="apps.kruise.io/v1alpha1", kind="BroadcastJob",
            meta=ObjectMeta(name="bj"),
            spec={"template": {"metadata": {"labels": {"ctrl": "owner"}}, "spec": {}}},
        )
        out = interp.retain(desired, observed)
        assert out.spec["template"]["metadata"]["labels"] == {"ctrl": "owner"}


class TestFlux:
    def helmrelease(self):
        return Resource(
            api_version="helm.toolkit.fluxcd.io/v2beta1",
            kind="HelmRelease",
            meta=ObjectMeta(name="hr", namespace="apps"),
            spec={
                "chart": {"spec": {"sourceRef": {"kind": "HelmRepository",
                                                 "name": "bitnami", "namespace": "flux-system"}}},
                "valuesFrom": [
                    {"kind": "ConfigMap", "name": "hr-values"},
                    {"kind": "Secret", "name": "hr-secrets"},
                ],
            },
            status={},
        )

    def test_suspend_retained(self):
        interp = default_interpreter()
        desired = self.helmrelease()
        observed = self.helmrelease()
        observed.spec["suspend"] = True
        out = interp.retain(desired, observed)
        assert out.spec["suspend"] is True
        # nothing retained when the member hasn't written suspend
        out2 = interp.retain(self.helmrelease(), self.helmrelease())
        assert "suspend" not in out2.spec

    def test_ready_condition_health(self):
        interp = default_interpreter()
        hr = self.helmrelease()
        hr.status = {"conditions": [
            {"type": "Ready", "status": "True", "reason": "ReconciliationSucceeded"}]}
        assert interp.interpret_health(hr)
        hr.status["conditions"][0]["reason"] = "ArtifactFailed"
        assert not interp.interpret_health(hr)

    def test_dependencies_follow_source_ref_kind(self):
        interp = default_interpreter()
        deps = interp.get_dependencies(self.helmrelease())
        got = {(d.kind, d.api_version, d.namespace, d.name) for d in deps}
        # the object actually referenced: sourceRef.kind, per-kind api group
        assert (
            "HelmRepository", "source.toolkit.fluxcd.io/v1beta2", "flux-system", "bitnami"
        ) in got
        assert ("ConfigMap", "v1", "apps", "hr-values") in got
        assert ("Secret", "v1", "apps", "hr-secrets") in got

    def test_kustomization_oci_source_kind(self):
        interp = default_interpreter()
        ks = Resource(
            api_version="kustomize.toolkit.fluxcd.io/v1",
            kind="Kustomization",
            meta=ObjectMeta(name="infra", namespace="flux-system"),
            spec={"sourceRef": {"kind": "OCIRepository", "name": "manifests"}},
        )
        deps = interp.get_dependencies(ks)
        assert {(d.kind, d.api_version, d.name) for d in deps} == {
            ("OCIRepository", "source.toolkit.fluxcd.io/v1beta2", "manifests")
        }

    def test_gitrepository_secret_dep_and_health(self):
        interp = default_interpreter()
        gr = Resource(
            api_version="source.toolkit.fluxcd.io/v1",
            kind="GitRepository",
            meta=ObjectMeta(name="repo", namespace="flux-system"),
            spec={"secretRef": {"name": "git-creds"}},
            status={"conditions": [
                {"type": "Ready", "status": "True", "reason": "Succeeded"}]},
        )
        assert interp.interpret_health(gr)
        assert {(d.kind, d.name) for d in interp.get_dependencies(gr)} == {
            ("Secret", "git-creds")
        }


class TestArgoFlinkKyverno:
    def test_workflow_defaults_and_status_retention(self):
        interp = default_interpreter()
        wf = Resource(
            api_version="argoproj.io/v1alpha1", kind="Workflow",
            meta=ObjectMeta(name="wf", namespace="ci"),
            spec={"parallelism": 4},
            status={"phase": "Running"},
        )
        replicas, _ = interp.get_replicas(wf)
        assert replicas == 4
        assert interp.interpret_health(wf)
        wf.status["phase"] = "Failed"
        assert not interp.interpret_health(wf)
        # member owns the whole status
        desired = Resource(api_version="argoproj.io/v1alpha1", kind="Workflow",
                           meta=ObjectMeta(name="wf"), spec={}, status={})
        observed = Resource(api_version="argoproj.io/v1alpha1", kind="Workflow",
                            meta=ObjectMeta(name="wf"), spec={"suspend": True},
                            status={"phase": "Succeeded"})
        out = interp.retain(desired, observed)
        assert out.status == {"phase": "Succeeded"}
        assert out.spec["suspend"] is True

    def test_flink_health_states(self):
        interp = default_interpreter()
        fd = Resource(
            api_version="flink.apache.org/v1beta1", kind="FlinkDeployment",
            meta=ObjectMeta(name="fd"),
            spec={}, status={"jobStatus": {"state": "RUNNING"}},
        )
        assert interp.interpret_health(fd)
        fd.status = {"jobStatus": {"state": "RECONCILING"},
                     "jobManagerDeploymentStatus": "READY"}
        assert not interp.interpret_health(fd)
        fd.status["jobManagerDeploymentStatus"] = "ERROR"
        assert interp.interpret_health(fd)

    def test_kyverno_ready_and_aggregation(self):
        interp = default_interpreter()
        pol = Resource(
            api_version="kyverno.io/v1", kind="ClusterPolicy",
            meta=ObjectMeta(name="require-labels"),
            spec={}, status={"ready": True},
        )
        assert interp.interpret_health(pol)
        out = interp.aggregate_status(
            pol, [item("m1", {"ready": True}), item("m2", {"ready": False})]
        )
        assert out.status["ready"] is False


class TestChainOrder:
    def test_user_customization_overrides_thirdparty(self):
        interp = default_interpreter()
        gvk = "apps.kruise.io/v1alpha1/CloneSet"
        interp.register_customized(
            gvk, "GetReplicas", lambda obj: (42, None)
        )
        replicas, _ = interp.get_replicas(cloneset(replicas=7))
        assert replicas == 42
        interp.deregister_customized(gvk, "GetReplicas")
        replicas, _ = interp.get_replicas(cloneset(replicas=7))
        assert replicas == 7

    def test_corpus_covers_reference_kinds(self):
        expected = {
            "apps.kruise.io/v1alpha1/AdvancedCronJob",
            "apps.kruise.io/v1alpha1/BroadcastJob",
            "apps.kruise.io/v1alpha1/CloneSet",
            "apps.kruise.io/v1alpha1/DaemonSet",
            "apps.kruise.io/v1beta1/StatefulSet",
            "argoproj.io/v1alpha1/Workflow",
            "flink.apache.org/v1beta1/FlinkDeployment",
            "helm.toolkit.fluxcd.io/v2beta1/HelmRelease",
            "kustomize.toolkit.fluxcd.io/v1/Kustomization",
            "kyverno.io/v1/ClusterPolicy",
            "kyverno.io/v1/Policy",
            "source.toolkit.fluxcd.io/v1/GitRepository",
            "source.toolkit.fluxcd.io/v1beta2/Bucket",
            "source.toolkit.fluxcd.io/v1beta2/HelmChart",
            "source.toolkit.fluxcd.io/v1beta2/HelmRepository",
            "source.toolkit.fluxcd.io/v1beta2/OCIRepository",
        }
        assert expected <= set(THIRDPARTY_CUSTOMIZATIONS)
